"""apex_tpu.mlp — whole-MLP fused forward/backward.

Parity target: ``apex.mlp.MLP`` (apex/mlp/mlp.py:11-87) over the ``mlp_cuda``
extension (csrc/mlp_cuda.cu:436-571): N stacked Linear(+bias)+activation
layers executed as one fused unit (cuBLAS GEMMs + custom bias/activation
kernels).

TPU design: expressing the whole stack inside one jitted call gives XLA the
full chain to fuse (bias+activation become GEMM epilogues; backward
reuses saved activations exactly like the CUDA implementation).  Supported
activations match the reference: 'none', 'relu', 'sigmoid'.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax.numpy as jnp

import flax.linen as nn

from apex_tpu.fused_dense import linear_bias

__all__ = ["MLP", "mlp_forward"]

_ACTIVATIONS = {
    "none": lambda x: x,
    "relu": nn.relu,
    "sigmoid": nn.sigmoid,
}


def mlp_forward(x, kernels: Sequence, biases: Sequence, activation: str = "relu"):
    """Run the full MLP chain functionally (mlp_cuda.forward parity)."""
    try:
        act = _ACTIVATIONS[activation]
    except KeyError:
        raise ValueError(  # mlp.py:30 raises TypeError for bad activation
            f"activation must be one of {sorted(_ACTIVATIONS)}, got {activation!r}")
    n = len(kernels)
    for i, (k, b) in enumerate(zip(kernels, biases)):
        x = linear_bias(x, k.astype(x.dtype), b)
        if i != n - 1:
            x = act(x)
    return x


class MLP(nn.Module):
    """Fused MLP module (apex.mlp.MLP).

    ``mlp_sizes`` lists layer widths including the input width, exactly like
    the reference; the activation applies between layers (not after the last).
    """

    mlp_sizes: Sequence[int]
    use_bias: bool = True
    activation: str = "relu"
    param_dtype: Any = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        sizes = list(self.mlp_sizes)
        if len(sizes) < 2:
            raise ValueError("mlp_sizes must name at least input and output widths")
        if x.shape[-1] != sizes[0]:
            raise ValueError(f"input width {x.shape[-1]} != mlp_sizes[0] {sizes[0]}")
        kernels, biases = [], []
        for i, (d_in, d_out) in enumerate(zip(sizes[:-1], sizes[1:])):
            kernels.append(self.param(f"kernel_{i}", self.kernel_init,
                                      (d_in, d_out), self.param_dtype))
            biases.append(self.param(f"bias_{i}", nn.initializers.zeros,
                                     (d_out,), self.param_dtype) if self.use_bias else None)
        return mlp_forward(x, kernels, biases, self.activation)
