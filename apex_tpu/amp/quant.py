"""Per-channel symmetric int8 quantize/dequantize primitives.

The ONE spelling site for the serving quantization subsystem
(:mod:`apex_tpu.serving.quant`) and any future training use: everything
that turns a float tensor into an ``(int8 payload, fp32 scale)`` pair —
weight tensors at load time, KV-cache rows at append time, allreduce
operands mid-collective — goes through these two functions, so the
rounding convention, the clip range, and the zero-row guard are defined
exactly once (unit-tested against a numpy oracle in
``tests/test_serving_quant.py``).

Convention (the symmetric scheme EQuARX and the int8 serving
literature share):

- **Symmetric, zero-point-free**: ``q = round(x / scale)`` clipped to
  ``[-127, 127]`` — the -128 code is unused, so negation and the
  dequant ``q * scale`` are exact mirrors and no zero-point arithmetic
  rides the hot path.
- **Per-channel scales**: ``scale = amax(|x|) / 127`` reduced over the
  caller-chosen ``axis`` (the non-channel axes).  A weight ``[in,
  out]`` quantized over ``axis=0`` gets one fp32 scale per output
  channel; a KV row ``[..., kv_heads, head_dim]`` quantized over
  ``axis=-1`` gets one scale per (position, head).
- **Zero-amax guard**: an all-zero group takes ``scale = 1.0`` (not 0,
  which would NaN the dequant; not an epsilon, which would manufacture
  denormals) — the payload is all zeros either way, so the roundtrip
  is exact.
- **fp32 scales**: scale precision bounds the whole scheme's error;
  half-precision scales would double the relative scale error for a
  byte nobody is short of (the scale tensor is smaller than the
  payload by the group size).

Roundtrip property the serving capture/restore path leans on: because
the group's amax element quantizes to exactly ±127,
``quantize(dequantize(q, s))`` reproduces ``q`` bit-for-bit and ``s``
to within 1 ulp — see ``serving/quant.py`` for the argument.
"""

from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp

__all__ = ["INT8_QMAX", "quantize_int8", "dequantize_int8"]

# symmetric clip bound: ±127, the -128 code deliberately unused
INT8_QMAX = 127.0


def _norm_axes(axis: Union[int, Tuple[int, ...]], ndim: int
               ) -> Tuple[int, ...]:
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    out = tuple(sorted(a % ndim for a in axes))
    if len(set(out)) != len(out):
        raise ValueError(f"duplicate quantization axes {axes}")
    return out


def quantize_int8(x, axis: Union[int, Tuple[int, ...]] = -1
                  ) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-channel int8 quantization.

    ``axis`` (int or tuple) names the dimensions the amax reduces
    *over* — the remaining dimensions are the channels, one fp32 scale
    each.  Returns ``(q, scale)`` with ``q`` int8 shaped like ``x`` and
    ``scale`` fp32 shaped like ``x`` with the reduced axes removed, so
    ``dequantize_int8(q, scale, axis)`` restores ``x``'s shape.
    """
    axes = _norm_axes(axis, jnp.ndim(x))
    xf = jnp.asarray(x).astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axes, keepdims=True)
    scale = jnp.where(amax > 0, amax / INT8_QMAX, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(xf / scale), -INT8_QMAX,
                 INT8_QMAX).astype(jnp.int8)
    return q, jnp.squeeze(scale, axis=axes)


def dequantize_int8(q, scale, axis: Union[int, Tuple[int, ...]] = -1,
                    dtype=jnp.float32) -> jax.Array:
    """Exact symmetric dequant: ``q * scale`` with the scale broadcast
    back over the reduced ``axis`` positions (the same ``axis`` the
    matching :func:`quantize_int8` call used), cast to ``dtype``."""
    axes = _norm_axes(axis, jnp.ndim(q))
    s = jnp.expand_dims(jnp.asarray(scale, jnp.float32), axes)
    out = q.astype(jnp.float32) * s
    return out if dtype == jnp.float32 else out.astype(dtype)
