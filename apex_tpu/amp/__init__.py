"""apex_tpu.amp — mixed precision with O0–O3 semantics (apex/amp parity).

Functional re-design of ``amp.initialize`` (apex/amp/frontend.py:197),
``amp.scale_loss`` (apex/amp/handle.py:16-160), and the dynamic
``LossScaler`` (apex/amp/scaler.py).  No monkey-patching: the policy is data,
the scaler is a pytree, and the train step stays jittable.

Typical use::

    from apex_tpu import amp

    amped = amp.initialize(model.apply, params, opt_level="O2")
    scaler, sstate = amped.scaler, amped.scaler_state

    def train_step(params, sstate, batch):
        def loss_fn(p):
            out = amped.apply(p, batch["x"])
            return compute_loss(out, batch["y"])
        loss, grads = jax.value_and_grad(
            lambda p: scaler.scale_loss(loss_fn(p), sstate))(params)
        grads, found_inf = scaler.unscale(grads, sstate)
        new_params, opt_state = opt.step(grads, params, opt_state,
                                         found_inf=found_inf)
        return new_params, scaler.update(sstate, found_inf)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax.numpy as jnp

from apex_tpu.amp import functional, lists, quant
from apex_tpu.amp.functional import active_policy, set_active_policy
from apex_tpu.amp.policy import O0, O1, O2, O3, PrecisionPolicy, get_policy
from apex_tpu.amp.quant import INT8_QMAX, dequantize_int8, quantize_int8
from apex_tpu.amp.scaler import LossScaler, LossScalerState, static_loss_scaler

__all__ = [
    "initialize",
    "AmpState",
    "functional",
    "lists",
    "quant",
    "INT8_QMAX",
    "quantize_int8",
    "dequantize_int8",
    "active_policy",
    "set_active_policy",
    "PrecisionPolicy",
    "get_policy",
    "O0",
    "O1",
    "O2",
    "O3",
    "LossScaler",
    "LossScalerState",
    "static_loss_scaler",
    "state_dict",
    "load_state_dict",
]


@dataclasses.dataclass
class AmpState:
    """What ``amp.initialize`` hands back: policy-cast params, wrapped apply,
    a configured scaler + its state (one per loss, ``num_losses`` parity with
    apex/amp/_initialize.py)."""

    apply: Callable
    params: Any
    policy: PrecisionPolicy
    scaler: LossScaler
    scaler_states: list[LossScalerState]

    @property
    def scaler_state(self) -> LossScalerState:
        return self.scaler_states[0]


def initialize(
    apply_fn: Callable,
    params: Any,
    opt_level: str = "O1",
    half_dtype=jnp.bfloat16,
    num_losses: int = 1,
    loss_scale: Optional[Any] = None,
    **overrides,
) -> AmpState:
    """Configure mixed precision (apex/amp/frontend.py:197 parity).

    Returns an :class:`AmpState`; unlike the reference nothing is patched —
    use ``amped.apply``/``amped.params`` and thread scaler state explicitly.
    """
    if loss_scale is not None:
        overrides["loss_scale"] = loss_scale
    policy = get_policy(opt_level, half_dtype=half_dtype, **overrides)
    # O1's patched-namespace semantics: ops called through amp.functional
    # follow this policy's cast lists from now on.  Other levels don't
    # patch (frontend.py patch_torch_functions=False) — and must not
    # clobber an O1 policy installed by an earlier initialize.
    if opt_level == "O1":
        set_active_policy(policy)
    scaler = policy.make_scaler()
    return AmpState(
        apply=policy.wrap_apply(apply_fn),
        params=policy.cast_params(params),
        policy=policy,
        scaler=scaler,
        scaler_states=[scaler.init() for _ in range(num_losses)],
    )


def state_dict(amp_state: AmpState) -> dict:
    """Checkpoint all loss scalers (apex README "Checkpointing", amp.state_dict)."""
    return {
        f"loss_scaler{i}": amp_state.scaler.state_dict(s)
        for i, s in enumerate(amp_state.scaler_states)
    }


def load_state_dict(amp_state: AmpState, d: dict) -> AmpState:
    states = [
        amp_state.scaler.load_state_dict(d[f"loss_scaler{i}"])
        for i in range(len(amp_state.scaler_states))
    ]
    return dataclasses.replace(amp_state, scaler_states=states)
