"""The amp O1 cast-list contract as data.

Parity target: ``apex.amp.lists`` (torch_overrides.py:7-115,
functional_overrides.py:1-80, tensor_overrides.py:1-63) and the promotion
engine (``apex/amp/amp.py:73-183``).

The reference expresses O1 by monkey-patching every listed torch function;
the *behavioral contract* underneath is three rules, which is what this
module encodes for JAX ops:

- **HALF ops** (tensor-core / MXU beneficiaries): inputs cast to the
  policy's half dtype before the op.
- **FLOAT ops** (numerically sensitive: transcendentals, reductions,
  norms, losses): inputs cast to fp32.
- **PROMOTE ops** (multi-array math): all array inputs cast to the widest
  participating float dtype ("widest wins", amp.py promote_match_arg0);
  comparisons follow the same rule.
- **SEQUENCE ops** (cat/stack): the whole sequence is cast to its widest
  member (amp.py sequence_promote).
- **BANNED ops**: calling raises with migration guidance
  (functional_overrides.BANNED_FUNCS).

``REFERENCE_MAP`` records EVERY entry of the reference's three registries:
either the JAX op name that carries the rule here (wrapped by
:mod:`apex_tpu.amp.functional`), a pointer to the apex_tpu module that owns
the semantics (fp32-internal kernels need no cast wrapper), or an explicit
N/A with the reason.  ``tensor_overrides`` dunders (``__add__`` etc.,
tensor_overrides.py:25-48) alias the same ops as the function registries —
JAX has one namespace, so each dunder maps to its function row.

Names refer to ``jax.numpy`` / ``jax.nn`` / ``jax.lax`` / ``jnp.linalg``
functions; the dispatcher in :mod:`apex_tpu.amp.functional` wraps exactly
the list entries.
"""

from __future__ import annotations

# MXU-bound ops: run in half under O1 (torch_overrides.FP16_FUNCS:7-27 +
# functional_overrides.FP16_FUNCS + the _bmms batched family:73-83)
HALF_FUNCS = [
    "matmul", "dot", "tensordot", "einsum", "vdot", "inner", "outer",
    # the one true JAX GEMM primitive (addmm/mm/mv/bmm all lower to it)
    "dot_general",
    # lax conv family (conv1d/2d/3d/transpose in the reference)
    "conv_general_dilated", "conv", "conv_transpose",
]

# numerically-sensitive ops: run in fp32 under O1
# (torch_overrides.FP32_FUNCS:29-61 + functional_overrides.FP32_FUNCS)
FLOAT_FUNCS = [
    # pointwise transcendentals
    "acos", "asin", "cosh", "sinh", "tan", "exp", "expm1",
    "log", "log10", "log2", "log1p", "reciprocal", "rsqrt", "power",
    "erf_inv",
    # reductions
    "sum", "prod", "mean", "std", "var", "cumsum", "cumprod",
    "linalg.norm", "logsumexp",
    # softmax/activation family (functional_overrides.FP32_FUNCS)
    "softmax", "log_softmax", "softplus", "gelu",
    # F.normalize analog (jax.nn.standardize)
    "standardize",
]

# multi-array math: promote to the widest float dtype
# (torch_overrides.CASTS:86-108)
PROMOTE_FUNCS = [
    "add", "subtract", "multiply", "divide", "true_divide",
    "arctan2", "cross", "hypot",
    # comparisons promote their operands the same way
    "equal", "not_equal", "greater", "greater_equal", "less", "less_equal",
]

# sequence ops: cast all members to the widest member
# (torch_overrides.SEQUENCE_CASTS:110-112)
SEQUENCE_FUNCS = ["concatenate", "stack", "hstack", "vstack"]

# functional_overrides.BANNED_FUNCS: name -> error guidance
BANNED_FUNCS = {
    "binary_cross_entropy": (
        "amp does not work out-of-the-box with a sigmoid-then-BCE pair: "
        "the probabilities must already be fp32.  Fuse them — compute BCE "
        "from *logits* (see examples/dcgan/main_amp.py bce_with_logits) — "
        "or register sigmoid as a float op via "
        "amp.functional.register_float_function."),
}

# ---------------------------------------------------------------------------
# Every reference registry entry, mapped (VERDICT r2 item 8).
# value = JAX op name in the lists above, "module: ..." when an apex_tpu
# component owns the fp32-internal semantics, or "N/A: reason".
# ---------------------------------------------------------------------------
REFERENCE_MAP = {
    # --- torch_overrides.FP16_FUNCS ---
    "conv1d": "conv_general_dilated",
    "conv2d": "conv_general_dilated",
    "conv3d": "conv_general_dilated",
    "conv_transpose1d": "conv_transpose",
    "conv_transpose2d": "conv_transpose",
    "conv_transpose3d": "conv_transpose",
    "conv_tbc": "N/A: time-batch-channel conv is a torch-internal layout; "
                "conv_general_dilated expresses it via dimension_numbers",
    "prelu": "N/A: no jax.nn.prelu; parametric slope is a user elementwise "
             "expression XLA fuses (dtype follows its inputs)",
    "matmul": "matmul",
    "addmm": "matmul",          # the add rides XLA epilogue fusion
    "addmv": "matmul",
    "addr": "outer",
    "mm": "matmul",
    "mv": "matmul",
    "bmm": "matmul",            # _bmms:73-83 (CUDA>=9.1 branch = fp16)
    "addbmm": "matmul",
    "baddbmm": "matmul",
    # --- torch_overrides.FP32_FUNCS ---
    "acos": "acos", "asin": "asin", "cosh": "cosh", "sinh": "sinh",
    "tan": "tan", "exp": "exp", "expm1": "expm1", "log": "log",
    "log10": "log10", "log2": "log2", "reciprocal": "reciprocal",
    "rsqrt": "rsqrt", "erfinv": "erf_inv", "pow": "power",
    "cumprod": "cumprod", "cumsum": "cumsum",
    "dist": "N/A: torch.dist(a,b,p) = linalg.norm(a-b); the subtraction "
            "promotes and the norm is FLOAT-listed",
    "norm": "linalg.norm", "prod": "prod", "std": "std", "sum": "sum",
    "var": "var", "mean": "mean",   # ref gates mean on torch<1.1; always on
    "renorm": "N/A: no JAX analog; per-slice clamping composes from "
              "FLOAT-listed linalg.norm + promote-listed divide",
    # --- torch_overrides.CASTS ---
    "addcdiv": "N/A: fused a+v*(t1/t2) is a user expression; the divide/"
               "multiply/add components are PROMOTE-listed",
    "addcmul": "N/A: as addcdiv",
    "atan2": "arctan2",
    "cross": "cross",
    "bilinear": "N/A: torch.bilinear is einsum('bn,onm,bm->bo'); "
                "einsum is HALF-listed (MXU-bound on TPU)",
    "dot": "dot",  # HALF here, CASTS there: 1-D dot hits the MXU on TPU
    "add": "add", "div": "divide", "mul": "multiply",
    "eq": "equal", "ge": "greater_equal", "gt": "greater",
    "le": "less_equal", "lt": "less", "ne": "not_equal",
    "equal": "equal",
    # --- torch_overrides.SEQUENCE_CASTS ---
    "cat": "concatenate", "stack": "stack",
    # --- functional_overrides.FP16_FUNCS (conv family mapped above) ---
    "linear": "N/A: flax Dense lowers to dot_general (HALF-listed); O2 "
              "casts its params wholesale",
    # --- functional_overrides.FP32_FUNCS ---
    "interpolate": "N/A: jax.image.resize; fp32-sensitive only for "
                   "area/cubic — cast explicitly or register it",
    "grid_sample": "N/A: no JAX analog (gather-based samplers are user "
                   "code)",
    "softplus": "softplus", "softmin": "N/A: softmax(-x); softmax is "
                                       "FLOAT-listed",
    "log_softmax": "log_softmax", "softmax": "softmax", "gelu": "gelu",
    "layer_norm": "module: apex_tpu.normalization.FusedLayerNorm "
                  "(fp32 statistics in-kernel, ops/layer_norm.py)",
    "group_norm": "module: apex_tpu.contrib.group_norm (fp32 statistics)",
    "local_response_norm": "N/A: obsolete (AlexNet-era); no JAX analog",
    "normalize": "standardize",
    "cosine_similarity": "N/A: composes from FLOAT-listed linalg.norm",
    "poisson_nll_loss": "N/A: losses compose from FLOAT-listed exp/log/"
                        "mean — the components carry the fp32 rule",
    "cosine_embedding_loss": "N/A: as poisson_nll_loss",
    "cross_entropy": "module: apex_tpu.contrib.xentropy / "
                     "ops.fused_lm_head (fp32 logsumexp in-kernel)",
    "hinge_embedding_loss": "N/A: as poisson_nll_loss",
    "kl_div": "N/A: as poisson_nll_loss",
    "l1_loss": "N/A: as poisson_nll_loss (abs/mean)",
    "mse_loss": "N/A: as poisson_nll_loss (square/mean)",
    "margin_ranking_loss": "N/A: as poisson_nll_loss",
    "multilabel_margin_loss": "N/A: as poisson_nll_loss",
    "multilabel_soft_margin_loss": "N/A: as poisson_nll_loss",
    "multi_margin_loss": "N/A: as poisson_nll_loss",
    "nll_loss": "N/A: as poisson_nll_loss (gather/mean)",
    "binary_cross_entropy_with_logits": "N/A: composes from FLOAT-listed "
                                        "softplus (see examples/dcgan)",
    "smooth_l1_loss": "N/A: as poisson_nll_loss",
    "soft_margin_loss": "N/A: as poisson_nll_loss",
    "triplet_margin_loss": "N/A: as poisson_nll_loss",
    "ctc_loss": "module: optax.ctc_loss computes fp32 log-space "
                "internally; no cast wrapper needed",
    # --- functional_overrides.BANNED_FUNCS ---
    "binary_cross_entropy": "BANNED (see lists.BANNED_FUNCS)",
    # --- tensor_overrides (dunders alias the function rows) ---
    "__matmul__": "matmul",
    "__pow__": "power", "__ipow__": "power", "__rpow__": "power",
    "cpu": "N/A: jax.device_get is dtype-preserving; host transfer does "
           "not need an fp32 cast on TPU (no half-precision host penalty)",
    "__add__": "add", "__iadd__": "add", "__radd__": "add",
    "__sub__": "subtract", "__isub__": "subtract", "__rsub__": "subtract",
    "__mul__": "multiply", "__imul__": "multiply", "__rmul__": "multiply",
    "__div__": "divide", "__idiv__": "divide", "__rdiv__": "divide",
    "__truediv__": "true_divide", "__itruediv__": "true_divide",
    "__rtruediv__": "true_divide",
    "__eq__": "equal", "__ne__": "not_equal", "__ge__": "greater_equal",
    "__gt__": "greater", "__le__": "less_equal", "__lt__": "less",
}
