"""The amp O1 cast-list contract as data.

Parity target: ``apex.amp.lists`` (torch_overrides.py:7-112,
functional_overrides.py, tensor_overrides.py — ~2.9k LoC of op
classification) and the promotion engine (``apex/amp/amp.py:73-183``).

The reference expresses O1 by monkey-patching every listed torch function;
the *behavioral contract* underneath is three rules, which is what this
module encodes for JAX ops:

- **HALF ops** (tensor-core / MXU beneficiaries): inputs cast to the
  policy's half dtype before the op.
- **FLOAT ops** (numerically sensitive: transcendentals, reductions,
  norms, losses): inputs cast to fp32.
- **PROMOTE ops** (multi-array math): all array inputs cast to the widest
  participating float dtype ("widest wins", amp.py promote_match_arg0);
  comparisons follow the same rule.
- **SEQUENCE ops** (cat/stack): the whole sequence is cast to its widest
  member (amp.py sequence_promote).

Names refer to ``jax.numpy`` / ``jax.lax`` / ``jax.nn`` functions; the
dispatcher in :mod:`apex_tpu.amp.functional` wraps exactly these.
"""

from __future__ import annotations

# MXU-bound ops: run in half under O1 (torch_overrides.FP16_FUNCS:7-27)
HALF_FUNCS = [
    "matmul", "dot", "tensordot", "einsum", "vdot", "inner", "outer",
    # lax conv family (conv1d/2d/3d/transpose in the reference)
    "conv_general_dilated", "conv", "conv_transpose",
]

# numerically-sensitive ops: run in fp32 under O1
# (torch_overrides.FP32_FUNCS:29-61 + functional_overrides losses/norms)
FLOAT_FUNCS = [
    # pointwise transcendentals
    "acos", "asin", "cosh", "sinh", "tan", "exp", "expm1",
    "log", "log10", "log2", "log1p", "reciprocal", "rsqrt", "power",
    # reductions
    "sum", "prod", "mean", "std", "var", "cumsum", "cumprod",
    "linalg.norm", "logsumexp",
    # softmax/loss family (functional_overrides.FP32_FUNCS)
    "softmax", "log_softmax", "softplus",
]

# multi-array math: promote to the widest float dtype
# (torch_overrides.CASTS:86-108)
PROMOTE_FUNCS = [
    "add", "subtract", "multiply", "divide", "true_divide",
    "arctan2", "cross", "hypot",
    # comparisons promote their operands the same way
    "equal", "not_equal", "greater", "greater_equal", "less", "less_equal",
]

# sequence ops: cast all members to the widest member
# (torch_overrides.SEQUENCE_CASTS:110-112)
SEQUENCE_FUNCS = ["concatenate", "stack", "hstack", "vstack"]
