"""Precision policies: the O0–O3 semantics of apex.amp, TPU-first.

The reference's amp (apex/amp/frontend.py:9-193) defines four opt levels via
``Properties``: cast_model_type, patch_torch_functions, keep_batchnorm_fp32,
master_weights, loss_scale.  The O1 mechanism — monkey-patching the torch
namespace from FP16/FP32/promote lists (apex/amp/lists/*.py, amp/amp.py:73-183)
— has no JAX analog (SURVEY.md §7 "amp O1 function patching"); instead the
policy is applied *explicitly*: cast params once, cast inputs at module
boundaries, and keep normalization/losses in fp32.  This matches how JAX/Flax
users express mixed precision and what XLA can optimize.

On TPU the natural half dtype is bfloat16 (no loss scaling needed); fp16 is
supported for parity, in which case a dynamic :class:`LossScaler` is the
default, as in the reference.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScaler, static_loss_scaler

# Parameter-name fragments treated as "norm-like" and kept fp32 when
# keep_norm_fp32 is set (the keep_batchnorm_fp32 semantics of O2,
# apex/amp/frontend.py:118-143).
_NORM_NAME_HINTS = ("norm", "bn", "batch_stats", "scale_param", "ln_")


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """Dtype rules for one training setup (apex.amp ``Properties`` parity)."""

    opt_level: str
    param_dtype: Any  # dtype model params are stored in
    compute_dtype: Any  # dtype matmuls/convs run in
    output_dtype: Any  # dtype activations are returned in
    keep_norm_fp32: bool  # keep_batchnorm_fp32 analog
    master_weights: bool  # fp32 master copies in the optimizer
    loss_scale: Any  # "dynamic" | float | None

    # ---- casting helpers -------------------------------------------------
    def cast_params(self, params: Any) -> Any:
        """Cast params to param_dtype, keeping norm-like leaves fp32 if asked.

        O2's ``model.to(cast_model_type)`` with BN exemption
        (apex/amp/_initialize.py:176-239).
        """
        if self.param_dtype == jnp.float32:
            return params

        def cast(path, leaf):
            name = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path).lower()
            if self.keep_norm_fp32 and any(h in name for h in _NORM_NAME_HINTS):
                return leaf
            if jnp.issubdtype(leaf.dtype, jnp.floating):
                return leaf.astype(self.param_dtype)
            return leaf

        return jax.tree_util.tree_map_with_path(cast, params)

    def cast_inputs(self, *args):
        """Cast floating-point array args to compute_dtype (the patched-forward
        input cast of O2, apex/amp/_initialize.py:206-239)."""

        def cast(x):
            if isinstance(x, jax.Array) and jnp.issubdtype(x.dtype, jnp.floating):
                return x.astype(self.compute_dtype)
            return x

        out = jax.tree.map(cast, args)
        return out[0] if len(args) == 1 else out

    def cast_output(self, x):
        """Cast floating leaves of a model output pytree to this policy's
        ``output_dtype`` (O1/O2 return fp32 outputs from a half-precision
        body, mirroring the reference's output-cast contract)."""
        def cast(leaf):
            if isinstance(leaf, jax.Array) and jnp.issubdtype(leaf.dtype, jnp.floating):
                return leaf.astype(self.output_dtype)
            return leaf

        return jax.tree.map(cast, x)

    def wrap_apply(self, apply_fn):
        """Wrap a model apply fn so inputs/outputs follow this policy."""

        def wrapped(params, *args, **kwargs):
            args = tuple(self.cast_inputs(a) for a in args)
            kwargs = {k: self.cast_inputs(v) for k, v in kwargs.items()}
            return self.cast_output(apply_fn(params, *args, **kwargs))

        return wrapped

    def make_scaler(self) -> LossScaler:
        """The loss scaler this policy prescribes: dynamic (fp16 default),
        static at a fixed value, or the identity static-1.0 scaler when
        ``loss_scale`` is None (bf16 policies need no scaling)."""
        if self.loss_scale == "dynamic":
            return LossScaler()
        if self.loss_scale is None:
            return static_loss_scaler(1.0)
        return static_loss_scaler(float(self.loss_scale))


def O0() -> PrecisionPolicy:
    """Pure fp32 (apex/amp/frontend.py O0)."""
    return PrecisionPolicy("O0", jnp.float32, jnp.float32, jnp.float32, False, False, None)


def O1(half_dtype=jnp.bfloat16) -> PrecisionPolicy:
    """Per-op mixed precision: fp32 params, half compute at matmul-like ops.

    The reference implements O1 by patching the torch namespace; here the
    contract is: params stay fp32, modules cast to compute_dtype at GEMM
    boundaries, reductions/norms/losses stay fp32.  apex_tpu layers honor
    ``compute_dtype`` natively.
    """
    ls = "dynamic" if half_dtype == jnp.float16 else None
    return PrecisionPolicy("O1", jnp.float32, half_dtype, jnp.float32, True, False, ls)


def O2(half_dtype=jnp.bfloat16) -> PrecisionPolicy:
    """"Almost FP16": half params/compute, fp32 norms, master weights,
    dynamic loss scale (apex/amp/frontend.py O2)."""
    ls = "dynamic" if half_dtype == jnp.float16 else None
    return PrecisionPolicy("O2", half_dtype, half_dtype, half_dtype, True, True, ls)


def O3(half_dtype=jnp.bfloat16) -> PrecisionPolicy:
    """Pure half: speed baseline, no fp32 exemptions (apex/amp/frontend.py O3)."""
    return PrecisionPolicy("O3", half_dtype, half_dtype, half_dtype, False, False, None)


_LEVELS = {"O0": O0, "O1": O1, "O2": O2, "O3": O3}


def get_policy(opt_level: str, half_dtype=jnp.bfloat16, **overrides) -> PrecisionPolicy:
    """Build a policy by opt level with explicit overrides.

    Override validation parity: apex rejects overrides that contradict the
    level only when incoherent; here any field can be overridden via
    dataclasses.replace semantics.
    """
    if opt_level not in _LEVELS:
        raise ValueError(f"Unexpected optimization level {opt_level!r} (expected O0..O3)")
    pol = _LEVELS[opt_level]() if opt_level == "O0" else _LEVELS[opt_level](half_dtype)
    if overrides:
        pol = dataclasses.replace(pol, **overrides)
    return pol
