"""Functional loss scaling.

Re-design of the reference's dynamic loss scaler (apex/amp/scaler.py:33-217),
the legacy ``fp16_utils`` scalers (apex/fp16_utils/loss_scaler.py:10-129) and
the on-device hysteresis scale update (csrc/update_scale_hysteresis.cu:5-45).

Under jit there is no "skip the step on overflow" control flow: the scaler
state is a pytree threaded through the train step, ``found_inf`` is computed
on-device, and the optimizer applies ``jnp.where(found_inf, old, new)`` — the
same sync-free pattern as the reference's *capturable* FusedAdam
(apex/optimizers/fused_adam.py:199-263).
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import _nonfinite
from apex_tpu.optimizers._common import unscale_grads


class LossScalerState(NamedTuple):
    """Device-resident scaler state (all scalars; jit-safe)."""

    scale: jax.Array  # f32 current loss scale
    growth_tracker: jax.Array  # i32 consecutive non-overflow steps
    hysteresis_tracker: jax.Array  # i32 remaining overflows before backoff
    unskipped: jax.Array  # i32 total applied steps (checkpoint parity: scaler.py "unskipped")


@dataclasses.dataclass(frozen=True)
class LossScaler:
    """Static / dynamic / hysteresis loss scaling as a pure transform.

    Defaults mirror the reference: init scale 2**16, x2 growth every 2000
    clean steps, /2 backoff on overflow (apex/amp/scaler.py:33-64), optional
    hysteresis>1 to tolerate several overflows before backing off
    (csrc/update_scale_hysteresis.cu).  ``dynamic=False`` gives the static
    scaler (``loss_scale=N`` in amp.initialize).
    """

    init_scale: float = 2.0**16
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    growth_interval: int = 2000
    hysteresis: int = 1
    min_loss_scale: float = 1.0
    max_loss_scale: float = 2.0**24
    dynamic: bool = True

    def init(self) -> LossScalerState:
        """Fresh on-device scaler state at ``init_scale`` with zeroed
        growth/unskipped counters and a full hysteresis budget."""
        return LossScalerState(
            scale=jnp.float32(self.init_scale),
            growth_tracker=jnp.int32(0),
            hysteresis_tracker=jnp.int32(self.hysteresis),
            unskipped=jnp.int32(0),
        )

    def scale_loss(self, loss: jax.Array, state: LossScalerState) -> jax.Array:
        """loss * scale in fp32 (apex/amp/handle.py:113)."""
        return loss.astype(jnp.float32) * state.scale

    def unscale(self, grads: Any, state: LossScalerState):
        """Unscale grads and report overflow: (grads/scale, found_inf).

        Parity: ``LossScaler.unscale_with_stashed``/``unscale``
        (apex/amp/scaler.py:105-190) via multi_tensor_scale's overflow check.
        """
        found_inf = _nonfinite(grads)
        # Unscale in fp32 (shared helper): the reference unscales into fp32
        # master grads (scaler.py:105-118); dividing fp16 grads by 2^16 in
        # fp16 would flush to subnormals.
        return unscale_grads(grads, state.scale), found_inf

    def update(
        self,
        state: LossScalerState,
        found_inf: jax.Array,
        *,
        min_scale: Optional[jax.Array] = None,
    ) -> LossScalerState:
        """Post-step scale update (branch-free; csrc/update_scale_hysteresis.cu:5-45).

        ``min_scale`` overrides the static ``min_loss_scale`` clamp with a
        (possibly traced) dynamic floor — the hook
        :func:`apex_tpu.resilience.guarded.guarded_update` uses to lower
        the floor after sustained skipping instead of looping forever at a
        scale that still overflows.
        """
        if not self.dynamic:
            return state._replace(
                unskipped=state.unskipped + jnp.where(found_inf, 0, 1).astype(jnp.int32)
            )
        found_inf = found_inf.astype(jnp.bool_)

        # The CUDA kernel resets the tracker on EVERY clean step ("Reset the
        # hysteresis tracker if no infs are found", update_scale_hysteresis.cu),
        # so only *consecutive* overflows burn hysteresis.
        hys_after = jnp.where(found_inf,
                              jnp.maximum(state.hysteresis_tracker - 1, 0),
                              jnp.int32(self.hysteresis))
        backoff = jnp.logical_and(found_inf, hys_after <= 0)
        floor = (jnp.float32(self.min_loss_scale) if min_scale is None
                 else jnp.asarray(min_scale, jnp.float32))
        scale = jnp.where(
            backoff,
            jnp.maximum(state.scale * self.backoff_factor, floor),
            state.scale,
        )
        growth = jnp.where(found_inf, 0, state.growth_tracker + 1)
        grow_now = growth >= self.growth_interval
        scale = jnp.where(
            grow_now, jnp.minimum(scale * self.growth_factor, self.max_loss_scale), scale
        )
        growth = jnp.where(grow_now, 0, growth).astype(jnp.int32)
        # No reset on backoff: the CUDA kernel only resets the tracker on
        # clean steps, so a sustained overflow burst keeps backing off every
        # step once hysteresis is burnt (update_scale_hysteresis.cu).
        hys_after = hys_after.astype(jnp.int32)
        return LossScalerState(
            scale=scale.astype(jnp.float32),
            growth_tracker=growth,
            hysteresis_tracker=hys_after,
            unskipped=state.unskipped + jnp.where(found_inf, 0, 1).astype(jnp.int32),
        )

    # -- checkpoint parity (amp.state_dict / load_state_dict; README.md:66-104) --
    def state_dict(self, state: LossScalerState) -> dict:
        """Host-side dict of the scaler state (scale + trackers), the
        checkpointable form of ``amp.state_dict()``."""
        return {k: jax.device_get(v) for k, v in state._asdict().items()}

    def load_state_dict(self, d: dict) -> LossScalerState:
        """Rebuild on-device scaler state from a ``state_dict`` dict —
        exact-trajectory resume of the dynamic scale and its trackers."""
        return LossScalerState(
            scale=jnp.float32(d["scale"]),
            growth_tracker=jnp.int32(d["growth_tracker"]),
            hysteresis_tracker=jnp.int32(d["hysteresis_tracker"]),
            unskipped=jnp.int32(d["unskipped"]),
        )


def static_loss_scaler(loss_scale: float = 1.0) -> LossScaler:
    return LossScaler(init_scale=loss_scale, dynamic=False)
