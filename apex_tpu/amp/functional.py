"""Policy-aware op namespace — the executable form of the O1 cast lists.

Parity target: the patched namespaces of ``apex.amp``
(amp/amp.py:73-183 ``wrap.cached_cast`` / ``wrap.promote`` /
``wrap.sequence_promote``).  The reference mutates ``torch.*`` in place;
mutating ``jax.numpy`` would break tracing and every other library, so the
policy is scoped instead: ops are used through this module
(``from apex_tpu.amp import functional as F; F.matmul(a, b)``) and consult
the *active policy* installed by :func:`apex_tpu.amp.initialize` or the
:func:`active_policy` context manager.  With no active policy (or O0)
every wrapper is an exact pass-through.

The three wrap rules:
- half ops   -> float inputs cast to ``policy.compute_dtype``
- float ops  -> float inputs cast to fp32
- promote ops / sequences -> all float inputs cast to the widest
  participating float dtype (fp32 wins over half; bf16 and fp16 both
  count as "narrow")
"""

from __future__ import annotations

import contextlib
import functools
import sys
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.amp import lists as _lists

_state = threading.local()   # per-thread override (the context manager)
_default_policy = None       # process-wide default (amp.initialize)
_UNSET = object()


def _current():
    thread_local = getattr(_state, "policy", _UNSET)
    return _default_policy if thread_local is _UNSET else thread_local


@contextlib.contextmanager
def active_policy(policy):
    """Scope a PrecisionPolicy over ops called through this module (this
    thread only)."""
    prev = getattr(_state, "policy", _UNSET)
    _state.policy = policy
    try:
        yield
    finally:
        if prev is _UNSET:
            del _state.policy
        else:
            _state.policy = prev


def set_active_policy(policy) -> None:
    """Install a policy process-wide, visible from every thread (the
    ``amp.initialize`` analog)."""
    global _default_policy
    _default_policy = policy


def _is_float_array(x) -> bool:
    return isinstance(x, (jax.Array, jnp.ndarray)) and jnp.issubdtype(
        jnp.result_type(x), jnp.floating)


def _cast_tree(tree, dtype):
    return jax.tree.map(
        lambda x: x.astype(dtype) if _is_float_array(x) else x, tree)


def widest_dtype(*arrays) -> Optional[Any]:
    """fp32 if any float input is fp32, else the (common) half dtype."""
    dtypes = [jnp.result_type(a) for a in jax.tree.leaves(arrays)
              if _is_float_array(a)]
    if not dtypes:
        return None
    # jnp's lattice: same-half stays narrow, fp16+bf16 and half+fp32 -> fp32
    return jnp.result_type(*dtypes)


def _resolve(name: str):
    """Find the op in jnp / jax.nn / jax.lax / jnp.linalg (first match)."""
    for ns in (jnp, jax.nn, jax.lax, jnp.linalg):
        obj = ns
        found = True
        for part in name.split("."):
            if not hasattr(obj, part):
                found = False
                break
            obj = getattr(obj, part)
        if found and callable(obj):
            return obj
    raise AttributeError(f"no jax op named {name!r}")


def _wrap(name: str, rule: str):
    return _wrap_callable(name, _resolve(name), rule)


def _wrap_callable(name: str, fn, rule: str):
    """The cast-rule dispatch, over any callable (listed op or
    user-registered via register_*_function)."""
    del name  # identification lives on fn via functools.wraps

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        policy = _current()
        # only O1 patches functions (frontend.py patch_torch_functions is
        # False for O0/O2/O3 — O3's "no fp32 exemptions" depends on it)
        if policy is None or policy.opt_level != "O1":
            return fn(*args, **kwargs)
        if rule == "half":
            args, kwargs = _cast_tree((args, kwargs), policy.compute_dtype)
        elif rule == "float":
            args, kwargs = _cast_tree((args, kwargs), jnp.float32)
        elif rule == "promote":
            target = widest_dtype(args, kwargs)
            if target is not None:
                args, kwargs = _cast_tree((args, kwargs), target)
        elif rule == "sequence":
            # the sequence may arrive positionally or by keyword; find the
            # first argument that actually holds float arrays
            if args:
                seq, rest = args[0], args[1:]
                target = widest_dtype(seq)
                if target is not None:
                    args = (_cast_tree(tuple(seq), target),) + rest
            else:
                for key, value in kwargs.items():
                    target = widest_dtype(value)
                    if target is not None:
                        kwargs = {**kwargs,
                                  key: _cast_tree(tuple(value), target)}
                        break
        return fn(*args, **kwargs)

    wrapped.__amp_rule__ = rule
    return wrapped


def _banned(name: str, guidance: str):
    def banned(*args, **kwargs):
        raise RuntimeError(f"amp: {name} is banned under mixed precision.  "
                           + guidance)

    banned.__name__ = name
    banned.__amp_rule__ = "banned"
    return banned


_module = sys.modules[__name__]
for _name in _lists.HALF_FUNCS:
    setattr(_module, _name.replace(".", "_"), _wrap(_name, "half"))
for _name in _lists.FLOAT_FUNCS:
    setattr(_module, _name.replace(".", "_"), _wrap(_name, "float"))
for _name in _lists.PROMOTE_FUNCS:
    setattr(_module, _name.replace(".", "_"), _wrap(_name, "promote"))
for _name in _lists.SEQUENCE_FUNCS:
    setattr(_module, _name.replace(".", "_"), _wrap(_name, "sequence"))
for _name, _msg in _lists.BANNED_FUNCS.items():
    setattr(_module, _name, _banned(_name, _msg))


def _register(name: str, rule: str, func=None) -> None:
    """apex.amp.register_*_function parity: add a cast rule for ``name``
    (resolved in the jax namespaces, or ``func`` if given) and expose the
    wrapped op as ``amp.functional.<name>``."""
    target = {"half": _lists.HALF_FUNCS, "float": _lists.FLOAT_FUNCS,
              "promote": _lists.PROMOTE_FUNCS}[rule]
    if name not in target:
        target.append(name)
    if func is not None:
        wrapped = _wrap_callable(name, func, rule)
    else:
        wrapped = _wrap(name, rule)
    setattr(_module, name.replace(".", "_"), wrapped)


def register_half_function(name: str, func=None) -> None:
    """amp.register_half_function(module, name) analog — one namespace."""
    _register(name, "half", func)


def register_float_function(name: str, func=None) -> None:
    _register(name, "float", func)


def register_promote_function(name: str, func=None) -> None:
    _register(name, "promote", func)


__all__ = (["active_policy", "set_active_policy", "widest_dtype",
            "register_half_function", "register_float_function",
            "register_promote_function"]
           + [n.replace(".", "_") for n in
              _lists.HALF_FUNCS + _lists.FLOAT_FUNCS
              + _lists.PROMOTE_FUNCS + _lists.SEQUENCE_FUNCS]
           + list(_lists.BANNED_FUNCS))
