"""Elementwise and reduction math over pytrees.

These are the jnp building blocks behind :mod:`apex_tpu.multi_tensor_apply`;
under ``jit`` XLA fuses the per-leaf ops, which is the TPU analog of the
reference's single-launch multi-tensor CUDA kernels
(csrc/multi_tensor_apply.cuh:16-133).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def tree_add(a: Any, b: Any) -> Any:
    return jax.tree.map(jnp.add, a, b)


def tree_scale(tree: Any, scale) -> Any:
    return jax.tree.map(lambda x: x * jnp.asarray(scale, x.dtype), tree)


def tree_axpby(a, x: Any, b, y: Any) -> Any:
    """out = a*x + b*y per leaf (amp_C.multi_tensor_axpby parity)."""
    return jax.tree.map(
        lambda xi, yi: jnp.asarray(a, xi.dtype) * xi + jnp.asarray(b, xi.dtype) * yi, x, y
    )


def tree_l2norm(tree: Any, per_leaf: bool = False):
    """Global (and optionally per-leaf) L2 norm, accumulated in fp32.

    Mirrors ``amp_C.multi_tensor_l2norm`` (csrc/multi_tensor_l2norm_kernel.cu)
    which returns the global norm and, with ``per_tensor=True``, per-tensor norms.
    """
    leaves = jax.tree.leaves(tree)
    if not leaves:
        zero = jnp.zeros((), jnp.float32)
        return (zero, []) if per_leaf else zero
    sq = [jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves]
    total = jnp.sqrt(sum(sq))
    if per_leaf:
        return total, [jnp.sqrt(s) for s in sq]
    return total


def tree_cast(tree: Any, dtype) -> Any:
    return jax.tree.map(lambda x: x.astype(dtype), tree)


def tree_zeros_like(tree: Any, dtype=None) -> Any:
    return jax.tree.map(lambda x: jnp.zeros(x.shape, dtype or x.dtype), tree)
