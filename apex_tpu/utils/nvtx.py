"""Trace annotation ranges (the NVTX surface, TPU-backed).

Parity target: the reference's hand-inserted NVTX ranges
(apex/parallel/distributed.py:364, sync_batchnorm.py:71-134, and the
``--prof`` window of examples/imagenet/main_amp.py:360).

TPU design: one annotation does two jobs —
- ``jax.named_scope`` labels the *traced* ops so the region survives into
  the XLA profile (what nvtx gives nsight), and
- ``jax.profiler.TraceAnnotation`` marks host wall-time spans for the
  TensorBoard trace viewer (what nvtx gives the CPU timeline).

``range_push``/``range_pop`` mirror ``torch.cuda.nvtx`` so ported scripts
keep working; prefer the :func:`range` context manager in new code.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, List

import jax

__all__ = ["range", "range_push", "range_pop"]

_stack: List = []


@contextlib.contextmanager
def range(name: str) -> Iterator[None]:  # noqa: A001 - nvtx API name
    """Label everything traced inside with ``name`` (device + host)."""
    with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
        yield


def range_push(name: str) -> int:
    """torch.cuda.nvtx.range_push parity; returns the new stack depth."""
    cm = range(name)
    cm.__enter__()
    _stack.append(cm)
    return len(_stack)


def range_pop() -> int:
    """torch.cuda.nvtx.range_pop parity; returns the depth popped from."""
    if not _stack:
        raise RuntimeError("range_pop without a matching range_push")
    depth = len(_stack)
    _stack.pop().__exit__(None, None, None)
    return depth
