"""Loader for the native host runtime (csrc/packing.cpp).

Compiles the C++ source once into a per-user cached shared object and
binds it through ctypes (this environment has no pybind11; ctypes is the
zero-dependency binding path).  Everything degrades gracefully: with no
toolchain or a failed build, ``lib()`` returns None and callers use their
numpy fallbacks — the same contract as the reference's optional
``--cpp_ext`` build.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from pathlib import Path
from typing import Optional

_ABI = 1
_lib: Optional[ctypes.CDLL] = None
_tried = False

_SRC = Path(__file__).resolve().parent.parent / "csrc" / "packing.cpp"


def _cache_dir() -> Path:
    # user-private cache (0700, ownership verified): a predictable /tmp
    # path would let another local user pre-plant a .so that CDLL executes
    default = os.path.join(
        os.environ.get("XDG_CACHE_HOME",
                       os.path.join(os.path.expanduser("~"), ".cache")),
        "apex_tpu_native")
    path = Path(os.environ.get("APEX_TPU_CACHE", default))
    path.mkdir(parents=True, exist_ok=True, mode=0o700)
    stat = path.stat()
    if stat.st_uid != os.getuid():
        raise RuntimeError(f"native cache dir {path} is not owned by the "
                           "current user; refusing to load code from it")
    os.chmod(path, 0o700)
    return path


def _build() -> Optional[ctypes.CDLL]:
    src = _SRC.read_text()
    tag = hashlib.sha256(src.encode()).hexdigest()[:16]
    so_path = _cache_dir() / f"packing_{tag}.so"
    if not so_path.exists():
        # per-process tmp name: concurrent cold-cache builders must not
        # interleave writes into one file before the atomic replace
        tmp = so_path.with_suffix(f".build{os.getpid()}.so")
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
               str(_SRC), "-o", str(tmp)]
        result = subprocess.run(cmd, capture_output=True, text=True,
                                timeout=120)
        if result.returncode != 0:
            return None
        os.replace(tmp, so_path)
    lib = ctypes.CDLL(str(so_path))
    lib.apex_tpu_native_abi.restype = ctypes.c_int32
    if lib.apex_tpu_native_abi() != _ABI:
        return None
    lib.apex_tpu_flatten.restype = ctypes.c_int64
    lib.apex_tpu_flatten.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.c_void_p]
    lib.apex_tpu_unflatten.restype = ctypes.c_int64
    lib.apex_tpu_unflatten.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_void_p)]
    return lib


def lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, or None when unavailable."""
    global _lib, _tried
    if not _tried:
        _tried = True
        try:
            _lib = _build()
        except Exception:
            _lib = None
    return _lib


def flatten_into(arrays, out) -> int:
    """memcpy every contiguous numpy array in ``arrays`` into ``out``
    (1-D, matching total nbytes).  Returns bytes written; raises
    RuntimeError when the native library is unavailable."""
    native = lib()
    if native is None:
        raise RuntimeError("native runtime unavailable")
    n = len(arrays)
    srcs = (ctypes.c_void_p * n)(
        *[a.ctypes.data for a in arrays])
    sizes = (ctypes.c_int64 * n)(*[a.nbytes for a in arrays])
    return native.apex_tpu_flatten(srcs, sizes, n,
                                   ctypes.c_void_p(out.ctypes.data))


def unflatten_from(flat, arrays) -> int:
    """Inverse of :func:`flatten_into`: scatter ``flat``'s bytes into the
    pre-allocated contiguous numpy ``arrays``."""
    native = lib()
    if native is None:
        raise RuntimeError("native runtime unavailable")
    n = len(arrays)
    dsts = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrays])
    sizes = (ctypes.c_int64 * n)(*[a.nbytes for a in arrays])
    return native.apex_tpu_unflatten(ctypes.c_void_p(flat.ctypes.data),
                                     sizes, n, dsts)
