"""Host-side pytree serialization primitives.

The reference checkpoints through ``torch.save`` (opaque pickle); at pod
scale a checkpoint must instead be *inspectable and validatable* — a
preempted worker restoring a half-written pickle fails deep inside torch,
while a manifest of (path, shape, dtype, crc32) per leaf lets the restore
path prove a file good **before** any state is overwritten.  These helpers
are the leaf-level layer under :mod:`apex_tpu.resilience.checkpoint` and
the generic ``FusedOptimizer.state_dict``.

Leaves are addressed by their ``jax.tree_util.keystr`` path, so any
combination of dicts / NamedTuples (``AdamState``, ``LossScalerState``) /
dataclass pytrees round-trips without registering custom serializers.
Typed PRNG keys (``jax.random.key``) are stored as their raw
``key_data`` and re-wrapped against the template on load.
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from typing import Any

import jax
import numpy as np


def atomic_write_json(path: str, payload: Any, **dump_kwargs) -> None:
    """Crash-safe JSON rewrite: temp file + ``fsync`` + ``os.replace``.

    The temp name embeds pid AND thread id, so concurrent writers (the
    watchdog monitor thread marking a stall while the main thread
    beats) never share a temp file and every rename stays atomic.  One
    helper for every small-JSON writer in the tree — heartbeats, metric
    snapshots, trace exports.
    """
    _atomic_write_text(path, lambda f: json.dump(payload, f,
                                                 **dump_kwargs))


def atomic_write_jsonl(path: str, rows: Any, **dump_kwargs) -> None:
    """Crash-safe JSON-Lines rewrite: one compact ``json.dumps`` line
    per row, through the same temp + ``fsync`` + ``os.replace`` dance
    as :func:`atomic_write_json` — a reader never sees a half-written
    file.  Rows must each be JSON-serializable under ``dump_kwargs``
    (pre-sanitize with :func:`json_finite` for ``allow_nan=False``)."""
    def write(f):
        for row in rows:
            f.write(json.dumps(row, **dump_kwargs))
            f.write("\n")

    _atomic_write_text(path, write)


def _atomic_write_text(path: str, write_fn) -> None:
    """The one temp + ``fsync`` + ``os.replace`` implementation both
    JSON writers share — a future fix to the atomic dance (parent-dir
    fsync, collision handling) lands in exactly one place."""
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "w") as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        # an unserializable payload must not litter half-written temp
        # files next to checkpoints on every failed export
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def json_finite(obj: Any) -> Any:
    """Deep-copy with non-finite floats replaced by None (JSON has no
    NaN/Inf; a strict parser must never choke on an exported snapshot).
    Tuples/sets normalize to lists — ``json.dump`` serializes them
    natively, so a NaN nested in a tuple would otherwise slip past this
    walk straight into ``allow_nan=False``'s raise.  Shared by the
    metrics and trace exporters."""
    if isinstance(obj, float):
        return obj if -float("inf") < obj < float("inf") else None
    if isinstance(obj, dict):
        return {k: json_finite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [json_finite(v) for v in obj]
    return obj


def np_dtype(name: str) -> np.dtype:
    """Resolve a dtype name including the ml_dtypes extras (bfloat16,
    float8_*) that ``np.dtype`` alone cannot parse."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def is_prng_key(leaf: Any) -> bool:
    """True for new-style typed PRNG key arrays (old uint32 keys are
    ordinary arrays and need no special casing)."""
    try:
        return jax.dtypes.issubdtype(leaf.dtype, jax.dtypes.prng_key)
    except (AttributeError, TypeError):
        return False


def leaf_spec(leaf: Any) -> tuple[tuple, np.dtype]:
    """(shape, numpy dtype) of a leaf's serialized form WITHOUT any
    device-to-host transfer — template checks on a multi-GB live state
    must not device_get it just to read shapes.  Typed PRNG keys report
    the shape/dtype of their raw ``key_data``."""
    if is_prng_key(leaf):
        spec = jax.eval_shape(jax.random.key_data, leaf)
        return tuple(spec.shape), np.dtype(spec.dtype)
    return tuple(np.shape(leaf)), np.dtype(getattr(leaf, "dtype", np.asarray(leaf).dtype))


def leaf_to_numpy(leaf: Any) -> np.ndarray:
    """Device array -> host numpy, unwrapping typed PRNG keys to raw data."""
    if is_prng_key(leaf):
        leaf = jax.random.key_data(leaf)
    return np.asarray(jax.device_get(leaf))


def leaf_from_numpy(arr: np.ndarray, like: Any) -> Any:
    """Host numpy -> array matching ``like`` (re-wrapping PRNG keys and
    re-applying the template's sharding, so restoring a state sharded
    across chips does not collapse it onto the default device)."""
    import jax.numpy as jnp

    if is_prng_key(like):
        out = jax.random.wrap_key_data(
            jnp.asarray(arr), impl=jax.random.key_impl(like))
    else:
        out = jnp.asarray(arr)
    sharding = getattr(like, "sharding", None)
    if sharding is not None:
        out = jax.device_put(out, sharding)
    return out


def tree_paths(tree: Any) -> list[str]:
    """``keystr`` path of every leaf, in flatten order."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [jax.tree_util.keystr(path) for path, _ in flat]


def tree_to_host_dict(tree: Any) -> dict[str, np.ndarray]:
    """Flatten a pytree to ``{keystr_path: numpy array}`` (checkpointable
    form; the pytree analog of the reference's ``state_dict()``)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {jax.tree_util.keystr(p): leaf_to_numpy(l) for p, l in flat}


def tree_from_host_dict(d: dict[str, np.ndarray], like: Any) -> Any:
    """Rebuild a pytree structured like ``like`` from a host dict.

    Strict: every template leaf must be present with matching shape and
    dtype — a silent partial restore is exactly the failure mode the
    resilience subsystem exists to prevent.
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(like)
    leaves = []
    for path, tmpl in flat:
        key = jax.tree_util.keystr(path)
        if key not in d:
            raise KeyError(f"state dict is missing leaf {key!r}")
        arr = np.asarray(d[key])
        want_shape, want_dtype = leaf_spec(tmpl)
        if tuple(arr.shape) != want_shape:
            raise ValueError(
                f"leaf {key!r}: shape {arr.shape} != template {want_shape}")
        if arr.dtype != want_dtype:
            raise ValueError(
                f"leaf {key!r}: dtype {arr.dtype} != template {want_dtype}")
        leaves.append(leaf_from_numpy(arr, tmpl))
    extra = set(d) - {jax.tree_util.keystr(p) for p, _ in flat}
    if extra:
        raise KeyError(
            f"state dict has leaves the template does not: "
            f"{sorted(extra)[:5]}")
    return jax.tree_util.tree_unflatten(treedef, leaves)


def leaf_crc32(arr: np.ndarray) -> int:
    """crc32 of the leaf's raw little-endian bytes (manifest validation)."""
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF
