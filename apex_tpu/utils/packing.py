"""Flatten / pack a list (or pytree) of arrays into one contiguous buffer.

TPU-native equivalent of the reference's ``apex_C`` extension
(csrc/flatten_unflatten.cpp:16-17, wrapping
``torch::utils::flatten_dense_tensors``) used for DDP gradient bucketing
(apex/parallel/distributed.py:15-36), and of the contiguous grad/param
buffers in the ZeRO optimizer (apex/contrib/optimizers/distributed_fused_adam.py).

On TPU a single flat buffer is also the shape strategy for the Pallas
multi-tensor kernels (SURVEY.md §7 "Multi-tensor apply in Pallas"): instead of
packing 110 tensor pointers per CUDA launch, we concatenate once (XLA keeps
this cheap and fusable) and run one kernel over the padded flat buffer.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def flatten_dense_tensors(tensors: Sequence[jax.Array]) -> jax.Array:
    """Concatenate arrays into one 1-D buffer (apex_C.flatten parity)."""
    return jnp.concatenate([jnp.ravel(t) for t in tensors])


def unflatten_dense_tensors(flat: jax.Array, like: Sequence[jax.Array]) -> list[jax.Array]:
    """Split a flat buffer back into arrays shaped like ``like`` (apex_C.unflatten)."""
    sizes = [int(np.prod(t.shape)) if t.ndim else 1 for t in like]
    offsets = np.cumsum([0] + sizes)
    return [
        jax.lax.dynamic_slice(flat, (int(offsets[i]),), (sizes[i],)).reshape(like[i].shape)
        for i in range(len(like))
    ]


def host_flatten_dense_tensors(arrays: Sequence[np.ndarray]) -> np.ndarray:
    """Host-side apex_C.flatten: pack numpy arrays (checkpoint shards,
    staged batches) into one contiguous buffer via the C++ runtime
    (csrc/packing.cpp), numpy fallback when no toolchain exists.

    All arrays must share a dtype; non-contiguous inputs are copied.
    """
    from apex_tpu.utils import _native

    if not arrays:
        return np.empty((0,), np.float32)
    dtype = arrays[0].dtype
    if any(a.dtype != dtype for a in arrays):
        raise ValueError("host flatten requires a single dtype")
    arrays = [np.ascontiguousarray(a) for a in arrays]
    total = sum(a.size for a in arrays)
    out = np.empty((total,), dtype)
    if _native.lib() is not None:
        _native.flatten_into(arrays, out)
        return out
    off = 0
    for a in arrays:
        out[off:off + a.size] = a.ravel()
        off += a.size
    return out


def host_unflatten_dense_tensors(flat: np.ndarray,
                                 like: Sequence[np.ndarray]) -> list[np.ndarray]:
    """Host-side apex_C.unflatten: scatter one flat buffer into arrays
    shaped like ``like`` (C++ runtime with numpy fallback)."""
    from apex_tpu.utils import _native

    flat = np.ascontiguousarray(flat)
    need = sum(int(np.prod(t.shape)) if np.ndim(t) else 1 for t in like)
    if flat.size < need:
        raise ValueError(
            f"flat buffer has {flat.size} elements; 'like' needs {need}")
    # apex_C.unflatten returns like-typed tensors; outputs here are allocated
    # in flat.dtype, so a mixed-dtype 'like' would silently change dtypes
    bad = {str(np.asarray(t).dtype) for t in like} - {str(flat.dtype)}
    if bad:
        raise ValueError(
            f"'like' arrays have dtypes {sorted(bad)} != flat buffer dtype "
            f"{flat.dtype}; unflatten preserves the flat dtype (flatten "
            "likewise requires a single dtype)")
    outs = [np.empty(t.shape, flat.dtype) for t in like]
    if _native.lib() is not None:
        _native.unflatten_from(flat, outs)
        return outs
    off = 0
    for o in outs:
        n = o.size
        o[...] = flat[off:off + n].reshape(o.shape)
        off += n
    return outs


@dataclasses.dataclass(frozen=True)
class PackedSpec:
    """Static description of a packed pytree: treedef + per-leaf shape/dtype/offset."""

    treedef: Any
    shapes: tuple[tuple[int, ...], ...]
    dtypes: tuple[Any, ...]
    offsets: tuple[int, ...]  # start offset of each leaf in the flat buffer
    total: int  # unpadded element count
    padded_total: int  # element count after padding to `pad_to`

    @property
    def num_leaves(self) -> int:
        return len(self.shapes)

    @property
    def sizes(self) -> tuple[int, ...]:
        """Element count per leaf — the one definition of leaf size."""
        return tuple(int(np.prod(s)) if len(s) else 1 for s in self.shapes)


@dataclasses.dataclass
class PackedBuffer:
    """A pytree flattened into one 1-D buffer plus its static spec.

    The packed form is what the Pallas multi-tensor kernels operate on; the
    ``spec`` lets us restore the original pytree exactly.
    """

    flat: jax.Array
    spec: PackedSpec

    def unpack(self) -> Any:
        """Rebuild the original pytree from the flat buffer (inverse of
        ``pack_pytree``; zero-copy reshape/slice under jit)."""
        return unpack_pytree(self.flat, self.spec)


def make_packed_spec(tree: Any, pad_to: int = 1024) -> PackedSpec:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = tuple(tuple(l.shape) for l in leaves)
    dtypes = tuple(l.dtype for l in leaves)
    sizes = [int(np.prod(s)) if len(s) else 1 for s in shapes]  # = spec.sizes
    offsets = tuple(int(o) for o in np.cumsum([0] + sizes)[:-1])
    total = int(sum(sizes))
    padded_total = ((total + pad_to - 1) // pad_to) * pad_to if total else pad_to
    return PackedSpec(treedef, shapes, dtypes, offsets, total, padded_total)


def pack_pytree(tree: Any, dtype=None, pad_to: int = 1024) -> PackedBuffer:
    """Flatten a pytree of arrays into one padded 1-D buffer.

    ``pad_to`` keeps the buffer length a multiple of the TPU lane*sublane tile
    (8*128=1024 for f32) so Pallas kernels see aligned shapes.
    """
    spec = make_packed_spec(tree, pad_to=pad_to)
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return PackedBuffer(jnp.zeros((spec.padded_total,), dtype or jnp.float32), spec)
    cat_dtype = dtype or jnp.result_type(*[l.dtype for l in leaves])
    flat = jnp.concatenate([jnp.ravel(l).astype(cat_dtype) for l in leaves])
    pad = spec.padded_total - spec.total
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), cat_dtype)])
    return PackedBuffer(flat, spec)


def unpack_pytree(flat: jax.Array, spec: PackedSpec) -> Any:
    leaves = []
    for shape, dtype, offset in zip(spec.shapes, spec.dtypes, spec.offsets):
        size = int(np.prod(shape)) if len(shape) else 1
        leaf = jax.lax.dynamic_slice(flat, (offset,), (size,))
        leaves.append(leaf.reshape(shape).astype(dtype))
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)
