"""JAX version-compatibility shims, probed once at import.

Two renames keep biting every shard_map call site on this codebase's
jax 0.4.x floor:

- ``shard_map`` moved from ``jax.experimental.shard_map`` to the top
  level in jax >= 0.6;
- its replication-check kwarg was renamed ``check_rep`` (0.4.x) ->
  ``check_vma``.

This module is the ONE place that knows both (the probe previously
lived copy-pasted in ``resilience.consistency``, ``__graft_entry__``
and two test files — a future jax rename now lands here only):

    from apex_tpu.utils.compat import NO_REP_CHECK, shard_map
    f = shard_map(fn, mesh=mesh, in_specs=..., out_specs=...,
                  **NO_REP_CHECK)
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.6 exports it at top level
    from jax import shard_map
except ImportError:  # jax 0.4.x keeps it under experimental
    from jax.experimental.shard_map import shard_map


def compile_count(fn) -> int:
    """Number of compiled variants a ``jax.jit``-wrapped function holds.

    The serving contract ("the decode step compiles exactly ONCE",
    "prefill compiles are bounded by the bucket table") is asserted in
    tier-1 through jit cache statistics, but the probe is private API
    that has already been renamed once across jax versions
    (``_cache_size()`` today, ``cache_size()`` upstream).  This helper
    is the ONE place that knows the spelling — every compile-count
    assertion (``DecodeEngine.decode_compiles()`` /
    ``prefill_compiles()``, bench regression guards, tests) goes
    through it, so the next rename is a one-line fix here instead of a
    scavenger hunt.
    """
    for probe in ("_cache_size", "cache_size"):
        attr = getattr(fn, probe, None)
        if callable(attr):
            return int(attr())
    raise AttributeError(
        f"{fn!r} exposes no jit cache-size probe (tried _cache_size/"
        f"cache_size) — is it a jax.jit-wrapped function on a supported "
        f"jax version?")

# Disabling the replication checker is the repo-wide default for
# shard_map: the collective helpers mix per-leaf specs and produce
# outputs made replicated by explicit psum/all_gather, which older
# rep-checkers reject conservatively.
NO_REP_CHECK = (
    {"check_vma": False}
    if "check_vma" in inspect.signature(shard_map).parameters
    else {"check_rep": False})

#: Mesh axis name of the serving tensor-parallel mesh.  Deliberately
#: the same spelling as ``parallel_state.TENSOR_PARALLEL_AXIS`` so the
#: tensor_parallel layers' ``tp_world_size(axis_name)`` probe binds to
#: it inside the serving shard_map exactly as it does under the
#: training mesh — without importing the training-side global mesh
#: state into a serving process.
SERVING_TP_AXIS = "tp"


def devices_available(n: int) -> bool:
    """Whether ``n`` devices are visible to jax (the serving-tp
    device-count guard; pair with :func:`device_count_skip_reason` for
    the human-readable skip message)."""
    import jax

    return len(jax.devices()) >= int(n)


def device_count_skip_reason(n: int) -> str:
    """One clear sentence for a skipped multi-device test/bench site."""
    import jax

    return (f"needs {int(n)} devices, found {len(jax.devices())} — on "
            f"CPU export XLA_FLAGS=--xla_force_host_platform_device_"
            f"count={int(n)} before jax initializes (tests/conftest.py "
            f"does this for the suite)")


def serving_mesh(size: int):
    """The 1-D tensor-parallel serving mesh over the first ``size``
    visible devices, axis-named :data:`SERVING_TP_AXIS`.

    The ONE place the jax-0.4.37 ``Mesh(np.array(devices), ("tp",))``
    dance is spelled (engine construction, weights-onto-mesh restore,
    tests and bench all call this), so a future Mesh-API rename lands
    here only.  Raises :class:`RuntimeError` with the
    ``--xla_force_host_platform_device_count`` recipe when the host
    exposes fewer devices than ``size``.
    """
    import jax
    import numpy as np

    size = int(size)
    if size < 1:
        raise ValueError(f"mesh size must be >= 1, got {size}")
    if not devices_available(size):
        raise RuntimeError(f"serving_mesh({size}): "
                           + device_count_skip_reason(size))
    return jax.sharding.Mesh(np.array(jax.devices()[:size]),
                             (SERVING_TP_AXIS,))


__all__ = ["NO_REP_CHECK", "SERVING_TP_AXIS", "compile_count",
           "device_count_skip_reason", "devices_available",
           "serving_mesh", "shard_map"]
