"""Shared utilities: pytree flatten/packing, dtype helpers, tree math."""

from apex_tpu.utils.packing import (
    flatten_dense_tensors,
    unflatten_dense_tensors,
    PackedBuffer,
    pack_pytree,
    unpack_pytree,
)
from apex_tpu.utils.tree_math import (
    tree_add,
    tree_scale,
    tree_axpby,
    tree_l2norm,
    tree_cast,
    tree_zeros_like,
)

__all__ = [
    "flatten_dense_tensors",
    "unflatten_dense_tensors",
    "PackedBuffer",
    "pack_pytree",
    "unpack_pytree",
    "tree_add",
    "tree_scale",
    "tree_axpby",
    "tree_l2norm",
    "tree_cast",
    "tree_zeros_like",
]
