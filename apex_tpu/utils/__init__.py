"""Shared utilities: pytree flatten/packing, dtype helpers, tree math,
host-side pytree serialization."""

from apex_tpu.utils.packing import (
    flatten_dense_tensors,
    unflatten_dense_tensors,
    PackedBuffer,
    pack_pytree,
    unpack_pytree,
)
from apex_tpu.utils.serialization import (
    leaf_crc32,
    tree_from_host_dict,
    tree_paths,
    tree_to_host_dict,
)
from apex_tpu.utils.tree_math import (
    tree_add,
    tree_scale,
    tree_axpby,
    tree_l2norm,
    tree_cast,
    tree_zeros_like,
)

__all__ = [
    "leaf_crc32",
    "tree_from_host_dict",
    "tree_paths",
    "tree_to_host_dict",
    "flatten_dense_tensors",
    "unflatten_dense_tensors",
    "PackedBuffer",
    "pack_pytree",
    "unpack_pytree",
    "tree_add",
    "tree_scale",
    "tree_axpby",
    "tree_l2norm",
    "tree_cast",
    "tree_zeros_like",
]
