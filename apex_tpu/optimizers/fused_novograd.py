"""FusedNovoGrad — NovoGrad with layer-wise (per-tensor scalar) second moment.

Parity: ``apex.optimizers.FusedNovoGrad`` (apex/optimizers/fused_novograd.py)
over ``multi_tensor_novograd`` (csrc/multi_tensor_novograd.cu): the second
moment is one scalar per tensor (||g||^2 EMA); supports L2 vs decoupled wd,
grad averaging, norm init with first-step grad norm (init_zero=False default).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import FusedOptimizer, bias_corrections, tree_map_multi


class NovoGradState(NamedTuple):
    step: jax.Array
    exp_avg: Any  # per-element fp32 m
    exp_avg_sq: Any  # per-tensor scalar v


class FusedNovoGrad(FusedOptimizer):
    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas=(0.95, 0.98),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        grad_averaging: bool = False,
        reg_inside_moment: bool = False,
        norm_type: int = 2,
        init_zero: bool = False,
        master_weights: bool = False,
        packed: bool = False,
    ):
        if norm_type != 2:
            raise RuntimeError("FusedNovoGrad only supports the L2 norm.")
        super().__init__(master_weights=master_weights)
        self.packed = packed
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.grad_averaging = grad_averaging
        self.reg_inside_moment = reg_inside_moment
        self.init_zero = init_zero

    def _init(self, params: Any) -> NovoGradState:
        if self.packed:
            from apex_tpu.utils.packing import make_packed_spec

            spec = make_packed_spec(params)
            return NovoGradState(
                jnp.int32(0),
                jnp.zeros((spec.padded_total,), jnp.float32),
                jnp.zeros((spec.num_leaves + 1,), jnp.float32))
        m = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        v = jax.tree.map(lambda p: jnp.zeros((), jnp.float32), params)
        return NovoGradState(jnp.int32(0), m, v)

    def _packed_update(self, grads: Any, params: Any, state: NovoGradState):
        """One flat multi-tensor sweep (ops/packed_update.py)."""
        from apex_tpu.ops.packed_update import (packed_novograd_update,
                                                segment_ids_for_spec)
        from apex_tpu.utils.packing import (make_packed_spec, pack_pytree,
                                            unpack_pytree)

        step = state.step + 1
        if self.bias_correction:
            bc1, bc2 = bias_corrections(step, self.beta1, self.beta2)
        else:
            bc1 = bc2 = jnp.float32(1.0)
        spec = make_packed_spec(params)
        new_p, new_m, new_v = packed_novograd_update(
            pack_pytree(grads, dtype=jnp.float32).flat,
            pack_pytree(params).flat, state.exp_avg, state.exp_avg_sq,
            segment_ids_for_spec(spec), num_leaves=spec.num_leaves,
            lr=self.lr, beta1=self.beta1, beta2=self.beta2,
            beta3=(1.0 - self.beta1 if self.grad_averaging else 1.0),
            eps=self.eps, weight_decay=self.weight_decay,
            bias_correction1=bc1, bias_correction2=bc2,
            is_first_step=(step == 1), init_zero=self.init_zero,
            reg_inside_moment=self.reg_inside_moment)
        return unpack_pytree(new_p, spec), NovoGradState(step, new_m, new_v)

    def _update(self, grads: Any, params: Any, state: NovoGradState):
        if self.packed:
            return self._packed_update(grads, params, state)
        step = state.step + 1
        if self.bias_correction:
            bc1, bc2 = bias_corrections(step, self.beta1, self.beta2)
        else:
            bc1 = bc2 = jnp.float32(1.0)
        beta3 = 1.0 - self.beta1 if self.grad_averaging else 1.0
        lr = jnp.float32(self.lr)
        wd = jnp.float32(self.weight_decay)
        b1, b2, eps = self.beta1, self.beta2, self.eps
        first = (step == 1)

        def leaf(p, g, m, v):
            p32 = p.astype(jnp.float32)
            g_sq = jnp.sum(g * g)
            # first step: v initialized to ||g||^2 (init_zero=False path)
            v_upd = b2 * v + (1.0 - b2) * g_sq
            v_init = jnp.zeros((), jnp.float32) if self.init_zero else g_sq
            v_new = jnp.where(first, v_init, v_upd)
            denom = jnp.sqrt(v_new / bc2) + eps
            g_hat = g / denom
            if self.weight_decay and self.reg_inside_moment:
                g_hat = g_hat + wd * p32
            m_new = b1 * m + beta3 * g_hat
            update = m_new / bc1
            if self.weight_decay and not self.reg_inside_moment:
                update = update + wd * p32
            new_p = p32 - lr * update
            return new_p.astype(p.dtype), m_new, v_new

        new_p, new_m, new_v = tree_map_multi(leaf, 3, params, grads, state.exp_avg, state.exp_avg_sq)
        return new_p, NovoGradState(step, new_m, new_v)
