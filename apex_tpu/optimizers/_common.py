"""Shared machinery for the fused optimizers.

Design (SURVEY.md §7): the reference's multi-tensor CUDA kernels
(csrc/multi_tensor_adam.cu etc., dispatched via
apex/optimizers/fused_adam.py:109-117) collapse on TPU to one jitted pytree
update — XLA fuses the per-leaf elementwise ops, and the *capturable*
CUDA-graph-safe variant (apex/optimizers/fused_adam.py:199-263) is the
default semantics here: step count, loss scale, and the overflow flag all
live on device, and an overflow turns the whole update into a no-op via
``jnp.where`` (sync-free step skipping).

Every optimizer exposes:

- ``init(params) -> state``
- ``step(grads, params, state, *, grad_scale=None, found_inf=None)
    -> (new_params, new_state)``
- ``as_optax() -> optax.GradientTransformation`` for ecosystem interop.

``master_weights=True`` keeps fp32 master copies when params are half
(the fused_adam master-weight path, fused_adam.py:84-98): updates are
computed on masters and params re-cast each step.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

import optax


def apply_if_finite(found_inf: Optional[jax.Array], new: Any, old: Any) -> Any:
    """tree = found_inf ? old : new — the capturable skip (fused_adam.py:199-263)."""
    if found_inf is None:
        return new
    return jax.tree.map(lambda n, o: jnp.where(found_inf, o, n), new, old)


def master_copy(params: Any) -> Any:
    """fp32 master copies that never alias the model params.

    ``astype(fp32)`` is a no-op returning the *same* array for fp32 leaves
    (e.g. norm params kept fp32 by the precision policy), which would break
    buffer donation and the master/model distinction — hence the copy.
    """
    return jax.tree.map(lambda p: jnp.copy(p).astype(jnp.float32), params)


def unscale_grads(grads: Any, grad_scale: Optional[jax.Array]) -> Any:
    """grads / grad_scale in fp32 (the kernel-side inv_scale of capturable adam)."""
    grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
    if grad_scale is None:
        return grads
    inv = 1.0 / jnp.asarray(grad_scale, jnp.float32)
    return jax.tree.map(lambda g: g * inv, grads)


def is_half(x) -> bool:
    return x.dtype in (jnp.bfloat16, jnp.float16)


class MasterState(NamedTuple):
    master_params: Any  # fp32 copies (or None when unused)


class FusedOptimizer:
    """Base class: master-weight handling + optax adapter."""

    def __init__(self, master_weights: bool = False):
        self.master_weights = master_weights

    # -- subclass interface ------------------------------------------------
    def _init(self, params: Any) -> Any:
        raise NotImplementedError

    def _update(self, grads: Any, params: Any, inner_state: Any):
        """Return (new_params, new_inner_state); grads are fp32, unscaled."""
        raise NotImplementedError

    # -- public API --------------------------------------------------------
    def init(self, params: Any) -> Any:
        """Build the optimizer state for ``params``: the subclass's inner
        state (moments etc.) plus an fp32 master copy of the params when
        ``master_weights=True`` (the reference's ``master_weights`` flag)."""
        inner = self._init(params)
        if self.master_weights:
            return (inner, MasterState(master_copy(params)))
        return (inner, MasterState(None))

    def step(
        self,
        grads: Any,
        params: Any,
        state: Any,
        *,
        grad_scale: Optional[jax.Array] = None,
        found_inf: Optional[jax.Array] = None,
    ):
        """One optimizer step: ``(grads, params, state) -> (new_params,
        new_state)``.  ``grad_scale`` divides the (loss-scaled) grads in
        fp32 before the update; ``found_inf`` is the capturable skip — a
        true flag returns params/state unchanged on device, with no host
        sync (the reference's capturable step/scale/overflow contract)."""
        inner, masters = state
        g32 = unscale_grads(grads, grad_scale)
        work_params = masters.master_params if masters.master_params is not None else params
        new_work, new_inner = self._update(g32, work_params, inner)
        new_work = apply_if_finite(found_inf, new_work, work_params)
        new_inner = apply_if_finite(found_inf, new_inner, inner)
        if masters.master_params is not None:
            new_params = jax.tree.map(lambda m, p: m.astype(p.dtype), new_work, params)
            return new_params, (new_inner, MasterState(new_work))
        return new_work, (new_inner, MasterState(None))

    # -- checkpointing (optimizer.state_dict parity; README "Checkpointing") --
    def state_dict(self, state: Any) -> dict:
        """Host-side ``{leaf path: numpy array}`` of the full optimizer
        state — moments, on-device step counter, fp32 masters.  Structure
        lives in code (rebuild the optimizer, then ``load_state_dict``),
        data lives in the dict; the resilience checkpoint layer persists
        exactly this form with a validation manifest."""
        from apex_tpu.utils.serialization import tree_to_host_dict

        return tree_to_host_dict(state)

    def load_state_dict(self, d: dict, like: Any) -> Any:
        """Rebuild on-device optimizer state from :meth:`state_dict`
        output.  ``like`` is a freshly built state (``init(params)``)
        providing the pytree structure; shapes and dtypes are checked
        strictly so a mismatched restore fails before training resumes."""
        from apex_tpu.utils.serialization import tree_from_host_dict

        return tree_from_host_dict(d, like)

    def as_optax(self) -> optax.GradientTransformation:
        """Expose as an optax transform producing *updates* (param deltas)."""

        def init_fn(params):
            return self.init(params)

        def update_fn(grads, state, params=None):
            new_params, new_state = self.step(grads, params, state)
            updates = jax.tree.map(lambda n, p: (n - p.astype(n.dtype)), new_params, params)
            return updates, new_state

        return optax.GradientTransformation(init_fn, update_fn)


def bias_corrections(step: jax.Array, beta1: float, beta2: float):
    t = step.astype(jnp.float32)
    return 1.0 - beta1**t, 1.0 - beta2**t


def tree_map_multi(fn: Callable, n_out: int, *trees: Any) -> tuple:
    """Map ``fn`` (returning an ``n_out``-tuple) over trees; return n_out trees.

    Unlike returning tuples from ``jax.tree.map`` this is safe when the
    pytree itself contains tuples.
    """
    flat, treedef = jax.tree_util.tree_flatten(trees[0])
    rest = [treedef.flatten_up_to(t) for t in trees[1:]]
    outs = [fn(*args) for args in zip(flat, *rest)]
    return tuple(
        jax.tree_util.tree_unflatten(treedef, [o[i] for o in outs]) for i in range(n_out)
    )
