"""FusedAdam — Adam/AdamW with multi-tensor-fused semantics.

Parity target: ``apex.optimizers.FusedAdam`` (apex/optimizers/fused_adam.py:68-305)
and the ``multi_tensor_adam`` kernel (csrc/multi_tensor_adam.cu): fp32 state,
load→fp32→update→store-in-param-dtype, adam_w_mode (decoupled wd) vs. L2 mode,
bias correction, and the *capturable* on-device step/scale/overflow handling
(fused_adam.py:199-263) — which is simply the default under jit.

On TPU the whole update is one fused XLA loop over the pytree; a Pallas
packed-buffer variant lives in :mod:`apex_tpu.ops.packed_update` for
many-small-tensor models.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import FusedOptimizer, bias_corrections, tree_map_multi


class AdamState(NamedTuple):
    step: jax.Array  # i32 on device (capturable parity)
    exp_avg: Any  # m, stored in state_dtype (fp32 default)
    exp_avg_sq: Any  # v, stored in state_dtype (fp32 default)


class FusedAdam(FusedOptimizer):
    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas=(0.9, 0.999),
        eps: float = 1e-8,
        adam_w_mode: bool = True,
        weight_decay: float = 0.0,
        amsgrad: bool = False,
        master_weights: bool = False,
        state_dtype: Any = jnp.float32,
    ):
        """``state_dtype`` stores m/v in reduced precision (the same HBM-traffic
        lever as ``FusedLAMB(state_dtype=...)``): each step loads them, computes
        in fp32, and stores back in ``state_dtype``.  At 1B+ params bf16 moments
        halve both the optimizer state footprint and its per-step read+write
        traffic; trajectory parity vs fp32 state is pinned in
        tests/test_optimizers.py."""
        if amsgrad:
            # fused_adam.py:102 raises the same way
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        super().__init__(master_weights=master_weights)
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self.state_dtype = state_dtype

    def _init(self, params: Any) -> AdamState:
        zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, self.state_dtype), params)
        return AdamState(step=jnp.int32(0), exp_avg=zeros, exp_avg_sq=jax.tree.map(jnp.copy, zeros))

    def _update(self, grads: Any, params: Any, state: AdamState):
        step = state.step + 1
        if self.bias_correction:
            bc1, bc2 = bias_corrections(step, self.beta1, self.beta2)
        else:
            bc1 = bc2 = jnp.float32(1.0)
        lr = jnp.float32(self.lr)
        wd = jnp.float32(self.weight_decay)
        b1, b2, eps = self.beta1, self.beta2, self.eps

        sdt = self.state_dtype

        def leaf(p, g, m, v):
            p32 = p.astype(jnp.float32)
            m, v = m.astype(jnp.float32), v.astype(jnp.float32)
            if not self.adam_w_mode and self.weight_decay:
                g = g + wd * p32  # ADAM_MODE_0: L2 into the gradient
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * g * g
            update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if self.adam_w_mode and self.weight_decay:
                update = update + wd * p32  # ADAM_MODE_1: decoupled wd
            new_p = p32 - lr * update
            return new_p.astype(p.dtype), m.astype(sdt), v.astype(sdt)

        new_p, new_m, new_v = tree_map_multi(
            leaf, 3, params, grads, state.exp_avg, state.exp_avg_sq
        )
        return new_p, AdamState(step=step, exp_avg=new_m, exp_avg_sq=new_v)
