"""apex_tpu.optimizers — fused multi-tensor optimizers.

Parity: ``apex.optimizers`` (apex/optimizers/__init__.py): FusedAdam,
FusedLAMB, FusedSGD, FusedNovoGrad, FusedAdagrad, FusedMixedPrecisionLamb.
All are capturable-by-construction (device step/scale/overflow; see
apex/optimizers/fused_adam.py:199-263) and support fp32 master weights for
half-precision params.  ``.as_optax()`` adapts any of them to an optax
``GradientTransformation``.
"""

from apex_tpu.optimizers._common import FusedOptimizer
from apex_tpu.optimizers.fused_adam import AdamState, FusedAdam
from apex_tpu.optimizers.fused_adagrad import AdagradState, FusedAdagrad
from apex_tpu.optimizers.fused_lamb import FusedLAMB, LambState
from apex_tpu.optimizers.fused_mixed_precision_lamb import (
    FusedMixedPrecisionLamb,
    MixedPrecisionLambState,
)
from apex_tpu.optimizers.fused_novograd import FusedNovoGrad, NovoGradState
from apex_tpu.optimizers.fused_sgd import FusedSGD, SGDState

__all__ = [
    "FusedOptimizer",
    "FusedAdam",
    "AdamState",
    "FusedLAMB",
    "LambState",
    "FusedSGD",
    "SGDState",
    "FusedNovoGrad",
    "NovoGradState",
    "FusedAdagrad",
    "AdagradState",
    "FusedMixedPrecisionLamb",
    "MixedPrecisionLambState",
]
