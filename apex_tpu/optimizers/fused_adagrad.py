"""FusedAdagrad.

Parity: ``apex.optimizers.FusedAdagrad`` (apex/optimizers/fused_adagrad.py)
over ``multi_tensor_adagrad`` (csrc/multi_tensor_adagrad.cu): h += g^2;
p -= lr * g / (sqrt(h) + eps); ``adagrad_w_mode`` gives decoupled weight decay.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import FusedOptimizer, tree_map_multi


class AdagradState(NamedTuple):
    step: jax.Array
    sum_sq: Any  # "h"


class FusedAdagrad(FusedOptimizer):
    def __init__(
        self,
        lr: float = 1e-2,
        eps: float = 1e-10,
        weight_decay: float = 0.0,
        adagrad_w_mode: bool = False,
        master_weights: bool = False,
        packed: bool = False,
    ):
        super().__init__(master_weights=master_weights)
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self.adagrad_w_mode = adagrad_w_mode
        self.packed = packed

    def _init(self, params: Any) -> AdagradState:
        if self.packed:
            from apex_tpu.utils.packing import make_packed_spec

            n = make_packed_spec(params).padded_total
            return AdagradState(jnp.int32(0), jnp.zeros((n,), jnp.float32))
        h = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return AdagradState(jnp.int32(0), h)

    def _packed_update(self, grads: Any, params: Any, state: AdagradState):
        """One multi-tensor Pallas sweep (ops/packed_update.py)."""
        from apex_tpu.ops.packed_update import packed_adagrad_update
        from apex_tpu.utils.packing import (make_packed_spec, pack_pytree,
                                            unpack_pytree)

        spec = make_packed_spec(params)
        new_p, new_h = packed_adagrad_update(
            pack_pytree(grads, dtype=jnp.float32).flat,
            pack_pytree(params).flat, state.sum_sq,
            lr=self.lr, eps=self.eps, weight_decay=self.weight_decay,
            adagrad_w_mode=self.adagrad_w_mode)
        return unpack_pytree(new_p, spec), AdagradState(state.step + 1, new_h)

    def _update(self, grads: Any, params: Any, state: AdagradState):
        if self.packed:
            return self._packed_update(grads, params, state)
        lr = jnp.float32(self.lr)
        wd = jnp.float32(self.weight_decay)

        def leaf(p, g, h):
            p32 = p.astype(jnp.float32)
            if self.weight_decay and not self.adagrad_w_mode:
                g = g + wd * p32
            h = h + g * g
            update = g / (jnp.sqrt(h) + self.eps)
            if self.weight_decay and self.adagrad_w_mode:
                update = update + wd * p32
            return (p32 - lr * update).astype(p.dtype), h

        new_p, new_h = tree_map_multi(leaf, 2, params, grads, state.sum_sq)
        return new_p, AdagradState(state.step + 1, new_h)
