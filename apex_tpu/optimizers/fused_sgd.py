"""FusedSGD — momentum SGD with multi-tensor-fused semantics.

Parity: ``apex.optimizers.FusedSGD`` (apex/optimizers/fused_sgd.py) over the
``multi_tensor_sgd`` kernel (csrc/multi_tensor_sgd_kernel.cu:280): momentum,
dampening, nesterov, weight decay (optionally applied *after* momentum), and
first-step momentum initialization identical to torch.optim.SGD.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import FusedOptimizer, tree_map_multi


class SGDState(NamedTuple):
    step: jax.Array
    momentum_buffer: Any  # fp32 (None-like zeros when momentum == 0)


class FusedSGD(FusedOptimizer):
    def __init__(
        self,
        lr: float,
        momentum: float = 0.0,
        dampening: float = 0.0,
        weight_decay: float = 0.0,
        nesterov: bool = False,
        wd_after_momentum: bool = False,
        materialize_master_grads: bool = True,  # accepted for API parity
        master_weights: bool = False,
    ):
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError("Nesterov momentum requires a momentum and zero dampening")
        super().__init__(master_weights=master_weights)
        self.lr = lr
        self.momentum = momentum
        self.dampening = dampening
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.wd_after_momentum = wd_after_momentum

    def _init(self, params: Any) -> SGDState:
        buf = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return SGDState(step=jnp.int32(0), momentum_buffer=buf)

    def _update(self, grads: Any, params: Any, state: SGDState):
        step = state.step + 1
        lr = jnp.float32(self.lr)
        wd = jnp.float32(self.weight_decay)
        mu, damp = self.momentum, self.dampening
        # torch/apex semantics: on the first step the buffer is initialized to
        # the (wd-adjusted) gradient, not damped (multi_tensor_sgd "first_run").
        first = (step == 1)

        def leaf(p, g, buf):
            p32 = p.astype(jnp.float32)
            if self.weight_decay and not self.wd_after_momentum:
                g = g + wd * p32
            if mu:
                init_buf = g
                upd_buf = mu * buf + (1.0 - damp) * g
                buf = jnp.where(first, init_buf, upd_buf)
                d_p = g + mu * buf if self.nesterov else buf
            else:
                d_p = g
            if self.weight_decay and self.wd_after_momentum:
                d_p = d_p + wd * p32
            new_p = p32 - lr * d_p
            return new_p.astype(p.dtype), buf

        new_p, new_buf = tree_map_multi(leaf, 2, params, grads, state.momentum_buffer)
        return new_p, SGDState(step=step, momentum_buffer=new_buf)
