"""FusedLAMB — layer-wise adaptive large-batch optimizer.

Parity: ``apex.optimizers.FusedLAMB`` (apex/optimizers/fused_lamb.py:63-213),
which runs in two fused phases: (1) ``multi_tensor_l2norm`` computes
per-tensor and global gradient norms; (2) ``multi_tensor_lamb``
(csrc/multi_tensor_lamb.cu) applies Adam-style moments, *global* grad-norm
clipping (divide by max(global_norm/max_grad_norm, 1)), then the per-tensor
trust ratio ||p|| / ||update|| scaling the learning rate.

``use_nvlamb=True`` applies the trust ratio even for tensors excluded from
weight decay (the NVLAMB variant note in fused_lamb.py).

``packed=True`` scale caveat (r3 measured, r5 re-measured after the dense
reformulation): the phase-2 per-tensor trust ratios make packing LOSE on
TPU at 100M+ params.  r3's segment-reduction form never completed at
355M (scatter lowering); r5 rewrote the norms as dense static-slice
reductions (ops/packed_update.py::per_leaf_sqnorms) — parity-pinned and
functional, but still measured 45.9 ms at 103M vs the unpacked path's
24 ms at 355M, with compile time growing superlinearly in leaf count:
per-leaf reductions over one flat buffer cannot fuse with the Pallas
phase-1 sweep, while the unpacked path fuses each leaf's norm into that
leaf's update.  (The CUDA reference packs to amortize kernel-LAUNCH
overhead; XLA has none to amortize.)  The default unpacked path is the
production configuration (PERF_NOTES.md r5 table); packed remains
parity-tested for the many-small-tensor case.

``state_dtype`` stores the moments (m, v) in a reduced precision while
still *computing* every step in fp32 (cast up, update, cast back).  With
``jnp.bfloat16`` this halves optimizer-state HBM (8 bytes/param for the
fp32 m+v pair -> 4) at a relative rounding error of ~2^-8 per step on
the moments — the same trade the reference's distributed Adam makes
for fp16 state with per-tensor scaling
(apex/contrib/optimizers/distributed_fused_adam.py:273 region,
store_param_remainders / reduced-precision state).  It is what lets a
1.3B-param GPT train on a single 16 GB chip (see bench.py --model 1.3b);
convergence parity vs fp32 state is pinned in
tests/test_optimizers.py::test_lamb_bf16_state_parity.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import multi_tensor_l2norm
from apex_tpu.optimizers._common import FusedOptimizer, bias_corrections, tree_map_multi


class LambState(NamedTuple):
    step: jax.Array
    exp_avg: Any
    exp_avg_sq: Any


class FusedLAMB(FusedOptimizer):
    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas=(0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        amsgrad: bool = False,
        adam_w_mode: bool = True,
        grad_averaging: bool = True,
        max_grad_norm: float = 1.0,
        use_nvlamb: bool = False,
        master_weights: bool = False,
        packed: bool = False,
        state_dtype: Any = jnp.float32,
    ):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad variant.")
        if packed and state_dtype != jnp.float32:
            raise ValueError("packed=True keeps fp32 flat-buffer state; "
                             "state_dtype applies to the unpacked path only")
        super().__init__(master_weights=master_weights)
        self.packed = packed
        self.state_dtype = state_dtype
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb

    def _init(self, params: Any) -> LambState:
        if self.packed:
            # state lives flat: the multi-tensor layout (packed_update.py)
            from apex_tpu.utils.packing import make_packed_spec

            n = make_packed_spec(params).padded_total
            z = jnp.zeros((n,), jnp.float32)
            return LambState(jnp.int32(0), z, jnp.copy(z))
        z = jax.tree.map(lambda p: jnp.zeros(p.shape, self.state_dtype), params)
        return LambState(jnp.int32(0), z, jax.tree.map(jnp.copy, z))

    def _packed_update(self, grads: Any, params: Any, state: LambState):
        """One packed multi-tensor sweep (ops/packed_update.py LAMB path)."""
        from apex_tpu.ops.packed_update import (packed_lamb_update,
                                                segment_ids_for_spec)
        from apex_tpu.utils.packing import (make_packed_spec, pack_pytree,
                                            unpack_pytree)

        step = state.step + 1
        spec = make_packed_spec(params)
        flat_g = pack_pytree(grads, dtype=jnp.float32).flat
        flat_p = pack_pytree(params).flat
        seg_ids = segment_ids_for_spec(spec)

        global_grad_norm = jnp.sqrt(jnp.sum(flat_g * flat_g))
        clip = (jnp.maximum(global_grad_norm / self.max_grad_norm, 1.0)
                if self.max_grad_norm else jnp.float32(1.0))
        if self.bias_correction:
            bc1, bc2 = bias_corrections(step, self.beta1, self.beta2)
        else:
            bc1 = bc2 = jnp.float32(1.0)
        new_p, new_m, new_v = packed_lamb_update(
            flat_g, flat_p, state.exp_avg, state.exp_avg_sq, seg_ids,
            num_leaves=spec.num_leaves, lr=self.lr, beta1=self.beta1,
            beta2=self.beta2,
            beta3=(1.0 - self.beta1 if self.grad_averaging else 1.0),
            eps=self.eps, weight_decay=self.weight_decay,
            bias_correction1=bc1, bias_correction2=bc2, global_clip=clip,
            adam_w_mode=self.adam_w_mode, use_nvlamb=self.use_nvlamb,
            spec=spec)
        return unpack_pytree(new_p, spec), LambState(step, new_m, new_v)

    def _update(self, grads: Any, params: Any, state: LambState):
        if self.packed:
            return self._packed_update(grads, params, state)
        step = state.step + 1
        # Phase 1 (fused_lamb.py:138-162): global grad norm + clip coefficient.
        global_grad_norm = multi_tensor_l2norm(grads)
        if self.max_grad_norm:
            clip = jnp.maximum(global_grad_norm / self.max_grad_norm, 1.0)
        else:
            clip = jnp.float32(1.0)

        if self.bias_correction:
            bc1, bc2 = bias_corrections(step, self.beta1, self.beta2)
        else:
            bc1 = bc2 = jnp.float32(1.0)
        beta3 = 1.0 - self.beta1 if self.grad_averaging else 1.0
        lr = jnp.float32(self.lr)
        wd = jnp.float32(self.weight_decay)
        b1, b2, eps = self.beta1, self.beta2, self.eps

        sdt = self.state_dtype

        def leaf(p, g, m, v):
            p32 = p.astype(jnp.float32)
            m, v = m.astype(jnp.float32), v.astype(jnp.float32)
            g = g / clip
            if not self.adam_w_mode and self.weight_decay:
                g = g + wd * p32  # LAMB "MODE 0": L2 into grad
            m = b1 * m + beta3 * g
            v = b2 * v + (1.0 - b2) * g * g
            update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if self.adam_w_mode and self.weight_decay:
                update = update + wd * p32
            # trust ratio: ||p|| / ||update|| per tensor (multi_tensor_lamb.cu
            # "lamb stage 2"); identity when either norm is 0, and — unless
            # nvlamb — when the tensor has no weight decay.
            p_norm = jnp.sqrt(jnp.sum(p32 * p32))
            u_norm = jnp.sqrt(jnp.sum(update * update))
            ratio = jnp.where(
                (p_norm > 0) & (u_norm > 0), p_norm / u_norm, jnp.float32(1.0)
            )
            if not (self.weight_decay or self.use_nvlamb):
                ratio = jnp.float32(1.0)
            new_p = p32 - lr * ratio * update
            return new_p.astype(p.dtype), m.astype(sdt), v.astype(sdt)

        new_p, new_m, new_v = tree_map_multi(leaf, 3, params, grads, state.exp_avg, state.exp_avg_sq)
        return new_p, LambState(step, new_m, new_v)
