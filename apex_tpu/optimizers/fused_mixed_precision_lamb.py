"""FusedMixedPrecisionLamb — LAMB with on-device hyperparameter state.

Parity: ``apex.optimizers.FusedMixedPrecisionLamb``
(apex/optimizers/fused_mixed_precision_lamb.py): lr and step live as device
tensors (CUDA-graph-capturable there; natural under jit here), gradient
clipping by global norm happens *before* the LAMB stages, and model params
may be half with fp32 masters held by the optimizer (master_weights defaults
True — the ``reduced_precision_dtype`` path).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import multi_tensor_l2norm
from apex_tpu.optimizers._common import FusedOptimizer, bias_corrections, tree_map_multi


class MixedPrecisionLambState(NamedTuple):
    step: jax.Array
    lr: jax.Array  # device-resident lr (tensor-lr parity)
    exp_avg: Any
    exp_avg_sq: Any


class FusedMixedPrecisionLamb(FusedOptimizer):
    def __init__(
        self,
        lr: float = 1e-3,
        step: int = 0,
        bias_correction: bool = True,
        betas=(0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        grad_averaging: bool = True,
        max_grad_norm: float = 1.0,
        use_nvlamb: bool = False,
        master_weights: bool = True,
    ):
        super().__init__(master_weights=master_weights)
        self.lr = lr
        self._init_step = step
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb

    def _init(self, params: Any) -> MixedPrecisionLambState:
        z = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return MixedPrecisionLambState(
            step=jnp.int32(self._init_step),
            lr=jnp.float32(self.lr),
            exp_avg=z,
            exp_avg_sq=jax.tree.map(jnp.copy, z),
        )

    def set_lr(self, state, lr):
        """Update the device-resident lr inside the full (inner, master) state."""
        inner, masters = state
        return (inner._replace(lr=jnp.asarray(lr, jnp.float32)), masters)

    def _update(self, grads: Any, params: Any, state: MixedPrecisionLambState):
        step = state.step + 1
        # Grad clipping by global norm happens BEFORE the lamb stages
        # (fused_mixed_precision_lamb.py step()).
        gnorm = multi_tensor_l2norm(grads)
        clip = jnp.maximum(gnorm / self.max_grad_norm, 1.0) if self.max_grad_norm else jnp.float32(1.0)

        if self.bias_correction:
            bc1, bc2 = bias_corrections(step, self.beta1, self.beta2)
        else:
            bc1 = bc2 = jnp.float32(1.0)
        beta3 = 1.0 - self.beta1 if self.grad_averaging else 1.0
        lr = state.lr
        wd = jnp.float32(self.weight_decay)
        b1, b2, eps = self.beta1, self.beta2, self.eps

        def leaf(p, g, m, v):
            p32 = p.astype(jnp.float32)
            g = g / clip
            m = b1 * m + beta3 * g
            v = b2 * v + (1.0 - b2) * g * g
            update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if self.weight_decay:
                update = update + wd * p32  # decoupled (adam_w) mode only
            p_norm = jnp.sqrt(jnp.sum(p32 * p32))
            u_norm = jnp.sqrt(jnp.sum(update * update))
            ratio = jnp.where((p_norm > 0) & (u_norm > 0), p_norm / u_norm, jnp.float32(1.0))
            if not (self.weight_decay or self.use_nvlamb):
                ratio = jnp.float32(1.0)
            new_p = p32 - lr * ratio * update
            return new_p.astype(p.dtype), m, v

        new_p, new_m, new_v = tree_map_multi(leaf, 3, params, grads, state.exp_avg, state.exp_avg_sq)
        return new_p, MixedPrecisionLambState(step, state.lr, new_m, new_v)
