"""Feature availability registry.

The reference gates each native extension behind a ``setup.py`` build flag
(``--cuda_ext``, ``--xentropy``, ... — setup.py:139-860) and guards imports at
use sites.  apex_tpu components are pure JAX and always importable; this
registry records which *backends* a component can use on the current platform
(Pallas TPU kernel vs. jnp/XLA fallback) so users and tests can introspect the
same way the reference's import guards allowed.
"""

from __future__ import annotations

import dataclasses
import functools


@dataclasses.dataclass(frozen=True)
class Feature:
    name: str
    description: str
    pallas: bool  # has a hand-written Pallas TPU kernel path
    fallback: str  # what runs when the Pallas path is unavailable


_FEATURES: dict[str, Feature] = {}


def register(name: str, description: str, pallas: bool, fallback: str = "jnp/XLA") -> None:
    _FEATURES[name] = Feature(name, description, pallas, fallback)


def available_features() -> dict[str, Feature]:
    return dict(_FEATURES)


@functools.cache
def on_tpu() -> bool:
    import jax

    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def pallas_enabled() -> bool:
    """Whether Pallas TPU kernels should be used (TPU backend present)."""
    import os

    if os.environ.get("APEX_TPU_DISABLE_PALLAS", "0") == "1":
        return False
    return on_tpu()


# Core features (mirrors SURVEY.md §2 component inventory).
register("multi_tensor_apply", "packed multi-tensor scale/axpby/l2norm/opt updates", True)
register("fused_optimizers", "FusedAdam/LAMB/SGD/NovoGrad/Adagrad", True)
register("fused_layer_norm", "LayerNorm/RMSNorm fwd/bwd", True)
register("fused_dense", "GEMM+bias(+gelu) epilogues", False, "XLA fusion")
register("scaled_masked_softmax", "scaled (masked/causal) softmax", True)
register("fused_rope", "rotary position embedding (sbhd/cached/thd/2d)", True)
register("sync_batchnorm", "distributed Welford BN", False, "psum over mesh axis")
register("flash_attention", "fused multihead attention (fmha parity)", True)
register("xentropy", "fused softmax cross-entropy with label smoothing", True)
register("group_norm", "NHWC group norm (+swish)", True)
register("sparsity", "2:4 structured sparsity (ASP)", False)
register("halo_exchange", "spatial-parallel halo exchange", False, "ppermute")
register("resilience", "validated checkpointing + fault injection + guarded stepping",
         False, "host I/O + jnp")
register("supervisor", "step watchdog + heartbeat + transient retry + data guard + escalation",
         False, "host threads + I/O")
register("serving", "slotted KV-cache decode + continuous batching + "
         "exact-greedy speculative decoding + checkpoint serving",
         False, "jnp/XLA + host scheduler")
register("prefix_cache", "cross-request prefix caching: chain-hashed shared-prompt "
         "K/V reuse with bit-exact mid-prompt prefill resume",
         False, "jnp/XLA + host block store")
register("obs", "metrics registry + span tracing + Prometheus/Chrome-trace exporters",
         False, "host-side stdlib")
register("serving_slo", "request-level lifecycle traces + deterministic open-loop "
         "load generation + SLO percentile reports (TTFT/TPOT/queue-wait/goodput)",
         False, "host-side stdlib")
register("serving_policy", "serving control plane: priority classes with lossless "
         "(bit-exact) preemption, cancellation, deadline shedding, per-tenant "
         "weighted-round-robin fairness + serving chaos injection",
         False, "host scheduler + existing capture/restore/alias programs")
register("serving_tp", "tensor-parallel serving: DecodeEngine sharded over a 1-D "
         "tp mesh (Megatron column/row params, head-split KV cache, replicated "
         "tables/lengths; token-identical greedy streams, one psum pair per layer)",
         False, "shard_map over the same jitted serving programs")
register("serving_fleet", "fault-tolerant fleet serving: prefix-affinity/WRR "
         "replica router with heartbeat health states, lossless stream failover "
         "(bit-exact capture-resume or deterministic replay), rolling drain, "
         "and replica-scale chaos (kill/wedge/slow)",
         False, "host-side router over N scheduler replicas")
register("serving_quant", "quantized serving: per-channel int8 weights, "
         "per-(position, head) int8 KV cache (dense + paged), and opt-in "
         "grouped-scale int8 tp allreduce — greedy-agreement tier vs fp32, "
         "default-off byte-identical, same bounded program families",
         False, "jnp/XLA int8 inside the existing jitted serving programs")
