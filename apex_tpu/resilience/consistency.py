"""Cross-replica consistency: detect, localize, and repair dp desync.

Data-parallel replicas are supposed to hold bit-identical state — the
gradient all-reduce hands every replica the same update.  At pod scale
that invariant silently breaks anyway: a bit flip in one replica's HBM,
a diverged host applying a stale update, a collective that dropped a
participant (PAPERS.md TPU-pod papers treat silent replica divergence as
a first-class fault).  An unnoticed desync is the *worst* failure mode:
every subsequent all-reduce averages the corruption into the whole pod.

This module makes the invariant checkable and repairable:

- **Representation.**  Per-replica state is *stacked*: each leaf carries
  a leading replica axis sharded over ``dp`` — shape ``(dp, ...)`` with
  spec ``P('dp', ...)`` — so replica copies are distinct buffers a fault
  can actually diverge (a logically-replicated array has one buffer and
  cannot).  ``expand_replicas`` / ``collapse_replicas`` convert between
  this and the logical single-copy form (which is what elastic sharded
  checkpoints persist — the stacked form's global shape depends on the
  mesh, the logical form does not).
- :func:`verify_replicas` hashes every leaf per dp-replica *inside*
  ``shard_map`` — only one u32 hash and one f32 delta per (leaf,
  replica) cross the wire, never the parameters — and localizes each
  diverged leaf (keystr path, diverged ranks, max-abs delta vs rank 0)
  through structured ``replica_desync`` events.
- :func:`resync_replicas` repairs in place by re-broadcasting rank 0's
  copy, reusing :func:`apex_tpu.parallel.distributed.broadcast_params`
  under ``shard_map`` over the replica axis.
- :class:`ReplicaConsistency` is the policy object
  :class:`~apex_tpu.resilience.supervisor.TrainingSupervisor` runs every
  ``consistency_check_interval`` steps: verify → resync → re-verify,
  raising :class:`ReplicaDesyncError` (one unrecovered failure in the
  supervisor's escalation ladder) only when the repair itself fails or
  resync is disabled.

Scope: pass the subtree that *should* be replica-identical (params,
optimizer state).  Leaves whose spec does not mention the replica axis
are logically shared and skipped; dp-*sharded data* (e.g. ZeRO-style
optimizer shards) is not replicated and must not be passed here.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# import-name and kwarg-name drift across jax versions is centralized
# in utils.compat (probed once); the hash pass disables the replication
# checker because it mixes per-leaf specs and makes outputs replicated
# by explicit psum/all_gather, which older rep-checkers reject
from apex_tpu.utils.compat import NO_REP_CHECK as _SHARD_MAP_KW
from apex_tpu.utils.compat import shard_map as _shard_map

from apex_tpu._logging import emit_event, get_logger
from apex_tpu.parallel.distributed import broadcast_params
from apex_tpu.utils.serialization import is_prng_key

__all__ = [
    "DivergedLeaf",
    "ReplicaConsistency",
    "ReplicaDesyncError",
    "collapse_replicas",
    "expand_replicas",
    "majority_root",
    "replica_hashes",
    "resync_replicas",
    "verify_replicas",
]

logger = get_logger("resilience.consistency")


class ReplicaDesyncError(RuntimeError):
    """Replicas diverged and could not (or may not) be resynced.

    Carries ``step`` and ``report`` (the :class:`DivergedLeaf` list).
    Deterministic by definition — re-running the hash pass re-proves the
    same divergence — so the retry layer must never retry it.
    """

    transient = False

    def __init__(self, step: int, report: Sequence["DivergedLeaf"]):
        names = ", ".join(f"{d.path} (ranks {list(d.ranks)})"
                          for d in report) or "<none>"
        super().__init__(
            f"replica desync at step {step}: {len(report)} diverged "
            f"leaves: {names}")
        self.step = int(step)
        self.report = list(report)


@dataclasses.dataclass(frozen=True)
class DivergedLeaf:
    """One localized divergence: which leaf, which replicas, how far."""

    path: str
    ranks: tuple  # dp ranks whose hash differs from rank 0's
    max_abs_delta: float  # max |replica - rank0| over the diverged ranks
    hashes: tuple  # per-rank u32 leaf hashes (diagnostic)


def _infer_mesh(tree: Any, mesh: Optional[Mesh] = None, *,
                required: bool = True) -> Optional[Mesh]:
    """The mesh a pass runs over: an explicit ``mesh`` wins, else the
    first NamedSharding in the tree, else the installed parallel_state
    mesh.  With ``required=False`` (the elastic-save caller) a missing
    mesh returns None — every leaf then saves as one replicated shard —
    instead of raising."""
    if mesh is not None:
        return mesh
    for leaf in jax.tree.leaves(tree):
        sharding = getattr(leaf, "sharding", None)
        if isinstance(sharding, NamedSharding):
            return sharding.mesh
    from apex_tpu.transformer import parallel_state

    if parallel_state.model_parallel_is_initialized():
        return parallel_state.get_mesh()
    if required:
        raise ValueError(
            "no mesh: pass mesh=, or put leaves with NamedSharding, or "
            "initialize parallel_state first")
    return None


def _entry_names(entry) -> tuple:
    """ONE PartitionSpec entry as a tuple of axis names — ``None`` →
    ``()``, ``'dp'`` → ``('dp',)``, ``('dp', 'tp')`` unchanged.  The
    single normalization every replica-stacked classification
    (verify/resync, collapse, fault injection, shard grids) shares, so
    they cannot drift on str-vs-tuple spec forms."""
    if entry is None:
        return ()
    if isinstance(entry, str):
        return (entry,)
    return tuple(entry)


def _full_spec(leaf: Any) -> P:
    """The leaf's PartitionSpec padded to full rank (shard_map wants
    exact-rank specs; trailing unmentioned dims are replicated)."""
    sharding = getattr(leaf, "sharding", None)
    spec = sharding.spec if isinstance(sharding, NamedSharding) else P()
    ndim = np.ndim(leaf)
    entries = [spec[d] if d < len(spec) else None for d in range(ndim)]
    return P(*entries)


def _participates(spec: P, axis_name: str) -> bool:
    return any(axis_name in _entry_names(entry) for entry in spec)


def _shard_hash(x):
    """Order-sensitive u32 checksum of a local shard's raw bytes.

    Bytes are packed into u32 WORDS (zero-padded tail) and positionally
    weighted: ``sum(word[i] * (i + 1)) mod 2**32``.  Any single flipped
    byte changes its word and therefore the sum, and two equal
    populations in different orders hash differently — cheap, jit-safe,
    and only the 4-byte digest ever leaves the device.  Packing keeps
    the transient working set at ~2x the shard's bytes (words +
    weights); a per-BYTE u32 expansion would be ~8x, a real HBM spike on
    the pod-scale leaves this pass exists for.
    """
    if x.size == 0:
        return jnp.zeros((), jnp.uint32)
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.uint8)
    b = jax.lax.bitcast_convert_type(x, jnp.uint8).reshape(-1)
    pad = (-b.size) % 4
    if pad:
        b = jnp.concatenate([b, jnp.zeros((pad,), jnp.uint8)])
    words = jax.lax.bitcast_convert_type(b.reshape(-1, 4), jnp.uint32)
    weights = jnp.arange(words.size, dtype=jnp.uint32) + jnp.uint32(1)
    return jnp.sum(words * weights, dtype=jnp.uint32)


def _select(tree: Any, axis_name: str):
    """Flatten ``tree`` into (paths, leaves, specs, participating mask),
    unwrapping typed PRNG keys to raw key data so byte hashing and the
    psum broadcast stay dtype-legal."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    paths, leaves, specs, part = [], [], [], []
    for path, leaf in flat:
        spec = _full_spec(leaf)
        if is_prng_key(leaf):
            leaf = jax.random.key_data(leaf)
            # key_data adds trailing dims; pad the spec back to full rank
            entries = list(spec) + [None] * (np.ndim(leaf) - len(spec))
            spec = P(*entries)
        paths.append(jax.tree_util.keystr(path))
        leaves.append(leaf)
        specs.append(spec)
        part.append(_participates(spec, axis_name))
    return treedef, flat, paths, leaves, specs, part


def replica_hashes(tree: Any, *, mesh: Optional[Mesh] = None,
                   axis_name: str = "dp") -> dict:
    """Per-replica hashes and max-abs deltas for every replica-stacked
    leaf: ``{keystr: {"hashes": (dp,) u32, "max_abs_delta": (dp,) f32}}``.

    Computed inside one ``shard_map`` over the full mesh: each leaf's
    local-shard hash is summed over the non-replica axes (combining a
    replica's tp/pp shards into one digest) and all-gathered over the
    replica axis; the delta is each replica's max ``|x - x_rank0|``
    (values cast to f32 — a diagnostic magnitude, not a comparison; the
    byte hash is the equality oracle).
    """
    mesh = _infer_mesh(tree, mesh)
    if axis_name not in mesh.axis_names:
        raise ValueError(f"mesh has no axis {axis_name!r} "
                         f"(axes: {mesh.axis_names})")
    _, _, paths, leaves, specs, part = _select(tree, axis_name)
    sel = [i for i, p in enumerate(part) if p]
    if not sel:
        return {}
    sel_leaves = tuple(leaves[i] for i in sel)
    sel_specs = tuple(specs[i] for i in sel)
    hashes, deltas = _hash_pass(mesh, axis_name, sel_specs)(sel_leaves)
    return {paths[i]: {"hashes": np.asarray(h), "max_abs_delta": np.asarray(d)}
            for i, h, d in zip(sel, hashes, deltas)}


@functools.lru_cache(maxsize=64)
def _hash_pass(mesh: Mesh, axis_name: str, specs: tuple):
    """The compiled hash computation for one (mesh, replica axis, spec
    tuple).  Cached — a fresh closure per call would defeat jax's trace
    cache and retrace/recompile the whole pass on EVERY periodic
    supervisor check."""
    other_axes = tuple(a for a in mesh.axis_names if a != axis_name)

    def hash_all(xs):
        hashes, deltas = [], []
        rank = jax.lax.axis_index(axis_name)
        for x in xs:
            h = _shard_hash(x)
            if other_axes:
                h = jax.lax.psum(h, other_axes)
            hashes.append(jax.lax.all_gather(h, axis_name))
            xv = x.astype(jnp.float32)
            x0 = jax.lax.psum(
                jnp.where(rank == 0, xv, jnp.zeros_like(xv)), axis_name)
            d = (jnp.max(jnp.abs(xv - x0)) if x.size
                 else jnp.zeros((), jnp.float32))
            if other_axes:
                d = jax.lax.pmax(d, other_axes)
            deltas.append(jax.lax.all_gather(d, axis_name))
        return tuple(hashes), tuple(deltas)

    return jax.jit(_shard_map(hash_all, mesh=mesh, in_specs=(specs,),
                              out_specs=P(), **_SHARD_MAP_KW))


def verify_replicas(tree: Any, *, mesh: Optional[Mesh] = None,
                    axis_name: str = "dp", step: int = 0,
                    emit: bool = True) -> list:
    """Prove dp replicas bit-identical; localize every divergence.

    Returns a (possibly empty) list of :class:`DivergedLeaf`, one per
    leaf whose per-replica hashes disagree with rank 0's, and (when
    ``emit``) a structured ``replica_desync`` event per diverged leaf —
    name, ranks, max-abs delta — so a fleet collector can alert on the
    exact parameter, not just "a replica is off".

    ``ranks`` is *relative to rank 0*: when the fault landed on rank 0
    itself, every OTHER rank is reported diverged.  The per-rank
    ``hashes`` carry the evidence either way — majority analysis (see
    :func:`majority_root`) identifies the actual outlier.
    """
    t0 = time.monotonic()
    report = []
    for path, rec in replica_hashes(tree, mesh=mesh,
                                    axis_name=axis_name).items():
        hashes = rec["hashes"]
        bad = tuple(int(r) for r in range(len(hashes))
                    if int(hashes[r]) != int(hashes[0]))
        if not bad:
            continue
        max_delta = float(np.max(rec["max_abs_delta"][list(bad)]))
        diverged = DivergedLeaf(path=path, ranks=bad,
                                max_abs_delta=max_delta,
                                hashes=tuple(int(h) for h in hashes))
        report.append(diverged)
        if emit:
            emit_event("replica_desync", leaf=path, step=int(step),
                       ranks=list(bad), max_abs_delta=max_delta,
                       replicas=int(len(hashes)))
    if emit and report:
        emit_event("replica_verify_failed", step=int(step),
                   diverged_leaves=[d.path for d in report], t0=t0)
    return report


@functools.lru_cache(maxsize=64)
def _resync_pass(mesh: Mesh, axis_name: str, root: int, specs: tuple):
    """Compiled re-broadcast for one (mesh, axis, root, spec tuple) —
    cached for the same retrace reason as :func:`_hash_pass`."""
    return jax.jit(_shard_map(
        lambda xs: tuple(broadcast_params(x, axis_name, root) for x in xs),
        mesh=mesh, in_specs=(specs,), out_specs=specs,
        **_SHARD_MAP_KW))


def resync_replicas(tree: Any, *, mesh: Optional[Mesh] = None,
                    axis_name: str = "dp", root: int = 0) -> Any:
    """Repair a desync: every replica adopts rank ``root``'s copy.

    Re-broadcasts each replica-stacked leaf from ``root`` with
    :func:`apex_tpu.parallel.distributed.broadcast_params` under
    ``shard_map`` over the replica axis (a masked psum — O(leaf) memory,
    bit-exact for the surviving copy).  Leaves that do not carry the
    replica axis pass through untouched; typed PRNG keys round-trip
    through their raw key data.
    """
    mesh = _infer_mesh(tree, mesh)
    treedef, flat, paths, leaves, specs, part = _select(tree, axis_name)
    sel = [i for i, p in enumerate(part) if p]
    if not sel:
        return tree
    sel_leaves = tuple(leaves[i] for i in sel)
    sel_specs = tuple(specs[i] for i in sel)

    synced = _resync_pass(mesh, axis_name, int(root),
                          sel_specs)(sel_leaves)

    out_leaves = []
    for i, (path, orig) in enumerate(flat):
        if i not in sel:
            out_leaves.append(orig)
            continue
        fixed = synced[sel.index(i)]
        if is_prng_key(orig):
            fixed = jax.random.wrap_key_data(
                fixed, impl=jax.random.key_impl(orig))
            sharding = getattr(orig, "sharding", None)
            if sharding is not None:
                fixed = jax.device_put(fixed, sharding)
        out_leaves.append(fixed)
    return jax.tree_util.tree_unflatten(treedef, out_leaves)


def majority_root(report: Sequence[DivergedLeaf], *,
                  default: int = 0) -> int:
    """The safest broadcast source for a repair: a replica whose hash
    agrees with the strict per-leaf MAJORITY for every diverged leaf.

    Always resyncing from rank 0 propagates the corruption when the
    fault landed on rank 0 itself (every other rank then reads as
    "diverged", but the majority is right and rank 0 is the outlier).
    Falls back to ``default`` when no rank is majority-consistent across
    all diverged leaves — e.g. a 50/50 split at dp=2, where the hashes
    alone cannot say who is right.
    """
    candidates: Optional[set] = None
    for d in report:
        counts: dict = {}
        for h in d.hashes:
            counts[h] = counts.get(h, 0) + 1
        best = max(counts.values())
        maj = (set() if best * 2 <= len(d.hashes)
               else {r for r, h in enumerate(d.hashes)
                     if counts[h] == best})
        candidates = maj if candidates is None else candidates & maj
    return min(candidates) if candidates else int(default)


# --------------------------------------------------------------------------
# stacked <-> logical conversion (what elastic checkpoints persist)
# --------------------------------------------------------------------------


def collapse_replicas(tree: Any, *, axis_name: str = "dp") -> Any:
    """Stacked per-replica state -> ONE logical copy (rank 0's).

    Drops the leading replica axis of every leaf whose spec starts with
    ``axis_name`` (other leaves pass through).  The result's global
    shapes no longer depend on the dp world size — the form
    :mod:`apex_tpu.resilience.elastic` persists, so a different-dp
    restart can re-expand.  Verify replicas first: collapsing a
    desynced state silently blesses rank 0's copy.
    """
    def collapse(leaf):
        sharding = getattr(leaf, "sharding", None)
        if not isinstance(sharding, NamedSharding):
            return leaf
        spec = _full_spec(leaf)
        lead = spec[0] if len(spec) else None
        # 'dp' and ('dp',) are the same sharding: the collapse must
        # agree with what verify/resync classify as stacked
        if _entry_names(lead) != (axis_name,):
            return leaf
        logical = leaf[0]
        return jax.device_put(
            logical, NamedSharding(sharding.mesh, P(*spec[1:])))

    return jax.tree.map(collapse, tree)


def expand_replicas(tree: Any, mesh: Mesh, *,
                    axis_name: str = "dp") -> Any:
    """ONE logical copy -> stacked per-replica state on ``mesh``.

    Broadcasts every leaf along a new leading replica axis of size
    ``mesh.shape[axis_name]`` and shards it ``P(axis_name, *leaf_spec)``
    — the inverse of :func:`collapse_replicas`, used after an elastic
    restore to rebuild the per-replica representation at the NEW dp
    world size.  Pass the subtree that should be per-replica (the same
    one you collapse).
    """
    n = int(mesh.shape[axis_name])

    def expand(leaf):
        spec = _full_spec(leaf)
        stacked = jnp.broadcast_to(
            jnp.asarray(leaf)[None], (n,) + tuple(np.shape(leaf)))
        return jax.device_put(
            stacked, NamedSharding(mesh, P(axis_name, *spec)))

    return jax.tree.map(expand, tree)


# --------------------------------------------------------------------------
# the supervisor's policy object
# --------------------------------------------------------------------------


class ReplicaConsistency:
    """verify -> resync -> re-verify, as one supervisor-pluggable pass.

    ``check(state, step)`` returns the (possibly repaired) state.  On
    divergence it resyncs from the :func:`majority_root` — the replica
    the per-leaf hash majority says is intact, so a fault on rank 0
    itself is repaired FROM the majority instead of broadcast to it —
    falling back to ``root`` when the hashes cannot elect one (a 50/50
    split), then re-verifies.  It raises :class:`ReplicaDesyncError`
    only when ``resync`` is disabled or the repair itself fails to
    converge — which the supervisor counts as an unrecovered failure and
    escalates through the retry → emergency-checkpoint → abort ladder.

    >>> sup = TrainingSupervisor(
    ...     mgr, SupervisorConfig(consistency_check_interval=50),
    ...     consistency=ReplicaConsistency(mesh=mesh))
    """

    def __init__(self, mesh: Optional[Mesh] = None, *,
                 axis_name: str = "dp", resync: bool = True,
                 root: int = 0):
        self.mesh = mesh
        self.axis_name = axis_name
        self.resync = resync
        self.root = root
        self.resyncs = 0  # lifetime repair count (observability)

    def check(self, tree: Any, *, step: int = 0) -> Any:
        report = verify_replicas(tree, mesh=self.mesh,
                                 axis_name=self.axis_name, step=step)
        if not report:
            return tree
        if not self.resync:
            raise ReplicaDesyncError(step, report)
        t0 = time.monotonic()
        root = majority_root(report, default=self.root)
        repaired = resync_replicas(tree, mesh=self.mesh,
                                   axis_name=self.axis_name,
                                   root=root)
        still_bad = verify_replicas(repaired, mesh=self.mesh,
                                    axis_name=self.axis_name, step=step,
                                    emit=False)
        if still_bad:
            raise ReplicaDesyncError(step, still_bad)
        self.resyncs += 1
        emit_event("replica_resync", step=int(step), root=root,
                   leaves=[d.path for d in report],
                   resyncs=self.resyncs, t0=t0)
        return repaired
