"""Training supervisor: step watchdog, heartbeat, escalation, clean abort.

PR 1's resilience machinery handles the failures that *announce*
themselves — corrupt checkpoints, NaN gradients, preemption signals.
The failures that dominate at pod scale are quieter (PAPERS.md:
"Exploring the limits of Concurrency in ML Training on Google TPUs";
MLPerf TPU-v3 pod runs): a step that silently never finishes, a
straggling host, an input pipeline that hangs or rots.  This module is
the host-side layer that turns those into *events with deadlines*:

- :class:`StepWatchdog` — a per-step deadline on a monotonic clock.
  ``arm``/``disarm`` bracket each step (or ``with watchdog.step(i):``);
  a background monitor thread notices a stall mid-step and dumps
  structured diagnostics (step, heartbeat age, pipeline timer snapshot,
  live-array count) through ``emit_event`` while the step is still
  stuck — the information an engineer needs *before* the job is killed.
  ``disarm`` raises :class:`StepDeadlineExceeded` for slow-but-finished
  steps, so deadline violations are deterministic control flow, not just
  log lines.
- **Heartbeat file** — ``beat`` atomically rewrites a small JSON file
  (step, wall/monotonic time, newest checkpoint path) that an external
  orchestrator can watch: "mtime stopped advancing" is the universal
  pod-level liveness probe, and the checkpoint path tells the restart
  where to resume from without parsing logs.
- :class:`TrainingSupervisor` — the escalation policy tying the pieces
  together: transient data-fetch failures are retried
  (:func:`~apex_tpu.resilience.retry.retry_transient`), corrupt batches
  are skipped within the guard's budget, and *unrecovered* step-level
  failures (deadline blown, retry exhausted, skip budget exceeded, data
  stall) feed a consecutive-failure counter.  At
  ``max_consecutive_failures`` the supervisor degrades gracefully:
  write an emergency checkpoint through PR 1's validated atomic
  machinery, prove it good, record it in the heartbeat, and raise
  :class:`TrainingAborted` — the run dies *clean and resumable* instead
  of wedged or half-written.

Everything is deterministic under test: the clock, sleeper, and fault
sources are injectable, and tier-1 drives every path on CPU
(``tests/test_supervisor.py``) with no sleep longer than ~1 s.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Iterable, Optional, Tuple

from apex_tpu._logging import emit_event, get_logger
from apex_tpu.obs import metrics as obs_metrics
from apex_tpu.obs import trace as obs_trace
from apex_tpu.resilience.async_checkpoint import AsyncCheckpointer, SaveVetoed
from apex_tpu.resilience.checkpoint import (
    CheckpointError,
    CheckpointManager,
    validate_checkpoint,
)
from apex_tpu.resilience.consistency import ReplicaDesyncError
from apex_tpu.resilience.data_guard import DataStallError, SkipBudgetExceeded
from apex_tpu.resilience.retry import (
    RetryExhausted,
    RetryPolicy,
    retry_transient,
)
from apex_tpu.utils.serialization import atomic_write_json

__all__ = [
    "StepDeadlineExceeded",
    "StepWatchdog",
    "SupervisorConfig",
    "TrainingAborted",
    "TrainingSupervisor",
    "read_heartbeat",
    "write_heartbeat",
]

logger = get_logger("resilience.supervisor")

# hot-path instruments (docs/api/observability.md): the histogram is the
# p99-step-time answer, the counter the progress rate, the gauge the
# liveness probe an exporter reads WITHOUT parsing heartbeat files —
# evaluated at scrape time via set_function, so it never goes stale
_STEP_SECONDS = obs_metrics.histogram(
    "apex_step_duration_seconds", "supervised train-step wall time")
_STEPS_TOTAL = obs_metrics.counter(
    "apex_supervisor_steps_total",
    "steps completed under the training supervisor")
_HEARTBEAT_AGE = obs_metrics.gauge(
    "apex_heartbeat_age_seconds",
    "seconds since the newest watchdog beat (-1 before the first)")


class StepDeadlineExceeded(RuntimeError):
    """A training step outlived its deadline (straggler or hang).

    Carries ``step``, ``deadline_s``, ``elapsed_s`` and the
    ``diagnostics`` dict dumped with the ``watchdog_stall`` event.
    """

    def __init__(self, step: int, deadline_s: float, elapsed_s: float,
                 diagnostics: Optional[dict] = None):
        super().__init__(
            f"step {step} exceeded its {deadline_s:.3f}s deadline "
            f"({elapsed_s:.3f}s elapsed)")
        self.step = step
        self.deadline_s = deadline_s
        self.elapsed_s = elapsed_s
        self.diagnostics = diagnostics or {}


class TrainingAborted(RuntimeError):
    """Clean abort after graceful degradation: the emergency checkpoint
    (``checkpoint_path``, when one could be written) is validated and
    resumable — restart from it."""

    def __init__(self, reason: str, step: int,
                 checkpoint_path: Optional[str] = None):
        super().__init__(
            f"training aborted at step {step}: {reason}"
            + (f" (emergency checkpoint: {checkpoint_path})"
               if checkpoint_path else " (no emergency checkpoint written)"))
        self.reason = reason
        self.step = step
        self.checkpoint_path = checkpoint_path


def write_heartbeat(path: str, step: int, *,
                    ckpt_path: Optional[str] = None,
                    stalled: bool = False) -> dict:
    """Atomically rewrite the heartbeat file; returns the payload.

    Same crash-safety move as the checkpoint writer (temp + ``os.replace``):
    a watcher never reads a half-written heartbeat.  ``monotonic`` rides
    along so in-process readers can compute stall-safe ages; external
    watchers use mtime / ``time``.
    """
    payload = {
        "step": int(step),
        "time": time.time(),
        "monotonic": time.monotonic(),
        "pid": os.getpid(),
        "ckpt_path": ckpt_path,
        "stalled": bool(stalled),
    }
    # which slice member wrote this heartbeat: on a pod the orchestrator
    # watches one file per process and needs the mesh coordinates to
    # requeue the RIGHT slice, not just "a worker" (ISSUE 3 satellite)
    try:
        from apex_tpu.transformer import parallel_state

        if parallel_state.model_parallel_is_initialized():
            payload["rank_info"] = parallel_state.get_rank_info()
            payload["mesh"] = parallel_state.mesh_axis_sizes()
    except Exception as e:  # liveness probe must outlive rank plumbing
        logger.debug("heartbeat rank info unavailable: %s: %s",
                     type(e).__name__, e)
    # atomic_write_json embeds the thread ident in its temp name: the
    # monitor thread (stall marker) and the main thread (beat) share a
    # pid and may write concurrently — each needs its own temp file for
    # os.replace to stay atomic
    atomic_write_json(path, payload)
    return payload


def read_heartbeat(path: str) -> dict:
    """Parse a heartbeat file (the watcher side of :func:`write_heartbeat`)."""
    with open(path) as f:
        return json.load(f)


class StepWatchdog:
    """Per-step deadline on a monotonic clock, with a monitor thread.

    Synchronous contract: ``arm(step)`` at step start, ``disarm()`` at
    step end — ``disarm`` raises :class:`StepDeadlineExceeded` when the
    deadline was blown (the straggler case: the step *finished*, late).
    Asynchronous contract: ``start()`` spawns a daemon monitor thread
    that polls the armed step and, the moment a stall crosses the
    deadline, dumps diagnostics via a ``watchdog_stall`` event, marks
    the heartbeat file ``stalled``, and invokes ``on_stall`` (the hook
    for ``_thread.interrupt_main`` or an orchestrator RPC) — so a truly
    hung step still leaves evidence even though no Python thread can
    unwedge it.  ``arm``/``disarm`` are single attribute swaps (atomic
    under the GIL): the per-step overhead is nanoseconds, measured by
    bench.py's ``supervisor`` block.
    """

    def __init__(self, deadline_s: float, *,
                 heartbeat_path: Optional[str] = None,
                 timers=None,
                 poll_interval_s: Optional[float] = None,
                 on_stall: Optional[Callable[[dict], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        if deadline_s <= 0.0:
            raise ValueError(f"deadline_s must be positive, got {deadline_s}")
        if poll_interval_s is not None and poll_interval_s <= 0.0:
            raise ValueError(
                f"poll_interval_s must be positive, got {poll_interval_s}")
        self.deadline_s = deadline_s
        self.heartbeat_path = heartbeat_path
        self.timers = timers
        self.poll_interval_s = (poll_interval_s if poll_interval_s is not None
                                else min(max(deadline_s / 4.0, 0.01), 10.0))
        self.on_stall = on_stall
        self._clock = clock
        self._armed: Optional[Tuple[int, float]] = None  # (step, t0) swap
        self._stall: Optional[dict] = None  # monitor-observed diagnostics
        self._last_beat: Optional[Tuple[int, float]] = None
        self._last_ckpt_path: Optional[str] = None  # newest known checkpoint
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # scrape-time heartbeat age: the gauge binding is acquired at
        # start() — NOT here, where merely constructing a second
        # watchdog would steal the gauge from a healthy running one and
        # report the -1 sentinel (a false wedged-host signal)
        self._released = False
        self._prev_beat_age: Optional[Callable[[], float]] = None

    def _beat_age(self) -> float:
        # a released (stopped) watchdog reports the no-live-beat
        # sentinel, NEVER a frozen last beat aging without bound — even
        # if a misordered stop() chain hands the gauge back to it
        beat = self._last_beat if not self._released else None
        return self._clock() - beat[1] if beat is not None else -1.0

    # -- monitor lifecycle -------------------------------------------------

    def start(self) -> "StepWatchdog":
        """Spawn the monitor thread (idempotent).  Acquires (or
        re-acquires after a stop()) the process-default heartbeat-age
        gauge: the newest STARTED watchdog wins, the displaced binding
        is remembered so stop() can hand it back, and a reused
        supervisor's second run keeps its liveness probe."""
        self._released = False
        if _HEARTBEAT_AGE.bound_function() != self._beat_age:
            self._prev_beat_age = _HEARTBEAT_AGE.bound_function()
            _HEARTBEAT_AGE.set_function(self._beat_age)
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._monitor, name="apex-step-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        """Stop and join the monitor thread (idempotent).  Also hands
        the heartbeat-age gauge back IF still bound to this watchdog: a
        finished run must not keep reporting an ever-growing age (a
        false wedged-host signal) or pin this object alive through the
        gauge's bound-method reference — and a short-lived inner
        watchdog must not leave a still-running outer one unreported,
        so the binding this one displaced at construction is restored
        rather than cleared.  A newer watchdog's binding is left
        untouched."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(self.poll_interval_s * 4, 1.0))
            self._thread = None
        self._released = True
        if _HEARTBEAT_AGE.bound_function() == self._beat_age:
            _HEARTBEAT_AGE.set_function(self._prev_beat_age)
            if self._prev_beat_age is None:
                # keep the series PRESENT with the honest sentinel: an
                # alert on -1 must read a sample, not a vanished series
                _HEARTBEAT_AGE.set(-1.0)

    def __enter__(self) -> "StepWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- per-step bracket --------------------------------------------------

    def arm(self, step: int) -> None:
        """Start the deadline for ``step`` (one attribute swap)."""
        self._stall = None
        self._armed = (int(step), self._clock())

    def cancel(self) -> None:
        """Clear the armed step without a deadline check (use when the
        step body raised for an unrelated reason — don't double-report)."""
        self._armed = None
        self._stall = None

    def disarm(self) -> None:
        """End the armed step; raises :class:`StepDeadlineExceeded` when
        the step overran its deadline (or the monitor already saw it)."""
        armed, self._armed = self._armed, None
        if armed is None:
            raise RuntimeError("disarm() without a matching arm()")
        step, t0 = armed
        elapsed = self._clock() - t0
        stall = self._stall
        self._stall = None
        if stall is not None and stall.get("step") != step:
            # the monitor raced arm(): it observed the PREVIOUS step's
            # stall and stored it after arm() cleared the slot — that
            # step already raised at its own disarm; not this step's miss
            stall = None
        if stall is None and elapsed <= self.deadline_s:
            return
        diag = stall or self._diagnostics(step, elapsed)
        if stall is None:
            # the monitor did not get there first (tight deadline or no
            # thread running): this is the one report for the step
            emit_event("watchdog_stall", **diag)
        raise StepDeadlineExceeded(step, self.deadline_s, elapsed, diag)

    @contextlib.contextmanager
    def step(self, step: int):
        """``with watchdog.step(i): ...`` — arm/disarm bracket that does
        not double-fire when the body raises on its own."""
        self.arm(step)
        try:
            yield self
        except BaseException:
            self.cancel()
            raise
        self.disarm()

    # -- heartbeat ---------------------------------------------------------

    def beat(self, step: int, *, ckpt_path: Optional[str] = None) -> None:
        """Record liveness (and optionally the newest checkpoint path);
        rewrites the heartbeat file when one is configured.  The
        checkpoint path is *sticky*: a ``beat`` without one re-publishes
        the newest path seen, so the heartbeat's resume pointer survives
        the (majority of) steps that don't save.  A heartbeat write
        failure is logged, never fatal — losing the liveness probe must
        not kill the run the probe exists to protect."""
        self._last_beat = (int(step), self._clock())
        if ckpt_path is not None:
            self._last_ckpt_path = ckpt_path
        if self.heartbeat_path is None:
            return
        try:
            write_heartbeat(self.heartbeat_path, step,
                            ckpt_path=self._last_ckpt_path)
        except OSError as e:
            logger.warning("heartbeat write to %s failed: %s",
                           self.heartbeat_path, e)

    # -- diagnostics -------------------------------------------------------

    def _diagnostics(self, step: int, elapsed_s: float) -> dict:
        """The stall dump: everything a post-mortem needs that vanishes
        with the process."""
        beat_age = None
        if self._last_beat is not None:
            beat_age = round(self._clock() - self._last_beat[1], 3)
        diag = {
            "step": int(step),
            "deadline_s": self.deadline_s,
            "elapsed_s": round(elapsed_s, 3),
            "heartbeat_age_s": beat_age,
        }
        try:
            import jax

            diag["live_arrays"] = len(jax.live_arrays())
        except Exception as e:  # diagnostics must never mask the stall
            diag["live_arrays"] = f"unavailable: {type(e).__name__}"
        if self.timers is not None:
            try:
                diag["timers"] = self.timers.snapshot()
            except Exception as e:
                diag["timers"] = f"unavailable: {type(e).__name__}"
        return diag

    def _monitor(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            armed = self._armed
            if armed is None or self._stall is not None:
                continue
            step, t0 = armed
            elapsed = self._clock() - t0
            if elapsed <= self.deadline_s:
                continue
            diag = self._diagnostics(step, elapsed)
            # heartbeat BEFORE the event: anything watching the event
            # stream may react immediately and must find the stall marker
            if self.heartbeat_path is not None:
                try:
                    write_heartbeat(self.heartbeat_path, step,
                                    ckpt_path=self._last_ckpt_path,
                                    stalled=True)
                except OSError as e:
                    logger.warning("stall heartbeat write failed: %s", e)
            emit_event("watchdog_stall", **diag)
            self._stall = diag  # one report per armed step
            if self.on_stall is not None:
                self.on_stall(diag)


@dataclasses.dataclass(frozen=True)
class SupervisorConfig:
    """Escalation policy knobs.

    ``step_deadline_s`` bounds one step (watchdog).  ``checkpoint_every``
    is the periodic-save interval in steps (the final step always saves).
    ``max_consecutive_failures`` is the graceful-degradation trigger:
    that many *unrecovered* failures in a row write an emergency
    checkpoint and abort cleanly.  ``retry`` governs every host-I/O
    retry (data fetch, checkpoint save).
    ``consistency_check_interval`` runs the supervisor's
    :class:`~apex_tpu.resilience.consistency.ReplicaConsistency` pass
    every that many steps (0 disables); a desync the pass cannot repair
    escalates through the same failure ladder as every other
    unrecovered failure.

    ``async_save`` (default off — the sync path is the escape hatch and
    the bit-identical reference) moves periodic checkpoint writes onto a
    background thread: the step loop blocks only on the device→host
    snapshot, at most one write is in flight (backpressure blocks the
    *next* save, not the step), a failed write surfaces at the next step
    boundary into the same retry/escalation ladder, emergency
    checkpoints and shutdown join the in-flight write first, and a
    failed consistency pass vetoes an in-flight commit.  On-disk bytes
    and restores are identical to sync mode."""

    step_deadline_s: float = 1800.0
    poll_interval_s: Optional[float] = None
    max_consecutive_failures: int = 3
    checkpoint_every: int = 1
    consistency_check_interval: int = 0
    heartbeat_path: Optional[str] = None
    async_save: bool = False
    # bound on joining a wedged background writer at escalation/shutdown:
    # the graceful-degradation contract ("a wedged process is worse than
    # a lost checkpoint interval") must hold against the writer too —
    # past the bound the emergency save proceeds anyway (the live-writer
    # registry makes the two writers safe concurrently) and the daemon
    # writer dies with the process, its temp dir never committable
    async_join_timeout_s: float = 120.0
    retry: RetryPolicy = RetryPolicy()

    def __post_init__(self):
        if self.step_deadline_s <= 0.0:
            raise ValueError("step_deadline_s must be positive")
        if self.max_consecutive_failures < 1:
            raise ValueError("max_consecutive_failures must be >= 1")
        if self.checkpoint_every < 1:
            raise ValueError("checkpoint_every must be >= 1")
        if self.consistency_check_interval < 0:
            raise ValueError("consistency_check_interval must be >= 0")
        if self.async_join_timeout_s <= 0.0:
            raise ValueError("async_join_timeout_s must be positive")


class TrainingSupervisor:
    """Supervised host loop: watchdog + retry + skip budget + escalation.

    ``run(step_fn, state, batches, num_steps=...)`` drives
    ``step_fn(state, batch, step) -> state`` over ``batches`` (wrap them
    in a :class:`~apex_tpu.resilience.data_guard.GuardedIterator` for
    validation/skip semantics), with:

    - every batch fetch retried under ``config.retry`` (transient
      producer errors cost attempts, not the run);
    - every step bracketed by the watchdog;
    - a heartbeat + periodic validated checkpoint after each step;
    - a periodic cross-replica consistency pass (``consistency=`` a
      :class:`~apex_tpu.resilience.consistency.ReplicaConsistency`, run
      every ``config.consistency_check_interval`` steps *before* the
      checkpoint commit) — silent replica divergence is detected,
      localized, and resynced in place; an unrepairable desync counts
      as an unrecovered failure;
    - an escalating consecutive-failure counter over the supervisor's
      failure domain (:class:`StepDeadlineExceeded`,
      :class:`~apex_tpu.resilience.retry.RetryExhausted`,
      :class:`~apex_tpu.resilience.data_guard.SkipBudgetExceeded`,
      :class:`~apex_tpu.resilience.data_guard.DataStallError`,
      :class:`~apex_tpu.resilience.consistency.ReplicaDesyncError`) —
      any other exception is not the supervisor's to absorb and
      propagates.

    A slow-but-finished step keeps its result (the work is real) but
    counts as a failure; escalation therefore checkpoints the *newest*
    state, and a restart resumes bit-identically
    (``tests/test_supervisor.py`` acceptance run).
    """

    FAILURE_DOMAIN = (StepDeadlineExceeded, RetryExhausted,
                      SkipBudgetExceeded, DataStallError,
                      ReplicaDesyncError)

    def __init__(self, manager: Optional[CheckpointManager] = None,
                 config: SupervisorConfig = SupervisorConfig(), *,
                 consistency=None,
                 persist_transform: Optional[Callable[[Any], Any]] = None,
                 timers=None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.manager = manager
        self.config = config
        self.consistency = consistency
        self.persist_transform = persist_transform
        self.consecutive_failures = 0
        self._sleep = sleep
        # async pipeline: periodic saves become snapshot + background
        # write; the emergency path stays synchronous (it must be
        # durable before TrainingAborted is raised) but joins the
        # in-flight write first — one writer per root, always
        self._async = (AsyncCheckpointer(manager, retry=config.retry,
                                         sleep=sleep)
                       if config.async_save and manager is not None
                       else None)
        # step label of the newest checkpoint pointer published to the
        # heartbeat: the shutdown drain must never overwrite a NEWER
        # pointer (e.g. the emergency checkpoint escalate() just beat)
        # with an older async commit
        self._published_ckpt_step: Optional[int] = None
        # did escalate() already perform the bounded in-flight join?  the
        # finally drain must not pay a SECOND async_join_timeout_s on the
        # very wedged-writer path the bound exists for
        self._escalate_joined = False
        self.watchdog = StepWatchdog(
            config.step_deadline_s,
            heartbeat_path=config.heartbeat_path,
            timers=timers,
            poll_interval_s=config.poll_interval_s,
            clock=clock)

    # -- failure accounting / graceful degradation -------------------------

    def record_success(self) -> None:
        self.consecutive_failures = 0

    def record_failure(self, step: int, state: Any, exc: BaseException, *,
                       completed_step: Optional[int] = None) -> None:
        """Count one unrecovered failure; escalate at the threshold.

        ``completed_step`` is the step whose completion produced
        ``state`` and therefore labels any emergency checkpoint; it
        defaults to ``step``.  A fetch-time failure passes the PREVIOUS
        step here — ``state`` predates ``step``, and labeling the
        checkpoint ``step`` would make a resume at ``step + 1`` silently
        skip the step that never ran."""
        self.consecutive_failures += 1
        emit_event("supervisor_failure", step=int(step),
                   failure=type(exc).__name__, error=str(exc)[:500],
                   consecutive=self.consecutive_failures,
                   max_consecutive=self.config.max_consecutive_failures)
        if self.consecutive_failures >= self.config.max_consecutive_failures:
            self.escalate(step, state,
                          reason=f"{self.consecutive_failures} consecutive "
                                 f"failures (last: {type(exc).__name__})",
                          completed_step=completed_step)

    def escalate(self, step: int, state: Any, *, reason: str,
                 completed_step: Optional[int] = None) -> None:
        """Graceful degradation: emergency checkpoint, then clean abort.

        The checkpoint is written through the validated atomic machinery
        (with transient-I/O retries) and re-validated before the abort is
        raised; if even that fails, the abort still happens — carrying
        the error — because a wedged process is worse than a lost
        checkpoint interval.
        """
        ckpt_step = step if completed_step is None else completed_step
        path, path_step, ckpt_error = None, None, None
        if self._async is not None:
            # join the in-flight background write FIRST (bounded: a
            # writer wedged on dead storage must not block the abort
            # forever — the live-writer registry keeps a concurrent
            # emergency save safe): the emergency save must not race a
            # healthy writer for the root, and the newest committed
            # periodic path is a resume pointer worth carrying
            joined = self._async.wait(
                timeout=self.config.async_join_timeout_s)
            self._escalate_joined = True
            if joined is None and self._async.inflight is not None:
                logger.warning(
                    "background checkpoint write still running after "
                    "%.0fs at escalation — proceeding with the "
                    "emergency checkpoint", self.config.async_join_timeout_s)
            lc = self._async.last_committed  # one atomic (step, path) read
            if lc is not None:
                path_step, path = lc
        if self.manager is not None:
            fallback = (path, path_step)  # newest COMMITTED async pointer
            try:
                path = self._checkpoint(ckpt_step, state,
                                        what="emergency_checkpoint")
                validate_checkpoint(path)
                path_step = int(ckpt_step)
            except (RetryExhausted, CheckpointError, OSError) as e:
                ckpt_error = f"{type(e).__name__}: {e}"
                # never publish a pointer that just failed validation —
                # the abort carries the newest checkpoint known GOOD (or
                # None), plus the error explaining what was lost
                path, path_step = fallback
        emit_event("supervisor_abort", step=int(step), reason=reason,
                   checkpoint=path, checkpoint_error=ckpt_error)
        self.watchdog.beat(step, ckpt_path=path)
        if path is not None:
            self._note_published(path_step)
        raise TrainingAborted(reason, int(step), path)

    def _beat_if_newer(self, at_step: int) -> None:
        """Publish the async pipeline's newest committed checkpoint to
        the heartbeat iff it is newer than anything already published.
        ``at_step`` is the training step to label the beat with — the
        heartbeat's ``step`` field must never run backwards just because
        the checkpoint being published is older than the loop's last
        beat."""
        lc = self._async.last_committed  # one atomic (step, path) read
        if lc is None:
            return
        lc_step, lc_path = lc
        if (self._published_ckpt_step is not None
                and lc_step <= self._published_ckpt_step):
            return
        self.watchdog.beat(max(int(at_step), lc_step), ckpt_path=lc_path)
        self._note_published(lc_step)

    def _consume_async_result(self, done, step: int, state: Any) -> None:
        """THE harvest policy for one completed background write, shared
        by the step-boundary poll and the return drain: a failure in the
        supervisor's domain joins the ladder, a veto was deliberate and
        already accounted by its cause, anything else propagates exactly
        as a synchronous save error would.  Commits are published via
        ``last_committed``, never here."""
        if done is None or done.error is None:
            return
        if isinstance(done.error, self.FAILURE_DOMAIN):
            self.record_failure(step, state, done.error)
        elif not isinstance(done.error, SaveVetoed):
            raise done.error

    def _note_published(self, step: Optional[int]) -> None:
        """Record the step label of the newest checkpoint pointer beaten
        into the heartbeat — the guard that keeps the pointer monotonic
        (a late drain must not regress it to an older commit)."""
        if step is None:
            return
        if (self._published_ckpt_step is None
                or int(step) > self._published_ckpt_step):
            self._published_ckpt_step = int(step)

    # -- the supervised loop ----------------------------------------------

    def _next_batch(self, it) -> Any:
        return retry_transient(lambda: next(it), policy=self.config.retry,
                               what="data_fetch", sleep=self._sleep)

    def _checkpoint(self, step: int, state: Any, *,
                    what: str = "checkpoint_save") -> Optional[str]:
        """One retried save.  A manager constructed with its own
        ``retry`` policy already wraps ``save`` in ``retry_transient``
        (the documented recipe does exactly that) — defer to it rather
        than nesting two loops into ``max_attempts**2`` save attempts.

        ``persist_transform`` (when set) maps the live state to its
        persistable form first — the stacked-per-replica workflow passes
        :func:`~apex_tpu.resilience.consistency.collapse_replicas` here
        so every periodic AND emergency checkpoint stores the
        mesh-shape-free logical copy an elastic restart can reshard,
        never the dp-world-size-dependent stacked form."""
        if self.persist_transform is not None:
            state = self.persist_transform(state)
        if self._async is not None and what == "checkpoint_save":
            # periodic save under async_save: block on the snapshot only
            # and hand the write to the background thread.  Returns None
            # — the heartbeat's resume pointer advances when the commit
            # is harvested at a later step boundary, never before the
            # step dir is durably in place.  (The emergency path stays
            # synchronous: durability before TrainingAborted.)
            self._async.save(int(step), state)
            return None
        if self.manager.retry is not None:
            return self.manager.save(int(step), state)
        return retry_transient(
            lambda: self.manager.save(int(step), state),
            policy=self.config.retry, what=what,
            sleep=self._sleep)

    def run(self, step_fn: Callable[[Any, Any, int], Any], state: Any,
            batches: Iterable, *, num_steps: int,
            start_step: int = 0) -> Tuple[Any, int]:
        """Drive ``step_fn`` for steps ``[start_step, num_steps)``.

        Returns ``(state, last_completed_step)`` — ``start_step - 1``
        when no step completed (e.g. the iterator was empty).  Raises
        :class:`TrainingAborted` on escalation; exceptions outside the
        supervisor's failure domain propagate unchanged.
        """
        it = iter(batches)
        step = int(start_step)
        last_completed = step - 1
        self._escalate_joined = False
        # STICKY across steps: once a consistency pass fails, the state
        # stays untrusted (no commit, no failure-counter reset) until a
        # later pass proves it clean — steps BETWEEN interval checks
        # neither re-earn trust nor bury the standing divergence
        state_trusted = True
        self.watchdog.start()
        try:
            while step < num_steps:
                # ONE span per step attempt, covering fetch -> step -> commit:
                # fetch-retry and skip events stamp it, and the train_step +
                # checkpoint_save spans nest inside — the trace of a slow
                # step IS its causal story (docs recipe)
                with obs_trace.span("supervisor_step", step=step):
                    # -- fetch (retried; guard skips ride inside the iterator)
                    try:
                        batch = self._next_batch(it)
                    except StopIteration:
                        break
                    except self.FAILURE_DOMAIN as e:
                        # state predates `step` (its fetch failed): any
                        # emergency checkpoint must carry the completed label
                        self.record_failure(step, state, e,
                                            completed_step=last_completed)
                        continue  # re-attempt the same step number

                    # -- the step itself, under the deadline
                    self.watchdog.arm(step)
                    t_step = time.perf_counter()
                    try:
                        with obs_trace.span("train_step", step=step):
                            new_state = step_fn(state, batch, step)
                    except BaseException:
                        self.watchdog.cancel()  # not a deadline event
                        raise
                    # the step COMPLETED (possibly late): record its latency
                    # unconditionally — the p99 answer must include stragglers
                    _STEP_SECONDS.observe(time.perf_counter() - t_step)
                    _STEPS_TOTAL.inc()
                    step_ok = True
                    try:
                        self.watchdog.disarm()
                    except StepDeadlineExceeded as e:
                        # late but finished: keep the result, count the miss
                        step_ok = False
                        self.record_failure(step, new_state, e)  # may abort
                    state = new_state
                    last_completed = step

                    # -- cross-replica consistency, BEFORE the checkpoint
                    # commit: a desynced state must never be persisted, and a
                    # resynced repair is what the periodic save should carry
                    if (self.consistency is not None
                            and self.config.consistency_check_interval
                            and (step + 1)
                            % self.config.consistency_check_interval == 0):
                        try:
                            state = self.consistency.check(state, step=step)
                            state_trusted = True  # proven clean (or repaired)
                        except ReplicaDesyncError as e:
                            # unrepaired divergence: one unrecovered failure
                            # (escalates to emergency-checkpoint + abort at
                            # the threshold, like every other failure kind);
                            # commits are SKIPPED until a later pass proves
                            # the state clean — it must not become
                            # latest_valid_step and survive the restart
                            step_ok = False
                            state_trusted = False
                            if self._async is not None:
                                # an in-flight background write is from the
                                # same untrusted lineage — veto its commit
                                # before it can publish a step dir
                                self._async.veto(
                                    f"consistency failure at step {step}")
                            self.record_failure(step, state, e)
                    # the consecutive-failure counter resets only while the
                    # state is trusted — otherwise a desync that re-proves
                    # itself every interval would be buried by the
                    # intervening successful steps and never escalate
                    if step_ok and state_trusted:
                        self.record_success()

                    # -- commit host-side progress
                    ckpt_path = None
                    ckpt_path_step = step
                    if self._async is not None:
                        # harvest the background write that (maybe)
                        # finished since the last boundary: a failure
                        # joins the ladder exactly one step boundary
                        # after it died
                        self._consume_async_result(self._async.poll(),
                                                   step, state)
                        # the resume pointer is the newest COMMITTED
                        # path — lossless even when a backpressure join
                        # (not poll) consumed a success's future; one
                        # atomic (step, path) read so the published
                        # bookkeeping can never run ahead of the path
                        lc = self._async.last_committed
                        if lc is not None:
                            ckpt_path_step, ckpt_path = lc
                    if self.manager is not None and state_trusted and (
                            (step + 1) % self.config.checkpoint_every == 0
                            or step + 1 >= num_steps):
                        try:
                            path = self._checkpoint(step, state)
                            if path is not None:  # None: async, in flight
                                ckpt_path = path
                        except RetryExhausted as e:
                            self.record_failure(step, state, e)  # may abort
                    self.watchdog.beat(step, ckpt_path=ckpt_path)
                    if ckpt_path is not None:
                        self._note_published(ckpt_path_step)
                    step += 1
            # drain the final in-flight write BEFORE returning: the last
            # periodic save must be durable — or its failure visible —
            # when the caller moves on (bounded: a wedged writer must
            # not wedge the return; it dies with the process, its temp
            # dir never committable)
            if self._async is not None:
                done = self._async.wait(
                    timeout=self.config.async_join_timeout_s)
                self._consume_async_result(done, last_completed, state)
                self._beat_if_newer(last_completed)
            return state, last_completed
        finally:
            if self._async is not None:
                # exception paths must not abandon a nearly committed
                # write; the newest commit still reaches the resume
                # pointer before the watchdog stops — but never by
                # REGRESSING it, and never by paying a SECOND bounded
                # join when escalate() already performed one on a
                # wedged writer
                if not self._escalate_joined:
                    self._async.wait(
                        timeout=self.config.async_join_timeout_s)
                self._beat_if_newer(max(last_completed, 0))
            self.watchdog.stop()
