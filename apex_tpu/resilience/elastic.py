"""Elastic sharded checkpoints: save per-shard, restore onto ANY mesh.

PR 1's whole-tree checkpoints serialize every global leaf from one host
and restore assumes the identical mesh — fine on a workstation, wrong at
pod scale, where a preempted job restarts onto *whatever slice is
available* (PAPERS.md: "Scale MLPerf-0.6 models on Google TPU-v3 Pods";
"Exploring the limits of Concurrency in ML Training on Google TPUs").
This module makes the checkpoint itself mesh-shape-agnostic:

- **Save** writes one *shard record* per (leaf, mesh-coordinate block):
  the leaf's :class:`~jax.sharding.PartitionSpec` determines the block
  grid, and each record carries its mesh coordinates, its concrete index
  (start/stop per dim), and its own CRC32 — so one flipped byte is
  localized to one shard of one leaf, not "the checkpoint is bad".
- **Manifest v2** extends the v1 schema: ``format_version: 2``,
  ``sharded: true``, the saving mesh's shape / axis names / dp-tp-pp
  world sizes, and per-leaf entries that record the GLOBAL shape, dtype,
  partition spec, and the shard list.
- **Restore** reassembles each global leaf from its shard records
  (seek + read + CRC per shard, placed by the recorded index) and then
  re-shards it onto the *template's* sharding — which may live on a
  completely different mesh shape.  Saving on ``(dp=4, tp=2)`` and
  resuming on ``(dp=2, tp=4)`` or ``dp=8`` is the tested contract
  (``tests/test_elastic.py``), bit-identical by construction because the
  bytes never pass through arithmetic.

Everything else — atomic temp-dir + rename commit, orphan sweep,
keep-last-K rotation that never shrinks the recoverable set, the
newest-valid fallback walk with ``checkpoint_rejected`` events — is the
same machinery as :mod:`apex_tpu.resilience.checkpoint`, reused, not
re-implemented.  A root may mix v1 and v2 directories: the fallback walk
loads whichever format each candidate carries (a v1 candidate still
requires a matching mesh; only v2 reshards).

Replica semantics: leaves whose leading axis stacks per-``dp``-replica
copies (the :mod:`apex_tpu.resilience.consistency` representation) are
mesh-shape-*dependent* — collapse them to one logical copy with
:func:`~apex_tpu.resilience.consistency.collapse_replicas` before
saving, and re-expand after restore.  The docs/index.md "resize the pod
mid-training" recipe shows the full sequence.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import time
import zlib
from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu._logging import emit_event, get_logger
from apex_tpu.resilience.checkpoint import (
    _DATA,
    _FSYNC_INTERVAL_BYTES,
    _SHARDED_FORMAT_VERSION,
    CheckpointError,
    TreeSnapshot,
    _leaf_snapshots,
    _list_steps,
    _mesh_metadata,
    _observed,
    _read_manifest,
    _rotate,
    _step_dirname,
    _write_step_dir,
    snapshot_tree,
)
from apex_tpu.resilience.consistency import _entry_names, _infer_mesh
from apex_tpu.utils.serialization import (
    leaf_from_numpy,
    leaf_spec,
    np_dtype,
)

__all__ = [
    "ShardedCheckpointManager",
    "restore_sharded_checkpoint",
    "save_sharded_checkpoint",
    "snapshot_sharded_tree",
    "validate_sharded_checkpoint",
]

logger = get_logger("resilience.elastic")


# --------------------------------------------------------------------------
# partition-spec / shard-grid geometry
# --------------------------------------------------------------------------


def _spec_entries(spec, ndim: int) -> list[tuple[str, ...]]:
    """Normalize a PartitionSpec to ``ndim`` per-dim tuples of axis names
    (``()`` = replicated dim).  Accepts None (fully replicated), short
    specs (trailing dims replicated), str / tuple entries."""
    return [_entry_names(spec[d] if spec is not None and d < len(spec)
                         else None)
            for d in range(ndim)]


def _shard_grid(entries: Sequence[tuple[str, ...]], shape: Sequence[int],
                axis_sizes: dict, what: str):
    """Yield ``(coords, index)`` for every shard of one leaf.

    ``coords`` maps each partitioning mesh axis to its coordinate;
    ``index`` is ``[[start, stop], ...]`` per array dim.  Tuple spec
    entries split a dim major-to-minor in axis order, matching jax's
    ``NamedSharding`` layout.  Raises :class:`CheckpointError` when a
    dim is not evenly divisible by its axes' product — uneven (padded)
    shards have no stable byte layout to reshard from.
    """
    axes: list[str] = [a for entry in entries for a in entry]
    if len(set(axes)) != len(axes):
        # a repeated axis would collapse in the coords dict and emit
        # duplicate shard indices — an unrestorable checkpoint that save
        # must refuse to write, not validation discover later
        raise CheckpointError(
            f"{what}: spec uses a mesh axis more than once ({axes})")
    blocks = []  # per-dim block size
    for d, entry in enumerate(entries):
        n = 1
        for a in entry:
            if a not in axis_sizes:
                raise CheckpointError(
                    f"{what}: spec axis {a!r} is not a mesh axis "
                    f"(mesh has {sorted(axis_sizes)})")
            n *= axis_sizes[a]
        if n and shape[d] % n:
            raise CheckpointError(
                f"{what}: dim {d} of size {shape[d]} is not divisible by "
                f"its partitioning axes {entry} (product {n})")
        blocks.append(shape[d] // n if n else shape[d])
    for combo in itertools.product(
            *[range(axis_sizes[a]) for a in axes]):
        coords = dict(zip(axes, combo))
        index = []
        for d, entry in enumerate(entries):
            block = 0
            for a in entry:  # major-to-minor, NamedSharding order
                block = block * axis_sizes[a] + coords[a]
            start = block * blocks[d]
            index.append([start, start + blocks[d]])
        yield coords, index


def _mesh_axis_sizes(mesh: Optional[Mesh]) -> dict:
    return {} if mesh is None else {name: int(size)
                                    for name, size in mesh.shape.items()}


def _spec_json(entries: Sequence[tuple[str, ...]]) -> list:
    return [list(e) if e else None for e in entries]


# --------------------------------------------------------------------------
# save
# --------------------------------------------------------------------------


def _resolve_spec_overrides(leaves: list, specs: Any) -> None:
    """Fold an explicit ``specs`` pytree (PartitionSpecs / None entries)
    into the snapshot leaves' captured shardings, in place.  After this,
    the snapshot is self-contained: the writer never looks at the live
    tree again."""
    if specs is None:
        return
    spec_leaves = jax.tree.leaves(
        specs, is_leaf=lambda x: x is None or isinstance(x, P))
    if len(spec_leaves) != len(leaves):
        raise ValueError(
            f"specs has {len(spec_leaves)} leaves for a tree of "
            f"{len(leaves)} (pass a matching pytree of PartitionSpecs)")
    for snap, override in zip(leaves, spec_leaves):
        if override is not None:
            snap.spec = override


def snapshot_sharded_tree(tree: Any, *, mesh: Optional[Mesh] = None,
                          specs: Any = None) -> TreeSnapshot:
    """Host snapshot for a *sharded* save: owned leaf copies plus the
    shard-grid geometry (mesh axis sizes, per-leaf partition specs)
    captured NOW, from the live leaves — a background writer must not
    read shardings off device arrays the step loop has since donated."""
    if mesh is None:
        mesh = _infer_mesh(tree, required=False)
    axis_sizes = _mesh_axis_sizes(mesh)
    snap = snapshot_tree(tree,
                         mesh_meta=_mesh_metadata(axis_sizes or None))
    _resolve_spec_overrides(snap.leaves, specs)
    snap.axis_sizes = axis_sizes
    return snap


def _write_sharded_checkpoint(root: str, step: int, leaves: list, *,
                              axis_sizes: dict,
                              mesh_meta: Optional[dict],
                              keep: int,
                              t0: Optional[float] = None,
                              commit_gate=None,
                              progress_hook=None,
                              event_fields: Optional[dict] = None) -> str:
    """The v2 shard-grid serialize/CRC machinery over the shared
    ``checkpoint._write_step_dir`` scaffolding (sweep, live temp dir,
    vetoable commit, hard-kill cleanup — ONE implementation for both
    formats), fed from host snapshots and shared by the sync save and
    the background writer.  ``progress_hook`` fires per leaf record;
    shard records are fsynced incrementally."""
    t0 = time.monotonic() if t0 is None else t0

    def payload(f):
        records, offset, unsynced = [], 0, 0
        for i, snap in enumerate(leaves):
            arr = snap.array
            entries = _spec_entries(snap.spec, arr.ndim)
            shards = []
            for coords, index in _shard_grid(entries, arr.shape,
                                             axis_sizes, snap.path):
                block = arr[tuple(slice(lo, hi) for lo, hi in index)]
                data = np.ascontiguousarray(block).tobytes()
                shards.append({
                    "coords": coords,
                    "index": index,
                    "offset": offset,
                    "nbytes": len(data),
                    "crc32": zlib.crc32(data) & 0xFFFFFFFF,
                })
                f.write(data)
                offset += len(data)
                unsynced += len(data)
                if unsynced >= _FSYNC_INTERVAL_BYTES:
                    f.flush()
                    os.fsync(f.fileno())
                    unsynced = 0
            records.append({
                "path": snap.path,
                "shape": list(arr.shape),  # GLOBAL shape
                "dtype": arr.dtype.name,
                "prng_key": snap.prng_key,
                "spec": _spec_json(entries),
                "shards": shards,
            })
            if progress_hook is not None:
                progress_hook({"step": int(step), "record": i,
                               "path": snap.path, "bytes": offset})
        return records, offset

    final_dir, records, nbytes = _write_step_dir(
        root, step, payload,
        head_fields={"format_version": _SHARDED_FORMAT_VERSION,
                     "sharded": True},
        mesh_meta=mesh_meta, commit_gate=commit_gate)
    _rotate(root, keep, protect_step=int(step))
    emit_event("checkpoint_saved", step=int(step), bytes=nbytes,
               path=final_dir, sharded=True,
               n_shards=sum(len(r["shards"]) for r in records), t0=t0,
               **(event_fields or {}))
    return final_dir


@_observed("save")
def save_sharded_checkpoint(root: str, step: int, tree: Any, *,
                            mesh: Optional[Mesh] = None,
                            specs: Any = None,
                            keep: int = 3) -> str:
    """Write ``tree`` as the step-``step`` *sharded* checkpoint.

    Each leaf is cut into the shard grid its PartitionSpec implies on
    ``mesh`` (leaves' own ``NamedSharding`` specs by default; ``specs``
    — a matching pytree of PartitionSpecs, or None entries — overrides
    per leaf) and every shard gets its own manifest record + CRC.  The
    atomic-commit / orphan-sweep / rotation contract is identical to
    :func:`~apex_tpu.resilience.checkpoint.save_checkpoint`, including
    the single-writer root assumption.
    """
    t0 = time.monotonic()
    if mesh is None:
        mesh = _infer_mesh(tree, required=False)
    axis_sizes = _mesh_axis_sizes(mesh)
    leaves = _leaf_snapshots(tree, copy=False)
    _resolve_spec_overrides(leaves, specs)
    return _write_sharded_checkpoint(
        root, step, leaves, axis_sizes=axis_sizes,
        mesh_meta=_mesh_metadata(axis_sizes or None), keep=keep, t0=t0)


# --------------------------------------------------------------------------
# validate / restore
# --------------------------------------------------------------------------


def _read_shard(f, shard: dict, rec: dict, ckpt_dir: str) -> np.ndarray:
    """Seek/read/CRC-check ONE shard record; the sharded counterpart of
    checkpoint._read_record, with the same error discipline: defects the
    bytes can produce come back as :class:`CheckpointError`; an OSError
    on the open file is host I/O and propagates for the retry layer."""
    try:
        offset, nbytes = int(shard["offset"]), int(shard["nbytes"])
        if offset < 0 or nbytes < 0:
            raise ValueError(f"negative extent ({offset}, {nbytes})")
        index = [(int(lo), int(hi)) for lo, hi in shard["index"]]
        shape = [hi - lo for lo, hi in index]
        if any(lo < 0 or hi < lo or hi > g
               for (lo, hi), g in zip(index, rec["shape"])):
            raise ValueError(f"index {index} outside global "
                             f"shape {rec['shape']}")
        f.seek(offset)
        chunk = f.read(nbytes)
        if len(chunk) != nbytes:
            raise ValueError(f"short read ({len(chunk)} of {nbytes} bytes)")
        arr = np.frombuffer(chunk, dtype=np_dtype(rec["dtype"]))
        arr = arr.reshape(shape)
    except CheckpointError:
        raise
    except OSError:
        raise
    except Exception as e:
        raise CheckpointError(
            f"{ckpt_dir}: unusable shard {shard.get('coords')} of leaf "
            f"{rec.get('path', '?')!r}: {type(e).__name__}: {e}") from e
    if (zlib.crc32(chunk) & 0xFFFFFFFF) != shard.get("crc32"):
        raise CheckpointError(
            f"{ckpt_dir}: CRC mismatch on shard {shard.get('coords')} of "
            f"leaf {rec.get('path', '?')!r}")
    return arr


def _iter_shard_records(manifest: dict, ckpt_dir: str):
    for rec in manifest["leaves"]:
        if not isinstance(rec, dict) or not isinstance(
                rec.get("shards"), list):
            raise CheckpointError(
                f"{ckpt_dir}: leaf record "
                f"{rec.get('path', '?') if isinstance(rec, dict) else rec!r} "
                f"has no shard list")
        yield rec


def _check_tiling(rec: dict, ckpt_dir: str) -> None:
    """Prove one leaf's shard list tiles its GLOBAL shape exactly.

    Per dim, the distinct ``(start, stop)`` intervals must chain
    ``0..size`` with no gap or overlap, and the shard index set must be
    precisely their cross product.  Byte totals alone cannot prove this:
    a damaged-but-parsable manifest with overlapping indices (CRCs
    intact — they cover the data bytes, not the index semantics) would
    pass a size check while leaving regions of the reassembled leaf
    unwritten."""
    what = f"{ckpt_dir}: leaf {rec.get('path', '?')!r}"
    try:  # a parsable-but-damaged record must reject, not TypeError —
        # latest_valid_step / the fallback walk only skip CheckpointError
        shape = [int(n) for n in rec["shape"]]
        ndim = len(shape)
        if 0 in shape:
            return  # empty leaf: every shard is degenerate, none placed
        indices = {tuple((int(lo), int(hi)) for lo, hi in s["index"])
                   for s in rec["shards"]}
    except Exception as e:
        raise CheckpointError(
            f"{what}: unusable shape/shard index list: "
            f"{type(e).__name__}: {e}") from e
    if len(indices) != len(rec["shards"]):
        raise CheckpointError(f"{what}: duplicate shard indices")
    if any(len(ix) != ndim for ix in indices):
        raise CheckpointError(f"{what}: shard index rank != leaf rank")
    n_blocks = 1
    for d in range(ndim):
        ivs = sorted({ix[d] for ix in indices})
        if not (ivs and ivs[0][0] == 0 and ivs[-1][1] == shape[d]
                and all(a[1] == b[0] for a, b in zip(ivs, ivs[1:]))):
            raise CheckpointError(
                f"{what}: dim {d} shard intervals {ivs} do not tile "
                f"[0, {shape[d]}) (gap or overlap)")
        n_blocks *= len(ivs)
    # distinct tuples, each component drawn from its dim's interval set,
    # matching the grid's cardinality == exactly the cross product
    if len(indices) != n_blocks:
        raise CheckpointError(
            f"{what}: {len(indices)} shards do not fill the "
            f"{n_blocks}-block grid their per-dim intervals imply")


def _validate_shards(ckpt_dir: str, manifest: dict) -> None:
    """Tiling-check and CRC every shard of every leaf (the v2 body of
    checkpoint.validate_checkpoint, which dispatches here)."""
    with open(os.path.join(ckpt_dir, _DATA), "rb") as f:
        for rec in _iter_shard_records(manifest, ckpt_dir):
            _check_tiling(rec, ckpt_dir)
            for shard in rec["shards"]:
                _read_shard(f, shard, rec, ckpt_dir)


def validate_sharded_checkpoint(ckpt_dir: str) -> None:
    """Prove a sharded checkpoint directory internally consistent:
    manifest structure, payload size, and every per-shard CRC.  Raises
    :class:`CheckpointError` on any defect."""
    manifest = _read_manifest(ckpt_dir)
    if manifest.get("format_version") != _SHARDED_FORMAT_VERSION:
        raise CheckpointError(
            f"{ckpt_dir}: not a sharded checkpoint (format_version "
            f"{manifest.get('format_version')})")
    _validate_shards(ckpt_dir, manifest)


def _assemble_leaf(f, rec: dict, tmpl: Any, ckpt_dir: str) -> Any:
    """Reassemble ONE global leaf from its shard records and re-shard it
    onto the template's sharding.  Peak host memory is the global leaf
    plus one shard."""
    key = rec["path"]
    want_shape, want_dtype = leaf_spec(tmpl)
    if (list(want_shape) != rec.get("shape")
            or want_dtype.name != rec.get("dtype")):
        raise CheckpointError(
            f"{ckpt_dir}: leaf {key!r} is "
            f"{rec.get('dtype')}{rec.get('shape')}, template wants "
            f"{want_dtype.name}{list(want_shape)}")
    try:
        dtype = np_dtype(rec["dtype"])
        out = np.empty(rec["shape"], dtype=dtype)
    except Exception as e:
        raise CheckpointError(
            f"{ckpt_dir}: unusable leaf record {key!r}: "
            f"{type(e).__name__}: {e}") from e
    # an exact disjoint tiling is proven BEFORE any byte is placed —
    # np.empty regions a gappy/overlapping shard list would leave
    # unwritten must never reach the caller as heap garbage
    _check_tiling(rec, ckpt_dir)
    for shard in rec["shards"]:
        arr = _read_shard(f, shard, rec, ckpt_dir)
        index = tuple(slice(int(lo), int(hi)) for lo, hi in shard["index"])
        out[index] = arr
    return leaf_from_numpy(out, tmpl)


def _load_validated_sharded(ckpt_dir: str, like: Any) -> tuple[Any, int]:
    """Validate-and-load in one pass: every shard is CRC-checked as it
    is placed, and the template's shape/dtype/structure is enforced both
    ways — the same strictness as the whole-tree loader."""
    manifest = _read_manifest(ckpt_dir)
    if manifest.get("format_version") != _SHARDED_FORMAT_VERSION:
        # a v1 candidate in a mixed root: the whole-tree loader owns it
        # (including its matching-mesh requirement)
        from apex_tpu.resilience.checkpoint import _load_validated

        return _load_validated(ckpt_dir, like)
    by_path = {r["path"]: r
               for r in _iter_shard_records(manifest, ckpt_dir)
               if isinstance(r.get("path"), str)}

    flat, treedef = jax.tree_util.tree_flatten_with_path(like)
    leaves = []
    with open(os.path.join(ckpt_dir, _DATA), "rb") as f:
        for path, tmpl in flat:
            key = jax.tree_util.keystr(path)
            rec = by_path.get(key)
            if rec is None:
                raise CheckpointError(
                    f"{ckpt_dir}: checkpoint has no leaf {key!r} "
                    f"(template/checkpoint structure mismatch)")
            leaves.append(_assemble_leaf(f, rec, tmpl, ckpt_dir))
    extra = set(by_path) - {jax.tree_util.keystr(p) for p, _ in flat}
    if extra:
        raise CheckpointError(
            f"{ckpt_dir}: checkpoint has leaves the template does not: "
            f"{sorted(extra)[:5]}")
    return jax.tree_util.tree_unflatten(treedef, leaves), manifest["step"]


@_observed("restore")
def restore_sharded_checkpoint(root: str, like: Any, *,
                               step: Optional[int] = None
                               ) -> tuple[Any, int]:
    """Restore the newest *valid* checkpoint, resharding onto ``like``.

    Every leaf is reassembled from its shard records and re-sharded onto
    the corresponding template leaf's sharding — which may live on a
    different mesh shape than the one that saved (the elastic-restart
    contract).  Fallback semantics mirror
    :func:`~apex_tpu.resilience.checkpoint.restore_checkpoint`: invalid
    candidates are skipped with a ``checkpoint_rejected`` event, ``step``
    pins an exact step, and :class:`CheckpointError` is raised when
    nothing valid remains.  v1 (whole-tree) candidates in a mixed root
    restore through the v1 loader, which requires a matching mesh.
    """
    candidates = ([step] if step is not None
                  else list(reversed(_list_steps(root))))
    errors: list[str] = []
    for s in candidates:
        ckpt_dir = os.path.join(root, _step_dirname(s))
        t0 = time.monotonic()
        try:
            tree, got_step = _load_validated_sharded(ckpt_dir, like)
        except CheckpointError as e:
            errors.append(str(e))
            emit_event("checkpoint_rejected", step=int(s), reason=str(e))
            if step is not None:
                raise
            continue
        emit_event("checkpoint_restored", step=int(got_step),
                   fallback=bool(candidates[0] != s), sharded=True, t0=t0)
        return tree, got_step
    raise CheckpointError(
        f"no valid checkpoint under {root!r}"
        + (f"; rejected: {errors}" if errors else " (directory empty)"))


@dataclasses.dataclass
class ShardedCheckpointManager:
    """Keep-last-K manager over one *sharded* checkpoint root.

    Drop-in for :class:`~apex_tpu.resilience.checkpoint.CheckpointManager`
    (same ``save``/``restore``/``latest_valid_step`` surface, so it slots
    under :class:`~apex_tpu.resilience.supervisor.TrainingSupervisor`)
    with mesh-elastic restore: the ``like`` template's shardings decide
    the new layout.  When the training state is the STACKED per-replica
    form, give the supervisor
    ``persist_transform=``:func:`~apex_tpu.resilience.consistency.collapse_replicas`
    — stacked global shapes depend on the dp world size, and persisting
    them would defeat the elastic-restart contract.

    >>> mgr = ShardedCheckpointManager("/ckpts/run7", keep=3)
    >>> mgr.save(step, state)                      # mesh (dp=4, tp=2)
    >>> state, resume = mgr.restore(like=template) # template on (dp=2, tp=4)
    """

    root: str
    keep: int = 3
    mesh: Optional[Mesh] = None
    retry: Optional["RetryPolicy"] = None

    def _retrying(self, fn, what: str):
        if self.retry is None:
            return fn()
        from apex_tpu.resilience.retry import retry_transient

        return retry_transient(fn, policy=self.retry, what=what)

    def save(self, step: int, tree: Any, *, specs: Any = None) -> str:
        return self._retrying(
            lambda: save_sharded_checkpoint(self.root, step, tree,
                                            mesh=self.mesh, specs=specs,
                                            keep=self.keep),
            "sharded_checkpoint_save")

    # -- the async pipeline's two-phase surface (same contract as
    #    CheckpointManager.snapshot/write_snapshot) ------------------------

    def snapshot(self, tree: Any, *, specs: Any = None) -> TreeSnapshot:
        """Host snapshot incl. shard-grid geometry (blocking, fast,
        donation-safe)."""
        return snapshot_sharded_tree(tree, mesh=self.mesh, specs=specs)

    def write_snapshot(self, step: int, snapshot: TreeSnapshot, *,
                       commit_gate=None, progress_hook=None) -> str:
        """Serialize/commit a sharded :class:`TreeSnapshot` (the slow
        phase; safe on a background thread), under the manager's
        ``retry`` policy."""
        return self._retrying(
            lambda: _write_sharded_checkpoint(
                self.root, step, snapshot.leaves,
                axis_sizes=snapshot.axis_sizes or {},
                mesh_meta=snapshot.mesh, keep=self.keep,
                commit_gate=commit_gate, progress_hook=progress_hook,
                event_fields={"background": True}),
            "sharded_checkpoint_write")

    def restore(self, like: Any, *, step: Optional[int] = None):
        return self._retrying(
            lambda: restore_sharded_checkpoint(self.root, like, step=step),
            "sharded_checkpoint_restore")

    def all_steps(self) -> list[int]:
        return _list_steps(self.root)

    def latest_valid_step(self) -> Optional[int]:
        from apex_tpu.resilience.checkpoint import latest_valid_step

        return latest_valid_step(self.root)

    def checkpoint_path(self, step: int) -> str:
        return os.path.join(self.root, _step_dirname(step))
