"""Asynchronous checkpoint pipeline: snapshot on the hot path, write off it.

A periodic save of a 1.3B-param train state (bf16 params + moments,
~10 GB serialized with per-leaf CRC32 and fsync) stalls a synchronous
step loop for the full device→host + serialize + write wall time — at
pod scale checkpoint stalls are a first-order throughput term once
steps are fast (PAPERS.md: "Exploring the limits of Concurrency in ML
Training on Google TPUs").  This module splits the save in two:

1. **Snapshot** (:func:`~apex_tpu.resilience.checkpoint.snapshot_tree`,
   via the manager's ``snapshot``): ONE batched device→host copy into
   owned host buffers.  This is the only phase the step loop ever
   blocks on — donation-safe by construction, so the very next step may
   overwrite the live state while the writer is still serializing.
2. **Write** (a daemon writer thread running the manager's
   ``write_snapshot``): the EXISTING serialize/CRC/manifest/
   atomic-rename/rotation machinery — v1
   :class:`~apex_tpu.resilience.checkpoint.CheckpointManager` and v2
   :class:`~apex_tpu.resilience.elastic.ShardedCheckpointManager` both
   slot in — producing bytes **identical** to a synchronous save (the
   two paths share one writer function; tier-1 compares the files).

Correctness invariants, all pinned by tier-1:

- **At most one write in flight** per :class:`AsyncCheckpointer`.
  Backpressure blocks the *next* ``save()`` (which joins the previous
  write first, counting ``apex_checkpoint_backpressure_total``), never
  the step loop itself.
- **Crash-safe mid-write**: the writer streams into a ``tmp_*`` dir
  (fsynced incrementally) that ``latest_valid_step`` / the restore walk
  can never select; only the final atomic rename publishes the step.
- **Failures surface**: a failed background write is stored on its
  :class:`SaveFuture` and re-raised/harvested at the caller's next poll
  or join — the supervisor feeds it into the same retry/escalation
  ladder as a synchronous save failure.
- **Vetoable commit**: :meth:`AsyncCheckpointer.veto` aborts an
  in-flight write at its commit gate, *before* the atomic rename (the
  temp dir is cleaned up; the future completes with
  :class:`SaveVetoed`) — the hook a failed cross-replica consistency
  pass uses against the write already in the air.  The veto is honored
  up to the gate; a write already past it lands — exactly the
  synchronous-mode outcome for the previous boundary's save — and the
  caller's trust machinery blocks all NEW commits either way.
- **Joins on emergency/shutdown**: ``wait()`` drains the in-flight
  write so an emergency checkpoint never races the background writer
  for the single-writer root, and process exit never abandons a nearly
  committed checkpoint.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

from apex_tpu._logging import emit_event, get_logger
from apex_tpu.obs import metrics as obs_metrics
from apex_tpu.obs import trace as obs_trace
from apex_tpu.resilience.checkpoint import _CKPT_SECONDS, CheckpointError

__all__ = ["AsyncCheckpointer", "SaveFuture", "SaveVetoed"]

logger = get_logger("resilience.async_checkpoint")

_INFLIGHT = obs_metrics.gauge(
    "apex_checkpoint_inflight",
    "background checkpoint writes currently in flight (at most one per "
    "AsyncCheckpointer; counted inc/dec so concurrent pipelines sum)")
_BACKPRESSURE = obs_metrics.counter(
    "apex_checkpoint_backpressure_total",
    "async saves that had to join a still-running previous write before "
    "starting (the NEXT save blocks, never the step)")


class SaveVetoed(CheckpointError):
    """An in-flight background write was vetoed before its atomic
    rename (consistency failure, deliberate abort): no step directory
    was produced, the temp dir was cleaned up.  Deterministic — never
    retried (inherits ``transient = False``)."""


class SaveFuture:
    """Completion handle for one background write.

    ``done()`` / ``join()`` / ``result()`` are the consumption surface;
    ``path`` and ``error`` are set exactly once, before the internal
    event fires.  ``snapshot_s`` (the step-loop blocking cost) is
    stamped by the checkpointer; ``write_s`` by the writer thread.
    """

    def __init__(self, step: int):
        self.step = int(step)
        self.path: Optional[str] = None
        self.error: Optional[BaseException] = None
        self.snapshot_s: Optional[float] = None
        self.write_s: Optional[float] = None
        self._done = threading.Event()
        self._veto = threading.Event()
        self._veto_reason = ""

    def done(self) -> bool:
        return self._done.is_set()

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for the write to finish (success, failure, or veto);
        returns whether it did.  Never raises — read ``error``/``path``,
        or call :meth:`result` to raise."""
        return self._done.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> str:
        """The committed checkpoint path; raises the writer's error (or
        :class:`TimeoutError` if still in flight after ``timeout``)."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"background write of step {self.step} still in flight")
        if self.error is not None:
            raise self.error
        return self.path

    # -- writer side -------------------------------------------------------

    def _commit_gate(self) -> None:
        """Runs inside the write machinery, immediately before the
        atomic rename — the last point a veto can stop publication."""
        if self._veto.is_set():
            raise SaveVetoed(
                f"step {self.step} commit vetoed: {self._veto_reason}")

    def _finish(self, *, path: Optional[str] = None,
                error: Optional[BaseException] = None) -> None:
        self.path = path
        self.error = error
        self._done.set()


class AsyncCheckpointer:
    """Drive a checkpoint manager's two-phase save surface from a
    background writer thread, one save in flight at a time.

    ``manager`` is any object with the ``snapshot(tree, specs=None)`` /
    ``write_snapshot(step, snap, commit_gate=, progress_hook=)`` pair —
    both checkpoint managers qualify, so v1 whole-tree and v2 sharded
    roots get async saves (and the manager's ``retry`` policy) for free.
    ``retry`` is the fallback transient-I/O policy applied only when the
    manager carries none (the supervisor passes its ``config.retry``
    here — same no-nesting rule as the synchronous save path, so a
    transient blip surfaces as :class:`RetryExhausted` in both modes).
    ``progress_hook`` is forwarded to every write (fault injection /
    tests).

    >>> ac = AsyncCheckpointer(CheckpointManager("/ckpts/run7", keep=3))
    >>> fut = ac.save(step, state)        # blocks ~snapshot time only
    >>> ...                               # training continues
    >>> fut.join(); assert fut.error is None
    """

    def __init__(self, manager: Any, *,
                 retry: Any = None,
                 progress_hook: Optional[Callable[[dict], None]] = None,
                 sleep: Callable[[float], None] = time.sleep):
        if not hasattr(manager, "snapshot") or not hasattr(
                manager, "write_snapshot"):
            raise TypeError(
                f"{type(manager).__name__} has no snapshot/write_snapshot "
                f"surface — pass a CheckpointManager or "
                f"ShardedCheckpointManager")
        self.manager = manager
        self.retry = retry
        self.progress_hook = progress_hook
        self._sleep = sleep  # injectable: virtualized-clock runs must not
        # spin real backoff waits inside the writer thread
        self._lock = threading.Lock()
        self._future: Optional[SaveFuture] = None
        self._thread: Optional[threading.Thread] = None
        # newest commit, written by the writer thread WITHOUT the lock
        # (a plain GIL-atomic assignment: the writer must never contend
        # with a save() that is holding the lock while joining it) —
        # the lossless record a backpressure join cannot drop, so the
        # heartbeat's resume pointer advances even when write duration
        # persistently exceeds the checkpoint interval
        self._last_committed: Optional[tuple] = None  # (step, path)

    # -- state -------------------------------------------------------------

    @property
    def inflight(self) -> Optional[SaveFuture]:
        """The current future, completed or not (None before any save or
        after the last one was harvested)."""
        return self._future

    @property
    def last_committed(self) -> Optional[tuple]:
        """``(step, path)`` of the newest committed checkpoint this
        pipeline wrote (None before the first commit) — ONE atomic read,
        so callers never see a torn step/path pair from a commit landing
        mid-read.  Lossless under backpressure: a success whose future
        was consumed by the next ``save()``'s join still shows up here."""
        return self._last_committed

    @property
    def last_committed_path(self) -> Optional[str]:
        lc = self._last_committed
        return lc[1] if lc is not None else None

    @property
    def committed_step(self) -> Optional[int]:
        """Step of the newest committed checkpoint, or None — the
        single-field read a serving-side
        :class:`~apex_tpu.serving.reload.WeightWatcher` polls every
        scheduler step (same torn-pair-free atomic read as
        ``last_committed``)."""
        lc = self._last_committed
        return lc[0] if lc is not None else None

    def poll(self) -> Optional[SaveFuture]:
        """Non-blocking harvest: return and CLEAR the tracked future if
        its write has completed (else None).  The step-boundary call —
        a failed write surfaces here, one step after it died."""
        with self._lock:
            fut = self._future
            if fut is None or not fut.done():
                return None
            self._future = None
            self._join_thread()
            return fut

    def wait(self, timeout: Optional[float] = None) -> Optional[SaveFuture]:
        """Join the in-flight write (emergency-checkpoint / shutdown
        path) and harvest its future; None when nothing was in flight.
        Never raises on write failure — inspect ``error``."""
        with self._lock:
            fut = self._future
            if fut is None:
                return None
            if not fut.join(timeout):
                return None  # still running; future stays tracked
            self._future = None
            self._join_thread()
            return fut

    def veto(self, reason: str) -> bool:
        """Request that the in-flight write (if any) not commit.  Best
        effort by nature: the writer honors the veto at its commit gate,
        immediately before the atomic rename — a write already past the
        gate lands anyway, which is exactly the synchronous-mode outcome
        for a save scheduled at the previous boundary (the caller's
        trust machinery blocks NEW commits; a durably published
        checkpoint cannot be unpublished).  Returns True when the
        request was delivered to a write still in flight, False when
        nothing was in flight or it had already finished; certainty
        about the outcome requires joining the future."""
        with self._lock:
            fut = self._future
        if fut is None or fut.done():
            return False
        fut._veto_reason = str(reason)
        fut._veto.set()
        emit_event("checkpoint_commit_vetoed", step=fut.step,
                   reason=str(reason)[:500])
        # did the veto land before the gate?  join-free check: the writer
        # will observe the event at its gate; callers that need certainty
        # join the future.  Report optimistically only if not yet done.
        return True

    # -- the pipeline ------------------------------------------------------

    def save(self, step: int, tree: Any, *, specs: Any = None) -> SaveFuture:
        """Snapshot ``tree`` (blocking, fast) and hand the write to the
        background thread; returns the new :class:`SaveFuture`.

        Backpressure: at most one write in flight — a still-running
        previous write is JOINED first (counted in
        ``apex_checkpoint_backpressure_total``).  A previous write that
        *failed* and was never harvested surfaces here: its error is
        raised before any new snapshot is taken, exactly where a
        synchronous ``manager.save`` would have raised (a vetoed write
        is not a failure and is silently cleared; a successful one
        stays visible through ``last_committed_path``).
        """
        with self._lock:
            prev = self._future
            if prev is not None:
                if not prev.done():
                    _BACKPRESSURE.inc()
                    emit_event("checkpoint_backpressure", step=int(step),
                               blocked_on_step=prev.step)
                    prev.join()
                self._future = None
                self._join_thread()
                if prev.error is not None and not isinstance(
                        prev.error, SaveVetoed):
                    raise prev.error
            t0 = time.perf_counter()
            snapshot = self.manager.snapshot(tree, specs=specs)
            fut = SaveFuture(step)
            fut.snapshot_s = time.perf_counter() - t0
            self._future = fut
            # inc/dec (not absolute set): two pipelines over different
            # roots must sum, not clobber each other's reading
            _INFLIGHT.inc()
            self._thread = threading.Thread(
                target=self._write, args=(fut, snapshot),
                name=f"apex-ckpt-writer-{int(step)}", daemon=True)
            try:
                self._thread.start()
            except BaseException:
                _INFLIGHT.dec()
                self._future = None
                self._thread = None
                raise
            return fut

    def _write(self, fut: SaveFuture, snapshot: Any) -> None:
        t0 = time.perf_counter()

        def write_fn():
            return self.manager.write_snapshot(
                fut.step, snapshot,
                commit_gate=fut._commit_gate,
                progress_hook=self.progress_hook)

        try:
            with obs_trace.span("checkpoint_write", step=fut.step):
                if (self.retry is not None
                        and getattr(self.manager, "retry", None) is None):
                    from apex_tpu.resilience.retry import retry_transient

                    path = retry_transient(write_fn, policy=self.retry,
                                           what="checkpoint_write",
                                           sleep=self._sleep)
                else:
                    path = write_fn()
        except BaseException as e:
            fut.write_s = time.perf_counter() - t0
            if isinstance(e, SaveVetoed):
                logger.info("background write of step %d vetoed: %s",
                            fut.step, e)
            else:
                logger.warning(
                    "background checkpoint write of step %d failed: "
                    "%s: %s", fut.step, type(e).__name__, e)
            fut._finish(error=e)
        else:
            fut.write_s = time.perf_counter() - t0
            _CKPT_SECONDS.observe(fut.write_s, op="write")
            self._last_committed = (fut.step, path)  # before done fires
            fut._finish(path=path)
        finally:
            _INFLIGHT.dec()

    def _join_thread(self) -> None:
        # the future is already done; the thread has at most its final
        # bookkeeping left — reap it so harvested saves leave no zombie
        thread, self._thread = self._thread, None
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=5.0)
