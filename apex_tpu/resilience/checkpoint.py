"""Validated atomic checkpointing for arbitrary pytrees.

Design constraints come straight from pod-scale operation (PAPERS.md:
"Exploring the limits of Concurrency in ML Training on Google TPUs" —
preemption is routine, not exceptional):

- **Atomic**: a checkpoint is a directory written under a temp name and
  ``os.replace``-renamed into place, so a SIGTERM at any byte offset
  leaves either the previous checkpoint set or a complete new one —
  never a half-written latest.
- **Validated**: ``manifest.json`` records (path, shape, dtype, offset,
  nbytes, crc32) for every leaf plus the total payload size; restore
  proves a candidate good *before* touching any training state.
- **Self-healing**: ``restore`` walks checkpoints newest-first and falls
  back to the newest one that validates, so a corrupt or truncated
  latest (disk full, preempted writer on a non-atomic filesystem) costs
  one checkpoint interval, not the run.
- **Bounded**: keep-last-K rotation; rotation happens only after the new
  checkpoint is durably in place.

On-disk layout (one directory per step)::

    <root>/step_0000000042/manifest.json   # schema + per-leaf records
    <root>/step_0000000042/data.bin        # concatenated raw leaf bytes

The wire format is raw little-endian numpy bytes addressed by
``jax.tree_util.keystr`` paths — no pickle, so a checkpoint can be
audited (or partially salvaged) with nothing but the manifest and
``np.frombuffer``.  Restore requires a template pytree (``like``) with
the same structure: structure lives in code, data lives on disk — the
same split as the reference's README "Checkpointing" recipe, where
``amp.load_state_dict`` is called on a freshly constructed object.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import shutil
import tempfile
import threading
import time
import zlib
from typing import Any, Callable, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding

from apex_tpu._logging import emit_event, get_logger
from apex_tpu.obs import metrics as obs_metrics
from apex_tpu.obs import trace as obs_trace
from apex_tpu.utils.serialization import (
    is_prng_key,
    leaf_from_numpy,
    leaf_spec,
    np_dtype,
)

__all__ = [
    "CheckpointError",
    "CheckpointManager",
    "LeafSnapshot",
    "TreeSnapshot",
    "latest_valid_step",
    "restore_checkpoint",
    "save_checkpoint",
    "snapshot_tree",
    "validate_checkpoint",
]

logger = get_logger("resilience.checkpoint")

_FORMAT_VERSION = 1            # whole-tree manifests (this module)
_SHARDED_FORMAT_VERSION = 2    # per-shard manifests (resilience.elastic)
_KNOWN_VERSIONS = (_FORMAT_VERSION, _SHARDED_FORMAT_VERSION)
_STEP_PREFIX = "step_"
_TMP_PREFIX = "tmp_"
_MANIFEST = "manifest.json"
_DATA = "data.bin"

_CKPT_SECONDS = obs_metrics.histogram(
    "apex_checkpoint_duration_seconds",
    "checkpoint operation wall time by op (save/validate/restore)",
    ("op",))


def _observed(op: str):
    """Bracket a checkpoint entry point with a trace span and (on
    success only — failed-attempt latencies would poison percentiles)
    an ``apex_checkpoint_duration_seconds{op=...}`` observation."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            t0 = time.perf_counter()
            with obs_trace.span(f"checkpoint_{op}"):
                result = fn(*args, **kwargs)
            _CKPT_SECONDS.observe(time.perf_counter() - t0, op=op)
            return result
        return wrapper
    return deco


class CheckpointError(RuntimeError):
    """A checkpoint failed validation, or no valid checkpoint exists."""

    # deterministic by definition, and the message may embed wrapped I/O
    # error text (the rejected-candidates list) that would match
    # RetryPolicy.transient_markers — never retried (retry.is_transient)
    transient = False


def _step_dirname(step: int) -> str:
    return f"{_STEP_PREFIX}{step:010d}"


def _list_steps(root: str) -> list[int]:
    """Completed checkpoint steps under ``root``, ascending."""
    if not os.path.isdir(root):
        return []
    steps = []
    for name in os.listdir(root):
        if name.startswith(_STEP_PREFIX):
            try:
                steps.append(int(name[len(_STEP_PREFIX):]))
            except ValueError:
                continue
    return sorted(steps)


def _fsync_dir(path: str) -> None:
    """Durably record a directory entry (rename atomicity needs the parent
    flushed too); best-effort on filesystems without dir fsync."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _mesh_metadata(axis_sizes: Optional[dict] = None) -> Optional[dict]:
    """Mesh shape + world sizes as manifest ``mesh`` metadata — the ONE
    schema both formats stamp (v1 whole-tree and v2 sharded manifests),
    so the mismatched-mesh guard and elastic resharding read the same
    fields.  ``axis_sizes`` (``{axis: size}``) keys the record; when
    omitted it is read from the installed parallel_state mesh, and None
    is returned outside model-parallel runs."""
    if axis_sizes is None:
        try:
            from apex_tpu.transformer import parallel_state

            axis_sizes = parallel_state.mesh_axis_sizes()
        except Exception as e:  # stamping is metadata, never save-fatal
            logger.debug("mesh metadata unavailable: %s: %s",
                         type(e).__name__, e)
            return None
        if axis_sizes is None:
            return None
    world = 1
    for n in axis_sizes.values():
        world *= n
    return {"axes": axis_sizes, "axis_names": list(axis_sizes),
            "world": world, "dp": axis_sizes.get("dp", 1),
            "tp": axis_sizes.get("tp", 1), "pp": axis_sizes.get("pp", 1)}


# Live-writer registry: while a (possibly background) writer is producing
# a checkpoint, its temp dir must survive another save's orphan sweep and
# its target step must survive rotation — the async pipeline serializes
# saves through backpressure, but the emergency path and direct manager
# calls share the root, so the protection is enforced here, at the one
# place sweeping/rotation happen, not by caller discipline.
_WRITERS_LOCK = threading.Lock()
_ACTIVE_TMP_DIRS: set[str] = set()            # abs temp dirs being produced
_ACTIVE_STEPS: set[tuple[str, int]] = set()   # (abs root, step) in flight


@contextlib.contextmanager
def _live_writer(root: str, step: int):
    """Create this writer's temp dir and mark it live in ONE atomic
    action (under ``_WRITERS_LOCK``, so a concurrent save's sweep can
    never observe the dir unregistered), yield its path, and unregister
    on exit, crashed or not — a crashed writer's litter becomes
    sweepable the moment this exits."""
    key = (os.path.abspath(root), int(step))
    with _WRITERS_LOCK:
        tmp_dir = tempfile.mkdtemp(prefix=_TMP_PREFIX, dir=root)
        tmp_abs = os.path.abspath(tmp_dir)
        _ACTIVE_TMP_DIRS.add(tmp_abs)
        _ACTIVE_STEPS.add(key)
    try:
        yield tmp_dir
    finally:
        with _WRITERS_LOCK:
            _ACTIVE_TMP_DIRS.discard(tmp_abs)
            _ACTIVE_STEPS.discard(key)


def _sweep_tmp_dirs(root: str) -> None:
    """Reclaim ``tmp_*`` dirs orphaned by a hard kill mid-save — except
    the ones a live writer (e.g. an in-flight background save) is still
    producing.  Liveness is re-checked under the lock per dir: creation
    and registration are one atomic action in :func:`_live_writer`, so
    a listed-but-unregistered dir is genuinely orphaned.  The
    single-writer root contract still holds for *foreign* processes:
    only this process's live writers are known."""
    for name in os.listdir(root):
        if not name.startswith(_TMP_PREFIX):
            continue
        full = os.path.abspath(os.path.join(root, name))
        with _WRITERS_LOCK:
            if full in _ACTIVE_TMP_DIRS:
                continue
        shutil.rmtree(full, ignore_errors=True)


def _commit_step_dir(root: str, tmp_dir: str, final_dir: str) -> None:
    """Atomically install ``tmp_dir`` as ``final_dir``.

    Re-save of an existing step moves the old dir ASIDE (rename) rather
    than rmtree-ing it before the new one lands — a kill between the two
    renames loses at most the microsecond swap window instead of the
    whole serialization time; the aside copy is deleted only after the
    new checkpoint is in place, and restored if the install fails.
    """
    aside = None
    try:
        if os.path.exists(final_dir):
            aside = tmp_dir + ".old"
            # the aside name starts with tmp_ — register it as live
            # BEFORE the rename so a concurrent writer's orphan sweep
            # cannot reap the only copy of the old checkpoint mid-swap
            with _WRITERS_LOCK:
                _ACTIVE_TMP_DIRS.add(os.path.abspath(aside))
            os.rename(final_dir, aside)
        try:
            os.replace(tmp_dir, final_dir)
        except BaseException:
            if aside is not None and not os.path.exists(final_dir):
                os.rename(aside, final_dir)  # put the old checkpoint back
            raise
        _fsync_dir(root)
        if aside is not None:
            shutil.rmtree(aside, ignore_errors=True)
    finally:
        if aside is not None:
            with _WRITERS_LOCK:
                _ACTIVE_TMP_DIRS.discard(os.path.abspath(aside))


def _rotate(root: str, keep: int, protect_step: int) -> None:
    """Keep-last-``keep`` rotation, strictly after the new checkpoint is
    durable.  Two rules keep it from ever shrinking the recoverable set:
    ``protect_step`` (the just-written step) is never deleted — even when
    an undetected-corrupt newer dir occupies the keep window — and
    checkpoints that fail the cheap structural check (unreadable
    manifest / truncated payload) are dropped first rather than counted
    toward ``keep``."""
    if keep <= 0:
        return
    steps = _list_steps(root)
    sound = [s for s in steps
             if _quick_valid(os.path.join(root, _step_dirname(s)))]
    # keep-last-K counts only COMMITTED dirs (_list_steps never sees a
    # temp dir), and a step an in-flight background write is still
    # producing is never deleted — without this, an emergency save's
    # rotation could reap the dir the writer is about to commit onto
    with _WRITERS_LOCK:
        in_flight = {s for r, s in _ACTIVE_STEPS
                     if r == os.path.abspath(root)}
    retain = set(sound[-keep:]) | {int(protect_step)} | in_flight
    for old in steps:
        if old not in retain:
            shutil.rmtree(os.path.join(root, _step_dirname(old)),
                          ignore_errors=True)


# --------------------------------------------------------------------------
# snapshot (the only phase an async save ever blocks the step loop on)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class LeafSnapshot:
    """One leaf, captured on the host: keystr path, an owned (or, for
    the in-line sync path, borrowed) numpy array, the PRNG-key flag, and
    the leaf's :class:`~jax.sharding.NamedSharding` partition spec when
    it had one — everything a writer needs so that nothing about the
    LIVE training state is read after the snapshot returns."""

    path: str
    array: np.ndarray
    prng_key: bool = False
    spec: Any = None  # Optional[jax.sharding.PartitionSpec]


@dataclasses.dataclass
class TreeSnapshot:
    """A host-side copy of a whole pytree plus the metadata a background
    writer needs (mesh stamp, shard-grid axis sizes).  Produced by
    :func:`snapshot_tree` / the managers' ``snapshot`` methods; consumed
    by their ``write_snapshot`` methods (possibly on another thread)."""

    leaves: list
    mesh: Optional[dict] = None          # manifest "mesh" stamp
    axis_sizes: Optional[dict] = None    # shard grid (sharded saves only)

    @property
    def nbytes(self) -> int:
        return sum(leaf.array.nbytes for leaf in self.leaves)


def _may_alias_live_state(leaf: Any) -> bool:
    """Can ``device_get(leaf)`` hand back memory the training loop might
    mutate?  Accelerator-resident ``jax.Array``s DMA into a fresh owned
    host buffer (no aliasing); host-platform arrays may come back as a
    VIEW of the live buffer, and plain ndarray leaves come back as the
    caller's own object — those must be copied for donation safety."""
    if isinstance(leaf, jax.Array):
        try:
            return any(d.platform == "cpu" for d in leaf.devices())
        except Exception as e:  # conservative: unknown placement -> copy
            logger.debug("leaf placement probe failed (%s: %s) — copying",
                         type(e).__name__, e)
            return True
    return True


def _leaf_snapshots(tree: Any, *, copy: bool) -> list[LeafSnapshot]:
    """Flatten + ONE batched device→host transfer (typed PRNG keys
    unwrapped to raw key data).  ``copy=True`` guarantees owned host
    buffers: leaves whose transfer may alias live memory (see
    :func:`_may_alias_live_state`) get one extra host copy, so a donated
    buffer can never be overwritten by the next step while a background
    writer is still serializing it — while accelerator leaves stay a
    single device→host transfer (no doubled blocking cost)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    host = jax.device_get(
        [jax.random.key_data(l) if is_prng_key(l) else l for _, l in flat])
    out = []
    for (path, leaf), arr in zip(flat, host):
        arr = np.asarray(arr)
        if copy and _may_alias_live_state(leaf):
            arr = np.array(arr, copy=True)
        sharding = getattr(leaf, "sharding", None)
        spec = sharding.spec if isinstance(sharding, NamedSharding) else None
        out.append(LeafSnapshot(path=jax.tree_util.keystr(path), array=arr,
                                prng_key=is_prng_key(leaf), spec=spec))
    return out


_AUTO_MESH = object()  # sentinel: None is a valid (absent) mesh stamp


@_observed("snapshot")
def snapshot_tree(tree: Any, *, mesh_meta: Any = _AUTO_MESH) -> TreeSnapshot:
    """Snapshot ``tree`` to owned host memory — the fast, blocking phase
    of an asynchronous save (``apex_checkpoint_duration_seconds``
    ``{op="snapshot"}``).  Donation-safe: every leaf whose transfer
    could alias live memory is copied (accelerator leaves are already a
    fresh DMA — one transfer, not two), so the step loop may overwrite
    or donate the live state the moment this returns while a background
    writer serializes the snapshot.  ``mesh_meta``
    overrides the manifest mesh stamp (the sharded snapshot passes its
    axis-sizes-keyed record; default reads the installed parallel
    state)."""
    t0 = time.monotonic()
    leaves = _leaf_snapshots(tree, copy=True)
    snap = TreeSnapshot(
        leaves=leaves,
        mesh=_mesh_metadata() if mesh_meta is _AUTO_MESH else mesh_meta)
    emit_event("checkpoint_snapshot", bytes=snap.nbytes,
               n_leaves=len(leaves), t0=t0)
    return snap


# flush+fsync cadence for the payload stream: bounds dirty-page debt so
# the final fsync (and the host page cache) never owes the whole
# multi-GB payload at once — a background writer must not convert the
# step loop's savings into one giant I/O stall at commit time
_FSYNC_INTERVAL_BYTES = 64 * 2**20


def _write_step_dir(root: str, step: int, payload: Callable, *,
                    head_fields: dict,
                    mesh_meta: Optional[dict],
                    commit_gate: Optional[Callable[[], None]] = None,
                    ) -> tuple[str, list, int]:
    """The atomic-write scaffolding shared by BOTH formats (and by the
    sync and background callers of each): orphan sweep, live-claimed
    temp dir, payload streaming, fsynced manifest, vetoable commit,
    hard-kill-aware cleanup.  One implementation, so a fix to the
    crash/veto machinery cannot drift between v1 and v2.

    ``payload(f) -> (records, nbytes)`` streams the data file and
    returns the manifest leaf records; ``head_fields`` leads the
    manifest (``format_version``, v2's ``sharded`` flag) so the on-disk
    key order stays byte-identical to the historical writers.
    ``commit_gate`` (async pipeline) runs immediately before the atomic
    rename: raising there aborts the commit with the temp dir cleaned
    up — the consistency-veto hook.  An exception carrying
    ``preserve_partial_write=True`` (the simulated-hard-kill fault)
    leaves the partial temp dir on disk exactly as a SIGKILL would —
    never committable (temp names are invisible to ``_list_steps``),
    reclaimed by the next save's orphan sweep.  Returns
    ``(final_dir, records, nbytes)``; the caller rotates and emits its
    format's ``checkpoint_saved`` event.
    """
    os.makedirs(root, exist_ok=True)
    _sweep_tmp_dirs(root)
    final_dir = os.path.join(root, _step_dirname(step))
    with _live_writer(root, step) as tmp_dir:
        try:
            with open(os.path.join(tmp_dir, _DATA), "wb") as f:
                records, nbytes = payload(f)
                f.flush()
                os.fsync(f.fileno())
            manifest = {
                **head_fields,
                "step": int(step),
                "data_nbytes": nbytes,
                "mesh": mesh_meta,
                "leaves": records,
            }
            with open(os.path.join(tmp_dir, _MANIFEST), "w") as f:
                json.dump(manifest, f, indent=1)
                f.flush()
                os.fsync(f.fileno())
            if commit_gate is not None:
                commit_gate()
            _commit_step_dir(root, tmp_dir, final_dir)
        except BaseException as e:
            if not getattr(e, "preserve_partial_write", False):
                shutil.rmtree(tmp_dir, ignore_errors=True)
            raise
    return final_dir, records, nbytes


def _write_checkpoint(root: str, step: int, leaves: list[LeafSnapshot], *,
                      keep: int,
                      mesh_meta: Optional[dict],
                      t0: Optional[float] = None,
                      commit_gate: Optional[Callable[[], None]] = None,
                      progress_hook: Optional[Callable[[dict], None]] = None,
                      event_fields: Optional[dict] = None) -> str:
    """The v1 serialize/CRC machinery over :func:`_write_step_dir`, fed
    from host snapshots — shared verbatim by the sync save and the
    background writer, so the two paths cannot drift a byte.
    ``progress_hook`` fires after every leaf record (fault injection /
    tests)."""
    t0 = time.monotonic() if t0 is None else t0

    def payload(f):
        # stream leaves straight to disk (no second in-RAM bytes copy
        # of a potentially multi-GB state), offsets/CRCs as we go,
        # fsync incrementally so a crash mid-write leaves bounded
        # unsynced bytes in a dir that was never committable anyway
        records, offset, unsynced = [], 0, 0
        for i, snap in enumerate(leaves):
            arr = snap.array
            # ONE bytes copy per leaf: CRC and write share it.  (NB
            # shape is recorded from `arr`, not the contiguous copy —
            # ascontiguousarray promotes 0-d to 1-d.)
            data = np.ascontiguousarray(arr).tobytes()
            records.append({
                "path": snap.path,
                "shape": list(arr.shape),
                "dtype": arr.dtype.name,
                "prng_key": snap.prng_key,  # informational only
                "offset": offset,
                "nbytes": len(data),
                "crc32": zlib.crc32(data) & 0xFFFFFFFF,
            })
            f.write(data)
            offset += len(data)
            unsynced += len(data)
            if unsynced >= _FSYNC_INTERVAL_BYTES:
                f.flush()
                os.fsync(f.fileno())
                unsynced = 0
            if progress_hook is not None:
                progress_hook({"step": int(step), "record": i,
                               "path": snap.path, "bytes": offset})
        return records, offset

    final_dir, _, nbytes = _write_step_dir(
        root, step, payload,
        head_fields={"format_version": _FORMAT_VERSION},
        mesh_meta=mesh_meta, commit_gate=commit_gate)
    _rotate(root, keep, protect_step=int(step))
    emit_event("checkpoint_saved", step=int(step), bytes=nbytes,
               path=final_dir, t0=t0, **(event_fields or {}))
    return final_dir


@_observed("save")
def save_checkpoint(root: str, step: int, tree: Any, *, keep: int = 3) -> str:
    """Write ``tree`` as the step-``step`` checkpoint; returns its path.

    Write order is the crash-safety argument: (1) leaves + manifest into a
    temp directory, fsynced; (2) one atomic ``os.replace`` onto the final
    name; (3) only then rotate old checkpoints down to ``keep``.  A kill
    between any two of these leaves a restorable set on disk.

    ``root`` must have a SINGLE writer: the orphan sweep below reclaims
    every ``tmp_*`` dir this process is not actively producing, so a
    concurrent foreign saver's in-progress temp dir would be deleted out
    from under it.  In multi-controller runs gate the save on
    ``jax.process_index() == 0`` or give each process its own root.
    In-process, :class:`~apex_tpu.resilience.async_checkpoint.AsyncCheckpointer`
    serializes background writes against this path by construction.
    """
    t0 = time.monotonic()
    leaves = _leaf_snapshots(tree, copy=False)
    return _write_checkpoint(root, step, leaves, keep=keep,
                             mesh_meta=_mesh_metadata(), t0=t0)


def _read_manifest(ckpt_dir: str) -> dict:
    """Manifest + structural checks (readable, right version, payload size
    matches — catches truncation and half-writes without touching data).

    Defensive throughout: bit corruption can hit the MANIFEST as easily as
    the payload, and a corrupt-but-parsable manifest must surface as
    :class:`CheckpointError` (so the restore walk falls back) rather than
    a stray KeyError/TypeError that aborts the walk.
    """
    manifest_path = os.path.join(ckpt_dir, _MANIFEST)
    data_path = os.path.join(ckpt_dir, _DATA)
    # ANY OSError here — missing, PermissionError after an orchestrator
    # restart — rejects the candidate so the fallback walk continues to an
    # older step: the manifest probe decides "is this a usable checkpoint",
    # unlike _read_record's mid-payload reads where an OSError on an open
    # file is environmental and propagates for the manager's retry.
    # UnicodeDecodeError: json.load on bit-flipped manifest bytes.
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        raise CheckpointError(f"{ckpt_dir}: unreadable manifest: {e}") from e
    if not isinstance(manifest, dict) or not isinstance(
            manifest.get("leaves"), list):
        raise CheckpointError(f"{ckpt_dir}: manifest is not a leaf listing")
    if not isinstance(manifest.get("step"), int):
        raise CheckpointError(
            f"{ckpt_dir}: manifest step {manifest.get('step')!r} "
            f"is not an integer")
    if manifest.get("format_version") not in _KNOWN_VERSIONS:
        raise CheckpointError(
            f"{ckpt_dir}: format_version {manifest.get('format_version')} "
            f"not in {_KNOWN_VERSIONS}")
    try:
        actual = os.path.getsize(data_path)
    except OSError as e:
        raise CheckpointError(f"{ckpt_dir}: missing data.bin: {e}") from e
    if actual != manifest.get("data_nbytes"):
        raise CheckpointError(
            f"{ckpt_dir}: data.bin is {actual} bytes, manifest says "
            f"{manifest.get('data_nbytes')} (truncated or overgrown)")
    return manifest


def _read_record(f, rec: dict, ckpt_dir: str) -> np.ndarray:
    """Seek/read/CRC-check ONE manifest record; the single shared reader
    under both :func:`validate_checkpoint` and :func:`_load_validated`.
    Any defect a corrupted record can produce — bad offsets, nbytes not a
    dtype multiple, unknown dtype name, shape/size mismatch, CRC failure —
    comes back as :class:`CheckpointError`."""
    try:
        offset, nbytes = int(rec["offset"]), int(rec["nbytes"])
        if offset < 0 or nbytes < 0:
            raise ValueError(f"negative extent ({offset}, {nbytes})")
        f.seek(offset)
        chunk = f.read(nbytes)
        if len(chunk) != nbytes:
            raise ValueError(f"short read ({len(chunk)} of {nbytes} bytes)")
        arr = np.frombuffer(chunk, dtype=np_dtype(rec["dtype"]))
        arr = arr.reshape(rec["shape"])
    except CheckpointError:
        raise
    except OSError:
        # seek/read failure on an OPEN file is host I/O (a blipping
        # network filesystem), not evidence about the checkpoint's bytes —
        # propagate unwrapped so CheckpointManager's RetryPolicy engages
        # instead of the fallback walk silently resuming an older step
        raise
    except Exception as e:  # corrupt record metadata, not a code path bug
        raise CheckpointError(
            f"{ckpt_dir}: unusable leaf record "
            f"{rec.get('path', '?')!r}: {type(e).__name__}: {e}") from e
    # CRC the bytes as read — the file bytes ARE the contiguous form the
    # manifest CRC was computed from, so this avoids leaf_crc32's tobytes()
    # copy (a second transient per-leaf allocation on multi-GB restores)
    if (zlib.crc32(chunk) & 0xFFFFFFFF) != rec.get("crc32"):
        raise CheckpointError(
            f"{ckpt_dir}: CRC mismatch on leaf {rec.get('path', '?')!r}")
    return arr


def _quick_valid(ckpt_dir: str) -> bool:
    """Cheap structural validity (no CRC pass) — the rotation-time check."""
    try:
        _read_manifest(ckpt_dir)
        return True
    except CheckpointError:
        return False


@_observed("validate")
def validate_checkpoint(ckpt_dir: str) -> None:
    """Prove a checkpoint directory internally consistent.

    Raises :class:`CheckpointError` on any defect: missing/unparsable
    manifest, wrong format version, payload size mismatch (truncation),
    or any per-leaf CRC mismatch (bit corruption).
    """
    manifest = _read_manifest(ckpt_dir)
    if manifest.get("format_version") == _SHARDED_FORMAT_VERSION:
        # v2 (sharded) dirs validate shard-by-shard; dispatching here
        # keeps latest_valid_step / rotation / the supervisor's
        # emergency-checkpoint validation working over mixed roots
        from apex_tpu.resilience.elastic import _validate_shards

        _validate_shards(ckpt_dir, manifest)
        return
    with open(os.path.join(ckpt_dir, _DATA), "rb") as f:
        for rec in manifest["leaves"]:
            _read_record(f, rec, ckpt_dir)


def _load_validated(ckpt_dir: str, like: Any) -> tuple[Any, int]:
    """Validate-and-load in ONE pass over the payload: structural checks
    up front, then each leaf streamed (seek+read per manifest record, so
    peak host memory is one leaf, not the whole payload) and CRC-verified
    before it is materialized — no leaf reaches the caller without its
    CRC having passed, and restore never re-reads a multi-GB data.bin
    just to prove it good first."""
    manifest = _read_manifest(ckpt_dir)
    if manifest.get("format_version") == _SHARDED_FORMAT_VERSION:
        raise CheckpointError(
            f"{ckpt_dir}: sharded (format v2) checkpoint — restore it "
            f"through apex_tpu.resilience.elastic.restore_sharded_checkpoint"
            f", which reassembles shard records and reshards onto the "
            f"current mesh")
    saved_mesh = manifest.get("mesh")
    cur_mesh = _mesh_metadata()
    if (isinstance(saved_mesh, dict) and cur_mesh is not None
            and saved_mesh.get("axes") != cur_mesh["axes"]):
        # a v1 checkpoint is one whole-tree byte stream with no shard
        # records: restoring it onto a different mesh shape would hand
        # every template leaf the OLD global bytes and silently reshard
        # them wrong.  Elastic restarts need the v2 sharded format.
        raise CheckpointError(
            f"{ckpt_dir}: whole-tree (v1) checkpoint was saved on mesh "
            f"{saved_mesh.get('axes')} but the current mesh is "
            f"{cur_mesh['axes']} — v1 checkpoints cannot reshard; save "
            f"sharded checkpoints (resilience.elastic) to resume on a "
            f"different mesh shape")
    by_path = {r.get("path"): r for r in manifest["leaves"]
               if isinstance(r, dict)}

    flat, treedef = jax.tree_util.tree_flatten_with_path(like)
    leaves = []
    with open(os.path.join(ckpt_dir, _DATA), "rb") as f:
        for path, tmpl in flat:
            key = jax.tree_util.keystr(path)
            rec = by_path.get(key)
            if rec is None:
                raise CheckpointError(
                    f"{ckpt_dir}: checkpoint has no leaf {key!r} "
                    f"(template/checkpoint structure mismatch)")
            # spec check without device_get-ing the live template state
            want_shape, want_dtype = leaf_spec(tmpl)
            if (list(want_shape) != rec.get("shape")
                    or want_dtype.name != rec.get("dtype")):
                raise CheckpointError(
                    f"{ckpt_dir}: leaf {key!r} is "
                    f"{rec.get('dtype')}{rec.get('shape')}, template wants "
                    f"{want_dtype.name}{list(want_shape)}")
            leaves.append(leaf_from_numpy(_read_record(f, rec, ckpt_dir),
                                          tmpl))
    # strict BOTH ways: checkpoint leaves the template does not expect
    # mean structure drift, and a silent partial restore is the failure
    # mode this subsystem exists to prevent
    extra = set(by_path) - {jax.tree_util.keystr(p) for p, _ in flat}
    if extra:
        raise CheckpointError(
            f"{ckpt_dir}: checkpoint has leaves the template does not: "
            f"{sorted(extra)[:5]}")
    return jax.tree_util.tree_unflatten(treedef, leaves), manifest["step"]


def in_flight_steps(root: str) -> set:
    """Steps an in-process writer is producing under ``root`` RIGHT NOW
    (the live-writer registry's view).  A reader walking the root —
    ``latest_valid_step``, the serving reload watcher — must skip
    these: a re-save of an existing step swaps the old dir aside before
    the new one lands, so the committed dir a concurrent reader sees
    for an in-flight step can vanish mid-read.  Steps a FOREIGN process
    is writing are invisible here (single-writer-root contract); their
    commits are atomic renames, so a reader only ever sees them whole.
    """
    root_abs = os.path.abspath(root)
    with _WRITERS_LOCK:
        return {s for r, s in _ACTIVE_STEPS if r == root_abs}


def latest_valid_step(root: str) -> Optional[int]:
    """Newest step whose checkpoint passes validation, or None.

    Race-hardened against a live writer sharing the root: steps the
    live-writer registry marks in flight (an ``AsyncCheckpointer``
    mid-commit) are skipped rather than half-read, and a step dir that
    vanishes mid-validation (rotation, or a re-save's aside swap) is
    treated as invalid-and-skipped instead of aborting the walk with a
    stray ``FileNotFoundError``."""
    live = in_flight_steps(root)
    for step in reversed(_list_steps(root)):
        if step in live:
            continue
        step_dir = os.path.join(root, _step_dirname(step))
        try:
            validate_checkpoint(step_dir)
            return step
        except CheckpointError:
            continue
        except OSError:
            if os.path.isdir(step_dir):
                raise          # environmental I/O error: genuinely fatal
            continue           # dir vanished under the walk: fall back
    return None


@_observed("restore")
def restore_checkpoint(root: str, like: Any, *,
                       step: Optional[int] = None) -> tuple[Any, int]:
    """Restore the newest *valid* checkpoint into ``like``'s structure.

    Returns ``(tree, step)``.  Invalid candidates (corrupt, truncated, or
    structurally incompatible with ``like``) are skipped with a logged
    ``checkpoint_rejected`` event and the walk continues to older steps —
    the automatic-fallback contract.  ``step`` pins an exact step instead
    (no fallback).  Raises :class:`CheckpointError` when nothing valid
    remains.
    """
    candidates = ([step] if step is not None
                  else list(reversed(_list_steps(root))))
    errors: list[str] = []
    for s in candidates:
        ckpt_dir = os.path.join(root, _step_dirname(s))
        t0 = time.monotonic()
        try:
            # validation is fused into the load (structural checks, then
            # per-leaf CRC as each chunk is sliced) — one payload pass
            tree, got_step = _load_validated(ckpt_dir, like)
        except CheckpointError as e:
            errors.append(str(e))
            emit_event("checkpoint_rejected", step=int(s), reason=str(e))
            if step is not None:
                raise
            continue
        emit_event("checkpoint_restored", step=int(got_step),
                   fallback=bool(candidates[0] != s), t0=t0)
        return tree, got_step
    raise CheckpointError(
        f"no valid checkpoint under {root!r}"
        + (f"; rejected: {errors}" if errors else " (directory empty)"))


@dataclasses.dataclass
class CheckpointManager:
    """Keep-last-K manager over one checkpoint root.

    ``retry`` (a :class:`~apex_tpu.resilience.retry.RetryPolicy`) makes
    save/restore survive *transient* host I/O errors — a blipping
    network filesystem, a busy disk.  Safe to retry by construction:
    the save path sweeps its own temp litter and commits by atomic
    rename (re-running is idempotent), and on restore only the
    transient class is retried — a :class:`CheckpointError` is
    deterministic (corrupt bytes stay corrupt) and propagates at once
    so the newest-valid fallback walk proceeds instead of stalling.

    >>> mgr = CheckpointManager("/ckpts/run7", keep=3)
    >>> mgr.save(step, {"params": params, "opt": opt_state,
    ...                 "scaler": sstate, "rng": rng_key,
    ...                 "step": jnp.int32(step)})
    >>> state, resume_step = mgr.restore(like=template)   # newest valid
    """

    root: str
    keep: int = 3
    retry: Optional["RetryPolicy"] = None

    def _retrying(self, fn, what: str):
        if self.retry is None:
            return fn()
        from apex_tpu.resilience.retry import retry_transient

        return retry_transient(fn, policy=self.retry, what=what)

    def save(self, step: int, tree: Any) -> str:
        return self._retrying(
            lambda: save_checkpoint(self.root, step, tree, keep=self.keep),
            "checkpoint_save")

    # -- the async pipeline's two-phase surface ---------------------------
    # (apex_tpu.resilience.async_checkpoint calls snapshot() on the step
    # loop's thread and write_snapshot() on the writer thread; together
    # they produce the EXACT bytes save() would — same machinery)

    def snapshot(self, tree: Any, *, specs: Any = None) -> TreeSnapshot:
        """Host snapshot of ``tree`` (blocking, fast, donation-safe).
        ``specs`` is accepted for drop-in symmetry with the sharded
        manager and must be None here."""
        if specs is not None:
            raise ValueError(
                "CheckpointManager.snapshot takes no partition specs — "
                "use ShardedCheckpointManager for sharded saves")
        return snapshot_tree(tree)

    def write_snapshot(self, step: int, snapshot: TreeSnapshot, *,
                       commit_gate: Optional[Callable[[], None]] = None,
                       progress_hook: Optional[Callable[[dict], None]] = None,
                       ) -> str:
        """Serialize/commit a :class:`TreeSnapshot` (the slow phase; safe
        to run on a background thread).  Applies the manager's ``retry``
        policy exactly as :meth:`save` does."""
        return self._retrying(
            lambda: _write_checkpoint(
                self.root, step, snapshot.leaves, keep=self.keep,
                mesh_meta=snapshot.mesh, commit_gate=commit_gate,
                progress_hook=progress_hook,
                event_fields={"background": True}),
            "checkpoint_write")

    def restore(self, like: Any, *, step: Optional[int] = None):
        return self._retrying(
            lambda: restore_checkpoint(self.root, like, step=step),
            "checkpoint_restore")

    def all_steps(self) -> list[int]:
        return _list_steps(self.root)

    def latest_valid_step(self) -> Optional[int]:
        return latest_valid_step(self.root)

    def checkpoint_path(self, step: int) -> str:
        return os.path.join(self.root, _step_dirname(step))
