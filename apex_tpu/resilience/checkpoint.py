"""Validated atomic checkpointing for arbitrary pytrees.

Design constraints come straight from pod-scale operation (PAPERS.md:
"Exploring the limits of Concurrency in ML Training on Google TPUs" —
preemption is routine, not exceptional):

- **Atomic**: a checkpoint is a directory written under a temp name and
  ``os.replace``-renamed into place, so a SIGTERM at any byte offset
  leaves either the previous checkpoint set or a complete new one —
  never a half-written latest.
- **Validated**: ``manifest.json`` records (path, shape, dtype, offset,
  nbytes, crc32) for every leaf plus the total payload size; restore
  proves a candidate good *before* touching any training state.
- **Self-healing**: ``restore`` walks checkpoints newest-first and falls
  back to the newest one that validates, so a corrupt or truncated
  latest (disk full, preempted writer on a non-atomic filesystem) costs
  one checkpoint interval, not the run.
- **Bounded**: keep-last-K rotation; rotation happens only after the new
  checkpoint is durably in place.

On-disk layout (one directory per step)::

    <root>/step_0000000042/manifest.json   # schema + per-leaf records
    <root>/step_0000000042/data.bin        # concatenated raw leaf bytes

The wire format is raw little-endian numpy bytes addressed by
``jax.tree_util.keystr`` paths — no pickle, so a checkpoint can be
audited (or partially salvaged) with nothing but the manifest and
``np.frombuffer``.  Restore requires a template pytree (``like``) with
the same structure: structure lives in code, data lives on disk — the
same split as the reference's README "Checkpointing" recipe, where
``amp.load_state_dict`` is called on a freshly constructed object.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import tempfile
import time
import zlib
from typing import Any, Optional

import jax
import numpy as np

from apex_tpu._logging import emit_event, get_logger
from apex_tpu.obs import metrics as obs_metrics
from apex_tpu.obs import trace as obs_trace
from apex_tpu.utils.serialization import (
    is_prng_key,
    leaf_from_numpy,
    leaf_spec,
    np_dtype,
)

__all__ = [
    "CheckpointError",
    "CheckpointManager",
    "latest_valid_step",
    "restore_checkpoint",
    "save_checkpoint",
    "validate_checkpoint",
]

logger = get_logger("resilience.checkpoint")

_FORMAT_VERSION = 1            # whole-tree manifests (this module)
_SHARDED_FORMAT_VERSION = 2    # per-shard manifests (resilience.elastic)
_KNOWN_VERSIONS = (_FORMAT_VERSION, _SHARDED_FORMAT_VERSION)
_STEP_PREFIX = "step_"
_TMP_PREFIX = "tmp_"
_MANIFEST = "manifest.json"
_DATA = "data.bin"

_CKPT_SECONDS = obs_metrics.histogram(
    "apex_checkpoint_duration_seconds",
    "checkpoint operation wall time by op (save/validate/restore)",
    ("op",))


def _observed(op: str):
    """Bracket a checkpoint entry point with a trace span and (on
    success only — failed-attempt latencies would poison percentiles)
    an ``apex_checkpoint_duration_seconds{op=...}`` observation."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            t0 = time.perf_counter()
            with obs_trace.span(f"checkpoint_{op}"):
                result = fn(*args, **kwargs)
            _CKPT_SECONDS.observe(time.perf_counter() - t0, op=op)
            return result
        return wrapper
    return deco


class CheckpointError(RuntimeError):
    """A checkpoint failed validation, or no valid checkpoint exists."""

    # deterministic by definition, and the message may embed wrapped I/O
    # error text (the rejected-candidates list) that would match
    # RetryPolicy.transient_markers — never retried (retry.is_transient)
    transient = False


def _step_dirname(step: int) -> str:
    return f"{_STEP_PREFIX}{step:010d}"


def _list_steps(root: str) -> list[int]:
    """Completed checkpoint steps under ``root``, ascending."""
    if not os.path.isdir(root):
        return []
    steps = []
    for name in os.listdir(root):
        if name.startswith(_STEP_PREFIX):
            try:
                steps.append(int(name[len(_STEP_PREFIX):]))
            except ValueError:
                continue
    return sorted(steps)


def _fsync_dir(path: str) -> None:
    """Durably record a directory entry (rename atomicity needs the parent
    flushed too); best-effort on filesystems without dir fsync."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _mesh_metadata(axis_sizes: Optional[dict] = None) -> Optional[dict]:
    """Mesh shape + world sizes as manifest ``mesh`` metadata — the ONE
    schema both formats stamp (v1 whole-tree and v2 sharded manifests),
    so the mismatched-mesh guard and elastic resharding read the same
    fields.  ``axis_sizes`` (``{axis: size}``) keys the record; when
    omitted it is read from the installed parallel_state mesh, and None
    is returned outside model-parallel runs."""
    if axis_sizes is None:
        try:
            from apex_tpu.transformer import parallel_state

            axis_sizes = parallel_state.mesh_axis_sizes()
        except Exception as e:  # stamping is metadata, never save-fatal
            logger.debug("mesh metadata unavailable: %s: %s",
                         type(e).__name__, e)
            return None
        if axis_sizes is None:
            return None
    world = 1
    for n in axis_sizes.values():
        world *= n
    return {"axes": axis_sizes, "axis_names": list(axis_sizes),
            "world": world, "dp": axis_sizes.get("dp", 1),
            "tp": axis_sizes.get("tp", 1), "pp": axis_sizes.get("pp", 1)}


def _sweep_tmp_dirs(root: str) -> None:
    """Reclaim ``tmp_*`` dirs orphaned by a hard kill mid-save.  Assumes
    the single-writer root contract: any tmp dir present at save time is
    dead weight rotation would never see."""
    for name in os.listdir(root):
        if name.startswith(_TMP_PREFIX):
            shutil.rmtree(os.path.join(root, name), ignore_errors=True)


def _commit_step_dir(root: str, tmp_dir: str, final_dir: str) -> None:
    """Atomically install ``tmp_dir`` as ``final_dir``.

    Re-save of an existing step moves the old dir ASIDE (rename) rather
    than rmtree-ing it before the new one lands — a kill between the two
    renames loses at most the microsecond swap window instead of the
    whole serialization time; the aside copy is deleted only after the
    new checkpoint is in place, and restored if the install fails.
    """
    aside = None
    if os.path.exists(final_dir):
        aside = tmp_dir + ".old"
        os.rename(final_dir, aside)
    try:
        os.replace(tmp_dir, final_dir)
    except BaseException:
        if aside is not None and not os.path.exists(final_dir):
            os.rename(aside, final_dir)  # put the old checkpoint back
        raise
    _fsync_dir(root)
    if aside is not None:
        shutil.rmtree(aside, ignore_errors=True)


def _rotate(root: str, keep: int, protect_step: int) -> None:
    """Keep-last-``keep`` rotation, strictly after the new checkpoint is
    durable.  Two rules keep it from ever shrinking the recoverable set:
    ``protect_step`` (the just-written step) is never deleted — even when
    an undetected-corrupt newer dir occupies the keep window — and
    checkpoints that fail the cheap structural check (unreadable
    manifest / truncated payload) are dropped first rather than counted
    toward ``keep``."""
    if keep <= 0:
        return
    steps = _list_steps(root)
    sound = [s for s in steps
             if _quick_valid(os.path.join(root, _step_dirname(s)))]
    retain = set(sound[-keep:]) | {int(protect_step)}
    for old in steps:
        if old not in retain:
            shutil.rmtree(os.path.join(root, _step_dirname(old)),
                          ignore_errors=True)


@_observed("save")
def save_checkpoint(root: str, step: int, tree: Any, *, keep: int = 3) -> str:
    """Write ``tree`` as the step-``step`` checkpoint; returns its path.

    Write order is the crash-safety argument: (1) leaves + manifest into a
    temp directory, fsynced; (2) one atomic ``os.replace`` onto the final
    name; (3) only then rotate old checkpoints down to ``keep``.  A kill
    between any two of these leaves a restorable set on disk.

    ``root`` must have a SINGLE writer: the orphan sweep below reclaims
    every ``tmp_*`` dir, so a concurrent saver's in-progress temp dir
    would be deleted out from under it.  In multi-controller runs gate
    the save on ``jax.process_index() == 0`` or give each process its
    own root.
    """
    t0 = time.monotonic()
    os.makedirs(root, exist_ok=True)
    _sweep_tmp_dirs(root)
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    # ONE batched transfer for the whole tree, not a blocking device_get
    # round-trip per leaf (typed PRNG keys unwrapped to raw key data)
    host_leaves = jax.device_get(
        [jax.random.key_data(l) if is_prng_key(l) else l for _, l in flat])
    host_leaves = [np.asarray(a) for a in host_leaves]

    final_dir = os.path.join(root, _step_dirname(step))
    tmp_dir = tempfile.mkdtemp(prefix=_TMP_PREFIX, dir=root)
    try:
        # stream leaves straight to disk (no second in-RAM bytes copy of
        # a potentially multi-GB state), recording offsets/CRCs as we go
        records, offset = [], 0
        with open(os.path.join(tmp_dir, _DATA), "wb") as f:
            for (path, leaf), arr in zip(flat, host_leaves):
                # ONE bytes copy per leaf: CRC and write share it.  (NB
                # shape is recorded from `arr`, not the contiguous copy —
                # ascontiguousarray promotes 0-d scalars to 1-d.)
                data = np.ascontiguousarray(arr).tobytes()
                records.append({
                    "path": jax.tree_util.keystr(path),
                    "shape": list(arr.shape),
                    "dtype": arr.dtype.name,
                    "prng_key": is_prng_key(leaf),  # informational only
                    "offset": offset,
                    "nbytes": len(data),
                    "crc32": zlib.crc32(data) & 0xFFFFFFFF,
                })
                f.write(data)
                offset += len(data)
            f.flush()
            os.fsync(f.fileno())
        manifest = {
            "format_version": _FORMAT_VERSION,
            "step": int(step),
            "data_nbytes": offset,
            "mesh": _mesh_metadata(),
            "leaves": records,
        }
        with open(os.path.join(tmp_dir, _MANIFEST), "w") as f:
            json.dump(manifest, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        _commit_step_dir(root, tmp_dir, final_dir)
    except BaseException:
        shutil.rmtree(tmp_dir, ignore_errors=True)
        raise

    _rotate(root, keep, protect_step=int(step))
    emit_event("checkpoint_saved", step=int(step), bytes=offset,
               path=final_dir, t0=t0)
    return final_dir


def _read_manifest(ckpt_dir: str) -> dict:
    """Manifest + structural checks (readable, right version, payload size
    matches — catches truncation and half-writes without touching data).

    Defensive throughout: bit corruption can hit the MANIFEST as easily as
    the payload, and a corrupt-but-parsable manifest must surface as
    :class:`CheckpointError` (so the restore walk falls back) rather than
    a stray KeyError/TypeError that aborts the walk.
    """
    manifest_path = os.path.join(ckpt_dir, _MANIFEST)
    data_path = os.path.join(ckpt_dir, _DATA)
    # ANY OSError here — missing, PermissionError after an orchestrator
    # restart — rejects the candidate so the fallback walk continues to an
    # older step: the manifest probe decides "is this a usable checkpoint",
    # unlike _read_record's mid-payload reads where an OSError on an open
    # file is environmental and propagates for the manager's retry.
    # UnicodeDecodeError: json.load on bit-flipped manifest bytes.
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        raise CheckpointError(f"{ckpt_dir}: unreadable manifest: {e}") from e
    if not isinstance(manifest, dict) or not isinstance(
            manifest.get("leaves"), list):
        raise CheckpointError(f"{ckpt_dir}: manifest is not a leaf listing")
    if not isinstance(manifest.get("step"), int):
        raise CheckpointError(
            f"{ckpt_dir}: manifest step {manifest.get('step')!r} "
            f"is not an integer")
    if manifest.get("format_version") not in _KNOWN_VERSIONS:
        raise CheckpointError(
            f"{ckpt_dir}: format_version {manifest.get('format_version')} "
            f"not in {_KNOWN_VERSIONS}")
    try:
        actual = os.path.getsize(data_path)
    except OSError as e:
        raise CheckpointError(f"{ckpt_dir}: missing data.bin: {e}") from e
    if actual != manifest.get("data_nbytes"):
        raise CheckpointError(
            f"{ckpt_dir}: data.bin is {actual} bytes, manifest says "
            f"{manifest.get('data_nbytes')} (truncated or overgrown)")
    return manifest


def _read_record(f, rec: dict, ckpt_dir: str) -> np.ndarray:
    """Seek/read/CRC-check ONE manifest record; the single shared reader
    under both :func:`validate_checkpoint` and :func:`_load_validated`.
    Any defect a corrupted record can produce — bad offsets, nbytes not a
    dtype multiple, unknown dtype name, shape/size mismatch, CRC failure —
    comes back as :class:`CheckpointError`."""
    try:
        offset, nbytes = int(rec["offset"]), int(rec["nbytes"])
        if offset < 0 or nbytes < 0:
            raise ValueError(f"negative extent ({offset}, {nbytes})")
        f.seek(offset)
        chunk = f.read(nbytes)
        if len(chunk) != nbytes:
            raise ValueError(f"short read ({len(chunk)} of {nbytes} bytes)")
        arr = np.frombuffer(chunk, dtype=np_dtype(rec["dtype"]))
        arr = arr.reshape(rec["shape"])
    except CheckpointError:
        raise
    except OSError:
        # seek/read failure on an OPEN file is host I/O (a blipping
        # network filesystem), not evidence about the checkpoint's bytes —
        # propagate unwrapped so CheckpointManager's RetryPolicy engages
        # instead of the fallback walk silently resuming an older step
        raise
    except Exception as e:  # corrupt record metadata, not a code path bug
        raise CheckpointError(
            f"{ckpt_dir}: unusable leaf record "
            f"{rec.get('path', '?')!r}: {type(e).__name__}: {e}") from e
    # CRC the bytes as read — the file bytes ARE the contiguous form the
    # manifest CRC was computed from, so this avoids leaf_crc32's tobytes()
    # copy (a second transient per-leaf allocation on multi-GB restores)
    if (zlib.crc32(chunk) & 0xFFFFFFFF) != rec.get("crc32"):
        raise CheckpointError(
            f"{ckpt_dir}: CRC mismatch on leaf {rec.get('path', '?')!r}")
    return arr


def _quick_valid(ckpt_dir: str) -> bool:
    """Cheap structural validity (no CRC pass) — the rotation-time check."""
    try:
        _read_manifest(ckpt_dir)
        return True
    except CheckpointError:
        return False


@_observed("validate")
def validate_checkpoint(ckpt_dir: str) -> None:
    """Prove a checkpoint directory internally consistent.

    Raises :class:`CheckpointError` on any defect: missing/unparsable
    manifest, wrong format version, payload size mismatch (truncation),
    or any per-leaf CRC mismatch (bit corruption).
    """
    manifest = _read_manifest(ckpt_dir)
    if manifest.get("format_version") == _SHARDED_FORMAT_VERSION:
        # v2 (sharded) dirs validate shard-by-shard; dispatching here
        # keeps latest_valid_step / rotation / the supervisor's
        # emergency-checkpoint validation working over mixed roots
        from apex_tpu.resilience.elastic import _validate_shards

        _validate_shards(ckpt_dir, manifest)
        return
    with open(os.path.join(ckpt_dir, _DATA), "rb") as f:
        for rec in manifest["leaves"]:
            _read_record(f, rec, ckpt_dir)


def _load_validated(ckpt_dir: str, like: Any) -> tuple[Any, int]:
    """Validate-and-load in ONE pass over the payload: structural checks
    up front, then each leaf streamed (seek+read per manifest record, so
    peak host memory is one leaf, not the whole payload) and CRC-verified
    before it is materialized — no leaf reaches the caller without its
    CRC having passed, and restore never re-reads a multi-GB data.bin
    just to prove it good first."""
    manifest = _read_manifest(ckpt_dir)
    if manifest.get("format_version") == _SHARDED_FORMAT_VERSION:
        raise CheckpointError(
            f"{ckpt_dir}: sharded (format v2) checkpoint — restore it "
            f"through apex_tpu.resilience.elastic.restore_sharded_checkpoint"
            f", which reassembles shard records and reshards onto the "
            f"current mesh")
    saved_mesh = manifest.get("mesh")
    cur_mesh = _mesh_metadata()
    if (isinstance(saved_mesh, dict) and cur_mesh is not None
            and saved_mesh.get("axes") != cur_mesh["axes"]):
        # a v1 checkpoint is one whole-tree byte stream with no shard
        # records: restoring it onto a different mesh shape would hand
        # every template leaf the OLD global bytes and silently reshard
        # them wrong.  Elastic restarts need the v2 sharded format.
        raise CheckpointError(
            f"{ckpt_dir}: whole-tree (v1) checkpoint was saved on mesh "
            f"{saved_mesh.get('axes')} but the current mesh is "
            f"{cur_mesh['axes']} — v1 checkpoints cannot reshard; save "
            f"sharded checkpoints (resilience.elastic) to resume on a "
            f"different mesh shape")
    by_path = {r.get("path"): r for r in manifest["leaves"]
               if isinstance(r, dict)}

    flat, treedef = jax.tree_util.tree_flatten_with_path(like)
    leaves = []
    with open(os.path.join(ckpt_dir, _DATA), "rb") as f:
        for path, tmpl in flat:
            key = jax.tree_util.keystr(path)
            rec = by_path.get(key)
            if rec is None:
                raise CheckpointError(
                    f"{ckpt_dir}: checkpoint has no leaf {key!r} "
                    f"(template/checkpoint structure mismatch)")
            # spec check without device_get-ing the live template state
            want_shape, want_dtype = leaf_spec(tmpl)
            if (list(want_shape) != rec.get("shape")
                    or want_dtype.name != rec.get("dtype")):
                raise CheckpointError(
                    f"{ckpt_dir}: leaf {key!r} is "
                    f"{rec.get('dtype')}{rec.get('shape')}, template wants "
                    f"{want_dtype.name}{list(want_shape)}")
            leaves.append(leaf_from_numpy(_read_record(f, rec, ckpt_dir),
                                          tmpl))
    # strict BOTH ways: checkpoint leaves the template does not expect
    # mean structure drift, and a silent partial restore is the failure
    # mode this subsystem exists to prevent
    extra = set(by_path) - {jax.tree_util.keystr(p) for p, _ in flat}
    if extra:
        raise CheckpointError(
            f"{ckpt_dir}: checkpoint has leaves the template does not: "
            f"{sorted(extra)[:5]}")
    return jax.tree_util.tree_unflatten(treedef, leaves), manifest["step"]


def latest_valid_step(root: str) -> Optional[int]:
    """Newest step whose checkpoint passes validation, or None."""
    for step in reversed(_list_steps(root)):
        try:
            validate_checkpoint(os.path.join(root, _step_dirname(step)))
            return step
        except CheckpointError:
            continue
    return None


@_observed("restore")
def restore_checkpoint(root: str, like: Any, *,
                       step: Optional[int] = None) -> tuple[Any, int]:
    """Restore the newest *valid* checkpoint into ``like``'s structure.

    Returns ``(tree, step)``.  Invalid candidates (corrupt, truncated, or
    structurally incompatible with ``like``) are skipped with a logged
    ``checkpoint_rejected`` event and the walk continues to older steps —
    the automatic-fallback contract.  ``step`` pins an exact step instead
    (no fallback).  Raises :class:`CheckpointError` when nothing valid
    remains.
    """
    candidates = ([step] if step is not None
                  else list(reversed(_list_steps(root))))
    errors: list[str] = []
    for s in candidates:
        ckpt_dir = os.path.join(root, _step_dirname(s))
        t0 = time.monotonic()
        try:
            # validation is fused into the load (structural checks, then
            # per-leaf CRC as each chunk is sliced) — one payload pass
            tree, got_step = _load_validated(ckpt_dir, like)
        except CheckpointError as e:
            errors.append(str(e))
            emit_event("checkpoint_rejected", step=int(s), reason=str(e))
            if step is not None:
                raise
            continue
        emit_event("checkpoint_restored", step=int(got_step),
                   fallback=bool(candidates[0] != s), t0=t0)
        return tree, got_step
    raise CheckpointError(
        f"no valid checkpoint under {root!r}"
        + (f"; rejected: {errors}" if errors else " (directory empty)"))


@dataclasses.dataclass
class CheckpointManager:
    """Keep-last-K manager over one checkpoint root.

    ``retry`` (a :class:`~apex_tpu.resilience.retry.RetryPolicy`) makes
    save/restore survive *transient* host I/O errors — a blipping
    network filesystem, a busy disk.  Safe to retry by construction:
    the save path sweeps its own temp litter and commits by atomic
    rename (re-running is idempotent), and on restore only the
    transient class is retried — a :class:`CheckpointError` is
    deterministic (corrupt bytes stay corrupt) and propagates at once
    so the newest-valid fallback walk proceeds instead of stalling.

    >>> mgr = CheckpointManager("/ckpts/run7", keep=3)
    >>> mgr.save(step, {"params": params, "opt": opt_state,
    ...                 "scaler": sstate, "rng": rng_key,
    ...                 "step": jnp.int32(step)})
    >>> state, resume_step = mgr.restore(like=template)   # newest valid
    """

    root: str
    keep: int = 3
    retry: Optional["RetryPolicy"] = None

    def _retrying(self, fn, what: str):
        if self.retry is None:
            return fn()
        from apex_tpu.resilience.retry import retry_transient

        return retry_transient(fn, policy=self.retry, what=what)

    def save(self, step: int, tree: Any) -> str:
        return self._retrying(
            lambda: save_checkpoint(self.root, step, tree, keep=self.keep),
            "checkpoint_save")

    def restore(self, like: Any, *, step: Optional[int] = None):
        return self._retrying(
            lambda: restore_checkpoint(self.root, like, step=step),
            "checkpoint_restore")

    def all_steps(self) -> list[int]:
        return _list_steps(self.root)

    def latest_valid_step(self) -> Optional[int]:
        return latest_valid_step(self.root)

    def checkpoint_path(self, step: int) -> str:
        return os.path.join(self.root, _step_dirname(step))
