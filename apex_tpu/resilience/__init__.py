"""apex_tpu.resilience — survive preemption, corruption, and blow-ups.

The reference threads recoverable state everywhere (fp32 masters, the
scaler's ``unskipped`` checkpoint-parity counter, per-rank RNG trackers)
but leaves actual recovery to the consumer.  At TPU-pod scale preemptions
and transient numerical blow-ups are routine (PAPERS.md: "Exploring the
limits of Concurrency in ML Training on Google TPUs", "Scale MLPerf-0.6
models on Google TPU-v3 Pods"), so this subsystem makes the full loop —
kill, corrupt, restart, converge — a tested code path:

- :mod:`.checkpoint` — validated atomic checkpoints of arbitrary pytrees
  (params, optimizer state, ``LossScalerState``, RNG keys, step counter):
  shape/dtype/CRC manifest, write-temp + atomic rename, keep-last-K
  rotation, automatic fallback to the newest checkpoint that validates.
- :mod:`.fault_injection` — deterministic seed-driven faults: NaN/Inf
  gradients at a chosen step, simulated preemption at the host step
  boundary, checkpoint byte corruption/truncation on disk.
- :mod:`.guarded` — anomaly-aware stepping on top of
  :mod:`apex_tpu.amp.scaler`: per-leaf non-finite localization, a
  consecutive-skip counter, and bounded degradation (halve the dynamic
  loss-scale floor after ``patience`` skips, with a structured event)
  instead of a silent infinite skip loop.
- :mod:`.supervisor` — the host-loop layer over all of it: a step
  watchdog (per-step deadline on a monotonic clock, monitor thread that
  dumps diagnostics mid-stall, heartbeat file for external
  orchestrators) and the :class:`TrainingSupervisor` escalation policy
  — consecutive unrecovered failures trigger emergency-checkpoint-then-
  clean-abort (graceful degradation, resumable by construction).
- :mod:`.retry` — classified-exception retry with exponential backoff
  and deterministic jitter for host I/O (checkpoint save/restore, data
  fetch), one structured event per attempt.
- :mod:`.data_guard` — validating iterator wrapper (tree/shape/dtype/
  finiteness against a batch spec) with a bounded corrupt-batch skip
  budget and a producer stall timeout.
- :mod:`.elastic` — *sharded* checkpoints (manifest v2: one CRC'd shard
  record per (leaf, mesh-coordinate block)) whose restore reassembles
  each global leaf and re-shards it onto the template's mesh — save on
  ``(dp=4, tp=2)``, resume bit-identically on ``(dp=2, tp=4)`` or
  ``dp=8`` (the elastic-restart contract).
- :mod:`.async_checkpoint` — the asynchronous save pipeline: the step
  loop blocks on ONE device→host snapshot, a background writer thread
  runs the existing serialize/CRC/commit machinery (v1 and v2 managers
  both), at most one write in flight, vetoable commit, failures
  surfaced at the next step boundary — on-disk bytes identical to a
  synchronous save (``SupervisorConfig(async_save=True)`` turns it on).
- :mod:`.consistency` — cross-replica desync detection and repair:
  per-replica leaf hashes inside ``shard_map`` (only u32 digests cross
  the wire), structured localization of diverged leaves, resync by
  re-broadcast from rank 0, and the :class:`ReplicaConsistency` policy
  the supervisor runs every ``consistency_check_interval`` steps.

End-to-end recipe (the shape tier-1's preemption/corruption test runs)::

    from apex_tpu import resilience as rz

    mgr = rz.CheckpointManager("/ckpts/run7", keep=3)
    scaler = LossScaler(); sstate = scaler.init()
    gstate = rz.init_guard_state(scaler)
    step = jax.jit(rz.make_guarded_step(loss_fn, opt, scaler))

    state = {"params": params, "opt": opt_state, "scaler": sstate,
             "guard": gstate, "rng": rng}
    try:
        restored, start = mgr.restore(like=state)   # newest VALID ckpt
        state, start = restored, start + 1
    except rz.CheckpointError:
        start = 0                                   # fresh run
    for i in range(start, num_steps):
        injector.check_preemption(i)                # tests only
        out = step(state["params"], state["opt"], state["scaler"],
                   state["guard"], batch(state["rng"], i))
        state = dict(zip(("params", "opt", "scaler", "guard"), out[:4]),
                     rng=state["rng"])
        mgr.save(i, state)

A checkpoint root assumes a single writer — in multi-controller runs
gate ``mgr.save`` on ``jax.process_index() == 0`` (or give each process
its own root); concurrent saves into one root race the temp-dir sweep.
"""

from apex_tpu.resilience.async_checkpoint import (
    AsyncCheckpointer,
    SaveFuture,
    SaveVetoed,
)
from apex_tpu.resilience.checkpoint import (
    CheckpointError,
    CheckpointManager,
    LeafSnapshot,
    TreeSnapshot,
    latest_valid_step,
    restore_checkpoint,
    save_checkpoint,
    snapshot_tree,
    validate_checkpoint,
)
from apex_tpu.resilience.consistency import (
    DivergedLeaf,
    ReplicaConsistency,
    ReplicaDesyncError,
    collapse_replicas,
    expand_replicas,
    majority_root,
    replica_hashes,
    resync_replicas,
    verify_replicas,
)
from apex_tpu.resilience.data_guard import (
    DataStallError,
    GuardedIterator,
    SkipBudgetExceeded,
    spec_of,
    validate_batch,
)
from apex_tpu.resilience.elastic import (
    ShardedCheckpointManager,
    restore_sharded_checkpoint,
    save_sharded_checkpoint,
    snapshot_sharded_tree,
    validate_sharded_checkpoint,
)
from apex_tpu.resilience.fault_injection import (
    CorruptBatch,
    CorruptShardFile,
    CrashCheckpointWriter,
    DesyncReplica,
    FaultInjector,
    FaultPlan,
    FlakyIterator,
    SimulatedPreemption,
    SimulatedWriterCrash,
    SlowStep,
)
from apex_tpu.resilience.guarded import (
    GuardConfig,
    GuardState,
    guarded_update,
    init_guard_state,
    make_guarded_step,
    nonfinite_counts,
    nonfinite_report,
)
from apex_tpu.resilience.retry import (
    RetryExhausted,
    RetryPolicy,
    TransientError,
    is_transient,
    retry_transient,
)
from apex_tpu.resilience.supervisor import (
    StepDeadlineExceeded,
    StepWatchdog,
    SupervisorConfig,
    TrainingAborted,
    TrainingSupervisor,
    read_heartbeat,
    write_heartbeat,
)

__all__ = [
    "AsyncCheckpointer",
    "SaveFuture",
    "SaveVetoed",
    "CheckpointError",
    "CheckpointManager",
    "LeafSnapshot",
    "TreeSnapshot",
    "latest_valid_step",
    "restore_checkpoint",
    "save_checkpoint",
    "snapshot_tree",
    "validate_checkpoint",
    "CorruptBatch",
    "CorruptShardFile",
    "CrashCheckpointWriter",
    "DesyncReplica",
    "FaultInjector",
    "FaultPlan",
    "FlakyIterator",
    "SimulatedPreemption",
    "SimulatedWriterCrash",
    "SlowStep",
    "DivergedLeaf",
    "ReplicaConsistency",
    "ReplicaDesyncError",
    "collapse_replicas",
    "expand_replicas",
    "majority_root",
    "replica_hashes",
    "resync_replicas",
    "verify_replicas",
    "ShardedCheckpointManager",
    "restore_sharded_checkpoint",
    "save_sharded_checkpoint",
    "snapshot_sharded_tree",
    "validate_sharded_checkpoint",
    "GuardConfig",
    "GuardState",
    "guarded_update",
    "init_guard_state",
    "make_guarded_step",
    "nonfinite_counts",
    "nonfinite_report",
    "DataStallError",
    "GuardedIterator",
    "SkipBudgetExceeded",
    "spec_of",
    "validate_batch",
    "RetryExhausted",
    "RetryPolicy",
    "TransientError",
    "is_transient",
    "retry_transient",
    "StepDeadlineExceeded",
    "StepWatchdog",
    "SupervisorConfig",
    "TrainingAborted",
    "TrainingSupervisor",
    "read_heartbeat",
    "write_heartbeat",
]
