"""Data-pipeline guard: validate every batch, skip within a budget.

At pod scale the input pipeline is the least reliable part of the
system: a corrupt shard serves NaN features, a mis-merged preprocessing
change flips a dtype, a straggling producer starves the accelerators
(PAPERS.md: MLPerf-scale TPU-v3 pod runs).  An unguarded loop either
trains on the garbage (silent quality loss — the worst outcome) or dies
on the first bad record (one shard kills the run).  The guard makes the
middle path explicit and *bounded*:

- :func:`validate_batch` checks a batch against a :func:`spec_of`-shaped
  template — tree structure, per-leaf shape and dtype, and finiteness of
  floating leaves — and returns human-readable reasons for any defect.
- :class:`GuardedIterator` wraps the real iterator: clean batches pass
  through untouched; corrupt ones are dropped with a structured
  ``batch_skipped`` event, up to ``skip_budget`` for the iterator's
  lifetime — one bad shard costs its batches, a *systematically* bad
  pipeline exhausts the budget and raises :class:`SkipBudgetExceeded`
  (data bugs must not degrade into silently training on 10% of the
  data).  A fetch slower than ``stall_timeout_s`` raises
  :class:`DataStallError` — the late batch is stashed and redelivered on
  the next call, so a stall costs a recorded failure, never data.

The checks run on the HOST batch (``np.asarray`` per leaf) — place the
guard on the host side of the pipeline, before device put, where the
bytes are already resident.  Stall detection is a *detector*, not an
interrupter: a synchronous ``next()`` cannot be preempted, so a truly
hung producer is surfaced by the step watchdog's monitor thread
(:mod:`apex_tpu.resilience.supervisor`) while this guard classifies the
slow-but-completing case deterministically.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Iterable, Iterator, List, Optional

import jax
import numpy as np

from apex_tpu._logging import emit_event
from apex_tpu.utils.serialization import leaf_spec, tree_paths

__all__ = [
    "DataStallError",
    "GuardedIterator",
    "SkipBudgetExceeded",
    "spec_of",
    "validate_batch",
]


class SkipBudgetExceeded(RuntimeError):
    """More corrupt batches than ``skip_budget`` allows — the pipeline is
    systematically bad, not sporadically unlucky."""

    def __init__(self, skipped: int, budget: int, reasons: List[str]):
        super().__init__(
            f"skipped {skipped} corrupt batches (budget {budget}); "
            f"last: {reasons}")
        self.skipped = skipped
        self.budget = budget
        self.reasons = reasons


class DataStallError(TimeoutError):
    """A batch fetch took longer than the configured stall timeout.

    The late batch itself is NOT lost: the guard stashes it and delivers
    it on the next ``__next__`` call, so a supervisor that records the
    stall and re-fetches consumes the identical stream."""

    # a TimeoutError subclass would be classified transient by the
    # default RetryPolicy — but each retried fetch would consume (and
    # discard) another successfully-produced batch and multiply the
    # stall wait by max_attempts; stalls are the supervisor's failure
    # domain, not the retry layer's
    transient = False

    def __init__(self, fetch_s: float, timeout_s: float):
        super().__init__(
            f"batch fetch took {fetch_s:.3f}s "
            f"(stall timeout {timeout_s:.3f}s)")
        self.fetch_s = fetch_s
        self.timeout_s = timeout_s


def spec_of(batch: Any) -> Any:
    """Batch spec (pytree of ``jax.ShapeDtypeStruct``) from an exemplar.

    Reuses :func:`~apex_tpu.utils.serialization.leaf_spec`, so shapes and
    dtypes are read without any device-to-host transfer.
    """
    return jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(*leaf_spec(l)), batch)


def _compile_spec(spec: Any) -> tuple:
    """Flatten a spec ONCE into ``(treedef, [(path, shape, dtype), ...])``
    — the per-batch validation cost must not include re-flattening the
    fixed spec and rebuilding every keystr path on every training step
    (GuardedIterator caches this for its locked spec)."""
    s_leaves, s_tree = jax.tree_util.tree_flatten(spec)
    recs = [(path, tuple(want.shape), np.dtype(want.dtype))
            for path, want in zip(tree_paths(spec), s_leaves)]
    return s_tree, recs


def _validate_compiled(batch: Any, s_tree, recs, *,
                       check_finite: bool) -> List[str]:
    b_leaves, b_tree = jax.tree_util.tree_flatten(batch)
    if b_tree != s_tree:
        return [f"structure mismatch: batch {str(b_tree)[:120]} != "
                f"spec {str(s_tree)[:120]}"]
    reasons = []
    for (path, want_shape, want_dtype), leaf in zip(recs, b_leaves):
        arr = np.asarray(leaf)
        if tuple(arr.shape) != want_shape:
            reasons.append(f"leaf {path!r}: shape {tuple(arr.shape)} != "
                           f"{want_shape}")
        elif arr.dtype != want_dtype:
            reasons.append(f"leaf {path!r}: dtype {arr.dtype.name} != "
                           f"{want_dtype.name}")
        elif check_finite and np.issubdtype(arr.dtype, np.floating) \
                and not np.isfinite(arr).all():
            bad = int(arr.size - np.count_nonzero(np.isfinite(arr)))
            reasons.append(f"leaf {path!r}: {bad} non-finite elements")
    return reasons


def validate_batch(batch: Any, spec: Any, *,
                   check_finite: bool = True) -> List[str]:
    """Defects of ``batch`` vs ``spec``; an empty list means clean.

    Checks, per leaf and in order: tree structure, shape, dtype, then
    (floating leaves only, when ``check_finite``) that every element is
    finite.  Reasons name the leaf by its ``keystr`` path so the skip
    event localizes the bad feature, not just the bad batch.
    """
    return _validate_compiled(batch, *_compile_spec(spec),
                              check_finite=check_finite)


class GuardedIterator:
    """Validating wrapper around a batch iterator (itself an iterator).

    ``spec`` pins the expected batch layout; when omitted it is locked
    from the *first* batch (which still gets the finiteness check, but a
    shape-corrupt first batch would then define the spec — pass an
    explicit spec for full protection).  Source exceptions propagate
    untouched, so a transient-failure retry wrapped *around* ``next()``
    (see :func:`~apex_tpu.resilience.retry.retry_transient`) composes:
    the guard's skip bookkeeping survives the re-call.

    ``skip_budget`` is a lifetime cap, not per-step: ``skipped`` counts
    every dropped batch and crossing the budget raises
    :class:`SkipBudgetExceeded`.  ``clock`` is injectable (monotonic) so
    stall detection is testable without real waits.
    """

    def __init__(self, it: Iterable, spec: Any = None, *,
                 check_finite: bool = True, skip_budget: int = 8,
                 stall_timeout_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        if skip_budget < 0:
            raise ValueError(f"skip_budget must be >= 0, got {skip_budget}")
        if stall_timeout_s is not None and stall_timeout_s <= 0.0:
            raise ValueError(
                f"stall_timeout_s must be positive, got {stall_timeout_s}")
        self._it = iter(it)
        self.spec = spec
        self.check_finite = check_finite
        self.skip_budget = skip_budget
        self.stall_timeout_s = stall_timeout_s
        self.skipped = 0
        self.delivered = 0
        self._clock = clock
        self._stalled = None  # late batch awaiting redelivery
        self._compiled = None      # _compile_spec view of the locked spec
        self._compiled_for = None  # identity key: recompile if spec swapped

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        while True:
            if self._stalled is not None:
                # a previous fetch stalled AFTER the producer delivered:
                # hand that batch over now instead of dropping it — a
                # chronically slow producer must cost stall *failures*,
                # never silent data loss
                batch, self._stalled = self._stalled, None
            else:
                t0 = self._clock()
                batch = next(self._it)  # StopIteration/source errs propagate
                fetch_s = self._clock() - t0
                if (self.stall_timeout_s is not None
                        and fetch_s > self.stall_timeout_s):
                    self._stalled = batch
                    emit_event("data_stall", fetch_s=round(fetch_s, 6),
                               stall_timeout_s=self.stall_timeout_s)
                    raise DataStallError(fetch_s, self.stall_timeout_s)
            if self.spec is None:
                self.spec = spec_of(batch)
            if self._compiled_for is not self.spec:
                self._compiled = _compile_spec(self.spec)
                self._compiled_for = self.spec
            reasons = _validate_compiled(batch, *self._compiled,
                                         check_finite=self.check_finite)
            if not reasons:
                self.delivered += 1
                return batch
            self.skipped += 1
            emit_event("batch_skipped", reasons=reasons,
                       skipped=self.skipped, skip_budget=self.skip_budget)
            if self.skipped > self.skip_budget:
                raise SkipBudgetExceeded(self.skipped, self.skip_budget,
                                         reasons)
