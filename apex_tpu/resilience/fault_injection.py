"""Deterministic, seed-driven fault injection for training loops.

Every recovery path in :mod:`apex_tpu.resilience` is exercised by tier-1
tests instead of being discovered in production — which requires faults
that are *reproducible*: the same :class:`FaultPlan` seed produces the
same corrupted gradient elements, the same preemption step, and the same
flipped checkpoint bytes on every run.

Three fault classes, matching what pod-scale training actually sees
(PAPERS.md TPU-pod papers; ROADMAP north-star):

- **Numerical**: :meth:`FaultInjector.inject_grads` flips chosen gradient
  elements to NaN/Inf at configured steps.  jit-safe — the injection is a
  branch-free ``jnp.where`` on the on-device step counter, so it composes
  with the capturable train step exactly like a real overflow would.
- **Preemption**: :meth:`FaultInjector.check_preemption` raises
  :class:`SimulatedPreemption` at the configured step from the host-side
  step boundary — the point where a real SIGTERM lands, after the device
  step was dispatched but before the host commits/extends its state.
- **Storage**: :meth:`FaultInjector.corrupt_checkpoint` /
  :meth:`truncate_checkpoint` damage checkpoint bytes on disk the way a
  preempted writer or a bad disk does, to drive the validation-fallback
  path of :mod:`apex_tpu.resilience.checkpoint`.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu._logging import emit_event

__all__ = [
    "FaultInjector",
    "FaultPlan",
    "SimulatedPreemption",
]


class SimulatedPreemption(RuntimeError):
    """Raised at an injected preemption boundary (stands in for SIGTERM)."""

    def __init__(self, step: int):
        super().__init__(f"simulated preemption at step {step}")
        self.step = step


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """What to break, and when (all step indices are host step numbers).

    ``nan_grad_steps`` / ``inf_grad_steps``: steps whose gradients get
    deterministic NaN / Inf elements injected.  ``preempt_steps``: steps
    whose host boundary raises :class:`SimulatedPreemption`.  ``seed``
    drives every placement choice.
    """

    seed: int = 0
    nan_grad_steps: Tuple[int, ...] = ()
    inf_grad_steps: Tuple[int, ...] = ()
    preempt_steps: Tuple[int, ...] = ()


class FaultInjector:
    """Executes a :class:`FaultPlan` against a training loop."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan

    # -- numerical faults (jit-safe) --------------------------------------

    def inject_grads(self, grads: Any, step: jax.Array) -> Any:
        """Return ``grads`` with NaN/Inf planted when ``step`` is a
        configured fault step; a no-op (same values) otherwise.

        jit-safe: ``step`` may be a traced on-device scalar.  The target
        leaf and element are chosen deterministically from the seed at
        trace time, so recompilation cannot move the fault.
        """
        plan = self.plan
        if not plan.nan_grad_steps and not plan.inf_grad_steps:
            return grads
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        # a fault can only live in a non-empty floating-point leaf
        candidates = [i for i, l in enumerate(leaves)
                      if l.size and jnp.issubdtype(l.dtype, jnp.inexact)]
        if not candidates:
            return grads
        rng = np.random.default_rng(plan.seed)
        step = jnp.asarray(step, jnp.int32)
        # the fault is planted in the leaf's OWN dtype (every float dtype
        # has nan/inf), so off-step execution is bit-identical — no
        # precision roundtrip that would desync a clean-vs-faulted
        # trajectory comparison
        for bad, steps in ((jnp.nan, plan.nan_grad_steps),
                           (jnp.inf, plan.inf_grad_steps)):
            # consume the seed stream even for unconfigured classes so a
            # plan's nan/inf placements do not depend on each other
            idx = candidates[int(rng.integers(len(candidates)))]
            leaf = leaves[idx]
            pos = int(rng.integers(leaf.size))
            if not steps:
                continue
            is_hit = jnp.any(step == jnp.asarray(steps, jnp.int32))
            flat = jnp.ravel(leaf)
            flat = flat.at[pos].set(
                jnp.where(is_hit, jnp.asarray(bad, leaf.dtype), flat[pos]))
            leaves[idx] = flat.reshape(leaf.shape)
        return jax.tree_util.tree_unflatten(treedef, leaves)

    # -- preemption (host boundary) ---------------------------------------

    def check_preemption(self, step: int) -> None:
        """Host-side step boundary: raises :class:`SimulatedPreemption`
        when ``step`` is a configured preemption step.

        Call it where a SIGTERM handler would fire — after dispatching the
        device step, before committing host-side state (checkpoint index,
        data-loader cursor).  The device computation in flight is simply
        abandoned, exactly as a real preemption abandons it.
        """
        if int(step) in self.plan.preempt_steps:
            emit_event("fault_injected", fault="preemption", step=int(step))
            raise SimulatedPreemption(int(step))

    # -- storage faults ----------------------------------------------------

    def corrupt_checkpoint(self, ckpt_dir: str, *, nbytes: int = 8) -> list[int]:
        """Flip ``nbytes`` seed-chosen bytes of ``<ckpt_dir>/data.bin``
        in place; returns the corrupted offsets (bit corruption)."""
        path = os.path.join(ckpt_dir, "data.bin")
        size = os.path.getsize(path)
        rng = np.random.default_rng(self.plan.seed)
        offsets = sorted(
            int(o) for o in rng.choice(size, size=min(nbytes, size),
                                       replace=False))
        with open(path, "r+b") as f:
            for off in offsets:
                f.seek(off)
                byte = f.read(1)[0]
                f.seek(off)
                f.write(bytes([byte ^ 0xFF]))
        emit_event("fault_injected", fault="checkpoint_corruption",
                   path=path, offsets=offsets)
        return offsets

    def truncate_checkpoint(self, ckpt_dir: str, *, drop_bytes: int = 1) -> None:
        """Truncate ``data.bin`` by ``drop_bytes`` (half-written writer)."""
        path = os.path.join(ckpt_dir, "data.bin")
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(size - drop_bytes, 0))
        emit_event("fault_injected", fault="checkpoint_truncation",
                   path=path, dropped=drop_bytes)
