"""Deterministic, seed-driven fault injection for training loops.

Every recovery path in :mod:`apex_tpu.resilience` is exercised by tier-1
tests instead of being discovered in production — which requires faults
that are *reproducible*: the same :class:`FaultPlan` seed produces the
same corrupted gradient elements, the same preemption step, and the same
flipped checkpoint bytes on every run.

Three fault classes, matching what pod-scale training actually sees
(PAPERS.md TPU-pod papers; ROADMAP north-star):

- **Numerical**: :meth:`FaultInjector.inject_grads` flips chosen gradient
  elements to NaN/Inf at configured steps.  jit-safe — the injection is a
  branch-free ``jnp.where`` on the on-device step counter, so it composes
  with the capturable train step exactly like a real overflow would.
- **Preemption**: :meth:`FaultInjector.check_preemption` raises
  :class:`SimulatedPreemption` at the configured step from the host-side
  step boundary — the point where a real SIGTERM lands, after the device
  step was dispatched but before the host commits/extends its state.
- **Storage**: :meth:`FaultInjector.corrupt_checkpoint` /
  :meth:`truncate_checkpoint` damage checkpoint bytes on disk the way a
  preempted writer or a bad disk does, to drive the validation-fallback
  path of :mod:`apex_tpu.resilience.checkpoint`.

PR 2 adds the *supervisor-domain* faults — the quiet failures that the
step watchdog, transient retry, and data guard exist to survive:

- **Stragglers**: :class:`SlowStep` stalls the host step body at chosen
  steps so the watchdog deadline fires deterministically.
- **Flaky producers**: :class:`FlakyIterator` makes a chosen fetch raise
  a transient error N times and then succeed *without consuming* the
  underlying item — the retry path recovers the exact same stream.
- **Corrupt records**: :class:`CorruptBatch` *inserts* a damaged copy of
  a chosen batch ahead of the clean one (NaN / shape / dtype damage),
  so a guarded run that skips it sees the identical clean stream as an
  unfaulted run — trajectory comparisons stay bit-exact.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable, Iterable, Iterator, Tuple, Type

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu._logging import emit_event

__all__ = [
    "CorruptBatch",
    "FaultInjector",
    "FaultPlan",
    "FlakyIterator",
    "SimulatedPreemption",
    "SlowStep",
]


class SimulatedPreemption(RuntimeError):
    """Raised at an injected preemption boundary (stands in for SIGTERM)."""

    def __init__(self, step: int):
        super().__init__(f"simulated preemption at step {step}")
        self.step = step


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """What to break, and when (all step indices are host step numbers).

    ``nan_grad_steps`` / ``inf_grad_steps``: steps whose gradients get
    deterministic NaN / Inf elements injected.  ``preempt_steps``: steps
    whose host boundary raises :class:`SimulatedPreemption`.  ``seed``
    drives every placement choice.
    """

    seed: int = 0
    nan_grad_steps: Tuple[int, ...] = ()
    inf_grad_steps: Tuple[int, ...] = ()
    preempt_steps: Tuple[int, ...] = ()


class FaultInjector:
    """Executes a :class:`FaultPlan` against a training loop."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan

    # -- numerical faults (jit-safe) --------------------------------------

    def inject_grads(self, grads: Any, step: jax.Array) -> Any:
        """Return ``grads`` with NaN/Inf planted when ``step`` is a
        configured fault step; a no-op (same values) otherwise.

        jit-safe: ``step`` may be a traced on-device scalar.  The target
        leaf and element are chosen deterministically from the seed at
        trace time, so recompilation cannot move the fault.
        """
        plan = self.plan
        if not plan.nan_grad_steps and not plan.inf_grad_steps:
            return grads
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        # a fault can only live in a non-empty floating-point leaf
        candidates = [i for i, l in enumerate(leaves)
                      if l.size and jnp.issubdtype(l.dtype, jnp.inexact)]
        if not candidates:
            return grads
        rng = np.random.default_rng(plan.seed)
        step = jnp.asarray(step, jnp.int32)
        # the fault is planted in the leaf's OWN dtype (every float dtype
        # has nan/inf), so off-step execution is bit-identical — no
        # precision roundtrip that would desync a clean-vs-faulted
        # trajectory comparison
        for bad, steps in ((jnp.nan, plan.nan_grad_steps),
                           (jnp.inf, plan.inf_grad_steps)):
            # consume the seed stream even for unconfigured classes so a
            # plan's nan/inf placements do not depend on each other
            idx = candidates[int(rng.integers(len(candidates)))]
            leaf = leaves[idx]
            pos = int(rng.integers(leaf.size))
            if not steps:
                continue
            is_hit = jnp.any(step == jnp.asarray(steps, jnp.int32))
            flat = jnp.ravel(leaf)
            flat = flat.at[pos].set(
                jnp.where(is_hit, jnp.asarray(bad, leaf.dtype), flat[pos]))
            leaves[idx] = flat.reshape(leaf.shape)
        return jax.tree_util.tree_unflatten(treedef, leaves)

    # -- preemption (host boundary) ---------------------------------------

    def check_preemption(self, step: int) -> None:
        """Host-side step boundary: raises :class:`SimulatedPreemption`
        when ``step`` is a configured preemption step.

        Call it where a SIGTERM handler would fire — after dispatching the
        device step, before committing host-side state (checkpoint index,
        data-loader cursor).  The device computation in flight is simply
        abandoned, exactly as a real preemption abandons it.
        """
        if int(step) in self.plan.preempt_steps:
            emit_event("fault_injected", fault="preemption", step=int(step))
            raise SimulatedPreemption(int(step))

    # -- storage faults ----------------------------------------------------

    def corrupt_checkpoint(self, ckpt_dir: str, *, nbytes: int = 8) -> list[int]:
        """Flip ``nbytes`` seed-chosen bytes of ``<ckpt_dir>/data.bin``
        in place; returns the corrupted offsets (bit corruption)."""
        path = os.path.join(ckpt_dir, "data.bin")
        size = os.path.getsize(path)
        rng = np.random.default_rng(self.plan.seed)
        offsets = sorted(
            int(o) for o in rng.choice(size, size=min(nbytes, size),
                                       replace=False))
        with open(path, "r+b") as f:
            for off in offsets:
                f.seek(off)
                byte = f.read(1)[0]
                f.seek(off)
                f.write(bytes([byte ^ 0xFF]))
        emit_event("fault_injected", fault="checkpoint_corruption",
                   path=path, offsets=offsets)
        return offsets

    def truncate_checkpoint(self, ckpt_dir: str, *, drop_bytes: int = 1) -> None:
        """Truncate ``data.bin`` by ``drop_bytes`` (half-written writer)."""
        path = os.path.join(ckpt_dir, "data.bin")
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(size - drop_bytes, 0))
        emit_event("fault_injected", fault="checkpoint_truncation",
                   path=path, dropped=drop_bytes)


# -- supervisor-domain faults (PR 2) --------------------------------------


class SlowStep:
    """Host-side straggler: stall the step body at configured steps.

    Call ``slow(step)`` at the top of the step function — inside the
    watchdog's armed window — to block for ``duration_s`` on the chosen
    steps.  The computation itself is untouched (a straggler finishes,
    late), so a run that tolerates the stall stays bit-identical to an
    unfaulted one.  ``sleep`` is injectable for wait-free tests.
    """

    def __init__(self, steps: Iterable[int], duration_s: float = 0.3, *,
                 sleep: Callable[[float], None] = time.sleep):
        self.steps = frozenset(int(s) for s in steps)
        self.duration_s = float(duration_s)
        self._sleep = sleep

    def __call__(self, step: int) -> None:
        if int(step) in self.steps:
            emit_event("fault_injected", fault="slow_step", step=int(step),
                       duration_s=self.duration_s)
            self._sleep(self.duration_s)


class FlakyIterator:
    """Transiently failing producer: chosen fetches raise, then succeed.

    The fetch at (0-based) index ``i`` for each ``i`` in ``fail_at``
    raises ``exc_type`` ``failures`` times before succeeding — and the
    failures do NOT consume the underlying item, exactly like a storage
    frontend that errors before delivering.  A retry wrapper therefore
    recovers the *identical* stream an unfaulted run would see.
    """

    def __init__(self, it: Iterable, *, fail_at: Iterable[int] = (),
                 failures: int = 2,
                 exc_type: Type[Exception] = OSError,
                 message: str = "injected flaky fetch"):
        if failures < 1:
            raise ValueError(f"failures must be >= 1, got {failures}")
        self._it = iter(it)
        self.fail_at = frozenset(int(i) for i in fail_at)
        self.failures = failures
        self.exc_type = exc_type
        self.message = message
        self._idx = 0      # index of the next successful fetch
        self._raised = 0   # failures already raised at the current index

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._idx in self.fail_at and self._raised < self.failures:
            self._raised += 1
            emit_event("fault_injected", fault="flaky_iterator",
                       index=self._idx, failure=self._raised,
                       failures=self.failures)
            raise self.exc_type(
                f"{self.message} (index {self._idx}, "
                f"failure {self._raised}/{self.failures})")
        item = next(self._it)
        self._idx += 1
        self._raised = 0
        return item


class CorruptBatch:
    """Insert a corrupted COPY of chosen batches ahead of the clean ones.

    Insertion (rather than replacement) is the property that makes
    recovery *testable*: a guarded run that drops every corrupted copy
    consumes the exact clean stream an unfaulted run consumes, so their
    trajectories must match bit for bit.  ``at`` indexes the underlying
    clean stream (0-based).  Damage modes, applied to the first array
    leaf on the host (seed-driven placement for ``nan``):

    - ``"nan"``    — plant NaNs (spec-valid shape/dtype; finiteness check
      must catch it),
    - ``"shape"``  — drop the leading row,
    - ``"dtype"``  — cast to a different dtype of the same shape.
    """

    _MODES = ("nan", "shape", "dtype")

    def __init__(self, it: Iterable, *, at: Iterable[int] = (),
                 mode: str = "nan", seed: int = 0, n_elements: int = 3):
        if mode not in self._MODES:
            raise ValueError(f"mode must be one of {self._MODES}, got {mode!r}")
        self._it = iter(it)
        self.at = frozenset(int(i) for i in at)
        self.mode = mode
        self.seed = seed
        self.n_elements = n_elements
        self._idx = 0             # clean items fetched from the source
        self._pending = None      # clean item to deliver after its corrupt copy

    def __iter__(self) -> Iterator:
        return self

    def _corrupt(self, batch: Any) -> Any:
        leaves, treedef = jax.tree_util.tree_flatten(batch)
        if self.mode == "nan":
            # NaN damage needs a floating leaf (an int leaf has no NaN a
            # finiteness check could catch)
            target = next(
                (i for i, l in enumerate(leaves) if np.size(l)
                 and np.issubdtype(np.asarray(l).dtype, np.floating)), None)
        else:
            target = next((i for i, l in enumerate(leaves) if np.size(l)),
                          None)
        if target is None:
            # silently inserting an UNcorrupted copy would desync the
            # stream from an unfaulted run without testing anything —
            # surface the plan/batch mismatch instead
            raise ValueError(
                f"CorruptBatch(mode={self.mode!r}): batch has no "
                f"{'floating-point ' if self.mode == 'nan' else ''}"
                "non-empty array leaf to corrupt")
        arr = np.array(leaves[target])  # host copy; never touch the original
        if self.mode == "nan":
            rng = np.random.default_rng(self.seed)
            flat = arr.reshape(-1)
            pos = rng.choice(flat.size,
                             size=min(self.n_elements, flat.size),
                             replace=False)
            flat[pos] = np.nan
            arr = flat.reshape(arr.shape)
        elif self.mode == "shape":
            arr = arr[1:] if arr.ndim and arr.shape[0] > 0 else arr.reshape(-1)
        else:  # dtype
            arr = arr.astype(np.float64 if arr.dtype != np.float64
                             else np.float32)
        leaves = list(leaves)
        leaves[target] = arr
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def __next__(self):
        if self._pending is not None:
            item, self._pending = self._pending, None
            return item
        item = next(self._it)
        idx = self._idx
        self._idx += 1
        if idx in self.at:
            corrupted = self._corrupt(item)  # before touching _pending
            emit_event("fault_injected", fault="corrupt_batch", index=idx,
                       mode=self.mode)
            self._pending = item
            return corrupted
        return item
