"""Deterministic, seed-driven fault injection for training loops.

Every recovery path in :mod:`apex_tpu.resilience` is exercised by tier-1
tests instead of being discovered in production — which requires faults
that are *reproducible*: the same :class:`FaultPlan` seed produces the
same corrupted gradient elements, the same preemption step, and the same
flipped checkpoint bytes on every run.

Three fault classes, matching what pod-scale training actually sees
(PAPERS.md TPU-pod papers; ROADMAP north-star):

- **Numerical**: :meth:`FaultInjector.inject_grads` flips chosen gradient
  elements to NaN/Inf at configured steps.  jit-safe — the injection is a
  branch-free ``jnp.where`` on the on-device step counter, so it composes
  with the capturable train step exactly like a real overflow would.
- **Preemption**: :meth:`FaultInjector.check_preemption` raises
  :class:`SimulatedPreemption` at the configured step from the host-side
  step boundary — the point where a real SIGTERM lands, after the device
  step was dispatched but before the host commits/extends its state.
- **Storage**: :meth:`FaultInjector.corrupt_checkpoint` /
  :meth:`truncate_checkpoint` damage checkpoint bytes on disk the way a
  preempted writer or a bad disk does, to drive the validation-fallback
  path of :mod:`apex_tpu.resilience.checkpoint`.

PR 2 adds the *supervisor-domain* faults — the quiet failures that the
step watchdog, transient retry, and data guard exist to survive:

- **Stragglers**: :class:`SlowStep` stalls the host step body at chosen
  steps so the watchdog deadline fires deterministically.
- **Flaky producers**: :class:`FlakyIterator` makes a chosen fetch raise
  a transient error N times and then succeed *without consuming* the
  underlying item — the retry path recovers the exact same stream.
- **Corrupt records**: :class:`CorruptBatch` *inserts* a damaged copy of
  a chosen batch ahead of the clean one (NaN / shape / dtype damage),
  so a guarded run that skips it sees the identical clean stream as an
  unfaulted run — trajectory comparisons stay bit-exact.

PR 13 adds the *serving* faults the scheduler control plane is graded
under (wired through
:class:`~apex_tpu.serving.loadgen.LoadGenerator`'s ``step_hook``):

- **Straggler decode steps**: :class:`SlowDecodeStep` inflates chosen
  scheduler steps on the injectable (virtual) clock — queueing and
  deadline pressure appear deterministically, while the token streams
  (clock-independent by construction) must stay bit-identical.
- **Abandoned streams**: :class:`StallStream` cancels chosen requests
  once they have emitted N tokens — the client that stopped reading;
  the scheduler must reclaim the slot without disturbing neighbors.
- **Cancellation storms**: :class:`CancelStorm` cancels a seed-chosen
  subset of in-flight/queued requests at chosen steps — the
  mass-disconnect burst (a gateway restart) that exercises slot/block/
  pin release under load.

The fleet PR adds the *replica-scale* faults the
:class:`~apex_tpu.serving.fleet.FleetRouter` is graded under (same
``step_hook`` wiring, router in place of the scheduler):

- **Replica loss**: :class:`KillReplica` hard-kills a replica at a
  chosen step — device memory gone, streams re-queue on survivors and
  replay deterministically.
- **Replica hang**: :class:`WedgeReplica` stops a replica's heartbeats
  so the watchdog declares it dead and drains it via preempt-capture.
- **Replica straggler**: :class:`SlowReplica` makes a replica miss
  chosen beats while the shared clock inflates — SUSPECT then recover,
  token streams untouched.

PR 3 adds the *pod-scale* faults the elastic/consistency layer exists
to survive:

- **Replica divergence**: :class:`DesyncReplica` perturbs ONE dp rank's
  copy of one (seed- or name-chosen) leaf at a chosen host step — the
  silent bit-rot a cross-replica hash pass must detect, localize, and
  resync before the next all-reduce averages it into the whole pod.
- **Shard corruption**: :class:`CorruptShardFile` flips bytes inside
  exactly one shard record of a *sharded* (manifest v2) checkpoint, so
  the per-shard CRCs localize the damage and the restore walk falls
  back to the newest fully-valid step.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import (Any, Callable, Dict, Iterable, Iterator, Optional,
                    Tuple, Type)

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu._logging import emit_event

__all__ = [
    "CancelStorm",
    "CorruptBatch",
    "CorruptCandidateMidRollout",
    "CorruptShardFile",
    "CrashCheckpointWriter",
    "DesyncReplica",
    "FaultInjector",
    "FaultPlan",
    "FlakyIterator",
    "KillCanary",
    "KillReplica",
    "RegressingWeights",
    "ReloadStorm",
    "SimulatedPreemption",
    "SimulatedWriterCrash",
    "SlowDecodeStep",
    "SlowReplica",
    "SlowStep",
    "StallStream",
    "WedgeReplica",
]


class SimulatedPreemption(RuntimeError):
    """Raised at an injected preemption boundary (stands in for SIGTERM)."""

    def __init__(self, step: int):
        super().__init__(f"simulated preemption at step {step}")
        self.step = step


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """What to break, and when (all step indices are host step numbers).

    ``nan_grad_steps`` / ``inf_grad_steps``: steps whose gradients get
    deterministic NaN / Inf elements injected.  ``preempt_steps``: steps
    whose host boundary raises :class:`SimulatedPreemption`.  ``seed``
    drives every placement choice.
    """

    seed: int = 0
    nan_grad_steps: Tuple[int, ...] = ()
    inf_grad_steps: Tuple[int, ...] = ()
    preempt_steps: Tuple[int, ...] = ()


class FaultInjector:
    """Executes a :class:`FaultPlan` against a training loop."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan

    # -- numerical faults (jit-safe) --------------------------------------

    def inject_grads(self, grads: Any, step: jax.Array) -> Any:
        """Return ``grads`` with NaN/Inf planted when ``step`` is a
        configured fault step; a no-op (same values) otherwise.

        jit-safe: ``step`` may be a traced on-device scalar.  The target
        leaf and element are chosen deterministically from the seed at
        trace time, so recompilation cannot move the fault.
        """
        plan = self.plan
        if not plan.nan_grad_steps and not plan.inf_grad_steps:
            return grads
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        # a fault can only live in a non-empty floating-point leaf
        candidates = [i for i, l in enumerate(leaves)
                      if l.size and jnp.issubdtype(l.dtype, jnp.inexact)]
        if not candidates:
            return grads
        rng = np.random.default_rng(plan.seed)
        step = jnp.asarray(step, jnp.int32)
        # the fault is planted in the leaf's OWN dtype (every float dtype
        # has nan/inf), so off-step execution is bit-identical — no
        # precision roundtrip that would desync a clean-vs-faulted
        # trajectory comparison
        for bad, steps in ((jnp.nan, plan.nan_grad_steps),
                           (jnp.inf, plan.inf_grad_steps)):
            # consume the seed stream even for unconfigured classes so a
            # plan's nan/inf placements do not depend on each other
            idx = candidates[int(rng.integers(len(candidates)))]
            leaf = leaves[idx]
            pos = int(rng.integers(leaf.size))
            if not steps:
                continue
            is_hit = jnp.any(step == jnp.asarray(steps, jnp.int32))
            flat = jnp.ravel(leaf)
            flat = flat.at[pos].set(
                jnp.where(is_hit, jnp.asarray(bad, leaf.dtype), flat[pos]))
            leaves[idx] = flat.reshape(leaf.shape)
        return jax.tree_util.tree_unflatten(treedef, leaves)

    # -- preemption (host boundary) ---------------------------------------

    def check_preemption(self, step: int) -> None:
        """Host-side step boundary: raises :class:`SimulatedPreemption`
        when ``step`` is a configured preemption step.

        Call it where a SIGTERM handler would fire — after dispatching the
        device step, before committing host-side state (checkpoint index,
        data-loader cursor).  The device computation in flight is simply
        abandoned, exactly as a real preemption abandons it.
        """
        if int(step) in self.plan.preempt_steps:
            emit_event("fault_injected", fault="preemption", step=int(step))
            raise SimulatedPreemption(int(step))

    # -- storage faults ----------------------------------------------------

    def corrupt_checkpoint(self, ckpt_dir: str, *, nbytes: int = 8) -> list[int]:
        """Flip ``nbytes`` seed-chosen bytes of ``<ckpt_dir>/data.bin``
        in place; returns the corrupted offsets (bit corruption)."""
        path = os.path.join(ckpt_dir, "data.bin")
        size = os.path.getsize(path)
        rng = np.random.default_rng(self.plan.seed)
        offsets = sorted(
            int(o) for o in rng.choice(size, size=min(nbytes, size),
                                       replace=False))
        with open(path, "r+b") as f:
            for off in offsets:
                f.seek(off)
                byte = f.read(1)[0]
                f.seek(off)
                f.write(bytes([byte ^ 0xFF]))
        emit_event("fault_injected", fault="checkpoint_corruption",
                   path=path, offsets=offsets)
        return offsets

    def truncate_checkpoint(self, ckpt_dir: str, *, drop_bytes: int = 1) -> None:
        """Truncate ``data.bin`` by ``drop_bytes`` (half-written writer)."""
        path = os.path.join(ckpt_dir, "data.bin")
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(size - drop_bytes, 0))
        emit_event("fault_injected", fault="checkpoint_truncation",
                   path=path, dropped=drop_bytes)


# -- supervisor-domain faults (PR 2) --------------------------------------


class SlowStep:
    """Host-side straggler: stall the step body at configured steps.

    Call ``slow(step)`` at the top of the step function — inside the
    watchdog's armed window — to block for ``duration_s`` on the chosen
    steps.  The computation itself is untouched (a straggler finishes,
    late), so a run that tolerates the stall stays bit-identical to an
    unfaulted one.  ``sleep`` is injectable for wait-free tests.
    """

    def __init__(self, steps: Iterable[int], duration_s: float = 0.3, *,
                 sleep: Callable[[float], None] = time.sleep):
        self.steps = frozenset(int(s) for s in steps)
        self.duration_s = float(duration_s)
        self._sleep = sleep

    def __call__(self, step: int) -> None:
        if int(step) in self.steps:
            emit_event("fault_injected", fault="slow_step", step=int(step),
                       duration_s=self.duration_s)
            self._sleep(self.duration_s)


class FlakyIterator:
    """Transiently failing producer: chosen fetches raise, then succeed.

    The fetch at (0-based) index ``i`` for each ``i`` in ``fail_at``
    raises ``exc_type`` ``failures`` times before succeeding — and the
    failures do NOT consume the underlying item, exactly like a storage
    frontend that errors before delivering.  A retry wrapper therefore
    recovers the *identical* stream an unfaulted run would see.
    """

    def __init__(self, it: Iterable, *, fail_at: Iterable[int] = (),
                 failures: int = 2,
                 exc_type: Type[Exception] = OSError,
                 message: str = "injected flaky fetch"):
        if failures < 1:
            raise ValueError(f"failures must be >= 1, got {failures}")
        self._it = iter(it)
        self.fail_at = frozenset(int(i) for i in fail_at)
        self.failures = failures
        self.exc_type = exc_type
        self.message = message
        self._idx = 0      # index of the next successful fetch
        self._raised = 0   # failures already raised at the current index

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._idx in self.fail_at and self._raised < self.failures:
            self._raised += 1
            emit_event("fault_injected", fault="flaky_iterator",
                       index=self._idx, failure=self._raised,
                       failures=self.failures)
            raise self.exc_type(
                f"{self.message} (index {self._idx}, "
                f"failure {self._raised}/{self.failures})")
        item = next(self._it)
        self._idx += 1
        self._raised = 0
        return item


class CorruptBatch:
    """Insert a corrupted COPY of chosen batches ahead of the clean ones.

    Insertion (rather than replacement) is the property that makes
    recovery *testable*: a guarded run that drops every corrupted copy
    consumes the exact clean stream an unfaulted run consumes, so their
    trajectories must match bit for bit.  ``at`` indexes the underlying
    clean stream (0-based).  Damage modes, applied to the first array
    leaf on the host (seed-driven placement for ``nan``):

    - ``"nan"``    — plant NaNs (spec-valid shape/dtype; finiteness check
      must catch it),
    - ``"shape"``  — drop the leading row,
    - ``"dtype"``  — cast to a different dtype of the same shape.
    """

    _MODES = ("nan", "shape", "dtype")

    def __init__(self, it: Iterable, *, at: Iterable[int] = (),
                 mode: str = "nan", seed: int = 0, n_elements: int = 3):
        if mode not in self._MODES:
            raise ValueError(f"mode must be one of {self._MODES}, got {mode!r}")
        self._it = iter(it)
        self.at = frozenset(int(i) for i in at)
        self.mode = mode
        self.seed = seed
        self.n_elements = n_elements
        self._idx = 0             # clean items fetched from the source
        self._pending = None      # clean item to deliver after its corrupt copy

    def __iter__(self) -> Iterator:
        return self

    def _corrupt(self, batch: Any) -> Any:
        leaves, treedef = jax.tree_util.tree_flatten(batch)
        if self.mode == "nan":
            # NaN damage needs a floating leaf (an int leaf has no NaN a
            # finiteness check could catch)
            target = next(
                (i for i, l in enumerate(leaves) if np.size(l)
                 and np.issubdtype(np.asarray(l).dtype, np.floating)), None)
        else:
            target = next((i for i, l in enumerate(leaves) if np.size(l)),
                          None)
        if target is None:
            # silently inserting an UNcorrupted copy would desync the
            # stream from an unfaulted run without testing anything —
            # surface the plan/batch mismatch instead
            raise ValueError(
                f"CorruptBatch(mode={self.mode!r}): batch has no "
                f"{'floating-point ' if self.mode == 'nan' else ''}"
                "non-empty array leaf to corrupt")
        arr = np.array(leaves[target])  # host copy; never touch the original
        if self.mode == "nan":
            rng = np.random.default_rng(self.seed)
            flat = arr.reshape(-1)
            pos = rng.choice(flat.size,
                             size=min(self.n_elements, flat.size),
                             replace=False)
            flat[pos] = np.nan
            arr = flat.reshape(arr.shape)
        elif self.mode == "shape":
            arr = arr[1:] if arr.ndim and arr.shape[0] > 0 else arr.reshape(-1)
        else:  # dtype
            arr = arr.astype(np.float64 if arr.dtype != np.float64
                             else np.float32)
        leaves = list(leaves)
        leaves[target] = arr
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def __next__(self):
        if self._pending is not None:
            item, self._pending = self._pending, None
            return item
        item = next(self._it)
        idx = self._idx
        self._idx += 1
        if idx in self.at:
            corrupted = self._corrupt(item)  # before touching _pending
            emit_event("fault_injected", fault="corrupt_batch", index=idx,
                       mode=self.mode)
            self._pending = item
            return corrupted
        return item


# -- serving faults (PR 13) -------------------------------------------------


class SlowDecodeStep:
    """Straggler scheduler steps: inflate chosen steps on the
    injectable clock.

    Install as a :class:`~apex_tpu.serving.loadgen.LoadGenerator`
    ``step_hook``: at each configured (0-based) step index the hook
    advances ``clock`` — which must be the scheduler's own
    :class:`~apex_tpu.serving.loadgen.VirtualClock` — by ``extra_s``,
    exactly as if that step's decode dispatch had stalled.  Queue wait,
    TTFT, and deadline pressure shift deterministically; the token
    streams must not move a bit (the scheduler's determinism contract:
    the clock feeds telemetry and policy, never token choice) — the
    chaos acceptance run asserts exactly that.
    """

    def __init__(self, steps: Iterable[int], extra_s: float, *, clock):
        if extra_s <= 0:
            raise ValueError(f"extra_s must be > 0, got {extra_s}")
        if not hasattr(clock, "advance"):
            raise ValueError(
                "SlowDecodeStep needs an advanceable clock — pass the "
                "scheduler's VirtualClock (a real monotonic clock "
                "cannot be inflated)")
        self.steps = frozenset(int(s) for s in steps)
        self.extra_s = float(extra_s)
        self._clock = clock

    def __call__(self, step: int, scheduler=None) -> None:
        if int(step) in self.steps:
            emit_event("fault_injected", fault="slow_decode_step",
                       step=int(step), extra_s=self.extra_s)
            self._clock.advance(self.extra_s)


class StallStream:
    """Abandoned-client streams: cancel chosen rids after N tokens.

    A client that stops reading mid-stream looks, server-side, like a
    request that must be cancelled to reclaim its slot.  Install as a
    ``step_hook``: once a configured rid's stream has emitted at least
    ``after_tokens`` tokens, it is cancelled (once).  The neighbors'
    streams must be bit-identical to an unstalled run — cancellation
    releases the slot, blocks, and pins without touching them.
    """

    def __init__(self, rids: Iterable[str], *, after_tokens: int = 2):
        if after_tokens < 1:
            raise ValueError(
                f"after_tokens must be >= 1, got {after_tokens}")
        self.rids = frozenset(str(r) for r in rids)
        self.after_tokens = int(after_tokens)
        self.stalled: list = []          # rids actually cancelled

    def __call__(self, step: int, scheduler) -> None:
        done = set(self.stalled)
        for rid in sorted(self.rids - done):
            if scheduler.phase_of(rid).value == "done":
                continue                 # finished before the stall bit
            tokens = scheduler.progress_of(rid)
            if tokens >= self.after_tokens:
                emit_event("fault_injected", fault="stall_stream",
                           rid=rid, step=int(step), tokens=tokens)
                scheduler.cancel(rid)
                self.stalled.append(rid)


class CancelStorm:
    """Mass-disconnect burst: cancel a seed-chosen subset of live
    requests at chosen steps.

    At each configured step, up to ``count`` rids are drawn
    (deterministically, from ``seed``) from everything currently
    queued or active on the scheduler and cancelled — the gateway
    -restart burst.  Surviving streams must be bit-identical to a
    storm-free run; every cancelled slot/block/pin must be reclaimed.
    ``cancelled`` records what the storm actually hit, for assertions.
    """

    def __init__(self, steps: Iterable[int], *, count: int = 2,
                 seed: int = 0):
        if count < 1:
            raise ValueError(f"count must be >= 1, got {count}")
        self.steps = frozenset(int(s) for s in steps)
        self.count = int(count)
        self.seed = int(seed)
        self.cancelled: list = []

    def __call__(self, step: int, scheduler) -> None:
        if int(step) not in self.steps:
            return
        live = sorted(scheduler.queued_rids + scheduler.active_rids)
        if not live:
            return
        rng = np.random.default_rng(self.seed + int(step))
        hit = [live[i] for i in sorted(
            rng.choice(len(live), size=min(self.count, len(live)),
                       replace=False))]
        emit_event("fault_injected", fault="cancel_storm",
                   step=int(step), rids=hit)
        for rid in hit:
            scheduler.cancel(rid)
            self.cancelled.append(rid)


class ReloadStorm:
    """Hot-reload pressure: force weight reload attempts at chosen
    steps while the scheduler is under load.

    Install as a ``step_hook`` alongside an overloaded open-loop
    workload: at each configured (0-based) step index the hook calls
    ``reloader.reload()`` (or ``maybe_reload()`` when ``force=False``
    — then only steps where the watcher actually sees a newer commit
    reload).  The chaos acceptance contract: however many swaps,
    refusals, and rollback-fodder candidates the storm generates,
    every in-flight stream survives and the scheduler's accounting
    (slots, blocks, pins, queue) stays exact.  ``outcomes`` records
    each attempt's :class:`~apex_tpu.serving.reload.ReloadOutcome`
    (or None for a no-op ``maybe_reload``) for assertions.
    """

    def __init__(self, steps: Iterable[int], *, reloader,
                 force: bool = False):
        self.steps = frozenset(int(s) for s in steps)
        self.reloader = reloader
        self.force = bool(force)
        self.outcomes: list = []

    def __call__(self, step: int, scheduler=None) -> None:
        if int(step) not in self.steps:
            return
        emit_event("fault_injected", fault="reload_storm",
                   step=int(step), forced=self.force)
        if self.force:
            out = self.reloader.reload()
        else:
            out = self.reloader.maybe_reload()
        self.outcomes.append(out)


# -- fleet faults (ISSUE 17) ------------------------------------------------


class KillReplica:
    """Hard-kill a fleet replica at a chosen step (device memory
    lost).

    Install as a :class:`~apex_tpu.serving.loadgen.LoadGenerator`
    ``step_hook`` driving a
    :class:`~apex_tpu.serving.fleet.FleetRouter`: at the configured
    (0-based) step the router's :meth:`~apex_tpu.serving.fleet.
    FleetRouter.kill` fires — the victim's in-flight streams re-queue
    on survivors from their host-side request records and **replay
    deterministically** (the final token streams are bit-identical to
    an unperturbed run; the device cache is honestly gone, so the
    already-emitted tokens are re-earned, not restored).  The killed
    scheduler is routed through ``close()`` so prefix pins and paged
    block holds never leak.
    """

    def __init__(self, replica: str, *, at_step: int):
        if at_step < 0:
            raise ValueError(f"at_step must be >= 0, got {at_step}")
        self.replica = str(replica)
        self.at_step = int(at_step)
        self.killed = False

    def __call__(self, step: int, router) -> None:
        if self.killed or int(step) != self.at_step:
            return
        emit_event("fault_injected", fault="kill_replica",
                   replica=self.replica, step=int(step))
        router.kill(self.replica)
        self.killed = True


class WedgeReplica:
    """Hard-hang a fleet replica at a chosen step: its steps never
    complete again, so it stops heartbeating and the router's watchdog
    walks it HEALTHY → SUSPECT → DEAD on the shared clock, then drains
    it via preempt-capture (host and device state are intact — a hang
    is not a loss), resuming dense victims on survivors **mid-stream,
    bit-exactly**.
    """

    def __init__(self, replica: str, *, at_step: int):
        if at_step < 0:
            raise ValueError(f"at_step must be >= 0, got {at_step}")
        self.replica = str(replica)
        self.at_step = int(at_step)
        self.wedged = False

    def __call__(self, step: int, router) -> None:
        if self.wedged or int(step) != self.at_step:
            return
        emit_event("fault_injected", fault="wedge_replica",
                   replica=self.replica, step=int(step))
        router.wedge(self.replica)
        self.wedged = True


class SlowReplica:
    """Straggler replica: at each configured step the replica's step
    fails to complete within the boundary (one missed heartbeat per
    configured step) and the shared clock inflates by ``extra_s``.  A
    run of stalls longer than ``suspect_after_s`` drives the replica
    SUSPECT (placements route around it); shorter than
    ``dead_after_s`` it recovers on its next completed beat — HEALTHY
    again with WRR credits reset.  Token streams must not move a bit
    (clock feeds health and telemetry, never token choice).
    """

    def __init__(self, replica: str, steps: Iterable[int],
                 extra_s: float, *, clock):
        if extra_s <= 0:
            raise ValueError(f"extra_s must be > 0, got {extra_s}")
        if not hasattr(clock, "advance"):
            raise ValueError(
                "SlowReplica needs an advanceable clock — pass the "
                "fleet's VirtualClock (a real monotonic clock cannot "
                "be inflated)")
        self.replica = str(replica)
        self.steps = frozenset(int(s) for s in steps)
        self.extra_s = float(extra_s)
        self._clock = clock

    def __call__(self, step: int, router) -> None:
        if int(step) not in self.steps:
            return
        emit_event("fault_injected", fault="slow_replica",
                   replica=self.replica, step=int(step),
                   extra_s=self.extra_s)
        router.stall(self.replica)
        self._clock.advance(self.extra_s)


# -- rollout faults (ISSUE 18) ----------------------------------------------


class CorruptCandidateMidRollout:
    """Flip bytes in the rollout's candidate checkpoint at a chosen
    loadgen step — the committed-but-rotted candidate a rolling
    upgrade must refuse.

    ``step_hook`` over a :class:`~apex_tpu.serving.fleet.FleetRouter`
    run driven by a :class:`~apex_tpu.serving.rollout.
    RollingReloadController`: at ``at_step`` the candidate's
    ``data.bin`` gets seed-chosen bytes flipped in place (the
    :meth:`FaultInjector.corrupt_checkpoint` corruption).  Any replica
    whose reload restores those bytes refuses first-class (the
    checksum/validation gate), which the controller turns into
    automatic halt + fleet rollback.  Fire it *before* the victim
    wave's prefetch — a stage restored earlier already holds clean
    bytes (restore-ahead is exactly that window).
    """

    def __init__(self, root: str, step: int, *, at_step: int,
                 seed: int = 0, nbytes: int = 8):
        if at_step < 0:
            raise ValueError(f"at_step must be >= 0, got {at_step}")
        self.root = str(root)
        self.step = int(step)
        self.at_step = int(at_step)
        self.seed = int(seed)
        self.nbytes = int(nbytes)
        self.corrupted = False

    def __call__(self, step: int, router=None) -> None:
        if self.corrupted or int(step) != self.at_step:
            return
        from apex_tpu.resilience.checkpoint import _step_dirname

        emit_event("fault_injected", fault="corrupt_candidate",
                   step=int(step), candidate_step=self.step)
        injector = FaultInjector(FaultPlan(seed=self.seed))
        injector.corrupt_checkpoint(
            os.path.join(self.root, _step_dirname(self.step)),
            nbytes=self.nbytes)
        self.corrupted = True


class RegressingWeights:
    """A candidate that *validates clean but serves measurably worse*
    — the regression only a canary gate catches.

    :meth:`publish` commits a spec-valid candidate (same tree,
    shapes, dtypes — every structural gate passes) whose weights are
    perturbed.  The serving regression itself is modeled by the hook:
    on a virtual clock no weight value can slow its own matmul, so
    *any* replica currently serving the candidate step is stalled
    every ``slow_every``-th call
    (:meth:`~apex_tpu.serving.fleet.FleetRouter.stall` — its streams
    miss that beat), inflating the candidate arm's per-token latency
    deterministically while old-version replicas run clean.  During a
    gated rollout only the canary serves the candidate, so only the
    canary degrades and the gate catches it; with the gate disabled
    the whole fleet ends up on the candidate and the whole fleet
    degrades — the goodput contrast the gate exists to buy.  Stalls
    are phase-offset per replica so a fully-upgraded fleet halves its
    capacity rather than freezing outright.  Keep ``slow_every *
    step_time`` under the fleet's ``suspect_after_s`` so the watchdog
    never escalates — the regression must be caught by the *gate*,
    not the health check.  The stalling stops on its own when a
    replica leaves the candidate step (rollback).
    """

    def __init__(self, controller, *, slow_every: int = 2):
        if slow_every < 2:
            raise ValueError(
                f"slow_every must be >= 2, got {slow_every} — at 1 "
                f"every step stalls and streams never finish")
        self.controller = controller
        self.slow_every = int(slow_every)
        self.stalls = 0
        self._announced = False
        self._ticks: Dict[str, int] = {}

    @staticmethod
    def publish(root: str, params: Any, step: int, *,
                delta: float = 1e-3) -> Any:
        """Commit the degraded-but-valid candidate
        ``{"params": params + delta}`` at ``step``; returns the
        perturbed tree (for bit-exactness assertions)."""
        from apex_tpu.resilience.checkpoint import save_checkpoint

        bad = jax.tree.map(
            lambda l: (l + jnp.asarray(delta, l.dtype)
                       if jnp.issubdtype(jnp.asarray(l).dtype,
                                         jnp.inexact) else l),
            params)
        save_checkpoint(str(root), int(step), {"params": bad})
        return bad

    def __call__(self, step: int, router) -> None:
        c = self.controller
        if c.target_step is None:
            return
        for idx, name in enumerate(router.replica_names):
            sched = router.replica(name)
            if getattr(sched, "weights_step", None) != c.target_step:
                continue                 # not serving the candidate
            if not self._announced:
                emit_event("fault_injected",
                           fault="regressing_weights", replica=name,
                           step=int(step),
                           candidate_step=c.target_step)
                self._announced = True
            tick = self._ticks.get(name, 0)
            self._ticks[name] = tick + 1
            if (tick + idx) % self.slow_every == 0:
                self.stalls += 1
                router.stall(name)


class KillCanary:
    """Kill the canary replica mid-verdict-window (device memory
    lost) — the rollout must halt and roll back, and the canary's
    in-flight streams must replay losslessly on the old-version
    survivors.

    ``step_hook``: once the controller enters its canary window
    (traffic pinned), waits ``after_window_steps`` window steps, then
    hard-kills whichever replica the controller chose as canary.
    """

    def __init__(self, controller, *, after_window_steps: int = 1):
        if after_window_steps < 1:
            raise ValueError(f"after_window_steps must be >= 1, got "
                             f"{after_window_steps}")
        self.controller = controller
        self.after = int(after_window_steps)
        self.killed = False
        self._seen = 0

    def __call__(self, step: int, router) -> None:
        if self.killed or self.controller.phase != "canary":
            return
        self._seen += 1
        if self._seen < self.after:
            return
        emit_event("fault_injected", fault="kill_canary",
                   replica=self.controller.canary, step=int(step))
        router.kill(self.controller.canary)
        self.killed = True


# -- pod-scale faults (PR 3) -----------------------------------------------


class DesyncReplica:
    """Silently diverge ONE dp rank's copy of one leaf at chosen steps.

    Operates on the *stacked* per-replica representation (leaves with a
    leading replica axis — see :mod:`apex_tpu.resilience.consistency`):
    ``desync(state, step)`` returns ``state`` with a deterministic
    perturbation added to one element of rank ``rank``'s slice of the
    chosen leaf, and ``state`` unchanged off the configured steps.  The
    perturbation is pure host-side array surgery — no collective runs,
    no event fires beyond ``fault_injected`` — exactly the silent HBM
    bit-rot / stale-update divergence a cross-replica hash pass exists
    to catch before the next all-reduce averages it into the whole pod.

    ``leaf`` selects the victim by keystr substring; None picks
    seed-deterministically among the floating stacked leaves.  The
    element offset within the slice is seed-chosen.
    """

    def __init__(self, steps: Iterable[int], *, rank: int = 1,
                 leaf: Any = None, seed: int = 0, delta: float = 1e-3,
                 axis_name: str = "dp"):
        self.steps = frozenset(int(s) for s in steps)
        self.rank = int(rank)
        self.leaf = leaf
        self.seed = int(seed)
        self.delta = float(delta)
        self.axis_name = axis_name

    def _stacked(self, leaf: Any) -> bool:
        """A perturbable per-replica leaf: non-empty floating array whose
        leading axis is the replica stack (spec leads with the replica
        axis when the leaf carries a NamedSharding; any leading axis
        wider than ``rank`` qualifies for plain host arrays)."""
        if np.ndim(leaf) < 1 or not np.size(leaf):
            return False
        try:
            if not jnp.issubdtype(leaf.dtype, jnp.inexact):
                return False
        except (AttributeError, TypeError):
            return False
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and hasattr(sharding, "spec"):
            from apex_tpu.resilience.consistency import _entry_names

            spec = sharding.spec
            lead = spec[0] if len(spec) else None
            if self.axis_name not in _entry_names(lead):
                return False
        return np.shape(leaf)[0] > self.rank

    def __call__(self, state: Any, step: int) -> Any:
        if int(step) not in self.steps:
            return state
        flat, treedef = jax.tree_util.tree_flatten_with_path(state)
        candidates = [
            (i, jax.tree_util.keystr(path))
            for i, (path, leaf) in enumerate(flat)
            if self._stacked(leaf)
            and (self.leaf is None or str(self.leaf) in
                 jax.tree_util.keystr(path))]
        if not candidates:
            raise ValueError(
                f"DesyncReplica(leaf={self.leaf!r}): no stacked floating "
                f"leaf with a replica axis wider than rank {self.rank}")
        rng = np.random.default_rng(self.seed)
        idx, key = candidates[int(rng.integers(len(candidates)))]
        _, victim = flat[idx]
        sharding = getattr(victim, "sharding", None)
        arr = np.array(jax.device_get(victim))  # writable host copy
        slice_flat = arr[self.rank].reshape(-1)
        pos = int(rng.integers(slice_flat.size))
        cell = slice_flat[pos:pos + 1]
        before = cell.tobytes()
        cell[0] = cell[0] + np.asarray(self.delta, arr.dtype)
        if cell.tobytes() == before:
            # delta rounded away (low-precision dtype, large magnitude):
            # the injection must still be a real byte-level divergence,
            # so flip the lowest mantissa bit instead of silently no-oping
            as_uint = cell.view(np.dtype(f"u{cell.dtype.itemsize}"))
            as_uint[0] ^= 1
        out = jnp.asarray(arr)
        if sharding is not None:
            out = jax.device_put(out, sharding)
        leaves = [l for _, l in flat]
        leaves[idx] = out
        emit_event("fault_injected", fault="desync_replica", step=int(step),
                   leaf=key, rank=self.rank, element=pos, delta=self.delta)
        return jax.tree_util.tree_unflatten(treedef, leaves)


class SimulatedWriterCrash(RuntimeError):
    """A checkpoint writer died mid-write (stands in for SIGKILL).

    ``preserve_partial_write`` makes the write machinery skip its
    temp-dir cleanup — exactly the on-disk state a hard kill leaves: a
    partially written ``tmp_*`` dir that ``latest_valid_step`` and the
    restore walk can never select, reclaimed by the next save's orphan
    sweep.  Deterministic (``transient = False``): a crashed writer is
    not an I/O blip, so the retry layer never re-runs it."""

    preserve_partial_write = True
    transient = False

    def __init__(self, step: int, record: int):
        super().__init__(
            f"simulated writer crash at step {step}, record {record}")
        self.step = step
        self.record = record


class CrashCheckpointWriter:
    """Kill the (background) checkpoint writer after N leaf records.

    Install as the write machinery's ``progress_hook`` (e.g.
    ``AsyncCheckpointer(manager, progress_hook=CrashCheckpointWriter())``
    or ``manager.write_snapshot(..., progress_hook=...)``): the hook
    fires after each manifest record is written, and once
    ``after_records`` records are on disk it raises
    :class:`SimulatedWriterCrash` — leaving the partial temp dir behind
    like a real SIGKILL (see ``preserve_partial_write``).  ``steps``
    optionally restricts the crash to chosen host steps; one crash per
    instance (``fired``), so a retried or subsequent save succeeds.
    """

    def __init__(self, *, after_records: int = 1,
                 steps: Optional[Iterable[int]] = None):
        if after_records < 1:
            raise ValueError(
                f"after_records must be >= 1, got {after_records}")
        self.after_records = int(after_records)
        self.steps = None if steps is None else frozenset(
            int(s) for s in steps)
        self.fired = False
        self._seen = 0

    def __call__(self, progress: dict) -> None:
        if self.fired:
            return
        if self.steps is not None and int(progress["step"]) not in self.steps:
            return
        self._seen += 1
        if self._seen >= self.after_records:
            self.fired = True
            emit_event("fault_injected", fault="writer_crash",
                       step=int(progress["step"]),
                       record=int(progress["record"]),
                       bytes=int(progress["bytes"]))
            raise SimulatedWriterCrash(int(progress["step"]),
                                       int(progress["record"]))


class CorruptShardFile:
    """Flip bytes inside exactly ONE shard record of a v2 checkpoint.

    The damage is confined to the chosen shard's byte extent in
    ``data.bin`` — the manifest and every other shard stay intact — so
    the per-shard CRCs must localize it (validation names the shard's
    mesh coordinates and leaf) and the restore walk must fall back to
    the newest fully-valid step.  ``leaf`` selects the victim leaf by
    keystr substring (None: seed-chosen among leaves with non-empty
    shards); ``shard`` indexes that leaf's shard list.  Returns what was
    damaged, for assertions.
    """

    def __init__(self, *, leaf: Any = None, shard: int = 0,
                 nbytes: int = 4, seed: int = 0):
        if nbytes < 1:
            raise ValueError(f"nbytes must be >= 1, got {nbytes}")
        self.leaf = leaf
        self.shard = int(shard)
        self.nbytes = int(nbytes)
        self.seed = int(seed)

    def __call__(self, ckpt_dir: str) -> dict:
        import json

        with open(os.path.join(ckpt_dir, "manifest.json")) as f:
            manifest = json.load(f)
        if manifest.get("format_version") != 2:
            raise ValueError(
                f"{ckpt_dir}: CorruptShardFile needs a sharded (v2) "
                f"checkpoint, got format_version "
                f"{manifest.get('format_version')}")
        recs = [r for r in manifest["leaves"]
                if r.get("shards")
                and any(s.get("nbytes") for s in r["shards"])
                and (self.leaf is None or str(self.leaf) in r["path"])]
        if not recs:
            raise ValueError(
                f"{ckpt_dir}: no leaf matching {self.leaf!r} with a "
                f"non-empty shard to corrupt")
        rng = np.random.default_rng(self.seed)
        rec = recs[int(rng.integers(len(recs)))]
        shards = [s for s in rec["shards"] if s.get("nbytes")]
        shard = shards[self.shard % len(shards)]
        offsets = sorted(
            int(shard["offset"]) + int(o)
            for o in rng.choice(int(shard["nbytes"]),
                                size=min(self.nbytes, int(shard["nbytes"])),
                                replace=False))
        path = os.path.join(ckpt_dir, "data.bin")
        with open(path, "r+b") as f:
            for off in offsets:
                f.seek(off)
                byte = f.read(1)[0]
                f.seek(off)
                f.write(bytes([byte ^ 0xFF]))
        emit_event("fault_injected", fault="shard_corruption", path=path,
                   leaf=rec["path"], coords=shard.get("coords"),
                   offsets=offsets)
        return {"leaf": rec["path"], "coords": shard.get("coords"),
                "offsets": offsets}
