"""Anomaly-aware stepping: localize, skip, degrade — never loop silently.

Layered on :mod:`apex_tpu.amp.scaler`: the capturable train step already
skips the optimizer update on overflow (``found_inf`` + ``jnp.where``),
but a bare skip loop has two production failure modes this module closes:

1. **No localization.**  The global ``found_inf`` bit says *that* a step
   overflowed, not *where*.  :func:`nonfinite_counts` is the jit-safe
   per-leaf census (count of NaN/Inf elements per gradient leaf);
   :func:`nonfinite_report` renders it as ``{leaf path: count}`` on the
   host — the difference between "step 4017 overflowed" and "step 4017
   overflowed in ``layers_12/attn/out_proj`` only".
2. **No escape hatch.**  If the loss scale backs off to its floor and
   gradients *still* blow up (a real divergence, not scale-induced
   overflow), ``update`` skips forever.  :func:`guarded_update` keeps a
   consecutive-skip counter in :class:`GuardState`; after ``patience``
   consecutive skips it halves the dynamic scale floor (letting backoff
   continue below the configured ``min_loss_scale``) and emits a
   structured ``loss_scale_floor_halved`` event through
   :func:`apex_tpu._logging.emit_event` — degradation is visible and
   bounded instead of silent and infinite.

Everything here is jit-safe; the event emission crosses to the host
through ``jax.debug.callback``, which is the supported effect boundary
under jit.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from apex_tpu._logging import emit_event
from apex_tpu.amp.scaler import LossScaler, LossScalerState
from apex_tpu.utils.serialization import tree_paths

__all__ = [
    "GuardConfig",
    "GuardState",
    "guarded_update",
    "init_guard_state",
    "make_guarded_step",
    "nonfinite_counts",
    "nonfinite_report",
]


class GuardState(NamedTuple):
    """Device-resident skip bookkeeping (jit-safe scalars, checkpointable
    alongside :class:`LossScalerState`)."""

    consecutive_skips: jax.Array  # i32 current skip run length
    total_skips: jax.Array  # i32 lifetime skipped steps
    scale_floor: jax.Array  # f32 dynamic min_loss_scale (halves on trip)


@dataclasses.dataclass(frozen=True)
class GuardConfig:
    """``patience``: consecutive skips tolerated before degrading.
    ``floor_backoff``: factor applied to the dynamic floor on each trip.
    ``min_floor``: hard lower bound — below this the run is diverging and
    no loss scale can save it (events keep firing so the operator sees)."""

    patience: int = 8
    floor_backoff: float = 0.5
    min_floor: float = 2.0**-14

    def __post_init__(self):
        # patience=0 would make the trip condition (consec >= patience)
        # true on CLEAN steps and silently destroy loss scaling
        if self.patience < 1:
            raise ValueError(f"patience must be >= 1, got {self.patience}")
        if not 0.0 < self.floor_backoff <= 1.0:
            raise ValueError(
                f"floor_backoff must be in (0, 1], got {self.floor_backoff}")
        if self.min_floor <= 0.0:
            raise ValueError(
                f"min_floor must be positive, got {self.min_floor}")


def init_guard_state(scaler: LossScaler) -> GuardState:
    """Zeroed counters; the dynamic floor starts at the scaler's
    configured ``min_loss_scale``."""
    return GuardState(
        consecutive_skips=jnp.int32(0),
        total_skips=jnp.int32(0),
        scale_floor=jnp.float32(scaler.min_loss_scale),
    )


def nonfinite_counts(grads: Any) -> Any:
    """Per-leaf count of non-finite elements (i32 scalars; jit-safe).

    This is the localizing refinement of the global overflow bit computed
    by ``multi_tensor_apply._nonfinite``: same traversal, but the result
    keeps the pytree structure instead of OR-reducing it away.
    """
    return jax.tree.map(
        lambda g: jnp.sum(
            ~jnp.isfinite(g.astype(jnp.float32))).astype(jnp.int32), grads)


def nonfinite_report(counts: Any) -> dict[str, int]:
    """Host-side ``{leaf path: nonfinite count}`` for the offending leaves
    only (empty dict == clean step).  Feed it ``nonfinite_counts`` output
    after the step has been fetched — not inside jit."""
    flat_paths = tree_paths(counts)
    leaves = jax.tree.leaves(counts)
    return {p: int(c) for p, c in zip(flat_paths, leaves) if int(c)}


def _emit_floor_event(scale, floor, consec, total) -> None:
    emit_event(
        "loss_scale_floor_halved",
        scale=float(scale), new_floor=float(floor),
        consecutive_skips=int(consec), total_skips=int(total))


def guarded_update(
    scaler: LossScaler,
    state: LossScalerState,
    guard: GuardState,
    found_inf: jax.Array,
    config: GuardConfig = GuardConfig(),
) -> Tuple[LossScalerState, GuardState]:
    """``scaler.update`` plus skip accounting and bounded degradation.

    Branch-free device math: the consecutive-skip counter increments on
    overflow and resets on clean steps; when it reaches ``patience`` the
    dynamic floor halves (clamped at ``min_floor``), the counter resets
    to give the lowered floor a fresh window, and a structured event is
    emitted from the host boundary.
    """
    found_inf = jnp.asarray(found_inf).astype(jnp.bool_)
    consec = jnp.where(found_inf, guard.consecutive_skips + 1, 0)
    tripped = consec >= config.patience
    new_floor = jnp.where(
        tripped,
        jnp.maximum(guard.scale_floor * config.floor_backoff,
                    config.min_floor),
        guard.scale_floor,
    ).astype(jnp.float32)
    new_state = scaler.update(state, found_inf, min_scale=new_floor)
    # The trip forces a backoff even when hysteresis had not burnt through
    # yet — patience expiring IS the stronger signal that the current
    # scale cannot work.  Forced only when update() did NOT already back
    # off this step, so a trip step always drops the scale exactly once
    # (never backoff_factor**2) — and never for a static scaler, whose
    # contract is that the scale does not move at all.
    if scaler.dynamic:
        already_backed = new_state.scale < state.scale
        forced = jnp.maximum(
            state.scale * jnp.float32(scaler.backoff_factor), new_floor)
        new_state = new_state._replace(
            scale=jnp.where(jnp.logical_and(tripped, ~already_backed),
                            forced, new_state.scale))
    new_guard = GuardState(
        consecutive_skips=jnp.where(tripped, 0, consec).astype(jnp.int32),
        total_skips=(guard.total_skips
                     + found_inf.astype(jnp.int32)),
        scale_floor=new_floor,
    )
    # host effect only on actual trips (lax.cond gates the callback), so
    # the common clean/skip path pays no per-step device->host transfer
    jax.lax.cond(
        tripped,
        lambda s, fl, c, t: jax.debug.callback(_emit_floor_event,
                                               s, fl, c, t),
        lambda s, fl, c, t: None,
        new_state.scale, new_floor, consec, new_guard.total_skips)
    return new_state, new_guard


def make_guarded_step(
    loss_fn: Callable[[Any, Any], jax.Array],
    optimizer,
    scaler: LossScaler,
    config: GuardConfig = GuardConfig(),
) -> Callable:
    """Build the jit-safe guarded train step.

    ``loss_fn(params, batch) -> scalar``; ``optimizer`` is any
    :class:`~apex_tpu.optimizers.FusedOptimizer`.  The returned function

    ``step(params, opt_state, sstate, gstate, batch)
        -> (params, opt_state, sstate, gstate, metrics)``

    scales the loss, localizes non-finite gradients per leaf, applies the
    capturable skip, and runs :func:`guarded_update`.  ``metrics`` is a
    dict of on-device scalars plus the per-leaf ``nonfinite`` census —
    pass the census to :func:`nonfinite_report` after fetching to name
    the offending parameters.
    """

    def step(params, opt_state, sstate: LossScalerState, gstate: GuardState,
             batch):
        def scaled(p):
            loss = loss_fn(p, batch)
            return scaler.scale_loss(loss, sstate), loss

        grads, loss = jax.grad(scaled, has_aux=True)(params)
        grads, found_inf = scaler.unscale(grads, sstate)
        counts = nonfinite_counts(grads)
        new_params, new_opt_state = optimizer.step(
            grads, params, opt_state, found_inf=found_inf)
        new_sstate, new_gstate = guarded_update(
            scaler, sstate, gstate, found_inf, config)
        metrics = {
            "loss": loss,
            "found_inf": found_inf,
            "skipped": found_inf,
            "scale": new_sstate.scale,
            "scale_floor": new_gstate.scale_floor,
            "consecutive_skips": new_gstate.consecutive_skips,
            "total_skips": new_gstate.total_skips,
            "nonfinite": counts,
        }
        return new_params, new_opt_state, new_sstate, new_gstate, metrics

    return step
