"""Classified-exception retry with exponential backoff + deterministic jitter.

Host-side I/O at pod scale — checkpoint writes to network filesystems,
data fetches through a flaky storage frontend — fails *transiently* far
more often than it fails *permanently* (PAPERS.md TPU-pod papers; the
same observation drove bench.py's ``_TRANSIENT_MARKERS`` harness after
round 3's capture died on one ``remote_compile`` blip).  This module is
the one retry policy for all of them, with three properties the ad-hoc
``try/sleep/except`` it replaces never had:

- **Classified**: only exceptions the policy names (by type, or by a
  status-code-anchored message marker) are retried.  Deterministic
  failures — a ``CheckpointError`` from corrupt bytes, a shape bug —
  propagate on the first attempt; retrying them only burns the deadline
  re-proving them (the bench.py round-4 lesson).
- **Deterministic jitter**: backoff delay is ``base * backoff**attempt``
  plus a jitter fraction derived from ``(seed, what, attempt)`` via
  CRC32 — the same call site produces the same delay schedule on every
  run, so tier-1 tests of the retry path are reproducible while a fleet
  of real hosts (different ``seed`` per process) still de-synchronizes
  its retry storms.
- **Observable**: every attempt, recovery, and exhaustion emits a
  structured event through :func:`apex_tpu._logging.emit_event` — a
  silent retry loop hides exactly the infrastructure rot an operator
  needs to see trending.
"""

from __future__ import annotations

import dataclasses
import time
import zlib
from typing import Callable, Tuple, Type, TypeVar

from apex_tpu._logging import emit_event

__all__ = [
    "RetryExhausted",
    "RetryPolicy",
    "TransientError",
    "is_transient",
    "retry_transient",
]

T = TypeVar("T")


class TransientError(RuntimeError):
    """Raise-to-retry marker: wrap an error the *caller* knows is
    transient (e.g. a storage frontend's custom exception type) so the
    default policy retries it without widening its type list."""


class RetryExhausted(RuntimeError):
    """The transient failure persisted through every allowed attempt.

    Carries ``what`` (the operation label), ``attempts``, and ``last``
    (the final underlying exception, also chained via ``__cause__``).
    """

    # never re-retried by an outer retry_transient, even though its
    # message embeds the (possibly marker-matching) underlying error text
    transient = False

    def __init__(self, what: str, attempts: int, last: BaseException):
        super().__init__(
            f"{what}: transient failure persisted through {attempts} "
            f"attempts (last: {type(last).__name__}: {last})")
        self.what = what
        self.attempts = attempts
        self.last = last


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """What to retry, how often, and how long to wait between attempts.

    ``transient_types`` classifies by exception type (``OSError`` covers
    the host-I/O family: ``ConnectionError``, ``TimeoutError``, disk
    errors).  ``transient_markers`` classifies by status-code-anchored
    message substring for runtime errors that arrive as generic types
    (the bench.py tunnel-error set).  Everything else is deterministic
    and propagates immediately.

    The delay for attempt ``n`` (1-based) is
    ``min(base_delay_s * backoff**(n-1), max_delay_s)`` stretched by a
    jitter fraction in ``[0, jitter)`` derived deterministically from
    ``(seed, what, n)`` — reproducible per call site, decorrelated
    across differently-seeded processes.
    """

    max_attempts: int = 4
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    backoff: float = 2.0
    jitter: float = 0.25
    seed: int = 0
    transient_types: Tuple[Type[BaseException], ...] = (
        OSError, TransientError)
    transient_markers: Tuple[str, ...] = (
        "UNAVAILABLE:", "DEADLINE_EXCEEDED", "remote_compile",
        "Socket closed", "Connection reset", "Stream removed")

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.base_delay_s < 0.0 or self.max_delay_s < 0.0:
            raise ValueError("delays must be non-negative")
        if self.backoff < 1.0:
            raise ValueError(f"backoff must be >= 1.0, got {self.backoff}")
        if self.jitter < 0.0:
            raise ValueError(f"jitter must be >= 0, got {self.jitter}")

    def delay_s(self, what: str, attempt: int) -> float:
        """Deterministic backoff+jitter delay before retry ``attempt``."""
        base = min(self.base_delay_s * self.backoff ** (attempt - 1),
                   self.max_delay_s)
        digest = zlib.crc32(f"{self.seed}:{what}:{attempt}".encode())
        frac = (digest % 10_000) / 10_000.0  # [0, 1), stable across runs
        return min(base * (1.0 + self.jitter * frac), self.max_delay_s)


def is_transient(exc: BaseException, policy: RetryPolicy) -> bool:
    """Does ``policy`` classify ``exc`` as worth retrying?

    An exception type can opt out unconditionally with a class attribute
    ``transient = False`` — the hook for *deterministic* errors that
    happen to subclass a transient family (``DataStallError`` is a
    ``TimeoutError``/``OSError``, but re-fetching throws away a batch
    per attempt) or to embed marker text (``RetryExhausted`` quotes the
    underlying error).
    """
    if getattr(exc, "transient", None) is False:
        return False
    if isinstance(exc, policy.transient_types):
        return True
    msg = str(exc)
    return any(m in msg for m in policy.transient_markers)


def retry_transient(fn: Callable[[], T], *,
                    policy: RetryPolicy = RetryPolicy(),
                    what: str = "operation",
                    sleep: Callable[[float], None] = time.sleep) -> T:
    """Call ``fn()`` with classified retries; return its result.

    Non-transient exceptions (per :func:`is_transient`) propagate from
    the first attempt untouched — including ``StopIteration``, so this
    wraps ``next(iterator)`` safely.  Transient ones are retried up to
    ``policy.max_attempts`` total attempts with deterministic
    backoff+jitter, one ``retry_attempt`` event per failure; exhaustion
    raises :class:`RetryExhausted` from the last error after a
    ``retry_exhausted`` event.  A success on attempt > 1 emits
    ``retry_recovered`` with the total attempt count and (monotonic)
    duration.  ``sleep`` is injectable so tests never really wait.
    """
    t0 = time.monotonic()
    for attempt in range(1, policy.max_attempts + 1):
        try:
            result = fn()
        except Exception as e:
            if not is_transient(e, policy):
                raise
            err = f"{type(e).__name__}: {e}"
            if attempt >= policy.max_attempts:
                emit_event("retry_exhausted", what=what, attempts=attempt,
                           error=err[:500], t0=t0)
                raise RetryExhausted(what, attempt, e) from e
            delay = policy.delay_s(what, attempt)
            emit_event("retry_attempt", what=what, attempt=attempt,
                       max_attempts=policy.max_attempts,
                       delay_s=round(delay, 6), error=err[:500])
            sleep(delay)
            continue
        if attempt > 1:
            emit_event("retry_recovered", what=what, attempts=attempt, t0=t0)
        return result
    raise AssertionError("unreachable")  # pragma: no cover
