"""apex_tpu.fused_dense — GEMM+bias(+GeLU+GEMM) fused dense layers.

Parity target: ``apex.fused_dense`` (apex/fused_dense/fused_dense.py:7-96) and
its ``fused_dense_cuda`` extension (csrc/fused_dense_cuda.cu:15-209), which
fuses bias/GeLU into the GEMM via cublasLt epilogues.

TPU design: the MXU + XLA fusion already gives exactly this — a jitted
``x @ w + b`` followed by ``gelu`` compiles to one GEMM with a fused epilogue,
and the backward ``dgelu`` fuses into the wgrad GEMMs.  So the value here is
the *API* (drop-in modules matching the reference) plus keeping everything in
one jittable function so XLA sees the whole chain.  bf16 inputs hit the MXU
natively; accumulation is fp32 (``preferred_element_type``).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

import flax.linen as nn

__all__ = [
    "linear_bias",
    "linear_gelu_linear",
    "FusedDense",
    "FusedDenseGeluDense",
    "DenseNoBias",
]


def _gemm(x, kernel):
    """MXU matmul with fp32 accumulation regardless of input dtype.

    fp32 inputs use HIGHEST precision (full-f32 MXU passes); half inputs use
    the native bf16 MXU path with fp32 accumulation via
    ``preferred_element_type`` — the cublasLt-epilogue dtype rules of the
    reference (csrc/fused_dense_cuda.cu).
    """
    precision = (jax.lax.Precision.HIGHEST
                 if x.dtype == jnp.float32 else jax.lax.Precision.DEFAULT)
    return jax.lax.dot_general(
        x, kernel,
        (((x.ndim - 1,), (0,)), ((), ())),
        precision=precision,
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)


def linear_bias(x, kernel, bias=None):
    """y = x @ kernel (+ bias).  Parity: ``fused_dense_cuda.linear_bias_forward``
    (csrc/fused_dense.cpp:188-191); backward epilogues come from autodiff + XLA
    fusion instead of hand-written dgrad/wgrad launches."""
    y = _gemm(x, kernel)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y


def linear_gelu_linear(x, kernel1, bias1, kernel2, bias2):
    """y = (gelu(x @ k1 + b1)) @ k2 + b2 in one jittable chain.

    Parity: ``fused_dense_cuda.linear_gelu_linear_forward/backward``.  Uses
    tanh-approx GeLU, matching the reference kernel's gelu.
    """
    h = linear_bias(x, kernel1, bias1)
    h = nn.gelu(h, approximate=True)
    return linear_bias(h, kernel2, bias2)


class FusedDense(nn.Module):
    """Linear + bias with fused epilogue (apex.fused_dense.FusedDense)."""

    features: int
    use_bias: bool = True
    param_dtype: Any = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", self.kernel_init,
                            (x.shape[-1], self.features), self.param_dtype)
        bias = (self.param("bias", nn.initializers.zeros, (self.features,),
                           self.param_dtype) if self.use_bias else None)
        return linear_bias(x, kernel.astype(x.dtype),
                           None if bias is None else bias)


class DenseNoBias(nn.Module):
    """Bias-free linear (apex.fused_dense.DenseNoBias)."""

    features: int
    param_dtype: Any = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", self.kernel_init,
                            (x.shape[-1], self.features), self.param_dtype)
        return linear_bias(x, kernel.astype(x.dtype))


class FusedDenseGeluDense(nn.Module):
    """Linear+GeLU+Linear (apex.fused_dense.FusedDenseGeluDense)."""

    intermediate_features: int
    out_features: int
    param_dtype: Any = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        k1 = self.param("kernel1", self.kernel_init,
                        (x.shape[-1], self.intermediate_features), self.param_dtype)
        b1 = self.param("bias1", nn.initializers.zeros,
                        (self.intermediate_features,), self.param_dtype)
        k2 = self.param("kernel2", self.kernel_init,
                        (self.intermediate_features, self.out_features), self.param_dtype)
        b2 = self.param("bias2", nn.initializers.zeros,
                        (self.out_features,), self.param_dtype)
        return linear_gelu_linear(x, k1.astype(x.dtype), b1, k2.astype(x.dtype), b2)
