"""Data-parallel gradient synchronization over a mesh axis.

Parity target: ``apex.parallel.DistributedDataParallel``
(apex/parallel/distributed.py:131): broadcast-at-init, per-param grad hooks,
flatten→allreduce→unflatten bucketing on side streams, and the knobs
``delay_allreduce``, ``allreduce_always_fp32``, ``gradient_predivide_factor``.

TPU-native design (SURVEY.md §7): a ``dp`` mesh axis replaces the NCCL process
group.  Under ``pjit`` with batch sharded over ``dp`` and replicated params,
XLA *already* inserts bucketed, overlapped gradient all-reduces — the entire
hook/bucket/stream machinery of the reference is the compiler's job here.
What remains ours is the semantics: predivide (average vs sum), fp32
allreduce for half grads, and deferred sync for gradient accumulation.  Those
live in :func:`allreduce_grads` (for explicit ``shard_map``/``pmap`` code) and
:class:`DistributedDataParallel` (a thin wrapper holding the options, the way
the reference's module wrapper holds them).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu._logging import get_logger

logger = get_logger("parallel.distributed")


def _bound_axis_size(axis_name: str, what: str) -> int:
    """Static size of a *bound* named axis, with a diagnosable failure.

    ``jax.lax.psum(1, axis)`` outside shard_map/pmap raises a raw
    ``NameError: unbound axis name`` that points at JAX internals, not at
    the actual mistake (calling a collective helper from unmapped code,
    or over a mesh that was never initialized).  Re-raise it as a
    RuntimeError that names the axis and the fix.
    """
    try:
        return jax.lax.psum(1, axis_name)
    except NameError as e:
        raise RuntimeError(
            f"{what}: axis {axis_name!r} is not bound — call this inside "
            f"shard_map/pmap over a mesh that has that axis (e.g. the "
            f"mesh from parallel_state.initialize_model_parallel)") from e


def allreduce_grads(
    grads: Any,
    axis_name: str = "dp",
    *,
    allreduce_always_fp32: bool = False,
    gradient_predivide_factor: float = 1.0,
    gradient_average: bool = True,
) -> Any:
    """Sum/average grads across ``axis_name`` (inside shard_map/pmap/vmap).

    Mirrors ``allreduce_bucket`` (apex/parallel/distributed.py:429-494):
    optionally cast half grads to fp32 for the reduction
    (``allreduce_always_fp32``), pre-divide by ``gradient_predivide_factor``
    before the sum and post-divide by ``world/predivide`` after (the
    reference's predivide split), or plain average.
    """

    axis_size = jax.lax.psum(1, axis_name)

    @jax.named_scope("apex_tpu.allreduce_grads")  # nvtx range parity
    def reduce_leaf(g):
        orig_dtype = g.dtype
        if allreduce_always_fp32:
            g = g.astype(jnp.float32)
        if gradient_predivide_factor != 1.0:
            g = g / jnp.asarray(gradient_predivide_factor, g.dtype)
        g = jax.lax.psum(g, axis_name)
        if gradient_average:
            # net effect is /world_size, split as /predivide before and
            # /(world/predivide) after, exactly like distributed.py:438-449
            g = g / (axis_size / jnp.asarray(gradient_predivide_factor, jnp.float32)).astype(g.dtype)
        if allreduce_always_fp32:
            g = g.astype(orig_dtype)
        return g

    return jax.tree.map(reduce_leaf, grads)


def broadcast_params(params: Any, axis_name: str = "dp", root: int = 0) -> Any:
    """Make every rank use root's params (DDP init broadcast, distributed.py:257).

    Under pjit with replicated sharding this is a no-op by construction; under
    shard_map it selects root's copy via an index-0 all-gather.

    ``root`` is validated eagerly against the (static) axis size: an
    out-of-range root would mask out EVERY rank and silently broadcast
    zeros — exactly the corruption a resync pass exists to repair.
    """
    axis_size = _bound_axis_size(axis_name, "broadcast_params")
    if not 0 <= root < axis_size:
        raise ValueError(
            f"broadcast_params: root {root} is outside axis {axis_name!r} "
            f"of size {axis_size} (an out-of-range root would broadcast "
            f"zeros, not any rank's params)")
    # trace-time breadcrumb (one line per compiled broadcast, not per step)
    logger.debug("broadcast_params over axis=%s size=%d root=%d",
                 axis_name, axis_size, root)

    def bcast(p):
        # psum of the root-masked value: O(|p|) memory, unlike an all_gather
        # (which would hold world_size copies just to index one out)
        rank = jax.lax.axis_index(axis_name)
        masked = jnp.where(rank == root, p, jnp.zeros_like(p))
        return jax.lax.psum(masked, axis_name)

    return jax.tree.map(bcast, params)


@dataclasses.dataclass
class DistributedDataParallel:
    """Options holder + helpers for data-parallel training over a mesh axis.

    Usage (explicit shard_map style, closest to the reference's semantics)::

        ddp = DistributedDataParallel(axis_name="dp", gradient_predivide_factor=2.0)
        def step(params, batch):             # runs inside shard_map over 'dp'
            grads = jax.grad(loss_fn)(params, batch)
            grads = ddp.allreduce(grads)     # or defer with delay_allreduce
            ...

    Usage (pjit style — recommended): shard the batch over ``dp``, replicate
    params, and let XLA insert the reduction; ``ddp.shard_batch``/
    ``ddp.replicate`` build the shardings.
    """

    axis_name: str = "dp"
    mesh: Optional[Mesh] = None
    allreduce_always_fp32: bool = False
    gradient_predivide_factor: float = 1.0
    gradient_average: bool = True
    delay_allreduce: bool = False

    def allreduce(self, grads: Any) -> Any:
        """Reduce grads across the dp axis (predivide/average/fp32 knobs
        applied) — or pass through untouched when ``delay_allreduce`` is
        set, to be reduced once by :meth:`sync` after accumulation."""
        if self.delay_allreduce:
            # the reference registers no hooks and reduces in one shot later
            return grads
        return self._reduce(grads)

    def sync(self, grads: Any) -> Any:
        """Force the reduction (used at the end of accumulation when
        delay_allreduce=True, mirroring needs_refresh/allreduce_params)."""
        return self._reduce(grads)

    def _reduce(self, grads: Any) -> Any:
        return allreduce_grads(
            grads,
            self.axis_name,
            allreduce_always_fp32=self.allreduce_always_fp32,
            gradient_predivide_factor=self.gradient_predivide_factor,
            gradient_average=self.gradient_average,
        )

    # -- pjit-style sharding helpers ---------------------------------------
    def shard_batch(self, batch: Any) -> Any:
        """Device_put a host batch sharded along the dp axis (dim 0)."""
        if self.mesh is None:
            raise ValueError("mesh is required for pjit-style sharding helpers")
        sharding = NamedSharding(self.mesh, P(self.axis_name))
        return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)

    def replicate(self, params: Any) -> Any:
        """Device_put params fully replicated over the mesh (init broadcast)."""
        if self.mesh is None:
            raise ValueError("mesh is required for pjit-style sharding helpers")
        sharding = NamedSharding(self.mesh, P())
        return jax.tree.map(lambda x: jax.device_put(x, sharding), params)


class Reducer:
    """Manual allreduce helper (apex.parallel.Reducer, distributed.py:91).

    The reference's Reducer broadcasts params at construction and averages
    them across ranks when ``reduce()`` is called; here ``reduce`` averages a
    pytree across the axis (call inside shard_map/pmap).
    """

    def __init__(self, axis_name: str = "dp"):
        self.axis_name = axis_name

    def reduce(self, tree: Any) -> Any:
        """Mean-reduce every leaf across the axis (the reference Reducer's
        allreduce-then-divide, as one psum inside shard_map/pmap).

        Raises ``RuntimeError`` (not a raw JAX ``NameError``) when called
        outside a mapped context binding ``axis_name`` — e.g. before the
        mesh exists, or from plain unmapped code.
        """
        size = _bound_axis_size(self.axis_name, "Reducer.reduce")
        return jax.tree.map(
            lambda x: jax.lax.psum(x, self.axis_name) / jnp.asarray(size, x.dtype), tree
        )
