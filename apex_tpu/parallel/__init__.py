"""apex_tpu.parallel — data-parallel machinery over a mesh axis.

Parity target: ``apex.parallel`` (SURVEY.md §2.3): DistributedDataParallel,
Reducer, SyncBatchNorm (+ convert_syncbn_model), LARC.  The reference's
``multiproc`` launcher is superseded by ``jax.distributed.initialize`` —
see :func:`apex_tpu.transformer.parallel_state.initialize_distributed`.
"""

from apex_tpu.parallel.distributed import (
    DistributedDataParallel,
    Reducer,
    allreduce_grads,
    broadcast_params,
)
from apex_tpu.parallel.LARC import LARC
from apex_tpu.parallel.sync_batchnorm import (
    SyncBatchNorm,
    convert_syncbn_model,
    sync_batch_stats,
)

__all__ = [
    "DistributedDataParallel",
    "Reducer",
    "allreduce_grads",
    "broadcast_params",
    "LARC",
    "SyncBatchNorm",
    "convert_syncbn_model",
    "sync_batch_stats",
]
