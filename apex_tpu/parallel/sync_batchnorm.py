"""SyncBatchNorm — batch norm with statistics reduced across the dp axis.

Parity target: ``apex.parallel.SyncBatchNorm``, both implementations — the
pure-Python fallback (apex/parallel/sync_batchnorm.py) and the ``syncbn``
kernel version (optimized_sync_batchnorm{,_kernel}.py over csrc/welford.cu):
Welford local stats → all_gather/merge → normalize, with process-group
support, channels-last, and the fused-ReLU variant.

TPU design: the Welford merge across ranks collapses to ``psum`` of
locally-centered (count, sum, M2) statistics over the mesh axis — the same
conditioning as the reference's Welford merge — and XLA
fuses the normalize+affine (+relu) into one elementwise pass (the syncbn
kernel's job).  Channels-last is the native TPU layout, so ``channel_axis``
defaults to -1 (the reference's NHWC path).  Autodiff through ``psum``
reproduces the reference's backward (local sums all_reduced, syncbn.cpp:102-103).

When no ``axis_name`` is given (or outside shard_map/pmap) stats are local —
matching plain BatchNorm, the reference's behavior in a 1-process group.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

import flax.linen as nn

__all__ = ["SyncBatchNorm", "sync_batch_stats", "convert_syncbn_model"]


def sync_batch_stats(x: jax.Array, channel_axis: int = -1,
                     axis_name: Optional[str] = None,
                     axis_index_groups=None,
                     use_fast_variance: bool = True):
    """(mean, var, count) of x over all non-channel dims and all ranks.

    The kernel path's welford_mean_var + welford_parallel
    (csrc/syncbn.cpp:99-100): locally-centered (mean, M2) per shard, one psum
    to merge.  Variance is biased (1/N), matching batch-norm semantics.

    ``axis_index_groups`` restricts the reduction to rank subgroups — the
    contrib GBN/bnp ``bn_group`` semantics (stats shared by groups of
    ``bn_group`` adjacent ranks rather than the whole world).

    ``use_fast_variance`` (local stats only): compute fp32 ``sum(x)`` and
    ``sum(x^2)`` in ONE fused read of x instead of the two dependent
    passes of the Welford form (mean, then centered M2) — measured 6%
    end-to-end on the ResNet-50 bench, where BN is bandwidth-bound
    (PERF_NOTES.md r5).  Cross-rank stats always go through the centered
    Welford merge: the cancellation risk of raw E[x^2]-E[x]^2 compounds
    with shard count, and the psum already forces a second phase anyway.
    """
    # named_scope = the reference's NVTX range (sync_batchnorm.py:71-134)
    with jax.named_scope("apex_tpu.sync_batch_stats"):
        return _batch_stats_impl(x, channel_axis, axis_name,
                                 axis_index_groups, use_fast_variance)


def _batch_stats_impl(x, channel_axis, axis_name, axis_index_groups,
                      use_fast_variance=True):
    x32 = x.astype(jnp.float32)
    axes = tuple(i for i in range(x.ndim) if i != channel_axis % x.ndim)
    n_local = 1
    for a in axes:
        n_local *= x.shape[a]
    n_l = jnp.asarray(n_local, jnp.float32)
    if axis_name is None and use_fast_variance:
        # one-pass local stats: both reductions fuse over a single read
        mean = jnp.mean(x32, axis=axes)
        mean2 = jnp.mean(jnp.square(x32), axis=axes)
        var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
        return mean, var, n_l
    # Welford-style merge: center locally first (mean_l, M2_l), then combine
    # shards with one psum.  Raw E[x^2]-E[x]^2 cancels catastrophically for
    # large-mean/small-variance channels (can go negative → NaN via rsqrt);
    # the local centering keeps M2 well-conditioned like the reference's
    # welford kernels, and the merge term only sees the variance *of the
    # shard means*.  Clamp guards the remaining rounding.
    mean_l = jnp.mean(x32, axis=axes)
    m2_l = jnp.sum(jnp.square(x32 - jnp.expand_dims(mean_l, axes)), axis=axes)
    if axis_name is not None:
        n, s1, m2, s2 = jax.lax.psum(
            (n_l, n_l * mean_l, m2_l, n_l * jnp.square(mean_l)), axis_name,
            axis_index_groups=axis_index_groups)
    else:
        n, s1, m2, s2 = n_l, n_l * mean_l, m2_l, n_l * jnp.square(mean_l)
    mean = s1 / n
    var = jnp.maximum((m2 + s2 - n * jnp.square(mean)) / n, 0.0)
    return mean, var, n


class SyncBatchNorm(nn.Module):
    """Drop-in synchronized BatchNorm (apex.parallel.SyncBatchNorm).

    - ``axis_name``: mesh axis to reduce stats over (the reference's
      ``process_group``); None = local stats.
    - ``fuse_relu``: the syncbn kernels' fused ReLU epilogue
      (csrc/syncbn.cpp batchnorm_forward + ReLU bwd fusion).
    - running stats live in the ``batch_stats`` collection like flax's own
      BatchNorm, so checkpointing works unchanged.
    """

    num_features: Optional[int] = None  # inferred from input when None
    eps: float = 1e-5
    momentum: float = 0.1
    affine: bool = True
    use_scale: Optional[bool] = None  # default: affine
    use_bias: Optional[bool] = None  # default: affine
    track_running_stats: bool = True
    axis_name: Optional[str] = None
    axis_index_groups: Optional[Any] = None  # rank subgroups (contrib GBN)
    channel_axis: int = -1
    fuse_relu: bool = False
    param_dtype: Any = jnp.float32
    # one-pass fp32 local stats (see sync_batch_stats); cross-rank merges
    # always use the Welford form regardless
    use_fast_variance: bool = True

    @nn.compact
    def __call__(self, x, use_running_average: bool = False):
        ca = self.channel_axis % x.ndim
        features = self.num_features if self.num_features else x.shape[ca]
        shape = tuple(features if i == ca else 1 for i in range(x.ndim))

        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((features,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((features,), jnp.float32))

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            # During init() the module runs outside any mapped axis context,
            # so the cross-rank reduction must be skipped.
            axis = None if self.is_initializing() else self.axis_name
            mean, var, n = sync_batch_stats(x, ca, axis,
                                            self.axis_index_groups,
                                            self.use_fast_variance)
            if self.track_running_stats and not self.is_initializing():
                m = self.momentum
                # unbiased variance goes into the running buffer
                # (sync_batchnorm.py matches torch BN semantics here)
                unbiased = var * n / jnp.maximum(n - 1.0, 1.0)
                ra_mean.value = (1 - m) * ra_mean.value + m * mean
                ra_var.value = (1 - m) * ra_var.value + m * unbiased

        y = (x.astype(jnp.float32) - mean.reshape(shape)) * jax.lax.rsqrt(
            var.reshape(shape) + self.eps)
        use_scale = self.affine if self.use_scale is None else self.use_scale
        use_bias = self.affine if self.use_bias is None else self.use_bias
        if use_scale:
            weight = self.param("scale", nn.initializers.ones,
                                (features,), self.param_dtype)
            y = y * weight.reshape(shape)
        if use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (features,), self.param_dtype)
            y = y + bias.reshape(shape)
        if self.fuse_relu:
            y = jnp.maximum(y, 0.0)
        return y.astype(x.dtype)


def convert_syncbn_model(module: nn.Module, axis_name: str = "dp") -> nn.Module:
    """Recursively swap ``flax.linen.BatchNorm`` for :class:`SyncBatchNorm`.

    Parity: ``apex.parallel.convert_syncbn_model`` (apex/parallel/__init__.py:21).
    Works for declaratively-defined submodules (dataclass fields and
    lists/dicts thereof); modules instantiated inline inside ``@nn.compact``
    bodies cannot be rewritten from outside — declare them as attributes, or
    use :class:`SyncBatchNorm` directly.
    """
    if isinstance(module, nn.BatchNorm):
        return SyncBatchNorm(
            eps=module.epsilon,
            momentum=1.0 - module.momentum,
            use_scale=module.use_scale,
            use_bias=module.use_bias,
            channel_axis=module.axis if isinstance(module.axis, int) else -1,
            axis_name=axis_name,
        )

    def walk(v):
        if isinstance(v, nn.Module):
            return convert_syncbn_model(v, axis_name)
        if isinstance(v, (list, tuple)):
            t = type(v)
            return t(walk(i) for i in v)
        if isinstance(v, dict):
            return {k: walk(i) for k, i in v.items()}
        return v

    changed = {}
    for f in getattr(module, "__dataclass_fields__", {}):
        if f in ("parent", "name"):
            continue
        v = getattr(module, f, None)
        nv = walk(v)
        if nv is not v:
            changed[f] = nv
    if changed:
        return module.clone(**changed)
    return module
