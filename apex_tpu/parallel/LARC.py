"""LARC — Layer-wise Adaptive Rate Clipping optimizer wrapper.

Parity target: ``apex.parallel.LARC`` (apex/parallel/LARC.py:5-99): wraps any
optimizer; before the inner step, each parameter's gradient is scaled by an
adaptive local LR

    adaptive_lr = trust_coefficient * ||p|| / (||g|| + wd * ||p|| + eps)

clipped to the global LR when ``clip=True`` (``min(adaptive_lr/lr, 1)``), or
used as a pure multiplier when ``clip=False``.  Parameters with zero norm (or
zero grad norm) pass through untouched, as in the reference (LARC.py:86-88).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

__all__ = ["LARC"]


class LARC:
    """Wraps an apex_tpu fused optimizer (init/step interface)."""

    def __init__(self, optimizer, trust_coefficient: float = 0.02,
                 clip: bool = True, eps: float = 1e-8):
        self.inner = optimizer
        self.trust_coefficient = trust_coefficient
        self.clip = clip
        self.eps = eps

    # delegate attributes (the reference proxies __getstate__/param_groups etc.)
    def __getattr__(self, name):
        return getattr(self.inner, name)

    def init(self, params: Any):
        """Delegates to the wrapped optimizer — LARC itself is stateless."""
        return self.inner.init(params)

    def _adjust(self, grads: Any, params: Any) -> Any:
        lr = jnp.asarray(getattr(self.inner, "lr", 1.0), jnp.float32)
        wd = jnp.asarray(getattr(self.inner, "weight_decay", 0.0), jnp.float32)

        def scale_leaf(g, p):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            pn = jnp.sqrt(jnp.sum(jnp.square(p32)))
            gn = jnp.sqrt(jnp.sum(jnp.square(g32)))
            adaptive = self.trust_coefficient * pn / (gn + wd * pn + self.eps)
            if self.clip:
                mult = jnp.minimum(adaptive / lr, 1.0)
            else:
                mult = adaptive
            ok = jnp.logical_and(pn != 0.0, gn != 0.0)
            mult = jnp.where(ok, mult, 1.0)
            # the reference folds weight decay into the gradient BEFORE the
            # adaptive scaling and zeroes the group's wd (LARC.py:95-105), so
            # decay is applied at the adaptive rate, not the full rate.  Like
            # the reference, the fold happens only inside the nonzero-norm
            # branch — zero-norm params' grads pass through untouched.
            g32 = g32 + jnp.where(ok, wd, 0.0) * p32
            return (g32 * mult).astype(g.dtype)

        return jax.tree.map(scale_leaf, grads, params)

    def step(self, grads: Any, params: Any, state: Any, **kw):
        """Scale each grad by the layerwise trust ratio (wd folded in at
        the adaptive rate), then run the wrapped optimizer's step with
        its own weight decay suppressed."""
        adjusted = self._adjust(grads, params)
        # inner wd was folded into the adjusted grads (reference zeroes
        # group['weight_decay'] for the inner step)
        saved_wd = getattr(self.inner, "weight_decay", 0.0)
        try:
            self.inner.weight_decay = 0.0
            return self.inner.step(adjusted, params, state, **kw)
        finally:
            self.inner.weight_decay = saved_wd
