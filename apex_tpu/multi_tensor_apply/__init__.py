"""Multi-tensor apply: one fused update over a whole list/pytree of tensors.

TPU-native re-design of the reference's ``amp_C`` multi-tensor kernel family
(csrc/amp_C_frontend.cpp:192-228, csrc/multi_tensor_apply.cuh:16-133) and its
Python trampoline ``multi_tensor_applier``
(apex/multi_tensor_apply/multi_tensor_apply.py:3-30).

On CUDA the point of multi_tensor_apply is to amortize kernel-launch overhead:
one launch updates up to 110 tensors in 320-block chunks.  Under XLA a jitted
function over a pytree already compiles to a handful of fused loops, so the
default implementations here are jnp tree ops (XLA fuses them); a Pallas
packed-buffer path (:mod:`apex_tpu.ops.packed_update`) exists for the
optimizer updates where one flat kernel beats per-tensor fusion.

API shape mirrors the reference: functions take (and functionally return)
an overflow flag instead of mutating a ``noop_flag`` buffer.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from apex_tpu.utils.tree_math import tree_axpby, tree_l2norm, tree_scale

__all__ = [
    "multi_tensor_scale",
    "multi_tensor_axpby",
    "multi_tensor_l2norm",
    "multi_tensor_unscale_l2norm",
    "MultiTensorApply",
]


def _nonfinite(tree: Any) -> jax.Array:
    """True if any leaf contains inf/nan (the amp_C overflow check)."""
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.bool_)
    flags = [jnp.logical_not(jnp.all(jnp.isfinite(l.astype(jnp.float32)))) for l in leaves]
    out = flags[0]
    for f in flags[1:]:
        out = jnp.logical_or(out, f)
    return out


def multi_tensor_scale(tree: Any, scale, check_overflow: bool = True):
    """out = tree * scale, returning (out, found_inf).

    Parity: ``amp_C.multi_tensor_scale`` (csrc/multi_tensor_scale_kernel.cu)
    as used by the amp LossScaler (apex/amp/scaler.py:105-118).
    """
    out = tree_scale(tree, scale)
    found_inf = _nonfinite(tree) if check_overflow else jnp.zeros((), jnp.bool_)
    return out, found_inf


def multi_tensor_axpby(a, x: Any, b, y: Any, check_overflow: bool = True):
    """out = a*x + b*y, returning (out, found_inf).

    Parity: ``amp_C.multi_tensor_axpby`` (csrc/multi_tensor_axpby_kernel.cu).
    """
    out = tree_axpby(a, x, b, y)
    if check_overflow:
        found_inf = jnp.logical_or(_nonfinite(x), _nonfinite(y))
    else:
        found_inf = jnp.zeros((), jnp.bool_)
    return out, found_inf


def multi_tensor_l2norm(tree: Any, per_tensor: bool = False):
    """Global L2 norm (and optionally per-tensor norms), fp32 accumulation.

    Parity: ``amp_C.multi_tensor_l2norm`` (csrc/multi_tensor_l2norm_kernel.cu),
    used by FusedLAMB (apex/optimizers/fused_lamb.py:63-213) and clip_grad.
    """
    return tree_l2norm(tree, per_leaf=per_tensor)


def multi_tensor_unscale_l2norm(tree: Any, inv_scale, per_tensor: bool = False):
    """Unscale then L2 norm in one pass (amp_C.multi_tensor_unscale_l2norm)."""
    unscaled = tree_scale(tree, inv_scale)
    return unscaled, tree_l2norm(unscaled, per_leaf=per_tensor)


class MultiTensorApply:
    """Trampoline parity shim (apex/multi_tensor_apply/multi_tensor_apply.py:3-30).

    The reference signature is ``applier(op, noop_flag, tensor_lists, *args)``.
    Here ``op`` is any of the functions above (or a custom callable) and the
    call is purely functional; ``chunk_size`` is accepted for API parity and
    ignored (XLA chooses its own tiling).
    """

    available = True

    def __init__(self, chunk_size: int = 2048 * 32):
        self.chunk_size = chunk_size

    def __call__(self, op, *args, **kwargs):
        return op(*args, **kwargs)


multi_tensor_applier = MultiTensorApply()
