"""apex_tpu.ops — the Pallas/XLA kernel toolbox (the reference's ``csrc/``).

Each module pairs a Pallas TPU kernel with a pure-jnp fallback behind a
dispatcher (mirroring the reference's "is this extension importable / is the
kernel available for these shapes" guards, e.g.
apex/transformer/functional/fused_softmax.py:164-275).  Public, stable
entry points live in the package-level modules (:mod:`apex_tpu.normalization`,
:mod:`apex_tpu.fused_dense`, ...); :mod:`apex_tpu.ops` is the kernel layer.
"""

from apex_tpu.ops._dispatch import kernels_enabled, on_tpu, use_interpret

__all__ = ["kernels_enabled", "on_tpu", "use_interpret"]
