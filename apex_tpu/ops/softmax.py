"""Scaled (masked) softmax family — Pallas TPU kernels + jnp fallback.

Parity targets (the four Megatron softmax extensions, SURVEY.md §2.1):

- ``scaled_upper_triang_masked_softmax_cuda`` — causal, in-kernel triangular
  mask (csrc/megatron/scaled_upper_triang_masked_softmax.h).
- ``scaled_masked_softmax_cuda`` — arbitrary [b,1,sq,sk] boolean mask
  (csrc/megatron/scaled_masked_softmax.h:71-110).
- ``generic_scaled_masked_softmax_cuda`` — fallback for arbitrary sizes.
- ``scaled_softmax_cuda`` — scale+softmax, no mask.

The CUDA kernels exist to fuse scale→mask→softmax into one pass and to keep
the sk-length row in registers (warp softmax).  The Pallas equivalents keep a
(rows, sk) tile in VMEM, do the reduction in fp32, and generate the causal
mask with iota instead of loading one.  The kernel path routes on alignment
and a VMEM-budget cap (``_MAX_SK``); everything else — including the CUDA
kernels' un-servable shapes (sk > 2048, non-pow2) — takes the jnp path, which
XLA still fuses into one pass.

Masked-out semantics match the reference: masked positions get -10000 before
softmax (mask==True means "mask out"), and fully-masked rows produce zeros
(the CUDA kernel writes 0 for rows with no valid element).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._dispatch import kernels_enabled, lane_aligned, use_interpret

_MASK_VALUE = -10000.0  # matches scaled_masked_softmax.h additive fill
_BLOCK_ROWS = 128


# ---------------------------------------------------------------------------
# jnp reference path
# ---------------------------------------------------------------------------


def _jnp_softmax(x, scale, mask=None, causal=False):
    x32 = x.astype(jnp.float32) * scale
    if causal:
        sq, sk = x.shape[-2], x.shape[-1]
        tri = jnp.tril(jnp.ones((sq, sk), jnp.bool_), k=sk - sq)
        x32 = jnp.where(tri, x32, _MASK_VALUE)
    if mask is not None:
        x32 = jnp.where(mask, _MASK_VALUE, x32)
    m = jnp.max(x32, axis=-1, keepdims=True)
    e = jnp.exp(x32 - m)
    s = jnp.sum(e, axis=-1, keepdims=True)
    y = e / s
    # rows that are entirely masked: every element sits at _MASK_VALUE and
    # softmax would be uniform; the CUDA kernels emit zeros instead.
    if mask is not None:
        all_masked = jnp.all(mask, axis=-1, keepdims=True)
        y = jnp.where(all_masked, 0.0, y)
    return y.astype(x.dtype)


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------


def _fwd_kernel(x_ref, mask_ref, y_ref, *, scale, causal, has_mask, sq):
    x = x_ref[0].astype(jnp.float32) * scale  # (block_rows, sk)
    rows, sk = x.shape
    valid = None
    if causal:
        i = pl.program_id(1)
        row = i * rows + jax.lax.broadcasted_iota(jnp.int32, (rows, sk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (rows, sk), 1)
        valid = col <= row + (sk - sq)
    if has_mask:
        keep = jnp.logical_not(mask_ref[0])
        valid = keep if valid is None else jnp.logical_and(valid, keep)
    if valid is not None:
        x = jnp.where(valid, x, _MASK_VALUE)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    s = jnp.sum(e, axis=-1, keepdims=True)
    y = e / s
    if valid is not None:
        any_valid = jnp.any(valid, axis=-1, keepdims=True)
        y = jnp.where(any_valid, y, 0.0)
    y_ref[0] = y.astype(y_ref.dtype)


def _bwd_kernel(y_ref, dy_ref, dx_ref, *, scale):
    y = y_ref[0].astype(jnp.float32)
    dy = dy_ref[0].astype(jnp.float32)
    inner = jnp.sum(y * dy, axis=-1, keepdims=True)
    dx_ref[0] = (scale * y * (dy - inner)).astype(dx_ref.dtype)


def _pallas_forward(x, scale, mask, causal):
    b, h, sq, sk = x.shape
    x3 = x.reshape(b * h, sq, sk)
    rows = min(_BLOCK_ROWS, sq)
    has_mask = mask is not None
    if has_mask:
        # [b, 1, sq, sk] → broadcast over heads at index-map level
        mask3 = jnp.broadcast_to(mask, (b, 1, sq, sk)).reshape(b, sq, sk)
    else:
        mask3 = jnp.zeros((1, 1, 1), jnp.bool_)
    grid = (b * h, sq // rows)
    y = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          has_mask=has_mask, sq=sq),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, rows, sk), lambda g, i: (g, i, 0)),
            (pl.BlockSpec((1, rows, sk), lambda g, i: (g // h, i, 0))
             if has_mask else pl.BlockSpec((1, 1, 1), lambda g, i: (0, 0, 0))),
        ],
        out_specs=pl.BlockSpec((1, rows, sk), lambda g, i: (g, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, sk), x.dtype),
        interpret=use_interpret(),
    )(x3, mask3)
    return y.reshape(b, h, sq, sk)


def _pallas_backward(y, dy, scale):
    b, h, sq, sk = y.shape
    rows = min(_BLOCK_ROWS, sq)
    grid = (b * h, sq // rows)
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, rows, sk), lambda g, i: (g, i, 0)),
            pl.BlockSpec((1, rows, sk), lambda g, i: (g, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, rows, sk), lambda g, i: (g, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, sk), dy.dtype),
        interpret=use_interpret(),
    )(y.reshape(b * h, sq, sk), dy.reshape(b * h, sq, sk))
    return dx.reshape(b, h, sq, sk)


# Each grid step keeps (1, block_rows, sk) fp32 tiles for x/mask/y (fwd) or
# y/dy/dx (bwd) in VMEM, so sk is capped at 4096 (~2 MiB per tile).  Longer
# rows fall back to jnp — and genuinely long sequences belong to the flash
# attention path (apex_tpu.contrib.fmha), not a materialized softmax.
_MAX_SK = 4096


def _kernel_ok(x) -> bool:
    if not kernels_enabled() or x.ndim != 4:
        return False
    sq, sk = x.shape[-2], x.shape[-1]
    return (lane_aligned(sk) and sk <= _MAX_SK
            and (sq % min(_BLOCK_ROWS, sq) == 0) and sq >= 8)


# ---------------------------------------------------------------------------
# custom_vjp entry points
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _softmax(x, mask, scale, causal):
    return _softmax_fwd(x, mask, scale, causal)[0]


def _softmax_fwd(x, mask, scale, causal):
    if _kernel_ok(x):
        y = _pallas_forward(x, scale, mask, causal)
    else:
        y = _jnp_softmax(x, scale, mask=mask, causal=causal)
    return y, y


def _softmax_bwd(scale, causal, y, dy):
    # dx = scale * y * (dy - sum(y*dy)); masked rows have y == 0 so their
    # gradient is exactly 0, matching the CUDA backward.
    if _kernel_ok(y):
        dx = _pallas_backward(y, dy, scale)
    else:
        y32 = y.astype(jnp.float32)
        dy32 = dy.astype(jnp.float32)
        inner = jnp.sum(y32 * dy32, axis=-1, keepdims=True)
        dx = (scale * y32 * (dy32 - inner)).astype(dy.dtype)
    return dx, None


_softmax.defvjp(_softmax_fwd, _softmax_bwd)


# Public API ----------------------------------------------------------------


def scaled_softmax(x, scale: float = 1.0):
    """scale+softmax, no mask (``scaled_softmax_cuda``). x: [b, np, sq, sk]."""
    return _softmax(x, None, float(scale), False)


def scaled_masked_softmax(x, mask, scale: float = 1.0):
    """Scaled softmax with additive-style boolean mask (True = mask out).

    Parity: ``scaled_masked_softmax_cuda`` — mask is [b, 1, sq, sk] (or
    broadcastable); fully-masked rows yield zeros.
    """
    return _softmax(x, mask.astype(jnp.bool_), float(scale), False)


def scaled_upper_triang_masked_softmax(x, scale: float = 1.0):
    """Causal scaled softmax (``scaled_upper_triang_masked_softmax_cuda``).

    x: [b*np or b, np, sq, sk] with sq == sk in the reference; we allow
    sq <= sk (mask aligned to the last query).
    """
    return _softmax(x, None, float(scale), True)


def scaled_causal_masked_softmax(x, mask, scale: float = 1.0):
    """Causal triangle AND an explicit [b, 1, sq, sk] padding mask.

    The reference's upper-triang kernel asserts the mask is None; its
    dispatcher therefore can never combine the two.  TPU-side both are just
    predicates on the same VMEM tile, so the combined path exists and the
    dispatcher (transformer.functional.FusedScaleMaskSoftmax) uses it instead
    of silently dropping the triangle.
    """
    return _softmax(x, mask.astype(jnp.bool_), float(scale), True)


def generic_scaled_masked_softmax(x, mask, scale: float = 1.0):
    """Arbitrary-size fallback (``generic_scaled_masked_softmax_cuda``)."""
    return _jnp_custom(x, mask, float(scale))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _jnp_custom(x, mask, scale):
    return _jnp_softmax(x, scale, mask=mask)


def _jnp_custom_fwd(x, mask, scale):
    y = _jnp_softmax(x, scale, mask=mask)
    return y, y


def _jnp_custom_bwd(scale, y, dy):
    y32 = y.astype(jnp.float32)
    dy32 = dy.astype(jnp.float32)
    inner = jnp.sum(y32 * dy32, axis=-1, keepdims=True)
    return (scale * y32 * (dy32 - inner)).astype(dy.dtype), None


_jnp_custom.defvjp(_jnp_custom_fwd, _jnp_custom_bwd)
