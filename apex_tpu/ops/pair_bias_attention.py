"""Pair-bias flash attention — the Evoformer attention core as one kernel.

Parity target: ``apex.contrib.openfold_triton.mha`` (mha.py:131-460) — the
Triton fused attention with pair bias + mask that the reference built
because framework-level fusion materializes the score matrix.  The same
is true of XLA: ``tools/openfold_microbench.py`` measured the one-jit jnp
``attention_core`` at the *materialized* bandwidth roofline (the
[r, h, s, s] fp32 scores round-trip HBM).  This module is the Pallas
kernel the r2 verdict asked for — with the honest caveat the same
microbench produced: at Evoformer scale (s=256, d=32) the materialized
XLA path wins outright (4.5 ms vs 89 ms — tiny tiles drown in per-step
grid overhead), so ``attention_core`` only routes here for s >= 1024,
where the s^2 score materialization actually hurts.  The kernel is the
long-sequence pair-biased attention story (and the dbias-reduction
pattern other kernels can reuse); both paths are parity-tested.

Shapes (Evoformer MSA-row pattern):

- q, k, v: ``[R, h, s, d]`` where ``R = r * b`` flattens (rows, batch)
  **rows-major** — the bias's batch must be the inner factor so the
  kernel can recover it as ``(g // h) % b``.
- bias: ``[b, h, s, s]`` pair bias, shared by all ``r`` MSA rows of a
  batch element, differentiable (the pair stack trains through it).
- mask: optional ``[R, s]`` bool kv-validity (True = attend).  Fully
  masked rows emit zeros (cleaner than the reference's NaN-prone
  softmax-over--inf).

Design: the forward is the flash online-softmax loop with a bias tile
added to each score block.  Backward recomputes score blocks from the
saved lse in a dq kernel (k innermost), a dkv kernel (q innermost), and a
dbias kernel whose grid puts the broadcast row dimension innermost so
``dbias = sum_r ds`` accumulates in VMEM scratch — the only cross-``g``
reduction, impossible to express as a revisited output in the other
kernels' grids.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._dispatch import kernels_enabled, use_interpret

__all__ = ["pair_bias_flash_attention", "pair_bias_reference"]

_NEG_INF = -1e30


def pair_bias_reference(q, k, v, bias, mask=None, scale=None):
    """Materialized reference with identical semantics (and the jnp
    fallback for unsupported shapes)."""
    R, h, s, d = q.shape
    b = bias.shape[0]
    r = R // b
    scale = 1.0 if scale is None else scale
    sc = jax.lax.dot_general(
        q.astype(jnp.float32) * scale, k.astype(jnp.float32),
        (((3,), (3,)), ((0, 1), (0, 1))))            # [R, h, s, s]
    # rows-major [r, b] flatten: g = t * b + b_idx → bias index = g % b,
    # i.e. the bias TILES over the row dim (concatenate, not repeat)
    big = jnp.concatenate([bias.astype(jnp.float32)] * r, axis=0)
    sc = sc + big
    if mask is not None:
        sc = jnp.where(mask[:, None, None, :], sc, _NEG_INF)
    m = jnp.max(sc, axis=-1, keepdims=True)
    p = jnp.exp(sc - m)
    if mask is not None:
        p = jnp.where(mask[:, None, None, :], p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    p = jnp.where(l > 0, p / jnp.where(l > 0, l, 1.0), 0.0)
    return jax.lax.dot_general(
        p, v.astype(jnp.float32),
        (((3,), (2,)), ((0, 1), (0, 1)))).astype(q.dtype)


# ---------------------------------------------------------------------------
# kernels: grid (R*h, nq, nk[, r]) — bias block index = ((g // h) % b, g % h)
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, mask_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, has_mask):
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    s = jax.lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    s = s + bias_ref[0].astype(jnp.float32)
    if has_mask:
        kvalid = mask_ref[0][:, :1].reshape(1, -1) != 0
        s = jnp.where(kvalid, s, _NEG_INF)
    m_prev = m_scr[:, :1]
    l_prev = l_scr[:, :1]
    m_cur = jnp.maximum(jnp.max(s, axis=-1, keepdims=True), m_prev)
    corr = jnp.where(m_prev == -jnp.inf, 0.0, jnp.exp(m_prev - m_cur))
    p = jnp.exp(s - m_cur)
    if has_mask:
        p = jnp.where(kvalid, p, 0.0)  # fully-masked rows stay zero
    l_cur = corr * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    v = v_ref[0]
    pv = jax.lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    acc_scr[...] = acc_scr[...] * corr + pv
    m_scr[...] = jnp.broadcast_to(m_cur, m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_cur, l_scr.shape)

    @pl.when(j == nj - 1)
    def _finish():
        l = l_scr[:, :1]
        m = m_scr[:, :1]
        o = jnp.where(l > 0, acc_scr[...] / jnp.where(l > 0, l, 1.0), 0.0)
        o_ref[0] = o.astype(o_ref.dtype)
        lse = jnp.where(l > 0, m + jnp.log(jnp.where(l > 0, l, 1.0)),
                        jnp.inf)
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _recompute_p(q_ref, k_ref, bias_ref, mask_ref, lse_ref, *, scale,
                 has_mask):
    s = jax.lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    s = s + bias_ref[0].astype(jnp.float32)
    if has_mask:
        kvalid = mask_ref[0][:, :1].reshape(1, -1) != 0
        s = jnp.where(kvalid, s, _NEG_INF)
    lse = lse_ref[0][:, :1]
    return jnp.exp(s - lse)  # lse=+inf on dead rows → p = 0


def _dq_kernel(q_ref, k_ref, v_ref, bias_ref, mask_ref, do_ref, lse_ref,
               delta_ref, dq_ref, dq_scr, *, scale, has_mask):
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    p = _recompute_p(q_ref, k_ref, bias_ref, mask_ref, lse_ref,
                     scale=scale, has_mask=has_mask)
    dp = jax.lax.dot_general(do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0][:, :1])
    k = k_ref[0]
    dq_scr[...] += scale * jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(j == nj - 1)
    def _finish():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, bias_ref, mask_ref, do_ref, lse_ref,
                delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *, scale,
                has_mask):
    i = pl.program_id(2)
    ni = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    p = _recompute_p(q_ref, k_ref, bias_ref, mask_ref, lse_ref,
                     scale=scale, has_mask=has_mask)
    do = do_ref[0]
    dv_scr[...] += jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(do, v_ref[0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0][:, :1])
    q = q_ref[0]
    dk_scr[...] += scale * jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(i == ni - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _dbias_kernel(q_ref, k_ref, v_ref, bias_ref, mask_ref, do_ref, lse_ref,
                  delta_ref, db_ref, db_scr, *, scale, has_mask):
    t = pl.program_id(3)           # the broadcast row dim, innermost
    nt = pl.num_programs(3)

    @pl.when(t == 0)
    def _init():
        db_scr[...] = jnp.zeros_like(db_scr)

    p = _recompute_p(q_ref, k_ref, bias_ref, mask_ref, lse_ref,
                     scale=scale, has_mask=has_mask)
    dp = jax.lax.dot_general(do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    db_scr[...] += p * (dp - delta_ref[0][:, :1])   # ds: d(s+bias)/dbias = 1

    @pl.when(t == nt - 1)
    def _finish():
        db_ref[0] = db_scr[...].astype(db_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing + custom_vjp
# ---------------------------------------------------------------------------


def _lane(x):
    """[R, s] -> [R, s, 128] lane-tiled copies."""
    return jnp.broadcast_to(x[:, :, None], (*x.shape, 128))


def _pallas_fwd(q, k, v, bias, mask, scale, bq, bk):
    from jax.experimental.pallas import tpu as pltpu

    R, h, s, d = q.shape
    b = bias.shape[0]
    has_mask = mask is not None
    m3 = (_lane(mask.astype(jnp.int32)) if has_mask
          else jnp.zeros((1, 1, 128), jnp.int32))
    q3 = q.reshape(R * h, s, d)
    k3 = k.reshape(R * h, s, d)
    v3 = v.reshape(R * h, s, d)
    b3 = bias.reshape(b * h, s, s)
    mspec_idx = (lambda g, i, j: (g // h, j, 0)) if has_mask else \
        (lambda g, i, j: (0, 0, 0))
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, has_mask=has_mask),
        grid=(R * h, s // bq, s // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, bk, d), lambda g, i, j: (g, j, 0)),
            pl.BlockSpec((1, bk, d), lambda g, i, j: (g, j, 0)),
            pl.BlockSpec((1, bq, bk),
                         lambda g, i, j: (((g // h) % b) * h + g % h, i, j)),
            pl.BlockSpec((1, bk, 128) if has_mask else (1, 1, 128),
                         mspec_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda g, i, j: (g, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((R * h, s, 128), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bq, 128), jnp.float32),
                        pltpu.VMEM((bq, 128), jnp.float32),
                        pltpu.VMEM((bq, d), jnp.float32)],
        interpret=use_interpret(),
    )(q3, k3, v3, b3, m3)
    return o.reshape(R, h, s, d), lse[:, :, 0].reshape(R, h, s)


def _pallas_bwd(q, k, v, bias, mask, o, lse, do, scale, bq, bk):
    from jax.experimental.pallas import tpu as pltpu

    R, h, s, d = q.shape
    b = bias.shape[0]
    r = R // b
    has_mask = mask is not None
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    lse3 = _lane(lse.reshape(R * h, s))
    delta3 = _lane(delta.reshape(R * h, s))
    m3 = (_lane(mask.astype(jnp.int32)) if has_mask
          else jnp.zeros((1, 1, 128), jnp.int32))
    q3 = q.reshape(R * h, s, d)
    k3 = k.reshape(R * h, s, d)
    v3 = v.reshape(R * h, s, d)
    do3 = do.reshape(R * h, s, d)
    b3 = bias.reshape(b * h, s, s)

    bias_idx = lambda g, i, j: (((g // h) % b) * h + g % h, i, j)
    mask_idx = (lambda g, i, j: (g // h, j, 0)) if has_mask else \
        (lambda g, i, j: (0, 0, 0))
    mshape = (1, bk, 128) if has_mask else (1, 1, 128)

    def call(kernel, grid, out_specs, out_shape, scratch, swap=False):
        # swap=True: grid is (g, k block, q block) — index maps flip i/j
        def fix(f):
            return (lambda g, j, i: f(g, i, j)) if swap else f

        return pl.pallas_call(
            functools.partial(kernel, scale=scale, has_mask=has_mask),
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, bq, d), fix(lambda g, i, j: (g, i, 0))),
                pl.BlockSpec((1, bk, d), fix(lambda g, i, j: (g, j, 0))),
                pl.BlockSpec((1, bk, d), fix(lambda g, i, j: (g, j, 0))),
                pl.BlockSpec((1, bq, bk), fix(bias_idx)),
                pl.BlockSpec(mshape, fix(mask_idx)),
                pl.BlockSpec((1, bq, d), fix(lambda g, i, j: (g, i, 0))),
                pl.BlockSpec((1, bq, 128), fix(lambda g, i, j: (g, i, 0))),
                pl.BlockSpec((1, bq, 128), fix(lambda g, i, j: (g, i, 0))),
            ],
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=scratch,
            interpret=use_interpret(),
        )(q3, k3, v3, b3, m3, do3, lse3, delta3)

    dq = call(_dq_kernel, (R * h, s // bq, s // bk),
              pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0)),
              jax.ShapeDtypeStruct((R * h, s, d), q.dtype),
              [pltpu.VMEM((bq, d), jnp.float32)])
    dk, dv = call(_dkv_kernel, (R * h, s // bk, s // bq),
                  [pl.BlockSpec((1, bk, d), lambda g, j, i: (g, j, 0)),
                   pl.BlockSpec((1, bk, d), lambda g, j, i: (g, j, 0))],
                  [jax.ShapeDtypeStruct((R * h, s, d), k.dtype),
                   jax.ShapeDtypeStruct((R * h, s, d), v.dtype)],
                  [pltpu.VMEM((bk, d), jnp.float32),
                   pltpu.VMEM((bk, d), jnp.float32)], swap=True)

    # dbias: grid (b*h, nq, nk, r) with the broadcast row dim innermost;
    # g for (bias graph index g2, row t) is (t*b + g2//h)*h + g2%h
    g_of = lambda g2, t: (t * b + g2 // h) * h + g2 % h
    db = pl.pallas_call(
        functools.partial(_dbias_kernel, scale=scale, has_mask=has_mask),
        grid=(b * h, s // bq, s // bk, r),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda g2, i, j, t: (g_of(g2, t), i, 0)),
            pl.BlockSpec((1, bk, d), lambda g2, i, j, t: (g_of(g2, t), j, 0)),
            pl.BlockSpec((1, bk, d), lambda g2, i, j, t: (g_of(g2, t), j, 0)),
            pl.BlockSpec((1, bq, bk), lambda g2, i, j, t: (g2, i, j)),
            pl.BlockSpec(mshape,
                         (lambda g2, i, j, t: (g_of(g2, t) // h, j, 0))
                         if has_mask else
                         (lambda g2, i, j, t: (0, 0, 0))),
            pl.BlockSpec((1, bq, d), lambda g2, i, j, t: (g_of(g2, t), i, 0)),
            pl.BlockSpec((1, bq, 128),
                         lambda g2, i, j, t: (g_of(g2, t), i, 0)),
            pl.BlockSpec((1, bq, 128),
                         lambda g2, i, j, t: (g_of(g2, t), i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, bk), lambda g2, i, j, t: (g2, i, j)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, s), bias.dtype),
        scratch_shapes=[pltpu.VMEM((bq, bk), jnp.float32)],
        interpret=use_interpret(),
    )(q3, k3, v3, b3, m3, do3, lse3, delta3)

    return (dq.reshape(R, h, s, d), dk.reshape(R, h, s, d),
            dv.reshape(R, h, s, d), db.reshape(b, h, s, s))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _flash(q, k, v, bias, mask, scale, bq, bk):
    o, _ = _pallas_fwd(q, k, v, bias, mask, scale, bq, bk)
    return o


def _flash_fwd(q, k, v, bias, mask, scale, bq, bk):
    o, lse = _pallas_fwd(q, k, v, bias, mask, scale, bq, bk)
    return o, (q, k, v, bias, mask, o, lse)


def _flash_bwd(scale, bq, bk, res, do):
    q, k, v, bias, mask, o, lse = res
    dq, dk, dv, db = _pallas_bwd(q, k, v, bias, mask, o, lse, do, scale,
                                 bq, bk)
    return dq, dk, dv, db, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def pair_bias_flash_attention(q, k, v, bias, mask=None,
                              scale: Optional[float] = None,
                              block_q: int = 128, block_k: int = 128):
    """softmax(q kᵀ · scale + bias [+ mask]) v without materializing scores.

    Args:
      q, k, v: ``[R, h, s, d]`` with ``R = r * b`` rows-major (see module
        docstring); OpenFold passes q already scaled, so ``scale``
        defaults to 1.
      bias: ``[b, h, s, s]`` differentiable pair bias shared across rows.
      mask: optional ``[R, s]`` bool kv validity (True = attend).
      block_q / block_k: tile sizes (clamped to s).

    Returns ``[R, h, s, d]`` in q's dtype; fully-masked rows give zeros.
    """
    R, h, s, d = q.shape
    b = bias.shape[0]
    scale = 1.0 if scale is None else float(scale)
    bq, bk = min(block_q, s), min(block_k, s)
    ok = (kernels_enabled() and R % b == 0 and d % 8 == 0
          and s % bq == 0 and s % bk == 0 and s % 128 == 0)
    if ok:
        return _flash(q, k, v, bias, mask, scale, bq, bk)
    return pair_bias_reference(q, k, v, bias, mask, scale)
