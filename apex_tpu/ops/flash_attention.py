"""Flash attention — Pallas TPU kernels + jnp fallback.

Parity targets (SURVEY.md §2.2): the ``fmhalib`` fused attention extension
(apex/contrib/csrc/fmha/, fixed seq {128,256,384,512}, head dim 64, fp16
tile kernels) and the attention core of ``fast_multihead_attn``
(apex/contrib/csrc/multihead_attn/, CUTLASS batched GEMM + fused
softmax).  Per the SURVEY design map, one Pallas flash-attention kernel with
online softmax supersedes both: it handles arbitrary sequence lengths
(no 512 cap), causal masking, and varlen packing via segment ids, and never
materializes the [b, h, sq, sk] score matrix.

Design (TPU-first, not a translation):

- Grid ``(b*h, num_q_blocks, num_k_blocks)`` with the k axis innermost.
  Scratch accumulators (running max ``m``, running sum ``l``, output
  accumulator) persist across the sequential k steps of one q block —
  the canonical TPU online-softmax layout.  Block sizes default to 128
  (MXU-shaped); both matmuls per step hit the MXU in fp32 accumulation.
- Causal masking is generated from iota (never loaded); whole k blocks
  strictly above the diagonal are skipped with ``pl.when``.
- Varlen ("THD"/packed) sequences use segment ids: query i attends to key j
  iff ``q_seg[i] == kv_seg[j]``.  A padding mask is the special case of
  giving pad positions segment id 0 and real tokens id 1.
- Backward recomputes attention probabilities blockwise from the saved
  logsumexp (no O(s^2) residual): a dq kernel (k innermost) and a dk/dv
  kernel (q innermost), plus a cheap jnp precompute of
  ``delta = rowsum(do * o)``.
- Fully-masked query rows produce zeros, matching the fused-softmax
  extensions' convention (and their gradient is exactly zero).
- Attention dropout runs *in kernel* (parity: the reference's fused
  softmax+dropout with Philox RNG, apex/contrib/csrc/multihead_attn/,
  setup.py:647).  Like Philox, the RNG is *counter-based*: the keep bit
  for score element (bh, qpos, kpos) is a stateless integer hash of
  ``(seed, bh, qpos, kpos)`` (murmur3-finalizer avalanche), so the exact
  mask is regenerated — never stored — in the forward and both backward
  kernels, on every platform (plain jnp integer ops; no TPU-only PRNG
  primitive, so interpret-mode CPU tests cover the real code path).  The
  softmax denominator accumulates the *undropped* probabilities (dropout
  applies to the normalized matrix), and the flash backward identity
  ``delta = rowsum(do*o) = rowsum(p_kept * dp_kept)`` still holds, so the
  delta precompute is unchanged.

The jnp fallback implements identical semantics for unsupported
shapes/backends and is what the parity tests diff against.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._dispatch import kernels_enabled, use_interpret

_NEG_INF = -1e30
# Large default tiles: at head dims of 64-128 a (128, d) step is too little
# work to amortize grid overhead (measured 5 TF/s at 128x128 vs ~90 TF/s at
# 1024x1024 on v5e, b8 h16 s1024 d64).  VMEM at 1024x1024: the fp32 p tile is
# 4 MiB + q/k/v/do/acc tiles ≈ 7 MiB total — comfortably under the ~16 MiB
# budget for d ≤ 128.  Longer sequences keep wide tiles and grid over the
# rest (causal whole-block skip then prunes the upper triangle).
# (an isolated block sweep suggested block_q=512 wins fwd+bwd, but the full
# training step measured WORSE at 512 — 220.5 vs 213 ms/step; in-model
# measurement is authoritative, so both defaults stay 1024)
_DEFAULT_BLOCK_Q = 1024
_DEFAULT_BLOCK = 1024


# ---------------------------------------------------------------------------
# jnp reference path (also the fallback — fully differentiable)
# ---------------------------------------------------------------------------


def mha_reference(q, k, v, *, causal=False, q_segment_ids=None,
                  kv_segment_ids=None, scale=None, dropout_rate=0.0,
                  dropout_seed=None):
    """Materialized attention with flash-identical masking semantics.

    q: [b, h, sq, d]; k/v: [b, h, sk, d]; segment ids: [b, s].  Dropout
    applies to the normalized probabilities and draws the SAME counter
    hash as the Pallas kernels — per (seed, coordinates) the two paths
    realize bit-identical keep masks (pinned by
    test_kernel_and_fallback_share_dropout_stream)."""
    d = q.shape[-1]
    scale = (1.0 / d ** 0.5) if scale is None else scale
    s = jax.lax.dot_general(
        q.astype(jnp.float32) * scale, k.astype(jnp.float32),
        (((3,), (3,)), ((0, 1), (0, 1))))  # [b, h, sq, sk]
    valid = None
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        row = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        valid = (col <= row + (sk - sq))[None, None]
    if q_segment_ids is not None:
        seg = (q_segment_ids[:, None, :, None] ==
               kv_segment_ids[:, None, None, :])
        valid = seg if valid is None else jnp.logical_and(valid, seg)
    if valid is not None:
        s = jnp.where(valid, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    l = jnp.sum(e, axis=-1, keepdims=True)
    p = e / l
    if valid is not None:
        any_valid = jnp.any(valid, axis=-1, keepdims=True)
        p = jnp.where(any_valid, p, 0.0)
    if dropout_rate > 0.0:
        # the SAME counter hash as the Pallas kernels, evaluated densely:
        # a shape-driven kernel/fallback routing change cannot silently
        # change the dropout stream (r3 advisor finding), and parity tests
        # compare realizations bit-for-bit
        bb, hh, sq_, sk_ = p.shape
        g = jnp.arange(bb * hh, dtype=jnp.uint32).reshape(bb, hh, 1, 1)
        qpos = jnp.arange(sq_, dtype=jnp.uint32).reshape(1, 1, sq_, 1)
        kpos = jnp.arange(sk_, dtype=jnp.uint32).reshape(1, 1, 1, sk_)
        keep = _hash_keep(jnp.asarray(dropout_seed, jnp.uint32), g, qpos,
                          kpos, dropout_rate)
        p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    out = jax.lax.dot_general(
        p, v.astype(jnp.float32), (((3,), (2,)), ((0, 1), (0, 1))))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas forward
# ---------------------------------------------------------------------------


def _fmix32(h):
    """murmur3's 32-bit finalizer: full avalanche (every input bit flips
    each output bit with ~1/2 probability)."""
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def _hash_keep(seed, g, qpos, kpos, rate):
    """Counter-based dropout keep decision for coordinates (g, qpos, kpos).

    Each coordinate is folded through the full finalizer in sequence
    (h = fmix(h ^ c)), not XOR-combined before one finalizer round: a
    single shared round would give distinct (qpos, kpos, g) triples with
    colliding pre-mix XORs identical keep bits — structured cross-position
    correlation (r3 advisor finding).  Chaining makes each coordinate
    avalanche independently, the property the reference gets from Philox
    key/counter separation.  All operands broadcast, so the same function
    serves the Pallas tiles and the dense jnp fallback — the two paths
    are bit-identical per (seed, coordinates).
    """
    h = _fmix32(seed ^ qpos)
    h = _fmix32(h ^ kpos)
    h = _fmix32(h ^ g)
    # P(h < T) = rate for T = rate * 2^32 (h uniform over uint32)
    threshold = jnp.uint32(min(int(rate * 4294967296.0), 4294967295))
    return h >= threshold


def _keep_mask(seed, g, i, j, bq, bk, rate):
    """Keep mask for tile (g, i, j).  Stateless, so the forward and both
    backward kernels regenerate the identical mask from the same
    coordinates (the Philox property the reference relies on)."""
    qpos = (i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            ).astype(jnp.uint32)
    kpos = (j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            ).astype(jnp.uint32)
    return _hash_keep(seed.astype(jnp.uint32), g.astype(jnp.uint32),
                      qpos, kpos, rate)


def _block_mask(i, j, bq, bk, sq, sk, causal, has_seg, qseg, kseg):
    """(bq, bk) bool validity for q block i vs k block j; None if all-valid."""
    valid = None
    if causal:
        row = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        col = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        valid = col <= row + (sk - sq)
    if has_seg:
        # segment refs are lane-tiled (rows, 128); column 0 holds the ids
        seg = qseg[:, :1] == kseg[:, :1].reshape(1, bk)
        valid = seg if valid is None else jnp.logical_and(valid, seg)
    return valid


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, qseg_ref, kseg_ref, o_ref,
                lse_ref, m_scr, l_scr, acc_scr, *, scale, causal, has_seg,
                sq, sk, dropout_rate):
    g, i, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nj = pl.num_programs(2)
    bq, d = q_ref.shape[1], q_ref.shape[2]
    bk = k_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Whole block strictly above the causal diagonal → nothing to do.
    live = (j * bk <= i * bq + bq - 1 + (sk - sq)) if causal else True

    @pl.when(live)
    def _step():
        # matmul operands stay in the input dtype: bf16 hits the MXU at
        # native rate with fp32 accumulation; scale applies to the fp32
        # product (an fp32 upcast of q/k forces the slow multi-pass path)
        s = jax.lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        valid = _block_mask(i, j, bq, bk, sq, sk, causal, has_seg,
                            qseg_ref[0] if has_seg else None,
                            kseg_ref[0] if has_seg else None)
        if valid is not None:
            s = jnp.where(valid, s, _NEG_INF)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.maximum(jnp.max(s, axis=-1, keepdims=True), m_prev)
        # exp(-inf - -inf) is nan; a still-empty row keeps correction 1
        corr = jnp.where(m_prev == -jnp.inf, 0.0, jnp.exp(m_prev - m_cur))
        if has_seg or (causal and sq > sk):
            # fully-masked rows (m_cur = -inf, or finite but all-_NEG_INF)
            # exist with segment padding and with causal sq > sk (leading
            # queries see no keys); square causal always keeps the diagonal
            corr = jnp.where(m_cur == -jnp.inf, 1.0, corr)
            p = jnp.exp(jnp.where(m_cur == -jnp.inf, 0.0, s - m_cur))
            p = jnp.where(valid, p, 0.0)  # fully-masked rows stay zero
        else:
            p = jnp.exp(s - m_cur)  # masked entries: exp(-1e30 - m) == 0
        l_cur = corr * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_rate > 0.0:
            keep = _keep_mask(seed_ref[0], g, i, j, bq, bk, dropout_rate)
            p = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
        v = v_ref[0]
        pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[...] = acc_scr[...] * corr + pv
        m_scr[...] = jnp.broadcast_to(m_cur, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_cur, l_scr.shape)

    @pl.when(j == nj - 1)
    def _finish():
        l = l_scr[:, :1]
        m = m_scr[:, :1]
        # fully-masked rows (l == 0) emit zeros; lse=+inf makes their
        # backward recomputed p exactly 0 as well
        o = jnp.where(l > 0, acc_scr[...] / jnp.where(l > 0, l, 1.0), 0.0)
        o_ref[0] = o.astype(o_ref.dtype)
        lse = jnp.where(l > 0, m + jnp.log(jnp.where(l > 0, l, 1.0)),
                        jnp.inf)
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _seg_specs(b, h, bq, bk, has_seg):
    """Block specs for [b, s]-shaped segment-id inputs (dummy if absent)."""
    if has_seg:
        qspec = pl.BlockSpec((1, bq, 128), lambda g, i, j: (g // h, i, 0))
        kspec = pl.BlockSpec((1, bk, 128), lambda g, i, j: (g // h, j, 0))
    else:
        qspec = pl.BlockSpec((1, 1, 128), lambda g, i, j: (0, 0, 0))
        kspec = pl.BlockSpec((1, 1, 128), lambda g, i, j: (0, 0, 0))
    return qspec, kspec


def _expand_seg(seg):
    """[b, s] → [b, s, 128] so segment ids tile cleanly in VMEM."""
    return jnp.broadcast_to(seg[:, :, None], (*seg.shape, 128))


def _pallas_fwd(q, k, v, qseg, kseg, seed, causal, scale, block_q, block_k,
                dropout_rate):
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, bk = min(block_q, sq), min(block_k, sk)
    has_seg = qseg is not None
    grid = (b * h, sq // bq, sk // bk)
    qseg3 = _expand_seg(qseg) if has_seg else jnp.zeros((1, 1, 128), jnp.int32)
    kseg3 = _expand_seg(kseg) if has_seg else jnp.zeros((1, 1, 128), jnp.int32)
    sqspec, skspec = _seg_specs(b, h, bq, bk, has_seg)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          has_seg=has_seg, sq=sq, sk=sk,
                          dropout_rate=dropout_rate),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, bk, d), lambda g, i, j: (g, j, 0)),
            pl.BlockSpec((1, bk, d), lambda g, i, j: (g, j, 0)),
            sqspec, skspec,
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda g, i, j: (g, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        interpret=use_interpret(),
    )(seed, q.reshape(b * h, sq, d), k.reshape(b * h, sk, d),
      v.reshape(b * h, sk, d), qseg3, kseg3)
    return (o.reshape(b, h, sq, d), lse[:, :, 0].reshape(b, h, sq))


# ---------------------------------------------------------------------------
# Pallas backward
# ---------------------------------------------------------------------------


def _dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               qseg_ref, kseg_ref, dq_ref, dq_scr,
               *, scale, causal, has_seg, sq, sk, dropout_rate):
    g, i, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nj = pl.num_programs(2)
    bq, d = q_ref.shape[1], q_ref.shape[2]
    bk = k_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    live = (j * bk <= i * bq + bq - 1 + (sk - sq)) if causal else True

    @pl.when(live)
    def _step():
        k = k_ref[0]
        s = jax.lax.dot_general(q_ref[0], k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        valid = _block_mask(i, j, bq, bk, sq, sk, causal, has_seg,
                            qseg_ref[0] if has_seg else None,
                            kseg_ref[0] if has_seg else None)
        if valid is not None:
            s = jnp.where(valid, s, _NEG_INF)
        lse = lse_ref[0][:, :1]
        p = jnp.exp(s - lse)  # lse=+inf on dead rows → p = 0
        dp = jax.lax.dot_general(do_ref[0], v_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            # d(softmax) sees the dropout-masked upstream cotangent
            keep = _keep_mask(seed_ref[0], g, i, j, bq, bk, dropout_rate)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        delta = delta_ref[0][:, :1]
        ds = p * (dp - delta)
        dq_scr[...] += scale * jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nj - 1)
    def _finish():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                qseg_ref, kseg_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                *, scale, causal, has_seg, sq, sk, dropout_rate):
    g = pl.program_id(0)
    j, i = pl.program_id(1), pl.program_id(2)  # k block outer, q block inner
    ni = pl.num_programs(2)
    bq, d = q_ref.shape[1], q_ref.shape[2]
    bk = k_ref.shape[1]

    @pl.when(i == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    live = (j * bk <= i * bq + bq - 1 + (sk - sq)) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(q, k_ref[0], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        valid = _block_mask(i, j, bq, bk, sq, sk, causal, has_seg,
                            qseg_ref[0] if has_seg else None,
                            kseg_ref[0] if has_seg else None)
        if valid is not None:
            s = jnp.where(valid, s, _NEG_INF)
        lse = lse_ref[0][:, :1]
        p = jnp.exp(s - lse)
        if dropout_rate > 0.0:
            keep = _keep_mask(seed_ref[0], g, i, j, bq, bk, dropout_rate)
            inv = 1.0 / (1.0 - dropout_rate)
            p_kept = jnp.where(keep, p * inv, 0.0)
        else:
            p_kept = p
        # dv sees the dropped-and-rescaled probabilities (O = P_kept V)
        dv_scr[...] += jax.lax.dot_general(
            p_kept.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            dp = jnp.where(keep, dp * inv, 0.0)
        delta = delta_ref[0][:, :1]
        ds = p * (dp - delta)
        dk_scr[...] += scale * jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == ni - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _pallas_bwd(q, k, v, o, lse, do, qseg, kseg, seed, causal, scale,
                block_q, block_k, dropout_rate):
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, bk = min(block_q, sq), min(block_k, sk)
    has_seg = qseg is not None
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    # [b*h, s, 128] lane-tiled copies of the per-row scalars
    lse3 = jnp.broadcast_to(lse.reshape(b * h, sq)[:, :, None],
                            (b * h, sq, 128))
    delta3 = jnp.broadcast_to(delta.reshape(b * h, sq)[:, :, None],
                              (b * h, sq, 128))
    qseg3 = _expand_seg(qseg) if has_seg else jnp.zeros((1, 1, 128), jnp.int32)
    kseg3 = _expand_seg(kseg) if has_seg else jnp.zeros((1, 1, 128), jnp.int32)
    q3 = q.reshape(b * h, sq, d)
    k3 = k.reshape(b * h, sk, d)
    v3 = v.reshape(b * h, sk, d)
    do3 = do.reshape(b * h, sq, d)

    sqspec, skspec = _seg_specs(b, h, bq, bk, has_seg)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          has_seg=has_seg, sq=sq, sk=sk,
                          dropout_rate=dropout_rate),
        grid=(b * h, sq // bq, sk // bk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, bk, d), lambda g, i, j: (g, j, 0)),
            pl.BlockSpec((1, bk, d), lambda g, i, j: (g, j, 0)),
            pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda g, i, j: (g, i, 0)),
            sqspec, skspec,
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=use_interpret(),
    )(seed, q3, k3, v3, do3, lse3, delta3, qseg3, kseg3)

    sqspec2, skspec2 = _seg_specs(b, h, bq, bk, has_seg)
    # swap index maps: grid is (bh, k block, q block)
    if has_seg:
        sqspec2 = pl.BlockSpec((1, bq, 128), lambda g, j, i: (g // h, i, 0))
        skspec2 = pl.BlockSpec((1, bk, 128), lambda g, j, i: (g // h, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          has_seg=has_seg, sq=sq, sk=sk,
                          dropout_rate=dropout_rate),
        grid=(b * h, sk // bk, sq // bq),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, d), lambda g, j, i: (g, i, 0)),
            pl.BlockSpec((1, bk, d), lambda g, j, i: (g, j, 0)),
            pl.BlockSpec((1, bk, d), lambda g, j, i: (g, j, 0)),
            pl.BlockSpec((1, bq, d), lambda g, j, i: (g, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda g, j, i: (g, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda g, j, i: (g, i, 0)),
            sqspec2, skspec2,
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda g, j, i: (g, j, 0)),
            pl.BlockSpec((1, bk, d), lambda g, j, i: (g, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=use_interpret(),
    )(seed, q3, k3, v3, do3, lse3, delta3, qseg3, kseg3)
    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


# ---------------------------------------------------------------------------
# custom_vjp + dispatch
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10))
def _flash(q, k, v, qseg, kseg, seed, causal, scale, block_q, block_k,
           dropout_rate):
    o, _ = _pallas_fwd(q, k, v, qseg, kseg, seed, causal, scale, block_q,
                       block_k, dropout_rate)
    return o


def _flash_fwd(q, k, v, qseg, kseg, seed, causal, scale, block_q, block_k,
               dropout_rate):
    o, lse = _pallas_fwd(q, k, v, qseg, kseg, seed, causal, scale, block_q,
                         block_k, dropout_rate)
    return o, (q, k, v, o, lse, qseg, kseg, seed)


def _flash_bwd(causal, scale, block_q, block_k, dropout_rate, res, do):
    q, k, v, o, lse, qseg, kseg, seed = res
    dq, dk, dv = _pallas_bwd(q, k, v, o, lse, do, qseg, kseg, seed, causal,
                             scale, block_q, block_k, dropout_rate)
    return dq, dk, dv, None, None, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def _kernel_ok(q, k, block_q, block_k) -> bool:
    if not kernels_enabled():
        return False
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, bk = min(block_q, sq), min(block_k, sk)
    return (d % 64 == 0 and sq % bq == 0 and sk % bk == 0
            and bq % 8 == 0 and bk % 8 == 0)


def flash_attention(q, k, v, *, causal: bool = False,
                    segment_ids=None,
                    scale: Optional[float] = None,
                    dropout_rate: float = 0.0,
                    dropout_seed=None,
                    block_q: int = _DEFAULT_BLOCK_Q,
                    block_k: int = _DEFAULT_BLOCK):
    """Fused attention: softmax(q kᵀ · scale [+ masks]) [dropout] v, never
    materializing the score matrix.

    Args:
      q: ``[b, h, sq, d]``; k, v: ``[b, h, sk, d]``.
      causal: apply a causal mask (aligned to the *last* query for sq < sk).
      segment_ids: ``None``, a single ``[b, s]`` int array (self-attention),
        or a ``(q_segment_ids, kv_segment_ids)`` pair.  Tokens attend only
        within their own segment — this is the varlen/"THD" packing story
        (reference fmha `fmha.py:33-109`) and also expresses padding masks.
      scale: logit scale; defaults to ``1/sqrt(d)``.
      dropout_rate: attention-probability dropout (kept values rescaled by
        ``1/(1-rate)``), regenerated counter-based in the backward — the
        reference's fused softmax+dropout (multihead_attn csrc).  Requires
        ``dropout_seed``.
      dropout_seed: int (or int32 scalar array) seeding the keep mask; the
        same seed reproduces the same mask exactly.
      block_q / block_k: kernel tile sizes (clamped to the sequence length).

    Returns ``[b, h, sq, d]`` in q's dtype.  Fully-masked rows give zeros.
    """
    if segment_ids is None:
        qseg = kseg = None
    elif isinstance(segment_ids, tuple):
        qseg, kseg = segment_ids
    else:
        qseg = kseg = segment_ids
    d = q.shape[-1]
    scale = (1.0 / d ** 0.5) if scale is None else float(scale)
    dropout_rate = float(dropout_rate)
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got {dropout_rate}")
    seed = jnp.atleast_1d(jnp.asarray(
        0 if dropout_seed is None else dropout_seed, jnp.int32))
    if _kernel_ok(q, k, block_q, block_k):
        return _flash(q, k, v, qseg, kseg, seed, causal, scale, block_q,
                      block_k, dropout_rate)
    return mha_reference(q, k, v, causal=causal, q_segment_ids=qseg,
                         kv_segment_ids=kseg, scale=scale,
                         dropout_rate=dropout_rate, dropout_seed=seed[0])
