"""Kernel-availability dispatch (the TPU analog of extension import guards).

The reference gates every fused path twice: once on "was the extension built"
(lazy ``import amp_C`` etc.) and once on shape/dtype predicates
(``FusedScaleMaskSoftmax.is_kernel_available``,
apex/transformer/functional/fused_softmax.py:164-275).  Here the analogs are:

- :func:`on_tpu` — Pallas TPU kernels only lower on a TPU backend.
- ``APEX_TPU_KERNELS`` env var — ``"0"`` disables Pallas everywhere
  (pure-jnp fallbacks, still jitted/fused by XLA), ``"interpret"`` runs
  Pallas kernels in interpreter mode so CPU tests exercise the kernel code
  path itself.
- per-op shape predicates live next to each kernel.
"""

from __future__ import annotations

import functools
import os

import jax

_ENV = "APEX_TPU_KERNELS"


@functools.lru_cache(maxsize=None)
def on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - backend init failure
        return False


def use_interpret() -> bool:
    """Run Pallas kernels in interpret mode (CPU testing of kernel code)."""
    return os.environ.get(_ENV, "").lower() == "interpret"


def kernels_enabled() -> bool:
    """Whether Pallas kernels should be used at all."""
    mode = os.environ.get(_ENV, "").lower()
    if mode == "0":
        return False
    if mode == "interpret":
        return True
    return on_tpu()


def lane_aligned(*dims: int, lane: int = 128) -> bool:
    """TPU kernels want the trailing dim to be a multiple of the lane width."""
    return all(d % lane == 0 for d in dims)
