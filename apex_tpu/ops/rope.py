"""Fused rotary positional embedding — all four reference layouts.

Parity target: ``fused_rotary_positional_embedding``
(csrc/megatron/fused_rotary_positional_embedding.h, .cpp:243 bindings) via
``apex.transformer.functional.fused_rope`` (fused_rope.py:19-280):

- sbhd layout, on-the-fly sincos from a freqs tensor  (forward/backward)
- sbhd layout, cached cos/sin                         (forward/backward_cached)
- thd packed-varlen layout with cu_seqlens            (forward/backward_thd)
- 2d image layout with separate height/width freqs    (forward/backward_2d)

RoPE is pure elementwise math with a broadcast — on TPU this is a VPU job that
XLA fuses into the surrounding GEMMs/attention in one pass, so the "fused
kernel" here is a jitted jnp expression (the CUDA kernel exists to avoid torch
dispatching per-op; XLA has no such overhead).  Gradients come from autodiff
and fuse identically: d/dt of (t*cos + rotate(t)*sin) is (g*cos + rotate⁻¹(g)*sin),
the same kernel the reference hand-writes.

Only the first ``d2 = freqs.shape[-1]`` channels are rotated; the rest pass
through (matching the CUDA kernels' d2 < d handling).
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = [
    "fused_apply_rotary_pos_emb",
    "fused_apply_rotary_pos_emb_cached",
    "fused_apply_rotary_pos_emb_thd",
    "fused_apply_rotary_pos_emb_2d",
]


def _rotate_half(x):
    """(x1, x2) -> (-x2, x1) over the last dim (the reference's v_src_rotate)."""
    half = x.shape[-1] // 2
    x1 = x[..., :half]
    x2 = x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def _apply(t, cos, sin):
    """Rotate the first d2 channels of t by (cos, sin); pass the rest through."""
    d2 = cos.shape[-1]
    t_rot = t[..., :d2]
    rotated = (t_rot.astype(jnp.float32) * cos.astype(jnp.float32)
               + _rotate_half(t_rot).astype(jnp.float32) * sin.astype(jnp.float32)
               ).astype(t.dtype)
    if d2 == t.shape[-1]:
        return rotated
    return jnp.concatenate([rotated, t[..., d2:]], axis=-1)


def fused_apply_rotary_pos_emb(t, freqs, transpose_output_memory: bool = False):
    """RoPE on sbhd input ([s, b, h, d]); freqs is [s, 1, 1, d2], float.

    ``transpose_output_memory`` is a CUDA memory-layout hint
    (fused_rope.py:59-82); XLA owns layout on TPU so it is accepted and
    ignored.
    """
    del transpose_output_memory
    return _apply(t, jnp.cos(freqs), jnp.sin(freqs))


def fused_apply_rotary_pos_emb_cached(t, cos_, sin_, transpose_output_memory: bool = False):
    """RoPE on sbhd input with precomputed cos/sin of shape [s, 1, 1, d2]."""
    del transpose_output_memory
    return _apply(t, cos_, sin_)


def fused_apply_rotary_pos_emb_thd(t, cu_seqlens, freqs):
    """RoPE on thd packed-varlen input ([total_t, h, d]).

    ``cu_seqlens`` is [b+1] int32 cumulative sequence lengths; each packed
    sequence restarts at position 0 (fused_rope.py:191-211 semantics).  The
    position of token i is i - cu_seqlens[seq_of(i)], computed with a
    searchsorted instead of the CUDA kernel's per-block binary search.
    """
    total = t.shape[0]
    idx = jnp.arange(total, dtype=jnp.int32)
    seq_id = jnp.searchsorted(cu_seqlens.astype(jnp.int32), idx, side="right") - 1
    pos = idx - jnp.take(cu_seqlens.astype(jnp.int32), seq_id)
    f = jnp.squeeze(freqs, axis=(1, 2))  # [max_s, d2]
    f_t = jnp.take(f, pos, axis=0)  # [total_t, d2]
    cos = jnp.cos(f_t)[:, None, :]  # [total_t, 1, d2]
    sin = jnp.sin(f_t)[:, None, :]
    return _apply(t, cos, sin)


def fused_apply_rotary_pos_emb_2d(t, img_h, img_w, cos_h, sin_h, cos_w, sin_w):
    """2D (image) RoPE on bshd input ([b, s, h, d]) with s == img_h * img_w.

    First d/2 channels rotate by the height freqs, second d/2 by the width
    freqs (fused_rope.py:263-330, kernel .h:276-296).  cos_h/sin_h are
    [1, H, 1, d//2] with H >= img_h; cos_w/sin_w are [1, W, 1, d//2].
    """
    b, s, h, d = t.shape
    if s != img_h * img_w:
        raise ValueError(f"sequence length {s} != img_h*img_w = {img_h * img_w}")
    t5 = t.reshape(b, img_h, img_w, h, d)
    t_h, t_w = t5[..., : d // 2], t5[..., d // 2:]
    # height half: cos_h indexed by row → broadcast over columns
    ch = cos_h[:, :img_h, None, :, :]  # [1, img_h, 1, 1, d//2]
    sh = sin_h[:, :img_h, None, :, :]
    cw = cos_w[:, None, :img_w, :, :]  # [1, 1, img_w, 1, d//2]
    sw = sin_w[:, None, :img_w, :, :]
    out_h = _apply(t_h, ch, sh)
    out_w = _apply(t_w, cw, sw)
    return jnp.concatenate([out_h, out_w], axis=-1).reshape(b, s, h, d)
