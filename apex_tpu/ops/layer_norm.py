"""Fused LayerNorm / RMSNorm forward+backward (Pallas TPU + jnp fallback).

Parity target: the reference's ``fused_layer_norm_cuda`` extension
(csrc/layer_norm_cuda.cpp:446-459, csrc/layer_norm_cuda_kernel.cu:13-212):
LayerNorm *and* RMSNorm, affine / non-affine, mixed input/weight dtype
(Megatron-compatible), and the ``memory_efficient`` variant that saves the
*output* instead of the input and reconstructs the normalized activations in
backward.

TPU design: statistics are a row reduction — a natural VPU job.  The Pallas
forward computes mean/rstd per row and writes (y, mean, rstd); the backward
kernel accumulates dgamma/dbeta across the sequential TPU grid.  Internals are
fp32 regardless of I/O dtype, matching the CUDA kernels' Welford-in-fp32
accumulation.  When shapes don't meet the lane constraints (trailing dim not a
multiple of 128) we fall back to jnp — XLA fuses that path well; the Pallas
kernel exists to keep the activation in VMEM across the two passes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._dispatch import kernels_enabled, lane_aligned, use_interpret

_INTERPRET = use_interpret

# Rows per grid step; amortizes the per-step overhead while keeping the
# (block_rows, H) tile + fp32 temps within VMEM for H up to ~16k.
_BLOCK_ROWS = 128


# ---------------------------------------------------------------------------
# jnp reference path (also the CPU fallback, like the reference's
# torch.nn.functional.layer_norm fallback in fused_layer_norm.py:16-472)
# ---------------------------------------------------------------------------


def _norm_stats(x32: jax.Array, rms_only: bool, eps: float):
    if rms_only:
        mean = jnp.zeros(x32.shape[:-1], jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1)
    else:
        mean = jnp.mean(x32, axis=-1)
        var = jnp.mean(jnp.square(x32 - mean[..., None]), axis=-1)
    rstd = jax.lax.rsqrt(var + eps)
    return mean, rstd


def _jnp_forward(x, weight, bias, eps, rms_only):
    x32 = x.astype(jnp.float32)
    mean, rstd = _norm_stats(x32, rms_only, eps)
    xhat = (x32 - mean[..., None]) * rstd[..., None] if not rms_only else x32 * rstd[..., None]
    y = xhat
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype), mean, rstd


def _jnp_backward(dy, xhat, rstd, weight, rms_only):
    """Shared math for dx given normalized activations xhat (fp32)."""
    h = xhat.shape[-1]
    dy32 = dy.astype(jnp.float32)
    wdy = dy32 * weight.astype(jnp.float32) if weight is not None else dy32
    c2 = jnp.sum(wdy * xhat, axis=-1, keepdims=True) / h
    if rms_only:
        dx = (wdy - xhat * c2) * rstd[..., None]
    else:
        c1 = jnp.sum(wdy, axis=-1, keepdims=True) / h
        dx = (wdy - c1 - xhat * c2) * rstd[..., None]
    return dx


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------


def _fwd_kernel(x_ref, w_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps, rms_only, affine, has_bias):
    x = x_ref[:].astype(jnp.float32)
    h = x.shape[-1]
    if rms_only:
        mean = jnp.zeros((x.shape[0],), jnp.float32)
        var = jnp.sum(x * x, axis=-1) / h
        xhat = x * jax.lax.rsqrt(var + eps)[:, None]
    else:
        mean = jnp.sum(x, axis=-1) / h
        xc = x - mean[:, None]
        var = jnp.sum(xc * xc, axis=-1) / h
        xhat = xc * jax.lax.rsqrt(var + eps)[:, None]
    rstd = jax.lax.rsqrt(var + eps)
    y = xhat
    if affine:
        y = y * w_ref[0].astype(jnp.float32)[None, :]
        if has_bias:
            y = y + b_ref[0].astype(jnp.float32)[None, :]
    y_ref[:] = y.astype(y_ref.dtype)
    # stats live in a (grid, _BLOCK_ROWS) matrix: row g holds the stats of the
    # g-th row block.  Keeps every Pallas operand 2-D with a 128-lane trailing
    # dim (1-D f32 outputs get XLA's T(1024) tiling, which Mosaic rejects).
    # The stats arrays are tiny, so they ride along as full-array blocks and
    # are indexed by grid step here.
    g = pl.program_id(0)
    mean_ref[g, :] = mean
    rstd_ref[g, :] = rstd


def _bwd_kernel(dy_ref, xin_ref, mean_ref, rstd_ref, w_ref, b_ref,
                dx_ref, dw_ref, db_ref, *, rms_only, affine, has_bias, mem_eff):
    """One grid step: dx for this row block; accumulate dw/db across steps.

    The TPU grid is sequential, so accumulating into dw_ref/db_ref across
    steps is race-free — this replaces the CUDA kernel's two-stage partial
    dgamma/dbeta reduction (csrc/layer_norm_cuda_kernel.cu part2 kernels).
    """
    dy = dy_ref[:].astype(jnp.float32)
    g = pl.program_id(0)
    rstd = rstd_ref[g]  # (block_rows,) — row g of the (grid, block_rows) stats
    xin = xin_ref[:].astype(jnp.float32)
    h = dy.shape[-1]
    if mem_eff:
        # xin is the *output* y; invert the affine to recover xhat
        # (layer_norm_cuda_kernel.cu memory-efficient path semantics).
        xhat = xin
        if affine:
            if has_bias:
                xhat = xhat - b_ref[0].astype(jnp.float32)[None, :]
            xhat = xhat / w_ref[0].astype(jnp.float32)[None, :]
    else:
        if rms_only:
            xhat = xin * rstd[:, None]
        else:
            xhat = (xin - mean_ref[g][:, None]) * rstd[:, None]

    wdy = dy * w_ref[0].astype(jnp.float32)[None, :] if affine else dy
    c2 = jnp.sum(wdy * xhat, axis=-1, keepdims=True) / h
    if rms_only:
        dx = (wdy - xhat * c2) * rstd[:, None]
    else:
        c1 = jnp.sum(wdy, axis=-1, keepdims=True) / h
        dx = (wdy - c1 - xhat * c2) * rstd[:, None]
    dx_ref[:] = dx.astype(dx_ref.dtype)

    if affine:
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            dw_ref[:] = jnp.zeros_like(dw_ref)
            if has_bias:
                db_ref[:] = jnp.zeros_like(db_ref)

        dw_ref[0] += jnp.sum(dy * xhat, axis=0).astype(dw_ref.dtype)
        if has_bias:
            db_ref[0] += jnp.sum(dy, axis=0).astype(db_ref.dtype)


def _pad_rows(n):
    return (-n) % _BLOCK_ROWS


def _pallas_forward(x2d, weight, bias, eps, rms_only):
    n, h = x2d.shape
    pad = _pad_rows(n)
    if pad:
        x2d = jnp.pad(x2d, ((0, pad), (0, 0)))
    np_ = x2d.shape[0]
    affine = weight is not None
    has_bias = bias is not None
    w = (weight if affine else jnp.zeros((h,), x2d.dtype)).reshape(1, h)
    b = (bias if has_bias else jnp.zeros((h,), x2d.dtype)).reshape(1, h)
    grid = np_ // _BLOCK_ROWS
    y, mean, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps, rms_only=rms_only,
                          affine=affine, has_bias=has_bias),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((_BLOCK_ROWS, h), lambda i: (i, 0)),
            pl.BlockSpec((grid, _BLOCK_ROWS), lambda i: (0, 0)),
            pl.BlockSpec((grid, _BLOCK_ROWS), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((np_, h), x2d.dtype),
            jax.ShapeDtypeStruct((grid, _BLOCK_ROWS), jnp.float32),
            jax.ShapeDtypeStruct((grid, _BLOCK_ROWS), jnp.float32),
        ],
        interpret=_INTERPRET(),
    )(x2d, w, b)
    mean, rstd = mean.reshape(np_), rstd.reshape(np_)
    if pad:
        y, mean, rstd = y[:n], mean[:n], rstd[:n]
    return y, mean, rstd


def _pallas_backward(dy2d, xin2d, mean, rstd, weight, bias, rms_only, mem_eff):
    n, h = dy2d.shape
    pad = _pad_rows(n)
    if pad:
        dy2d = jnp.pad(dy2d, ((0, pad), (0, 0)))
        xin2d = jnp.pad(xin2d, ((0, pad), (0, 0)))
        if mem_eff and bias is not None:
            # padded rows of y must still invert the affine cleanly; adding
            # bias there makes xhat zero instead of -b/w.
            xin2d = xin2d.at[n:].set(jnp.broadcast_to(bias.astype(xin2d.dtype), (pad, h)))
        mean = jnp.pad(mean, (0, pad))
        rstd = jnp.pad(rstd, (0, pad))
    np_ = dy2d.shape[0]
    affine = weight is not None
    has_bias = bias is not None
    w = (weight if affine else jnp.zeros((h,), dy2d.dtype)).reshape(1, h)
    b = (bias if has_bias else jnp.zeros((h,), dy2d.dtype)).reshape(1, h)
    wdtype = weight.dtype if affine else dy2d.dtype
    grid = np_ // _BLOCK_ROWS
    mean2 = mean.reshape(grid, _BLOCK_ROWS)
    rstd2 = rstd.reshape(grid, _BLOCK_ROWS)
    dx, dw, db = pl.pallas_call(
        functools.partial(_bwd_kernel, rms_only=rms_only, affine=affine,
                          has_bias=has_bias, mem_eff=mem_eff),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, h), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS, h), lambda i: (i, 0)),
            pl.BlockSpec((grid, _BLOCK_ROWS), lambda i: (0, 0)),
            pl.BlockSpec((grid, _BLOCK_ROWS), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((_BLOCK_ROWS, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((np_, h), dy2d.dtype),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
        ],
        interpret=_INTERPRET(),
    )(dy2d, xin2d, mean2, rstd2, w, b)
    if pad:
        dx = dx[:n]
    dw = dw.reshape(h).astype(wdtype) if affine else None
    db = db.reshape(h).astype(bias.dtype) if has_bias else None
    return dx, dw, db


# VMEM budget for the kernel path: each grid step holds a few
# (_BLOCK_ROWS, H) fp32 tiles (x/y/temps fwd; dy/xin/dx bwd), so H is capped
# at 4096 (~2 MiB per tile); the full-array stats blocks are (rows/128, 128)
# fp32, so the row count is capped to keep them small.  Larger shapes take
# the jnp fallback, which XLA handles fine.
_MAX_H = 4096
_MAX_ROWS = 256 * 1024


def _kernel_ok(n: int, h: int) -> bool:
    return (kernels_enabled() and lane_aligned(h)
            and h <= _MAX_H and n <= _MAX_ROWS)


# ---------------------------------------------------------------------------
# custom_vjp entry points
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _norm(x, weight, bias, eps, rms_only, memory_efficient):
    return _norm_fwd(x, weight, bias, eps, rms_only, memory_efficient)[0]


def _norm_fwd(x, weight, bias, eps, rms_only, memory_efficient):
    shape = x.shape
    h = shape[-1]
    x2d = x.reshape(-1, h)
    if _kernel_ok(x2d.shape[0], h):
        y2d, mean, rstd = _pallas_forward(x2d, weight, bias, eps, rms_only)
    else:
        y2d, mean, rstd = _jnp_forward(x2d, weight, bias, eps, rms_only)
    y = y2d.reshape(shape)
    saved = y2d if memory_efficient else x2d
    return y, (saved, mean, rstd, weight, bias)


def _norm_bwd(eps, rms_only, memory_efficient, res, dy):
    saved, mean, rstd, weight, bias = res
    shape = dy.shape
    h = shape[-1]
    dy2d = dy.reshape(-1, h)
    if _kernel_ok(dy2d.shape[0], h):
        dx2d, dw, db = _pallas_backward(dy2d, saved, mean, rstd, weight, bias,
                                        rms_only, memory_efficient)
    else:
        s32 = saved.astype(jnp.float32)
        if memory_efficient:
            xhat = s32
            if weight is not None:
                if bias is not None:
                    xhat = xhat - bias.astype(jnp.float32)
                xhat = xhat / weight.astype(jnp.float32)
        else:
            xhat = s32 * rstd[..., None] if rms_only else (s32 - mean[..., None]) * rstd[..., None]
        dx2d = _jnp_backward(dy2d, xhat, rstd, weight, rms_only).astype(dy.dtype)
        dy32 = dy2d.astype(jnp.float32)
        dw = jnp.sum(dy32 * xhat, axis=0).astype(weight.dtype) if weight is not None else None
        db = jnp.sum(dy32, axis=0).astype(bias.dtype) if bias is not None else None
    return dx2d.reshape(shape), dw, db


_norm.defvjp(_norm_fwd, _norm_bwd)


# Public functional API (apex.normalization functional forms,
# apex/normalization/fused_layer_norm.py fused_layer_norm{,_affine}, fused_rms_norm{,_affine}).


def fused_layer_norm(x, normalized_shape, eps: float = 1e-5, *,
                     memory_efficient: bool = False):
    _check_shape(x, normalized_shape)
    h = _numel(normalized_shape)
    y = _norm(x.reshape(*_lead(x, normalized_shape), h), None, None, eps, False,
              memory_efficient)
    return y.reshape(x.shape)


def fused_layer_norm_affine(x, weight, bias, normalized_shape, eps: float = 1e-5, *,
                            memory_efficient: bool = False):
    _check_shape(x, normalized_shape)
    h = _numel(normalized_shape)
    y = _norm(x.reshape(*_lead(x, normalized_shape), h), weight.reshape(h),
              bias.reshape(h), eps, False, memory_efficient)
    return y.reshape(x.shape)


def fused_rms_norm(x, normalized_shape, eps: float = 1e-5, *,
                   memory_efficient: bool = False):
    _check_shape(x, normalized_shape)
    h = _numel(normalized_shape)
    y = _norm(x.reshape(*_lead(x, normalized_shape), h), None, None, eps, True,
              memory_efficient)
    return y.reshape(x.shape)


def fused_rms_norm_affine(x, weight, normalized_shape, eps: float = 1e-5, *,
                          memory_efficient: bool = False):
    _check_shape(x, normalized_shape)
    h = _numel(normalized_shape)
    y = _norm(x.reshape(*_lead(x, normalized_shape), h), weight.reshape(h),
              None, eps, True, memory_efficient)
    return y.reshape(x.shape)


def _numel(shape) -> int:
    out = 1
    for s in tuple(shape):
        out *= int(s)
    return out


def _lead(x, normalized_shape):
    nd = len(tuple(normalized_shape))
    return x.shape[: x.ndim - nd]


def _check_shape(x, normalized_shape):
    ns = tuple(int(s) for s in tuple(normalized_shape))
    if tuple(x.shape[x.ndim - len(ns):]) != ns:
        raise ValueError(
            f"input trailing shape {x.shape[x.ndim - len(ns):]} != normalized_shape {ns}")
