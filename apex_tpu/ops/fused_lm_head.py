"""Fused LM head: tied-embedding logits + cross-entropy in one Pallas kernel.

Parity target: the reference's fused losses (apex/contrib/xentropy —
softmax_xentropy saving logits instead of probabilities — and the vocab-
parallel CE of apex/transformer/tensor_parallel/cross_entropy.py).  This
kernel goes one step further, TPU-first: it fuses the *logits matmul
itself* with an online-logsumexp cross-entropy, so the ``[tokens, vocab]``
logits matrix never exists in HBM at all.

Why: on a v5e the GPT-2 bench head (8192 tokens x 50304 vocab) costs
~27 ms/step materialized *inside the training step* — fp32 logits
(1.65 GB) written by the matmul, re-read by softmax, exp residuals saved
across the fwd/bwd boundary, dlogits written and re-read by the two wgrad
matmuls.  Fused, the forward reads H (16 MB) and E (103 MB) once and
emits per-token ``loss``/``lse`` (64 KB) — nothing O(T·V) survives the
forward.

Design (hybrid, measured — tools/head_bench.py on v5e):

- fwd: Pallas kernel, grid ``(T/Tb, V/Vb)`` vocab innermost: logits tile
  = H_tile @ E_tileᵀ (fp32 MXU accumulation), online max/sum-exp across
  vocab tiles in VMEM scratch, target logit gathered by comparing tile
  column ids to the label.  2.9 ms vs 4.6 ms materialized.
- bwd: two Pallas kernels (dH vocab-innermost, dE token-innermost), each
  recomputing logits tiles from the saved lse (see ``_pallas_bwd`` for
  the measured in-model rationale vs the alternatives) — only ``lse``
  (32 KB) crosses the fwd/bwd boundary.
- vocab is padded to the tile size in-kernel (masked to -inf / zero
  contribution), so any vocab works; tokens must divide Tb.

Single-shard only (the tensor-parallel vocab case keeps the psum-based
``vocab_parallel_cross_entropy``); the dispatcher in
``standalone_gpt.GPTModel`` routes tp-world-1 training through this kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._dispatch import kernels_enabled, use_interpret

__all__ = ["fused_lm_head_loss", "lm_head_loss_reference"]

_NEG_INF = -1e30


def lm_head_loss_reference(hidden, embedding, labels):
    """Materialized reference: logits = H Eᵀ (fp32), per-token CE loss.

    Out-of-range labels contribute a target logit of exactly 0 (loss =
    lse), matching the kernel's no-column-matches behavior — NOT torch's
    take-and-clamp.  See :func:`fused_lm_head_loss` for the contract.
    """
    logits = jax.lax.dot_general(
        hidden, embedding, (((hidden.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    m = jnp.max(logits, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[..., None]), axis=-1))
    valid = (labels >= 0) & (labels < embedding.shape[0])
    safe = jnp.clip(labels, 0, embedding.shape[0] - 1)
    tgt = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    return lse - jnp.where(valid, tgt, 0.0)


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------


def _fwd_kernel(h_ref, e_ref, lab_ref, loss_ref, lse_ref, m_scr, l_scr,
                t_scr, *, vocab, vb):
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        t_scr[...] = jnp.zeros_like(t_scr)

    # operands stay in the input dtype: bf16 hits the MXU at native rate
    # with fp32 accumulation (an fp32 upcast forces the slow fp32 path)
    s = jax.lax.dot_general(h_ref[...], e_ref[...], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # [Tb, Vb]
    tb = s.shape[0]
    col = j * vb + jax.lax.broadcasted_iota(jnp.int32, (tb, vb), 1)
    live = col < vocab                          # mask the padded vocab tail
    s = jnp.where(live, s, _NEG_INF)

    # target logit: labels are lane-tiled [Tb, 128]; column 0 holds the id.
    # The live guard keeps labels that land in the padded vocab tail (an
    # out-of-range id) from accumulating the -1e30 mask value: such rows
    # return lse - 0, identical to the materialized fallback.
    lab = lab_ref[...][:, :1]                   # [Tb, 1]
    t_scr[...] += jnp.sum(jnp.where((col == lab) & live, s, 0.0), axis=-1,
                          keepdims=True)

    m_prev = m_scr[:, :1]
    m_cur = jnp.maximum(jnp.max(s, axis=-1, keepdims=True), m_prev)
    corr = jnp.where(m_prev == -jnp.inf, 0.0, jnp.exp(m_prev - m_cur))
    p = jnp.exp(s - m_cur)
    p = jnp.where(live, p, 0.0)
    l_scr[...] = l_scr[...] * corr + jnp.broadcast_to(
        jnp.sum(p, axis=-1, keepdims=True), l_scr.shape)
    m_scr[...] = jnp.broadcast_to(m_cur, m_scr.shape)

    @pl.when(j == nj - 1)
    def _finish():
        m = m_scr[:, :1]
        l = l_scr[:, :1]
        lse = m + jnp.log(l)
        loss_ref[...] = jnp.broadcast_to(lse - t_scr[:, :1], loss_ref.shape)
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


# ---------------------------------------------------------------------------
# pallas_call plumbing + custom_vjp
# ---------------------------------------------------------------------------


def _lane_tile(x, dtype):
    """[T] -> [T, 128] so per-token scalars tile cleanly in VMEM."""
    return jnp.broadcast_to(x.astype(dtype)[:, None], (x.shape[0], 128))


def _pad_vocab(e, vb):
    v = e.shape[0]
    pad = (-v) % vb
    if pad:
        e = jnp.pad(e, ((0, pad), (0, 0)))
    return e, v


def _pallas_fused_fwd(h2, e, labels, tb, vb):
    from jax.experimental.pallas import tpu as pltpu

    t, hid = h2.shape
    ep, vocab = _pad_vocab(e, vb)
    vp = ep.shape[0]
    grid = (t // tb, vp // vb)
    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, vocab=vocab, vb=vb),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tb, hid), lambda i, j: (i, 0)),
            pl.BlockSpec((vb, hid), lambda i, j: (j, 0)),
            pl.BlockSpec((tb, 128), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tb, 128), lambda i, j: (i, 0)),
            pl.BlockSpec((tb, 128), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, 128), jnp.float32),
            jax.ShapeDtypeStruct((t, 128), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((tb, 128), jnp.float32),
                        pltpu.VMEM((tb, 128), jnp.float32),
                        pltpu.VMEM((tb, 1), jnp.float32)],
        interpret=use_interpret(),
    )(h2, ep, _lane_tile(labels, jnp.int32))
    return loss[:, 0], lse[:, 0]


def _dh_kernel(h_ref, e_ref, lab_ref, lse_ref, g_ref, dh_ref, dh_scr,
               *, vocab, vb):
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        dh_scr[...] = jnp.zeros_like(dh_scr)

    e = e_ref[...]
    s = jax.lax.dot_general(h_ref[...], e, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    tb = s.shape[0]
    col = j * vb + jax.lax.broadcasted_iota(jnp.int32, (tb, vb), 1)
    live = col < vocab
    lse = lse_ref[...][:, :1]
    p = jnp.where(live, jnp.exp(s - lse), 0.0)
    lab = lab_ref[...][:, :1]
    g = g_ref[...][:, :1]                       # upstream per-token cotangent
    dlog = (p - jnp.where(col == lab, 1.0, 0.0)) * g
    dh_scr[...] += jax.lax.dot_general(dlog.astype(e.dtype), e,
                                       (((1,), (0,)), ((), ())),
                                       preferred_element_type=jnp.float32)

    @pl.when(j == nj - 1)
    def _finish():
        dh_ref[...] = dh_scr[...].astype(dh_ref.dtype)


def _de_kernel(h_ref, e_ref, lab_ref, lse_ref, g_ref, de_ref, de_scr,
               *, vocab, vb):
    j, i = pl.program_id(0), pl.program_id(1)   # vocab block outer, T inner
    ni = pl.num_programs(1)

    @pl.when(i == 0)
    def _init():
        de_scr[...] = jnp.zeros_like(de_scr)

    h = h_ref[...]
    s = jax.lax.dot_general(h, e_ref[...], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    tb = s.shape[0]
    col = j * vb + jax.lax.broadcasted_iota(jnp.int32, (tb, vb), 1)
    live = col < vocab
    lse = lse_ref[...][:, :1]
    p = jnp.where(live, jnp.exp(s - lse), 0.0)
    lab = lab_ref[...][:, :1]
    g = g_ref[...][:, :1]
    dlog = (p - jnp.where(col == lab, 1.0, 0.0)) * g
    de_scr[...] += jax.lax.dot_general(dlog.astype(h.dtype), h,
                                       (((0,), (0,)), ((), ())),
                                       preferred_element_type=jnp.float32)

    @pl.when(i == ni - 1)
    def _finish():
        de_ref[...] = de_scr[...].astype(de_ref.dtype)


def _pallas_bwd(h2, e, labels, lse, g, tb, vb):
    """Backward as two Pallas kernels recomputing logits tiles from lse.

    Measured on v5e (tools/head_bench.py + bench.py): isolated, this
    double recompute (~3.4 TF) is slower than XLA's materialized backward
    (24.6 vs 19.5 ms fwd+bwd) — but *in the training step* it wins
    (212.9 vs 213.6 ms/step), and beats a single shared XLA recompute
    with a label scatter (216.6 ms/step): nothing O(T·V) is written, so
    the backward composes with the 24-layer body under HBM pressure where
    the materialized dlogits/residual traffic does not.
    """
    from jax.experimental.pallas import tpu as pltpu

    t, hid = h2.shape
    # backward tiles are smaller: dH/dE kernels hold extra fp32 tiles
    # (p, dlog, accumulator scratch) — 512x1536 overflows the ~16 MiB VMEM
    # budget on v5e (measured: 17.64M requested).  tb must still divide t:
    # shrink to the largest divisor of the caller's (valid) tb that is
    # <= 256, rather than falling back to one whole-token tile.
    while tb > 256 and tb % 2 == 0:
        tb //= 2
    # the vocab tile shrinks with hidden (the e tile and accumulator
    # scratch scale with vb*hid: at hid=1280 a 1024-wide tile overflows
    # VMEM by 144 KB, measured on GPT-2-large) — but never grows past the
    # 1024 cap (the fp32 score/dlog tiles scale with tb*vb regardless)
    vb = min(vb, 1024, max(128, (1024 * 1024 // hid) // 128 * 128))
    ep, vocab = _pad_vocab(e, vb)
    vp = ep.shape[0]
    lab3 = _lane_tile(labels, jnp.int32)
    lse3 = _lane_tile(lse, jnp.float32)
    g3 = _lane_tile(g, jnp.float32)

    dh = pl.pallas_call(
        functools.partial(_dh_kernel, vocab=vocab, vb=vb),
        grid=(t // tb, vp // vb),
        in_specs=[
            pl.BlockSpec((tb, hid), lambda i, j: (i, 0)),
            pl.BlockSpec((vb, hid), lambda i, j: (j, 0)),
            pl.BlockSpec((tb, 128), lambda i, j: (i, 0)),
            pl.BlockSpec((tb, 128), lambda i, j: (i, 0)),
            pl.BlockSpec((tb, 128), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((tb, hid), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((t, hid), h2.dtype),
        scratch_shapes=[pltpu.VMEM((tb, hid), jnp.float32)],
        interpret=use_interpret(),
    )(h2, ep, lab3, lse3, g3)

    de = pl.pallas_call(
        functools.partial(_de_kernel, vocab=vocab, vb=vb),
        grid=(vp // vb, t // tb),
        in_specs=[
            pl.BlockSpec((tb, hid), lambda j, i: (i, 0)),
            pl.BlockSpec((vb, hid), lambda j, i: (j, 0)),
            pl.BlockSpec((tb, 128), lambda j, i: (i, 0)),
            pl.BlockSpec((tb, 128), lambda j, i: (i, 0)),
            pl.BlockSpec((tb, 128), lambda j, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((vb, hid), lambda j, i: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((vp, hid), e.dtype),
        scratch_shapes=[pltpu.VMEM((vb, hid), jnp.float32)],
        interpret=use_interpret(),
    )(h2, ep, lab3, lse3, g3)
    return dh, de[:e.shape[0]]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused(h2, e, labels, tb, vb):
    loss, _ = _pallas_fused_fwd(h2, e, labels, tb, vb)
    return loss


def _fused_fwd(h2, e, labels, tb, vb):
    loss, lse = _pallas_fused_fwd(h2, e, labels, tb, vb)
    return loss, (h2, e, labels, lse)


def _fused_bwd(tb, vb, res, g):
    h2, e, labels, lse = res
    dh, de = _pallas_bwd(h2, e, labels, lse, g, tb, vb)
    return dh, de, None


_fused.defvjp(_fused_fwd, _fused_bwd)


def _kernel_ok(t, hid, block_t) -> bool:
    return (kernels_enabled() and t % block_t == 0 and hid % 128 == 0)


def fused_lm_head_loss(hidden, embedding, labels, *, block_t: int = 512,
                       block_v: int | None = None):
    """Per-token cross-entropy of ``hidden @ embedding.T`` without ever
    materializing the logits.

    Args:
      hidden: ``[..., h]`` activations (any leading shape; bf16/fp32).
      embedding: ``[vocab, h]`` tied LM-head table.
      labels: ``[...]`` int32 target ids (same leading shape as hidden).
        **Must be in ``[0, vocab)``.**  Out-of-range ids (e.g. an
        ignore_index like -100) are NOT supported: both paths then return
        ``lse`` (target logit treated as 0) with a zero gradient to the
        missing column — a deterministic, path-independent value, but not
        a cross-entropy.  Mask ignored tokens explicitly instead:
        ``jnp.where(labels == ignore, 0.0, loss)`` with safe labels.
      block_t / block_v: token / vocab tile sizes (vocab is padded to
        block_v internally; tokens must divide block_t for the kernel
        path, else the materialized reference runs).  ``block_v=None``
        (default) picks 1536, auto-shrunk past hid=1280 to fit the
        ~16 MiB VMEM budget; an explicit ``block_v`` is honored as given
        (ADVICE r4: no silent clamp of caller-supplied tiles).

    Returns per-token loss ``[...]`` in fp32: ``logsumexp(logits) -
    logits[label]``.
    """
    lead = hidden.shape[:-1]
    hid = hidden.shape[-1]
    h2 = hidden.reshape(-1, hid)
    lab = labels.reshape(-1).astype(jnp.int32)
    t = h2.shape[0]
    # the fwd VMEM footprint is dominated by the double-buffered e tile
    # (vb*hid) plus the fp32 score tile (tb*vb): the default 512x1536 fits
    # at hid<=1280 but overflows the ~16 MiB scoped budget at hid=2048
    # (measured: 17.25M requested compiling the 1.3B config) — the default
    # vocab tile shrinks as hid grows past the tuned point; an explicit
    # block_v is the caller's choice and is not overridden
    if block_v is None:
        block_v = 1536
        if hid > 1280:
            block_v = max(128, (1536 * 1280 // hid) // 128 * 128)
    if _kernel_ok(t, hid, block_t):
        loss = _fused(h2, embedding, lab, min(block_t, t), block_v)
    else:
        loss = lm_head_loss_reference(h2, embedding, lab)
    return loss.reshape(lead)
