"""Packed-buffer Pallas optimizer updates — the multi-tensor-apply kernel.

Parity target: ``amp_C.multi_tensor_adam`` / ``multi_tensor_sgd`` / the
``multi_tensor_apply<depth>`` chunking harness
(csrc/multi_tensor_apply.cuh:16-133, csrc/multi_tensor_adam.cu,
csrc/multi_tensor_sgd_kernel.cu).  On CUDA the harness packs up to 110 tensor
pointers and 320 (block→tensor, chunk) pairs per launch so one kernel updates
the whole parameter list.

TPU shape strategy (SURVEY.md §7 "Multi-tensor apply in Pallas"): ragged
pointer tables don't map to Pallas, so the model's parameters are packed once
into flat aligned buffers (:mod:`apex_tpu.utils.packing`) and ONE grid kernel
sweeps the flat buffer in VMEM-sized chunks.  This keeps many-small-tensor
models (embedding tables, biases, norm scales) from paying per-tensor
dispatch, the same problem the CUDA harness solves.

The kernels here are the innermost update math only; the user-facing
optimizers (:mod:`apex_tpu.optimizers`) use per-leaf fused XLA updates by
default and switch to the packed path via ``packed=True``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._dispatch import kernels_enabled, use_interpret

_CHUNK = 64 * 1024  # elements per grid step; 4 fp32 buffers/step ≈ 1 MiB VMEM


def _adam_kernel(g_ref, p_ref, m_ref, v_ref, scalars_ref,
                 p_out, m_out, v_out, *, adam_w_mode):
    """One packed-Adam chunk.  scalars = [lr, beta1, beta2, eps, wd, bc1, bc2, noop].

    Math matches AdamFunctor (csrc/multi_tensor_adam.cu): load→fp32→update→
    store; ``noop`` (overflow flag, fp32 0/1) makes the step an identity,
    which is the capturable skip-on-overflow path (fused_adam.py:199-263).
    """
    lr = scalars_ref[0]
    beta1 = scalars_ref[1]
    beta2 = scalars_ref[2]
    eps = scalars_ref[3]
    wd = scalars_ref[4]
    bc1 = scalars_ref[5]
    bc2 = scalars_ref[6]
    noop = scalars_ref[7]

    g = g_ref[:].astype(jnp.float32)
    p = p_ref[:].astype(jnp.float32)
    m = m_ref[:]
    v = v_ref[:]

    if adam_w_mode:
        m_new = beta1 * m + (1.0 - beta1) * g
        v_new = beta2 * v + (1.0 - beta2) * g * g
        denom = jnp.sqrt(v_new / bc2) + eps
        update = (m_new / bc1) / denom + wd * p
        p_new = p - lr * update
    else:
        g = g + wd * p
        m_new = beta1 * m + (1.0 - beta1) * g
        v_new = beta2 * v + (1.0 - beta2) * g * g
        denom = jnp.sqrt(v_new / bc2) + eps
        p_new = p - lr * (m_new / bc1) / denom

    keep = noop == 0.0
    p_out[:] = jnp.where(keep, p_new, p).astype(p_out.dtype)
    m_out[:] = jnp.where(keep, m_new, m)
    v_out[:] = jnp.where(keep, v_new, v)


def packed_adam_update(flat_grad, flat_param, flat_m, flat_v, *,
                       lr, beta1, beta2, eps, weight_decay,
                       bias_correction1, bias_correction2,
                       noop_flag=None, adam_w_mode: bool = True):
    """Run the packed Adam kernel over flat 1-D buffers of equal length.

    Buffers must be padded to a multiple of 1024 elements
    (``apex_tpu.utils.packing.pack_pytree`` guarantees this).  Returns
    (new_param, new_m, new_v).
    """
    n = flat_param.shape[0]
    scalars = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(beta1, jnp.float32),
        jnp.asarray(beta2, jnp.float32),
        jnp.asarray(eps, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        jnp.asarray(bias_correction1, jnp.float32),
        jnp.asarray(bias_correction2, jnp.float32),
        jnp.asarray(0.0 if noop_flag is None else noop_flag, jnp.float32),
    ])
    if not kernels_enabled() or n % 1024:
        # jnp fallback with identical math
        return _jnp_adam(flat_grad, flat_param, flat_m, flat_v, scalars, adam_w_mode)
    # View the 1024-aligned flat buffer as (rows, 128) so blocks satisfy the
    # (8, 128) f32 tiling; each grid step sweeps one VMEM-sized row chunk.
    rows = n // 128
    chunk_rows = min(_CHUNK // 128, rows)
    while rows % chunk_rows:
        chunk_rows //= 2
    as2d = lambda a: a.reshape(rows, 128)
    grid = rows // chunk_rows
    block = pl.BlockSpec((chunk_rows, 128), lambda i: (i, 0))
    p_new, m_new, v_new = pl.pallas_call(
        functools.partial(_adam_kernel, adam_w_mode=adam_w_mode),
        grid=(grid,),
        in_specs=[block, block, block, block,
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[block, block, block],
        out_shape=[
            jax.ShapeDtypeStruct((rows, 128), flat_param.dtype),
            jax.ShapeDtypeStruct((rows, 128), jnp.float32),
            jax.ShapeDtypeStruct((rows, 128), jnp.float32),
        ],
        interpret=use_interpret(),
    )(as2d(flat_grad), as2d(flat_param), as2d(flat_m), as2d(flat_v), scalars)
    return p_new.reshape(n), m_new.reshape(n), v_new.reshape(n)


def _jnp_adam(g, p, m, v, scalars, adam_w_mode):
    lr, beta1, beta2, eps, wd, bc1, bc2, noop = [scalars[i] for i in range(8)]
    g32 = g.astype(jnp.float32)
    p32 = p.astype(jnp.float32)
    if adam_w_mode:
        m_new = beta1 * m + (1 - beta1) * g32
        v_new = beta2 * v + (1 - beta2) * g32 * g32
        p_new = p32 - lr * ((m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps) + wd * p32)
    else:
        g32 = g32 + wd * p32
        m_new = beta1 * m + (1 - beta1) * g32
        v_new = beta2 * v + (1 - beta2) * g32 * g32
        p_new = p32 - lr * (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    keep = noop == 0.0
    return (jnp.where(keep, p_new, p32).astype(p.dtype),
            jnp.where(keep, m_new, m),
            jnp.where(keep, v_new, v))
