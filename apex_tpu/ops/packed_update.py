"""Packed-buffer Pallas optimizer updates — the multi-tensor-apply kernel.

Parity target: ``amp_C.multi_tensor_adam`` / ``multi_tensor_sgd`` / the
``multi_tensor_apply<depth>`` chunking harness
(csrc/multi_tensor_apply.cuh:16-133, csrc/multi_tensor_adam.cu,
csrc/multi_tensor_sgd_kernel.cu).  On CUDA the harness packs up to 110 tensor
pointers and 320 (block→tensor, chunk) pairs per launch so one kernel updates
the whole parameter list.

TPU shape strategy (SURVEY.md §7 "Multi-tensor apply in Pallas"): ragged
pointer tables don't map to Pallas, so the model's parameters are packed once
into flat aligned buffers (:mod:`apex_tpu.utils.packing`) and ONE grid kernel
sweeps the flat buffer in VMEM-sized chunks.  This keeps many-small-tensor
models (embedding tables, biases, norm scales) from paying per-tensor
dispatch, the same problem the CUDA harness solves.

The kernels here are the innermost update math only; the user-facing
optimizers (:mod:`apex_tpu.optimizers`) use per-leaf fused XLA updates by
default and switch to the packed path via ``packed=True``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._dispatch import kernels_enabled, use_interpret

_CHUNK = 64 * 1024  # elements per grid step; 4 fp32 buffers/step ≈ 1 MiB VMEM


def _adam_kernel(g_ref, p_ref, m_ref, v_ref, scalars_ref,
                 p_out, m_out, v_out, *, adam_w_mode):
    """One packed-Adam chunk.  scalars = [lr, beta1, beta2, eps, wd, bc1, bc2, noop].

    Math matches AdamFunctor (csrc/multi_tensor_adam.cu): load→fp32→update→
    store; ``noop`` (overflow flag, fp32 0/1) makes the step an identity,
    which is the capturable skip-on-overflow path (fused_adam.py:199-263).
    """
    lr = scalars_ref[0]
    beta1 = scalars_ref[1]
    beta2 = scalars_ref[2]
    eps = scalars_ref[3]
    wd = scalars_ref[4]
    bc1 = scalars_ref[5]
    bc2 = scalars_ref[6]
    noop = scalars_ref[7]

    g = g_ref[:].astype(jnp.float32)
    p = p_ref[:].astype(jnp.float32)
    m = m_ref[:]
    v = v_ref[:]

    if adam_w_mode:
        m_new = beta1 * m + (1.0 - beta1) * g
        v_new = beta2 * v + (1.0 - beta2) * g * g
        denom = jnp.sqrt(v_new / bc2) + eps
        update = (m_new / bc1) / denom + wd * p
        p_new = p - lr * update
    else:
        g = g + wd * p
        m_new = beta1 * m + (1.0 - beta1) * g
        v_new = beta2 * v + (1.0 - beta2) * g * g
        denom = jnp.sqrt(v_new / bc2) + eps
        p_new = p - lr * (m_new / bc1) / denom

    keep = noop == 0.0
    p_out[:] = jnp.where(keep, p_new, p).astype(p_out.dtype)
    m_out[:] = jnp.where(keep, m_new, m)
    v_out[:] = jnp.where(keep, v_new, v)


def packed_adam_update(flat_grad, flat_param, flat_m, flat_v, *,
                       lr, beta1, beta2, eps, weight_decay,
                       bias_correction1, bias_correction2,
                       noop_flag=None, adam_w_mode: bool = True):
    """Run the packed Adam kernel over flat 1-D buffers of equal length.

    Buffers must be padded to a multiple of 1024 elements
    (``apex_tpu.utils.packing.pack_pytree`` guarantees this).  Returns
    (new_param, new_m, new_v).
    """
    n = flat_param.shape[0]
    scalars = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(beta1, jnp.float32),
        jnp.asarray(beta2, jnp.float32),
        jnp.asarray(eps, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        jnp.asarray(bias_correction1, jnp.float32),
        jnp.asarray(bias_correction2, jnp.float32),
        jnp.asarray(0.0 if noop_flag is None else noop_flag, jnp.float32),
    ])
    if not kernels_enabled() or n % 1024:
        # jnp fallback with identical math
        return _jnp_adam(flat_grad, flat_param, flat_m, flat_v, scalars, adam_w_mode)
    # View the 1024-aligned flat buffer as (rows, 128) so blocks satisfy the
    # (8, 128) f32 tiling; each grid step sweeps one VMEM-sized row chunk.
    rows = n // 128
    chunk_rows = min(_CHUNK // 128, rows)
    while rows % chunk_rows:
        chunk_rows //= 2
    as2d = lambda a: a.reshape(rows, 128)
    grid = rows // chunk_rows
    block = pl.BlockSpec((chunk_rows, 128), lambda i: (i, 0))
    p_new, m_new, v_new = pl.pallas_call(
        functools.partial(_adam_kernel, adam_w_mode=adam_w_mode),
        grid=(grid,),
        in_specs=[block, block, block, block,
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[block, block, block],
        out_shape=[
            jax.ShapeDtypeStruct((rows, 128), flat_param.dtype),
            jax.ShapeDtypeStruct((rows, 128), jnp.float32),
            jax.ShapeDtypeStruct((rows, 128), jnp.float32),
        ],
        interpret=use_interpret(),
    )(as2d(flat_grad), as2d(flat_param), as2d(flat_m), as2d(flat_v), scalars)
    return p_new.reshape(n), m_new.reshape(n), v_new.reshape(n)


def _jnp_adam(g, p, m, v, scalars, adam_w_mode):
    lr, beta1, beta2, eps, wd, bc1, bc2, noop = [scalars[i] for i in range(8)]
    g32 = g.astype(jnp.float32)
    p32 = p.astype(jnp.float32)
    if adam_w_mode:
        m_new = beta1 * m + (1 - beta1) * g32
        v_new = beta2 * v + (1 - beta2) * g32 * g32
        p_new = p32 - lr * ((m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps) + wd * p32)
    else:
        g32 = g32 + wd * p32
        m_new = beta1 * m + (1 - beta1) * g32
        v_new = beta2 * v + (1 - beta2) * g32 * g32
        p_new = p32 - lr * (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    keep = noop == 0.0
    return (jnp.where(keep, p_new, p32).astype(p.dtype),
            jnp.where(keep, m_new, m),
            jnp.where(keep, v_new, v))


# ---------------------------------------------------------------------------
# packed SGD lives in this module too (kernel above); the remaining fused
# optimizers' packed paths follow.  LAMB/NovoGrad need *per-tensor* segment
# reductions over the flat buffer (trust ratios / per-tensor second moments)
# — those reductions run as XLA segment_sums (which lower to one fused
# scatter-add sweep) sandwiching the Pallas elementwise phases.
# ---------------------------------------------------------------------------

import numpy as np

from apex_tpu.utils.packing import PackedSpec


def segment_ids_for_spec(spec: PackedSpec) -> jnp.ndarray:
    """Leaf index per flat element; padding gets the dead segment
    ``spec.num_leaves`` (dropped by ``num_segments``-bounded reductions).

    Computed ON DEVICE from the tiny per-leaf boundary table
    (searchsorted over an iota): materializing the O(total-params) id
    array on the host would embed a multi-GB constant in the compiled
    program — large enough to break remote-compile transports — and cost
    a host->device upload per eager step.
    """
    if spec.padded_total >= 2 ** 31:
        raise NotImplementedError(
            f"packed buffer of {spec.padded_total} elements exceeds int32 "
            "segment-id range; shard the parameters (ZeRO) below 2**31 "
            "elements per buffer")
    # boundary[i] = end offset of leaf i; elements past the last boundary
    # (padding) land at index num_leaves.  searchsorted assumes leaves are
    # contiguous — assert against spec.offsets (the layout's source of
    # truth) so a future gapped layout fails loudly, not silently.
    ends = np.asarray(spec.offsets) + np.asarray(spec.sizes)
    if spec.num_leaves and not np.array_equal(
            np.asarray(spec.offsets)[1:], ends[:-1]):
        raise ValueError("segment_ids_for_spec requires a contiguous "
                         "packed layout (offsets must tile sizes)")
    boundaries = jnp.asarray(ends, jnp.int32)
    return jnp.searchsorted(boundaries,
                            jnp.arange(spec.padded_total, dtype=jnp.int32),
                            side="right").astype(jnp.int32)


def _segment_sqnorm(x32, seg_ids, num_segments):
    return jax.ops.segment_sum(x32 * x32, seg_ids,
                               num_segments=num_segments)


def per_leaf_sqnorms(x32, spec: "PackedSpec") -> jnp.ndarray:
    """Per-tensor ``sum(x^2)`` over the flat buffer as DENSE contiguous
    static-slice reductions — one ``[num_leaves]`` result, no scatter.

    ``segment_sum`` over the flat buffer lowers to a scatter-add sweep
    that is pathological at 100M+ elements on TPU (measured r3: the
    355M packed-LAMB step never finished a 25-step run).  The leaf
    offsets/sizes are static Python ints, so each per-tensor reduction
    is an ordinary dense reduce over a contiguous slice — the same ops
    the (fast) unpacked path runs, fused by XLA into full-buffer sweeps.
    Returns a length ``num_leaves + 1`` vector (dead padding slot last)
    to stay drop-in for the segment formulation.
    """
    sums = [jnp.sum(jnp.square(x32[o:o + s]))
            for o, s in zip(spec.offsets, spec.sizes)]
    sums.append(jnp.zeros((), x32.dtype))  # dead padding segment
    return jnp.stack(sums)


def _lamb_phase1_kernel(g_ref, p_ref, m_ref, v_ref, scalars_ref,
                        m_out, v_out, u_out, *, adam_w_mode):
    """Elementwise LAMB moments + raw update (multi_tensor_lamb.cu stage 1).

    scalars = [beta1, beta3, beta2, eps, wd, bc1, bc2, clip].
    """
    beta1 = scalars_ref[0]
    beta3 = scalars_ref[1]
    beta2 = scalars_ref[2]
    eps = scalars_ref[3]
    wd = scalars_ref[4]
    bc1 = scalars_ref[5]
    bc2 = scalars_ref[6]
    clip = scalars_ref[7]

    g = g_ref[:].astype(jnp.float32) / clip
    p = p_ref[:].astype(jnp.float32)
    if not adam_w_mode:
        g = g + wd * p  # LAMB "MODE 0": L2 folded into the gradient
    m_new = beta1 * m_ref[:] + beta3 * g
    v_new = beta2 * v_ref[:] + (1.0 - beta2) * g * g
    update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    if adam_w_mode:
        update = update + wd * p
    m_out[:] = m_new
    v_out[:] = v_new
    u_out[:] = update


def packed_lamb_update(flat_grad, flat_param, flat_m, flat_v, seg_ids, *,
                       num_leaves, lr, beta1, beta2, beta3, eps,
                       weight_decay, bias_correction1, bias_correction2,
                       global_clip, adam_w_mode: bool = True,
                       use_nvlamb: bool = False, spec: "PackedSpec" = None):
    """Packed FusedLAMB step over flat 1-D buffers.

    Phase 1 (Pallas): moments + raw update, one sweep.  Phase 2 (XLA):
    per-tensor ``||p||/||update||`` trust ratios and the final
    gathered-ratio apply — the fused equivalent of multi_tensor_lamb.cu
    stage 2.  With ``spec`` given the trust-ratio reductions lower DENSE
    (static contiguous slices, :func:`per_leaf_sqnorms`); without it they
    fall back to flat segment_sums, whose scatter lowering is pathological
    at 100M+ elements (VERDICT r4 item 6).  Returns (new_param, new_m,
    new_v).
    """
    n = flat_param.shape[0]
    scalars = jnp.stack([
        jnp.asarray(beta1, jnp.float32), jnp.asarray(beta3, jnp.float32),
        jnp.asarray(beta2, jnp.float32), jnp.asarray(eps, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        jnp.asarray(bias_correction1, jnp.float32),
        jnp.asarray(bias_correction2, jnp.float32),
        jnp.asarray(global_clip, jnp.float32),
    ])
    p32 = flat_param.astype(jnp.float32)
    if kernels_enabled() and n % 1024 == 0:
        rows = n // 128
        chunk_rows = min(_CHUNK // 128, rows)
        while rows % chunk_rows:
            chunk_rows //= 2
        as2d = lambda a: a.reshape(rows, 128)
        block = pl.BlockSpec((chunk_rows, 128), lambda i: (i, 0))
        m_new, v_new, update = pl.pallas_call(
            functools.partial(_lamb_phase1_kernel, adam_w_mode=adam_w_mode),
            grid=(rows // chunk_rows,),
            in_specs=[block, block, block, block,
                      pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_specs=[block, block, block],
            out_shape=[jax.ShapeDtypeStruct((rows, 128), jnp.float32)] * 3,
            interpret=use_interpret(),
        )(as2d(flat_grad), as2d(flat_param), as2d(flat_m), as2d(flat_v),
          scalars)
        m_new, v_new, update = (m_new.reshape(n), v_new.reshape(n),
                                update.reshape(n))
    else:
        g = flat_grad.astype(jnp.float32) / scalars[7]
        if not adam_w_mode:
            g = g + scalars[4] * p32
        m_new = scalars[0] * flat_m + scalars[1] * g
        v_new = scalars[2] * flat_v + (1.0 - scalars[2]) * g * g
        update = (m_new / scalars[5]) / (jnp.sqrt(v_new / scalars[6])
                                         + scalars[3])
        if adam_w_mode:
            update = update + scalars[4] * p32

    # phase 2: per-tensor trust ratios (dead padding segment dropped)
    if spec is not None:
        p_norms = jnp.sqrt(per_leaf_sqnorms(p32, spec))
        u_norms = jnp.sqrt(per_leaf_sqnorms(update, spec))
    else:
        p_norms = jnp.sqrt(_segment_sqnorm(p32, seg_ids, num_leaves + 1))
        u_norms = jnp.sqrt(_segment_sqnorm(update, seg_ids, num_leaves + 1))
    ratios = jnp.where((p_norms > 0) & (u_norms > 0), p_norms / u_norms, 1.0)
    if not (weight_decay or use_nvlamb):
        ratios = jnp.ones_like(ratios)
    p_new = p32 - jnp.asarray(lr, jnp.float32) * jnp.take(ratios, seg_ids) \
        * update
    return p_new.astype(flat_param.dtype), m_new, v_new


def packed_novograd_update(flat_grad, flat_param, flat_m, seg_v, seg_ids, *,
                           num_leaves, lr, beta1, beta2, beta3, eps,
                           weight_decay, bias_correction1, bias_correction2,
                           is_first_step, init_zero: bool = False,
                           reg_inside_moment: bool = False):
    """Packed FusedNovoGrad step; ``seg_v`` is the per-tensor second moment
    of shape [num_leaves + 1] (NovoGrad's v is one scalar per tensor; the
    final slot is the dead padding segment).  Entirely XLA: two segment ops bracket an elementwise
    chain the compiler fuses into one sweep; a Pallas kernel would add
    nothing (no reuse to capture, the chain is bandwidth-bound).
    Returns (new_param, new_m, new_seg_v).
    """
    p32 = flat_param.astype(jnp.float32)
    g = flat_grad.astype(jnp.float32)
    g_sq = _segment_sqnorm(g, seg_ids, num_leaves + 1)
    v_upd = beta2 * seg_v + (1.0 - beta2) * g_sq
    v_init = jnp.zeros_like(g_sq) if init_zero else g_sq
    v_new = jnp.where(is_first_step, v_init, v_upd)
    denom = jnp.sqrt(v_new / bias_correction2) + eps
    g_hat = g / jnp.take(denom, seg_ids)
    if weight_decay and reg_inside_moment:
        g_hat = g_hat + weight_decay * p32
    m_new = beta1 * flat_m + beta3 * g_hat
    update = m_new / bias_correction1
    if weight_decay and not reg_inside_moment:
        update = update + weight_decay * p32
    p_new = p32 - jnp.asarray(lr, jnp.float32) * update
    return p_new.astype(flat_param.dtype), m_new, v_new


def _adagrad_kernel(g_ref, p_ref, h_ref, scalars_ref, p_out, h_out, *,
                    adagrad_w_mode):
    """scalars = [lr, eps, wd, noop]."""
    lr = scalars_ref[0]
    eps = scalars_ref[1]
    wd = scalars_ref[2]
    noop = scalars_ref[3]
    g = g_ref[:].astype(jnp.float32)
    p = p_ref[:].astype(jnp.float32)
    if not adagrad_w_mode:
        g = g + wd * p
    h_new = h_ref[:] + g * g
    update = g / (jnp.sqrt(h_new) + eps)
    if adagrad_w_mode:
        update = update + wd * p
    p_new = p - lr * update
    keep = noop == 0.0
    p_out[:] = jnp.where(keep, p_new, p).astype(p_out.dtype)
    h_out[:] = jnp.where(keep, h_new, h_ref[:])


def packed_adagrad_update(flat_grad, flat_param, flat_h, *, lr, eps,
                          weight_decay, adagrad_w_mode: bool = False,
                          noop_flag=None):
    """Packed FusedAdagrad step (csrc/multi_tensor_adagrad.cu math).
    Returns (new_param, new_h)."""
    n = flat_param.shape[0]
    scalars = jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.asarray(eps, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        jnp.asarray(0.0 if noop_flag is None else noop_flag, jnp.float32),
    ])
    if not kernels_enabled() or n % 1024:
        g = flat_grad.astype(jnp.float32)
        p = flat_param.astype(jnp.float32)
        if not adagrad_w_mode:
            g = g + scalars[2] * p
        h_new = flat_h + g * g
        update = g / (jnp.sqrt(h_new) + scalars[1])
        if adagrad_w_mode:
            update = update + scalars[2] * p
        p_new = p - scalars[0] * update
        keep = scalars[3] == 0.0
        return (jnp.where(keep, p_new, p).astype(flat_param.dtype),
                jnp.where(keep, h_new, flat_h))
    rows = n // 128
    chunk_rows = min(_CHUNK // 128, rows)
    while rows % chunk_rows:
        chunk_rows //= 2
    as2d = lambda a: a.reshape(rows, 128)
    block = pl.BlockSpec((chunk_rows, 128), lambda i: (i, 0))
    p_new, h_new = pl.pallas_call(
        functools.partial(_adagrad_kernel, adagrad_w_mode=adagrad_w_mode),
        grid=(rows // chunk_rows,),
        in_specs=[block, block, block,
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[block, block],
        out_shape=[
            jax.ShapeDtypeStruct((rows, 128), flat_param.dtype),
            jax.ShapeDtypeStruct((rows, 128), jnp.float32),
        ],
        interpret=use_interpret(),
    )(as2d(flat_grad), as2d(flat_param), as2d(flat_h), scalars)
    return p_new.reshape(n), h_new.reshape(n)
