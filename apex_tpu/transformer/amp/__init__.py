"""apex_tpu.transformer.amp — model-parallel-aware grad scaler.

Parity: ``apex.transformer.amp.GradScaler`` (amp/grad_scaler.py:21-60): a
GradScaler whose ``found_inf`` is all-reduced across the **model-parallel
ranks** (tp × pp) before the step/skip decision, so every shard of one model
replica skips together.
"""

from __future__ import annotations

from typing import Any

import jax

from apex_tpu.amp.scaler import LossScaler, LossScalerState
from apex_tpu.transformer.parallel_state import (
    PIPELINE_PARALLEL_AXIS,
    TENSOR_PARALLEL_AXIS,
)

__all__ = ["GradScaler"]


class GradScaler(LossScaler):
    """LossScaler that syncs found_inf over the model-parallel axes.

    Use inside shard_map over ('pp','tp') (or whichever subset exists):
    ``unscale`` ORs the overflow flag across those axes (grad_scaler.py:38-60
    does MAX over the model-parallel group).
    """

    def unscale(self, grads: Any, state: LossScalerState):
        unscaled, found_inf = super().unscale(grads, state)
        for axis in (TENSOR_PARALLEL_AXIS, PIPELINE_PARALLEL_AXIS):
            try:
                found_inf = jax.lax.pmax(found_inf.astype(jax.numpy.float32), axis) > 0
            except NameError:
                continue
        return unscaled, found_inf
