"""Mixture-of-Experts with expert parallelism over a mesh axis.

Capability target: expert parallelism ("ep") as a first-class sharding —
experts live sharded across ranks and tokens travel to their expert via
``all_to_all``, the standard TPU MoE dataflow (GShard/Switch): gate →
capacity-bounded dispatch einsum → all_to_all over ``ep`` → batched
expert FFN on the MXU → all_to_all back → weighted combine.  (NVIDIA
Apex predates MoE and has no counterpart; this rounds out the dp/tp/pp/
sp/ep sharding set the framework targets.)

Design notes:
- dispatch/combine are dense einsums against a [tokens, experts,
  capacity] one-hot — no dynamic shapes, so XLA can tile everything;
  tokens over capacity are dropped and their outputs pass through as
  zeros scaled into the residual (Switch semantics).
- the router computes in fp32 regardless of activation dtype; an
  auxiliary load-balancing loss (Switch eq. 4) is returned alongside.
- with ``axis_name=None`` the same module runs single-rank (all experts
  local) — the parity oracle for the sharded path *while capacity does
  not bind*.  When it binds, drops differ by design: the sharded path
  cuts each rank's local queue (capacity slots per rank per expert, the
  GShard dataflow), the local path cuts one global queue.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

import flax.linen as nn

__all__ = ["ExpertParallelMLP", "top1_dispatch"]


def top1_dispatch(logits32, capacity: int):
    """Switch-style top-1 routing with position-in-expert capacity.

    logits32: [tokens, experts] fp32.  Returns (dispatch [t, e, c] float,
    combine [t, e, c] float, aux_loss scalar).
    """
    t, e = logits32.shape
    probs = jax.nn.softmax(logits32, axis=-1)
    expert = jnp.argmax(probs, axis=-1)                    # [t]
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)  # [t, e]

    # position of each token within its chosen expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0        # [t, e]
    in_cap = (pos >= 0) & (pos < capacity)
    dispatch = onehot[..., None] * jax.nn.one_hot(
        jnp.maximum(pos, 0.0).astype(jnp.int32), capacity,
        dtype=jnp.float32) * in_cap[..., None]             # [t, e, c]
    gate = jnp.sum(probs * onehot, axis=-1)                # [t]
    combine = dispatch * gate[:, None, None]

    # Switch load-balancing loss: e * sum_e(frac_tokens_e * frac_prob_e)
    frac_tokens = jnp.mean(onehot, axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac_tokens * frac_probs)
    return dispatch, combine, aux


class ExpertParallelMLP(nn.Module):
    """Top-1 MoE FFN; experts sharded over ``axis_name`` when set.

    Input ``[tokens, hidden]`` (flatten batch/sequence first); returns
    ``(output [tokens, hidden], aux_loss)``.  Under shard_map each rank
    holds ``num_experts / ep`` experts and its own token shard.
    """

    num_experts: int
    hidden_size: int
    ffn_hidden_size: Optional[int] = None
    capacity_factor: float = 1.25
    axis_name: Optional[str] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x) -> Tuple[jax.Array, jax.Array]:
        t, h = x.shape
        ffn = self.ffn_hidden_size or 4 * h
        ep = (jax.lax.psum(1, self.axis_name)  # static; no axis_size in 0.4.x
              if self.axis_name is not None else 1)
        if self.num_experts % ep:
            raise ValueError(f"num_experts ({self.num_experts}) must divide "
                             f"by the ep axis size ({ep})")
        local_e = self.num_experts // ep
        # per-rank slots per expert: the GShard/Switch bound — each expert
        # receives ep * capacity = cf * t_global / num_experts slots total,
        # so per-expert compute and all_to_all bytes stay flat as ep grows
        capacity = max(1, int(self.capacity_factor * t / self.num_experts))

        router = self.param("router", nn.initializers.lecun_normal(),
                            (h, self.num_experts), jnp.float32)
        # local experts only: [local_e, h, ffn] / [local_e, ffn, h]
        w_in = self.param("w_in", nn.initializers.lecun_normal(),
                          (local_e, h, ffn), self.param_dtype)
        w_out = self.param("w_out", nn.initializers.lecun_normal(),
                           (local_e, ffn, h), self.param_dtype)

        logits = x.astype(jnp.float32) @ router
        dispatch, combine, aux = top1_dispatch(logits, capacity)

        # [t, e, c] x [t, h] -> [e, c, h]: the dispatch einsum
        expert_in = jnp.einsum("tec,th->ech", dispatch.astype(x.dtype), x)

        if self.axis_name is not None:
            # rows [e, ...] regroup so each rank receives ITS experts'
            # slots from every rank: [e, c, h] -> [local_e, ep*c, h]
            expert_in = expert_in.reshape(ep, local_e, capacity, h)
            expert_in = jax.lax.all_to_all(
                expert_in, self.axis_name, split_axis=0, concat_axis=0,
                tiled=False)
            expert_in = expert_in.transpose(1, 0, 2, 3).reshape(
                local_e, ep * capacity, h)
        else:
            expert_in = expert_in.reshape(local_e, capacity, h)

        # batched expert FFN: one [local_e] batched MXU matmul pair
        hmid = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", expert_in,
                                      w_in.astype(x.dtype)))
        expert_out = jnp.einsum("ecf,efh->ech", hmid, w_out.astype(x.dtype))

        if self.axis_name is not None:
            expert_out = expert_out.reshape(local_e, ep, capacity, h)
            expert_out = expert_out.transpose(1, 0, 2, 3)
            expert_out = jax.lax.all_to_all(
                expert_out, self.axis_name, split_axis=0, concat_axis=0,
                tiled=False)
            expert_out = expert_out.reshape(self.num_experts, capacity, h)
        else:
            expert_out = expert_out.reshape(self.num_experts, capacity, h)

        out = jnp.einsum("tec,ech->th", combine.astype(x.dtype), expert_out)
        return out, aux
