"""Logging-level helpers (apex/transformer/log_util.py parity)."""

from __future__ import annotations

import logging


def get_transformer_logger(name: str) -> logging.Logger:
    return logging.getLogger(f"apex_tpu.transformer.{name}")


def set_logging_level(verbosity) -> None:
    """Set the apex_tpu root logger level (log_util.set_logging_level)."""
    logging.getLogger("apex_tpu").setLevel(verbosity)
