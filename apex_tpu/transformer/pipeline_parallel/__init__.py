"""apex_tpu.transformer.pipeline_parallel — pipeline schedules over the pp axis.

Parity: apex/transformer/pipeline_parallel (SURVEY.md §2.3): p2p layer,
no-pipelining / 1F1B / interleaved schedules, microbatch utils, timers.
"""

from apex_tpu.transformer.pipeline_parallel.schedules import (
    PipelineStageSpec,
    accumulated_found_inf,
    build_model,
    forward_backward_no_pipelining,
    forward_backward_pipelining_1f1b,
    forward_backward_pipelining_1f1b_interleaved,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
)
from apex_tpu.transformer.pipeline_parallel.utils import (
    get_current_global_batch_size,
    get_micro_batch_size,
    get_num_microbatches,
    setup_microbatch_calculator,
    update_num_microbatches,
)

__all__ = [
    "PipelineStageSpec",
    "accumulated_found_inf",
    "build_model",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_1f1b",
    "forward_backward_pipelining_1f1b_interleaved",
    "forward_backward_pipelining_with_interleaving",
    "forward_backward_pipelining_without_interleaving",
    "get_forward_backward_func",
    "get_current_global_batch_size",
    "get_micro_batch_size",
    "get_num_microbatches",
    "setup_microbatch_calculator",
    "update_num_microbatches",
]
