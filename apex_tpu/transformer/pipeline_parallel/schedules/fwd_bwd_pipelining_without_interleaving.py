"""Pipelined fwd+bwd over the pp mesh axis (non-interleaved).

Parity target: ``forward_backward_pipelining_without_interleaving`` — the
1F1B schedule (fwd_bwd_pipelining_without_interleaving.py:241-520: warmup of
``pp_size - rank - 1`` forwards, steady-state ``send_forward_recv_backward``,
cooldown, deferred grad sync).

TPU-native design (SURVEY.md §7 "Pipeline parallelism in JAX"): the schedule
is ONE differentiable SPMD program — a ``lax.scan`` over
``num_microbatches + pp - 1`` ticks in which every stage applies its layer
block and passes activations to the next stage with ``ppermute``.  JAX's
scan/ppermute transposition then *derives* the backward pipeline: cotangents
flow through the inverse permutes in reverse tick order, which is exactly the
cooldown/steady/warmup structure the reference hand-schedules, with the
deferred grad sync falling out of grad accumulation over the scan.

Differences vs the CUDA implementation, by design:

- fwd and bwd are two sweeps (forward scan, transposed scan) rather than
  interleaved 1F1B ticks; numerics are identical and on TPU both sweeps keep
  every stage busy outside the same (pp-1)-tick bubbles.  Peak activation
  memory is ``num_microbatches`` wire tensors per stage (GPipe profile) —
  use ``checkpoint_stages=True`` (the reference's activation checkpointing,
  :mod:`..random`) to keep only the wire tensors and recompute inside
  stages; the interleaved schedule (smaller bubbles) is in
  :mod:`.fwd_bwd_pipelining_with_interleaving`.
- ``tensor_shape``/``dtype`` negotiation is unnecessary (static shapes).

Run inside ``shard_map`` over the ``pp`` axis (composable with tp/dp axes).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import PIPELINE_PARALLEL_AXIS
from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    PipelineStageSpec,
)

__all__ = ["forward_backward_pipelining_without_interleaving", "pipeline_loss"]


def _index_mb(batches: Any, i) -> Any:
    """Select microbatch i (clamped) from [n_micro, ...] leaves."""
    n = jax.tree.leaves(batches)[0].shape[0]
    idx = jnp.clip(i, 0, n - 1)
    return jax.tree.map(
        lambda l: jax.lax.dynamic_index_in_dim(l, idx, 0, keepdims=False),
        batches)


def pipeline_loss(
    spec: PipelineStageSpec,
    params: Any,
    batches: Any,
    *,
    axis_name: str = PIPELINE_PARALLEL_AXIS,
    checkpoint_stages: bool = True,
    loss_scale=None,
) -> jax.Array:
    """Mean microbatch loss of the full pipeline as one differentiable value.

    Per-rank value is *masked to the last stage* (zero elsewhere) so that
    ``jax.grad`` under shard_map's summed-loss convention optimizes exactly
    the true loss; use ``lax.psum`` on the result for reporting.
    """
    n_micro = jax.tree.leaves(batches)[0].shape[0]
    p = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)

    stage_fn = spec.stage_fn
    if checkpoint_stages:
        stage_fn = jax.checkpoint(stage_fn)

    # wire template from the first-stage adapter on microbatch 0
    wire0 = spec.first_fn(params, _index_mb(batches, 0))
    wire_zero = jax.tree.map(jnp.zeros_like, wire0)

    def tick(buf, t):
        # stage 0 injects microbatch t; other stages consume the wire
        inj = spec.first_fn(params, _index_mb(batches, t))
        x = jax.tree.map(
            lambda a, b: jnp.where(rank == 0, a, b), inj, buf)
        y = stage_fn(params, x)

        # last stage emits microbatch (t - (p-1))'s loss
        out_idx = t - (p - 1)
        mb = _index_mb(batches, out_idx)
        loss_t = spec.last_fn(params, y, mb)
        valid = jnp.logical_and(rank == p - 1, out_idx >= 0).astype(jnp.float32)
        loss_contrib = loss_t * valid

        perm = [(i, i + 1) for i in range(p - 1)]
        nxt = jax.tree.map(
            lambda l: jax.lax.ppermute(l, axis_name, perm), y)
        return nxt, loss_contrib

    total_ticks = n_micro + p - 1
    _, losses = jax.lax.scan(tick, wire_zero, jnp.arange(total_ticks))
    loss = jnp.sum(losses) / n_micro
    if loss_scale is not None:
        loss = loss * loss_scale
    return loss


def forward_backward_pipelining_without_interleaving(
    spec: PipelineStageSpec,
    params: Any,
    batches: Any,
    *,
    forward_only: bool = False,
    axis_name: str = PIPELINE_PARALLEL_AXIS,
    checkpoint_stages: bool = True,
    grad_scaler=None,
    scaler_state=None,
    # accepted for reference-API familiarity; shapes are static under jit
    tensor_shape=None,
    dtype=None,
    disable_autocast: bool = False,
    deallocate_pipeline_outputs: bool = False,
) -> Tuple[jax.Array, Optional[Any]]:
    """Returns (mean_loss_on_all_ranks, grads_or_None).

    ``spec``/``params``/``batches`` as in :func:`pipeline_loss`.  The loss
    returned is psum'd over the pp axis so every rank reports the true value;
    the grads are per-rank stage grads (the caller feeds them to its
    optimizer; dp sync composes outside, as in the reference's deferred
    ``custom_sync_context_handler``).  With ``grad_scaler`` the backward runs
    on the scaled loss and grads come back *scaled*.
    """
    del tensor_shape, dtype, disable_autocast, deallocate_pipeline_outputs
    scale = None
    if grad_scaler is not None:
        scale = scaler_state.scale if scaler_state is not None else None

    loss_fn = functools.partial(
        pipeline_loss, spec, batches=batches, axis_name=axis_name,
        checkpoint_stages=checkpoint_stages, loss_scale=scale)

    if forward_only:
        local = loss_fn(params)
        return jax.lax.psum(local, axis_name), None

    local_loss, grads = jax.value_and_grad(loss_fn)(params)
    loss = jax.lax.psum(local_loss, axis_name)
    if scale is not None:
        loss = loss / scale
    return loss, grads
