"""Interleaved (virtual-stage) pipelining over the pp mesh axis.

Parity target: ``_forward_backward_pipelining_with_interleaving``
(fwd_bwd_pipelining_with_interleaving.py:27-560): each rank owns
``vpp`` model chunks; global stage ``s`` lives on rank ``s % pp`` as chunk
``s // pp``, shrinking the pipeline bubble by ``vpp``.

TPU-native design: the circular pipeline as one differentiable SPMD scan.
Each tick, every rank applies ALL of its chunks (one per in-flight
microbatch wave, the steady-state of the interleaved schedule); the wire is
circular — ``ppermute`` with wrap-around, so a tensor leaving the last rank
re-enters rank 0 at the next chunk.  Chunk bookkeeping that the reference
does with virtual-rank state and host-side scheduling
(parallel_state.py:675-697) collapses into the per-chunk buffers carried
through the scan.  Backward is the scan/ppermute transpose, as in the
non-interleaved schedule.

Params for rank ``r`` are a pytree whose leaves are stacked over the chunk
dim: leaf shape [vpp, ...] (``build_model`` with virtual pp returns the list
to stack).  first/last adapters run at (chunk 0, rank 0) and
(chunk vpp-1, rank pp-1).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import PIPELINE_PARALLEL_AXIS
from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    PipelineStageSpec,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_without_interleaving import (
    _index_mb,
)

__all__ = ["forward_backward_pipelining_with_interleaving"]


def _chunk_params(params: Any, v: int) -> Any:
    return jax.tree.map(lambda l: l[v], params)


def forward_backward_pipelining_with_interleaving(
    spec: PipelineStageSpec,
    params: Any,  # leaves stacked [vpp, ...]
    batches: Any,
    *,
    num_model_chunks: int,
    forward_only: bool = False,
    axis_name: str = PIPELINE_PARALLEL_AXIS,
    checkpoint_stages: bool = True,
    grad_scaler=None,
    scaler_state=None,
) -> Tuple[jax.Array, Optional[Any]]:
    """Returns (mean_loss_on_all_ranks, grads_or_None); grads leaves are
    stacked [vpp, ...] like the params."""
    vpp = num_model_chunks
    n_micro = jax.tree.leaves(batches)[0].shape[0]
    p = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)

    scale = None
    if grad_scaler is not None and scaler_state is not None:
        scale = scaler_state.scale

    stage_fn = spec.stage_fn
    if checkpoint_stages:
        stage_fn = jax.checkpoint(stage_fn)

    wire0 = spec.first_fn(_chunk_params(params, 0), _index_mb(batches, 0))
    wire_zero = jax.tree.map(jnp.zeros_like, wire0)

    def loss_of(prms):
        def tick(carry, t):
            bufs = carry  # tuple of vpp wire buffers arriving at this rank
            new_bufs = list(bufs)
            loss_contrib = jnp.zeros((), jnp.float32)
            shifted_prev = None  # chunk v-1's circular shift output
            for v in range(vpp):
                x = bufs[v]
                if v == 0:
                    # (chunk 0, rank 0) injects microbatch t
                    inj = spec.first_fn(_chunk_params(prms, 0), _index_mb(batches, t))
                    x = jax.tree.map(
                        lambda a, b: jnp.where(rank == 0, a, b), inj, x)
                y = stage_fn(_chunk_params(prms, v), x)

                if v == vpp - 1:
                    # (chunk vpp-1, rank p-1) emits microbatch t - (vpp*p - 1)
                    out_idx = t - (vpp * p - 1)
                    mb = _index_mb(batches, out_idx)
                    loss_t = spec.last_fn(_chunk_params(prms, vpp - 1), y, mb)
                    valid = jnp.logical_and(rank == p - 1, out_idx >= 0)
                    loss_contrib = loss_t * valid.astype(jnp.float32)

                # circular shift: rank p-1's output wraps to rank 0 — where it
                # belongs to the NEXT chunk
                perm = [(i, (i + 1) % p) for i in range(p)]
                shifted = jax.tree.map(
                    lambda l: jax.lax.ppermute(l, axis_name, perm), y)
                # this rank's next input for chunk v: from rank-1 same chunk,
                # except rank 0, whose chunk-v input is chunk v-1's wrap
                if shifted_prev is None:
                    new_bufs[v] = shifted  # rank 0 slot is overwritten by inj
                else:
                    new_bufs[v] = jax.tree.map(
                        lambda w, s: jnp.where(rank == 0, w, s),
                        shifted_prev, shifted)
                shifted_prev = shifted
            return tuple(new_bufs), loss_contrib

        total_ticks = n_micro + vpp * p - 1
        init = tuple(jax.tree.map(jnp.zeros_like, wire_zero) for _ in range(vpp))
        _, losses = jax.lax.scan(tick, init, jnp.arange(total_ticks))
        loss = jnp.sum(losses) / n_micro
        if scale is not None:
            loss = loss * scale
        return loss

    if forward_only:
        return jax.lax.psum(loss_of(params), axis_name), None

    local_loss, grads = jax.value_and_grad(loss_of)(params)
    loss = jax.lax.psum(local_loss, axis_name)
    if scale is not None:
        loss = loss / scale
    return loss, grads
