"""Memory-bounded interleaved (virtual-stage) 1F1B pipeline schedule.

Parity target: ``_forward_backward_pipelining_with_interleaving``
(fwd_bwd_pipelining_with_interleaving.py:27-560) — the reference's
interleaved schedule is 1F1B-shaped: each rank keeps only the *in-flight*
microbatches alive per model chunk, so activation memory is O(vpp * pp),
flat in ``num_microbatches``.  The autodiff-of-scan schedule in
:mod:`.fwd_bwd_pipelining_with_interleaving` is numerics-identical but
stacks residuals per tick (GPipe memory); this module generalizes the
banked-input manual-vjp design of :mod:`.fwd_bwd_1f1b` to vpp chunks.

TPU design — the grouped timetable as one SPMD ``lax.scan``:

- Work is enumerated by a per-rank *virtual stream*: at tick ``t`` rank
  ``r`` forwards virtual unit ``kf = t - r`` and backwards virtual unit
  ``kb = t - (p-1-r) - (S-1)`` (``S = vpp*p`` global stages).  A unit
  ``k`` decodes as Megatron's grouped order — group ``k // (p*vpp)``,
  chunk ``(k // p) % vpp`` (reversed for backward), lane ``k % p`` —
  i.e. each rank runs ``p`` microbatches of chunk 0, then ``p`` of
  chunk 1, ...  (the reference's get_model_chunk_id timetable,
  fwd_bwd_pipelining_with_interleaving.py:118-133).
- With this timetable both wires are single *circular* ``ppermute``s:
  the forward wire moves rank r -> r+1 (rank p-1's chunk-v output wraps
  to rank 0, arriving exactly when rank 0 starts chunk v+1 of that
  microbatch), and the backward wire is its mirror image.  No per-chunk
  Python loop — each rank applies ONE dynamically-indexed chunk per tick,
  so program size is flat in vpp (the per-tick vpp unroll of the autodiff
  schedule grew linearly).
- The only per-microbatch state is a ``2*S - 1``-slot circular bank of
  stage *inputs* (a chunk-0 input is in flight for at most ``2*(S-1)``
  ticks).  Backward recomputes the stage from its banked input inside an
  in-tick ``jax.vjp`` — whole-stage activation checkpointing, exactly as
  :mod:`.fwd_bwd_1f1b` — so residuals never cross tick boundaries and
  peak memory is flat in ``num_microbatches`` (asserted by
  ``tests/test_pipeline_parallel.py`` via compiled memory analysis).

Numerics match :func:`forward_backward_pipelining_with_interleaving`.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import PIPELINE_PARALLEL_AXIS
from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    PipelineStageSpec,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_without_interleaving import (
    _index_mb,
)

__all__ = ["forward_backward_pipelining_1f1b_interleaved"]


def forward_backward_pipelining_1f1b_interleaved(
    spec: PipelineStageSpec,
    params: Any,  # leaves stacked [vpp, ...]
    batches: Any,
    *,
    num_model_chunks: int,
    forward_only: bool = False,
    axis_name: str = PIPELINE_PARALLEL_AXIS,
    checkpoint_stages: bool = True,
    grad_scaler=None,
    scaler_state=None,
) -> Tuple[jax.Array, Optional[Any]]:
    """Returns (mean loss on all ranks, grads stacked [vpp, ...] or None).

    Stage recompute is always on (the memory bound depends on it), as in
    :func:`~.fwd_bwd_1f1b.forward_backward_pipelining_1f1b`.
    """
    vpp = num_model_chunks
    if not checkpoint_stages:
        import warnings

        warnings.warn(
            "forward_backward_pipelining_1f1b_interleaved always recomputes "
            "stages from banked inputs (the O(vpp*pp) memory bound depends "
            "on it); checkpoint_stages=False is ignored.", stacklevel=2)
    if forward_only:
        # the undifferentiated forward scan saves no residuals, so the
        # existing interleaved forward is already memory-bounded
        from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_with_interleaving import (  # noqa: E501
            forward_backward_pipelining_with_interleaving,
        )

        return forward_backward_pipelining_with_interleaving(
            spec, params, batches, num_model_chunks=vpp, forward_only=True,
            axis_name=axis_name)

    n_micro = jax.tree.leaves(batches)[0].shape[0]
    p = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    S = vpp * p                      # global stages
    group = p * vpp                  # virtual units per microbatch group
    n_groups = -(-n_micro // p)      # ceil: last group may be partial
    K = n_groups * group             # virtual units per rank
    k_slots = 2 * S - 1              # max in-flight span of a banked input

    scale = jnp.float32(1.0)
    if grad_scaler is not None and scaler_state is not None:
        scale = scaler_state.scale

    def chunk(prm, v):
        return jax.tree.map(
            lambda l: jax.lax.dynamic_index_in_dim(l, v, 0, keepdims=False),
            prm)

    def full(prm, x_wire, mb, v):
        """Uniform per-unit program: inject -> chunk-v stage -> head/loss.

        Differentiating wrt (prm, x_wire) yields stage grads for chunk v,
        embedding grads where (rank 0, chunk 0) injected, and head grads
        where (rank p-1, chunk vpp-1) computed the loss — all at once, as
        in fwd_bwd_1f1b.full.
        """
        inj = spec.first_fn(chunk(prm, 0), mb)
        is_inj = jnp.logical_and(rank == 0, v == 0)
        x = jax.tree.map(lambda a, b: jnp.where(is_inj, a, b), inj, x_wire)
        y = spec.stage_fn(chunk(prm, v), x)
        loss = spec.last_fn(chunk(prm, vpp - 1), y, mb)
        return y, loss

    wire0 = spec.first_fn(chunk(params, 0), _index_mb(batches, 0))
    wire_zero = jax.tree.map(jnp.zeros_like, wire0)

    def buf_like(w):
        return jax.tree.map(
            lambda l: jnp.zeros((k_slots,) + l.shape, l.dtype), w)

    fwd_perm = [(i, (i + 1) % p) for i in range(p)]
    bwd_perm = [(i, (i - 1) % p) for i in range(p)]

    def decode_fwd(k):
        g, v, lane = k // group, (k // p) % vpp, k % p
        return g * p + lane, v           # (microbatch, chunk)

    def decode_bwd(k):
        g, lane = k // group, k % p
        v = vpp - 1 - (k // p) % vpp     # backward visits chunks in reverse
        kf = g * group + v * p + lane    # the unit's forward stream index
        return g * p + lane, v, kf

    carry0 = dict(
        fwd_wire=wire_zero,
        bwd_wire=wire_zero,
        xbuf=buf_like(wire_zero),
        grads=jax.tree.map(jnp.zeros_like, params),
        loss=jnp.float32(0.0),
    )

    def tick(c, t):
        # ---- forward unit ------------------------------------------------
        kf = t - rank
        mb_f, v_f = decode_fwd(jnp.maximum(kf, 0))
        active_f = jnp.logical_and(
            jnp.logical_and(kf >= 0, kf < K), mb_f < n_micro)

        y, loss_f = full(params, c["fwd_wire"], _index_mb(batches, mb_f),
                         v_f)
        slot_f = jnp.where(active_f, jnp.maximum(kf, 0) % k_slots, 0)
        xbuf = jax.tree.map(
            lambda buf, w: jax.lax.dynamic_update_index_in_dim(
                buf, jnp.where(
                    active_f,
                    w.astype(buf.dtype),
                    jax.lax.dynamic_index_in_dim(buf, slot_f, 0, False)),
                slot_f, 0),
            c["xbuf"], c["fwd_wire"])
        emits = jnp.logical_and(rank == p - 1, v_f == vpp - 1)
        loss = c["loss"] + jnp.where(
            jnp.logical_and(emits, active_f),
            loss_f.astype(jnp.float32), 0.0)

        # ---- backward unit: recompute chunk v_b from its banked input ---
        kb = t - (p - 1 - rank) - (S - 1)
        mb_b, v_b, kf_b = decode_bwd(jnp.maximum(kb, 0))
        active_b = jnp.logical_and(
            jnp.logical_and(kb >= 0, kb < K), mb_b < n_micro)

        slot_b = jnp.where(active_b, kf_b % k_slots, 0)
        x_saved = jax.tree.map(
            lambda buf, w: jax.lax.dynamic_index_in_dim(
                buf, slot_b, 0, False).astype(w.dtype),
            xbuf, c["fwd_wire"])
        mb_batch = _index_mb(batches, mb_b)
        _, vjp_fn = jax.vjp(
            lambda prm, x: full(prm, x, mb_batch, v_b), params, x_saved)
        seeds = jnp.logical_and(rank == p - 1, v_b == vpp - 1)
        use_wire = jnp.logical_and(active_b, jnp.logical_not(seeds))
        dy = jax.tree.map(
            lambda w: jnp.where(use_wire, w, jnp.zeros_like(w)),
            c["bwd_wire"])
        dloss = jnp.where(jnp.logical_and(seeds, active_b),
                          scale / n_micro, 0.0).astype(loss_f.dtype)
        dparams, dx = vjp_fn((dy, dloss))
        grads = jax.tree.map(
            lambda g, d: g + jnp.where(active_b, d, jnp.zeros_like(d)
                                       ).astype(g.dtype),
            c["grads"], dparams)

        # ---- both wires move one hop around the ring --------------------
        new_c = dict(
            fwd_wire=jax.tree.map(
                lambda l: jax.lax.ppermute(l, axis_name, fwd_perm), y),
            bwd_wire=jax.tree.map(
                lambda l: jax.lax.ppermute(l, axis_name, bwd_perm), dx),
            xbuf=xbuf,
            grads=grads,
            loss=loss,
        )
        return new_c, None

    # last backward: unit K-1 on rank 0 at tick (K-1) + (p-1) + (S-1)
    total_ticks = K + p + S - 2
    final, _ = jax.lax.scan(tick, carry0, jnp.arange(total_ticks))

    loss = jax.lax.psum(final["loss"], axis_name) / n_micro
    return loss, final["grads"]
