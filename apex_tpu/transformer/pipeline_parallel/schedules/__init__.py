"""Pipeline schedules (apex/transformer/pipeline_parallel/schedules parity)."""

from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    PipelineStageSpec,
    accumulated_found_inf,
    build_model,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_1f1b import (
    forward_backward_pipelining_1f1b,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_1f1b_interleaved import (
    forward_backward_pipelining_1f1b_interleaved,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_no_pipelining import (
    forward_backward_no_pipelining,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_with_interleaving import (
    forward_backward_pipelining_with_interleaving,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_without_interleaving import (
    forward_backward_pipelining_without_interleaving,
    pipeline_loss,
)

__all__ = [
    "PipelineStageSpec",
    "accumulated_found_inf",
    "build_model",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_1f1b",
    "forward_backward_pipelining_1f1b_interleaved",
    "forward_backward_pipelining_with_interleaving",
    "forward_backward_pipelining_without_interleaving",
    "pipeline_loss",
    "get_forward_backward_func",
]


def get_forward_backward_func(virtual_pipeline_model_parallel_size,
                              pipeline_model_parallel_size):
    """schedules/__init__.py get_forward_backward_func parity.

    Both pp choices are the true-1F1B schedules (activation memory flat in
    num_microbatches, like the reference's): non-interleaved pp gets
    ``forward_backward_pipelining_1f1b`` (O(pp) in-flight bound) and
    interleaved pp gets ``forward_backward_pipelining_1f1b_interleaved``
    (O(vpp*pp) bound).  The autodiff two-sweep variants remain available
    directly as ``forward_backward_pipelining_without_interleaving`` /
    ``forward_backward_pipelining_with_interleaving``.
    """
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None:
            return forward_backward_pipelining_1f1b_interleaved
        return forward_backward_pipelining_1f1b
    return forward_backward_no_pipelining
