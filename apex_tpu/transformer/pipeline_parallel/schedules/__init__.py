"""Pipeline schedules (apex/transformer/pipeline_parallel/schedules parity)."""

from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    PipelineStageSpec,
    build_model,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_1f1b import (
    forward_backward_pipelining_1f1b,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_no_pipelining import (
    forward_backward_no_pipelining,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_with_interleaving import (
    forward_backward_pipelining_with_interleaving,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_without_interleaving import (
    forward_backward_pipelining_without_interleaving,
    pipeline_loss,
)

__all__ = [
    "PipelineStageSpec",
    "build_model",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_1f1b",
    "forward_backward_pipelining_with_interleaving",
    "forward_backward_pipelining_without_interleaving",
    "pipeline_loss",
    "get_forward_backward_func",
]


def get_forward_backward_func(virtual_pipeline_model_parallel_size,
                              pipeline_model_parallel_size):
    """schedules/__init__.py get_forward_backward_func parity.

    The non-interleaved choice is the true-1F1B schedule (O(pp)-bounded
    activation memory, like the reference's); the autodiff two-sweep
    remains available directly as
    ``forward_backward_pipelining_without_interleaving``.
    """
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None:
            return forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_1f1b
    return forward_backward_no_pipelining
