"""True 1F1B pipeline schedule with O(pp)-bounded activation memory.

Parity target: ``forward_backward_pipelining_without_interleaving``
(fwd_bwd_pipelining_without_interleaving.py:241-520) — the point of 1F1B
over GPipe is the *memory bound*: each stage holds at most O(pp) in-flight
microbatches, not O(num_microbatches).

TPU design: JAX's autodiff-of-scan (the two-sweep schedule in
:mod:`.fwd_bwd_pipelining_without_interleaving`) stacks one residual per
tick, which reproduces GPipe's memory profile.  To get the 1F1B bound the
backward must be scheduled *manually*: this module runs one ``lax.scan``
over ``num_micro + 2*(pp-1)`` ticks whose carry is

- the forward wire (activations moving rank r -> r+1),
- the backward wire (cotangents moving rank r -> r-1),
- a circular buffer of the last ``2*pp - 1`` stage *inputs* (the only
  thing 1F1B-with-recompute keeps alive per in-flight microbatch),
- the gradient accumulator and loss accumulator.

Per tick, rank r forwards microbatch ``f = t - r`` and backwards
microbatch ``b = t - 2*(pp-1) + r`` (the classic 1F1B timetable: the last
stage backwards a microbatch the same tick it forwards it).  The backward
is an in-tick ``jax.vjp`` over the stage, recomputing the stage forward
from the saved input — i.e. the reference's activation-checkpointing mode
(``jax.checkpoint`` granularity = whole stage); residuals never cross tick
boundaries, so the scan carries no stacked activations.  Because every
saved buffer lives in the fixed-size carry, peak memory is flat in
``num_microbatches`` — asserted by ``tests/test_pipeline_parallel.py``
via XLA's compiled memory analysis.

The partial-activation-checkpoint window (reference :351-361) trades this
recompute for memory on a prefix of microbatches; with whole-stage
recompute the equivalent knob is per-layer ``jax.checkpoint`` policies
*inside* ``stage_fn`` (e.g. ``checkpoint_dots``) — finer-grained than the
reference's window and compiler-schedulable.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import PIPELINE_PARALLEL_AXIS
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_without_interleaving import (
    _index_mb,
    pipeline_loss,
)

__all__ = ["forward_backward_pipelining_1f1b"]


def forward_backward_pipelining_1f1b(
    spec,
    params: Any,
    batches: Any,
    *,
    forward_only: bool = False,
    axis_name: str = PIPELINE_PARALLEL_AXIS,
    grad_scaler=None,
    scaler_state=None,
    # stage recompute is ALWAYS on here — the O(pp) memory bound depends on
    # it (backwards recompute from banked inputs); checkpoint_stages=False
    # is accepted for two-sweep API compat but cannot disable it.  Use the
    # two-sweep schedule for no-recompute, or jax.checkpoint policies
    # inside stage_fn for selective remat.
    checkpoint_stages: bool = True,
    # shape negotiation is meaningless under jit (static shapes):
    tensor_shape=None,
    dtype=None,
    disable_autocast: bool = False,
    deallocate_pipeline_outputs: bool = False,
) -> Tuple[jax.Array, Optional[Any]]:
    """Returns (mean loss on all ranks, per-rank stage grads), matching
    :func:`forward_backward_pipelining_without_interleaving` numerics with
    a 1F1B memory profile.  Grads come back scaled when a scaler is given.
    """
    if not checkpoint_stages:
        import warnings

        warnings.warn(
            "forward_backward_pipelining_1f1b always recomputes stages from "
            "banked inputs (the O(pp) memory bound depends on it); "
            "checkpoint_stages=False is ignored.  Use the two-sweep "
            "forward_backward_pipelining_without_interleaving schedule for "
            "a no-recompute backward.", stacklevel=2)
    del checkpoint_stages, tensor_shape, dtype, disable_autocast
    del deallocate_pipeline_outputs
    if forward_only:
        # an undifferentiated forward scan saves no residuals, so the
        # two-sweep loss is already memory-bounded here
        local = pipeline_loss(spec, params, batches, axis_name=axis_name)
        return jax.lax.psum(local, axis_name), None
    n_micro = jax.tree.leaves(batches)[0].shape[0]
    p = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    scale = jnp.float32(1.0)
    if grad_scaler is not None and scaler_state is not None:
        scale = scaler_state.scale

    def full(prm, x_wire, mb):
        """Uniform per-rank stage program: inject -> stage -> head/loss.

        Differentiating this one function wrt (prm, x_wire) yields every
        backward path at once: stage grads everywhere, embedding
        (first_fn) grads where rank 0, head/loss grads where last rank.
        """
        inj = spec.first_fn(prm, mb)
        x = jax.tree.map(lambda a, b: jnp.where(rank == 0, a, b), inj, x_wire)
        y = spec.stage_fn(prm, x)
        loss = spec.last_fn(prm, y, mb)
        return y, loss

    # wire template + fixed-size in-flight input buffer (2p-1 slots: a
    # microbatch is in flight at stage r for 2*(p-1-r) ticks, < 2p-1)
    wire0 = spec.first_fn(params, _index_mb(batches, 0))
    wire_zero = jax.tree.map(jnp.zeros_like, wire0)
    k_slots = 2 * p - 1

    def buf_like(w):
        return jax.tree.map(
            lambda l: jnp.zeros((k_slots,) + l.shape, l.dtype), w)

    fwd_perm = [(i, i + 1) for i in range(p - 1)]
    bwd_perm = [(i + 1, i) for i in range(p - 1)]

    carry0 = dict(
        fwd_wire=wire_zero,
        bwd_wire=wire_zero,
        xbuf=buf_like(wire_zero),
        grads=jax.tree.map(jnp.zeros_like, params),
        loss=jnp.float32(0.0),
    )

    def tick(c, t):
        f = t - rank                          # microbatch to forward
        b = t - 2 * (p - 1) + rank            # microbatch to backward
        active_f = jnp.logical_and(f >= 0, f < n_micro)
        active_b = jnp.logical_and(b >= 0, b < n_micro)

        # ---- forward: run the stage, bank the wire input, count the loss
        y, loss_f = full(params, c["fwd_wire"], _index_mb(batches, f))
        slot_f = jnp.where(active_f, f % k_slots, 0)
        xbuf = jax.tree.map(
            lambda buf, w: jax.lax.dynamic_update_index_in_dim(
                buf, jnp.where(
                    active_f,
                    w.astype(buf.dtype),
                    jax.lax.dynamic_index_in_dim(buf, slot_f, 0, False)),
                slot_f, 0),
            c["xbuf"], c["fwd_wire"])
        loss = c["loss"] + jnp.where(
            jnp.logical_and(rank == p - 1, active_f),
            loss_f.astype(jnp.float32), 0.0)

        # ---- backward: recompute mb b's stage from its banked input and
        # pull cotangents through it (whole-stage remat, in-tick residuals)
        slot_b = jnp.where(active_b, b % k_slots, 0)
        x_saved = jax.tree.map(
            lambda buf, w: jax.lax.dynamic_index_in_dim(
                buf, slot_b, 0, False).astype(w.dtype),
            xbuf, c["fwd_wire"])
        mb_b = _index_mb(batches, b)
        _, vjp_fn = jax.vjp(lambda prm, x: full(prm, x, mb_b), params,
                            x_saved)
        # cotangents (dtypes must match the primal outputs exactly):
        # non-last ranks pull the wire cotangent, the last rank seeds the
        # loss cotangent; both masked off for not-in-flight microbatches
        use_wire = jnp.logical_and(active_b, rank != p - 1)
        dy = jax.tree.map(
            lambda w: jnp.where(use_wire, w, jnp.zeros_like(w)),
            c["bwd_wire"])
        dloss = jnp.where(jnp.logical_and(rank == p - 1, active_b),
                          scale / n_micro, 0.0).astype(loss_f.dtype)
        dparams, dx = vjp_fn((dy, dloss))
        grads = jax.tree.map(
            lambda g, d: g + jnp.where(active_b, d, jnp.zeros_like(d)
                                       ).astype(g.dtype),
            c["grads"], dparams)

        # ---- move both wires one hop (forward up, cotangents down)
        new_c = dict(
            fwd_wire=jax.tree.map(
                lambda l: jax.lax.ppermute(l, axis_name, fwd_perm), y),
            bwd_wire=jax.tree.map(
                lambda l: jax.lax.ppermute(l, axis_name, bwd_perm), dx),
            xbuf=xbuf,
            grads=grads,
            loss=loss,
        )
        return new_c, None

    total_ticks = n_micro + 2 * (p - 1)
    final, _ = jax.lax.scan(tick, carry0, jnp.arange(total_ticks))

    loss = jax.lax.psum(final["loss"], axis_name) / n_micro
    return loss, final["grads"]
