"""No-pipelining schedule: sequential microbatches with deferred grad sync.

Parity target: ``forward_backward_no_pipelining``
(fwd_bwd_no_pipelining.py:23): run fwd+bwd per microbatch under ``no_sync``
(grad allreduce deferred), syncing only on the last microbatch.

TPU-native: grads are accumulated functionally over a ``lax.scan`` of
microbatches; the data-parallel reduction happens once on the summed grads
(either by the caller's pjit sharding or the explicit ``ddp.sync``), which is
exactly the deferred-sync semantics.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    accumulated_found_inf,
)

__all__ = ["forward_backward_no_pipelining"]


def forward_backward_no_pipelining(
    loss_fn: Callable[[Any, Any], jax.Array],
    params: Any,
    microbatches: Any,
    *,
    forward_only: bool = False,
    grad_scaler=None,
    scaler_state=None,
    with_found_inf: bool = False,
) -> "Tuple[jax.Array, Optional[Any]] | Tuple[jax.Array, Optional[Any], jax.Array]":
    """Returns (mean_loss, summed_grads or None).

    ``loss_fn(params, microbatch) -> scalar``; ``microbatches`` is a pytree
    whose leaves have a leading [num_microbatches, ...] dim.  When a
    ``grad_scaler`` is given, each microbatch loss is scaled before backward
    (common.py:253-420 semantics) and the returned grads are still *scaled*
    (unscale with the scaler, as the reference's trainer does).

    ``with_found_inf=True`` additionally returns the step-level overflow
    flag: ``(mean_loss, grads, found_inf)``.  Skip semantics are
    all-or-nothing at step granularity — one overflowing microbatch marks
    the whole accumulated step skipped.  The flag is ONE check on the
    summed grads, which is exactly the OR over per-microbatch checks
    because non-finite values are absorbing under the scan's summation
    (see :func:`..schedules.common.accumulated_found_inf`); the resilience
    guarded step is the consumer side.
    """
    n_micro = jax.tree.leaves(microbatches)[0].shape[0]

    def scaled_loss(p, mb):
        loss = loss_fn(p, mb)
        if grad_scaler is not None:
            return grad_scaler.scale_loss(loss, scaler_state), loss
        return loss, loss

    if forward_only:
        def fwd_body(acc, mb):
            _, loss = scaled_loss(params, mb)
            return acc + loss, None

        total, _ = jax.lax.scan(fwd_body, jnp.zeros((), jnp.float32), microbatches)
        if with_found_inf:
            return total / n_micro, None, jnp.zeros((), jnp.bool_)
        return total / n_micro, None

    grad_fn = jax.grad(scaled_loss, has_aux=True)

    def body(carry, mb):
        loss_acc, grad_acc = carry
        g, loss = grad_fn(params, mb)
        grad_acc = jax.tree.map(jnp.add, grad_acc, g)
        return (loss_acc + loss, grad_acc), None

    zero_grads = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
    (total_loss, grads), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), zero_grads), microbatches)
    if with_found_inf:
        return total_loss / n_micro, grads, accumulated_found_inf(grads)
    return total_loss / n_micro, grads
