"""Shared schedule machinery: build_model, stage specs, loss plumbing.

Parity target: ``apex.transformer.pipeline_parallel.schedules.common``
(common.py:30-420): ``build_model`` (virtual-pp returns a list of model
chunks), ``forward_step``/``backward_step``, ``custom_backward``.

TPU-native design: a pipeline-parallel model is described by a
:class:`PipelineStageSpec` — one jittable ``stage_fn(params, x, extras)``
applied by every pp rank to its own parameter shard, plus first/last-stage
adapters.  Because every rank runs the same SPMD program, per-rank structural
differences (embedding on stage 0, LM head on stage N-1) are expressed as
``lax.cond`` on the stage index or — preferably — folded into ``stage_fn``
with stage-sharded parameters (zero-size where unused).  The schedules
differentiate straight through the whole pipeline (scan + ppermute), so
``backward_step``/``custom_backward`` (manual vjp bookkeeping, common.py:219,
325-420) have no analog: JAX's scan transpose IS the backward schedule.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = ["PipelineStageSpec", "accumulated_found_inf", "build_model",
           "listify_model"]


@dataclasses.dataclass(frozen=True)
class PipelineStageSpec:
    """One pipeline stage as a pure function.

    - ``stage_fn(params, x)``: the per-rank transform applied at every stage
      (e.g. a block of transformer layers).  ``x`` and the return value must
      have identical shape/dtype (the inter-stage wire format).
    - ``first_fn(params, batch)``: stage-0 input adapter (embedding); maps the
      microbatch to the wire format.  Identity on other ranks' data is fine —
      it only runs meaningfully where ``stage == 0``.
    - ``last_fn(params, y, batch)``: final-stage head+loss; returns a scalar
      loss for one microbatch.
    """

    stage_fn: Callable[[Any, Any], Any]
    first_fn: Optional[Callable[[Any, Any], Any]] = None
    last_fn: Optional[Callable[[Any, Any, Any], Any]] = None


def listify_model(model) -> List[Any]:
    """common.py listify_model parity."""
    return list(model) if isinstance(model, (list, tuple)) else [model]


def build_model(
    model_provider_func: Callable,
    wrap_with_ddp: bool = True,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    **kwargs,
) -> List[Any]:
    """Instantiate one model chunk per virtual pipeline stage
    (common.py:30-151).

    With virtual pp the provider is called vpp times with
    ``pre_process``/``post_process`` flags describing whether the chunk
    contains the input embedding / the head, exactly like the reference.
    ``wrap_with_ddp`` has no wrapper to apply (grad sync is a sharding
    property on TPU) and is accepted for parity.
    """
    from apex_tpu.transformer import parallel_state

    if (parallel_state.get_pipeline_model_parallel_world_size() > 1
            and virtual_pipeline_model_parallel_size is not None):
        models = []
        for i in range(virtual_pipeline_model_parallel_size):
            parallel_state.set_virtual_pipeline_model_parallel_rank(i)
            pre = i == 0
            post = i == virtual_pipeline_model_parallel_size - 1
            models.append(model_provider_func(
                pre_process=pre, post_process=post, **kwargs))
        return models
    return [model_provider_func(pre_process=True, post_process=True, **kwargs)]


def _masked_mean(values: jax.Array, mask: jax.Array) -> jax.Array:
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.sum(values * mask) / denom


def accumulated_found_inf(grads: Any, *, axis_name: Optional[str] = None) -> jax.Array:
    """Step-level overflow flag for microbatch-accumulated gradients.

    Skip semantics must be *consistent across microbatches*: either every
    microbatch of a step contributes to the update, or none does — a
    per-microbatch skip would silently change the effective batch and the
    grad-accumulation denominator.  All schedules here accumulate grads by
    summation, and non-finite values are absorbing under IEEE addition
    (``inf + x = inf``, ``inf - inf = nan``, ``nan + x = nan``), so ONE
    overflow check on the summed grads is exactly the OR over microbatch
    checks — the same all-or-nothing contract the reference enforces by
    sharing one ``noop_flag`` buffer across the whole accumulation window.

    For pipeline schedules the per-rank grads see only this rank's stage
    params; pass ``axis_name`` to OR the flag across pipeline ranks so
    every rank skips (or applies) the same step.
    """
    from apex_tpu.multi_tensor_apply import _nonfinite

    flag = _nonfinite(grads)
    if axis_name is not None:
        flag = jax.lax.pmax(flag.astype(jnp.int32), axis_name) > 0
    return flag
