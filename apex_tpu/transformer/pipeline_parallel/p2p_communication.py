"""Pipeline p2p communication over the pp mesh axis.

Parity target: ``apex.transformer.pipeline_parallel.p2p_communication``
(p2p_communication.py:34-690): ``_communicate`` + the nine public
send/recv combinators built on ``batch_isend_irecv``.

TPU-native design (SURVEY.md §7): point-to-point sends between pipeline
neighbors are ``jax.lax.ppermute`` shifts over the ``pp`` axis — deadlock-free
by construction (one collective, not paired isend/irecv), riding ICI.  In
SPMD there is no separate "send" and "recv": a shift both sends this rank's
tensor and delivers the neighbor's, so each reference combinator maps to a
shift direction:

- send_forward / recv_forward           → :func:`shift_forward`
- send_backward / recv_backward         → :func:`shift_backward`
- send_forward_recv_backward            → shift_forward + shift_backward
  (XLA schedules both permutes concurrently on opposite ICI directions)
- shape negotiation (`tensor_shape`, p2p_communication.py:168-232) is
  unnecessary: shapes are static under jit.
- ``scatter_gather_tensors_in_pipeline`` (chunking over tp before the wire)
  is XLA's job; accepted and ignored where it appears in signatures.

The reference's fp32-residual dtype rule (`dtype_` override for fp32 residual
connections) maps to passing the tensor in whatever dtype it has — ppermute
is dtype-preserving.
"""

from __future__ import annotations

from typing import Any, Optional

import jax

from apex_tpu.transformer.parallel_state import PIPELINE_PARALLEL_AXIS


def _pp_size(axis_name):
    return jax.lax.psum(1, axis_name)


def shift_forward(x: Any, axis_name: str = PIPELINE_PARALLEL_AXIS,
                  wrap: bool = False) -> Any:
    """Deliver each stage's tensor to the *next* stage (stage 0 receives
    zeros, or the last stage's tensor when ``wrap`` — the interleaved
    schedule's circular edge)."""
    n = _pp_size(axis_name)

    def shift(leaf):
        perm = [(i, (i + 1) % n) for i in range(n if wrap else n - 1)]
        return jax.lax.ppermute(leaf, axis_name, perm)

    return jax.tree.map(shift, x)


def shift_backward(x: Any, axis_name: str = PIPELINE_PARALLEL_AXIS,
                   wrap: bool = False) -> Any:
    """Deliver each stage's tensor to the *previous* stage."""
    n = _pp_size(axis_name)

    def shift(leaf):
        perm = [((i + 1) % n, i) for i in range(n if wrap else n - 1)]
        return jax.lax.ppermute(leaf, axis_name, perm)

    return jax.tree.map(shift, x)


# --- reference-named combinators (p2p_communication.py:385-690) ------------


def send_forward_recv_forward(output, axis_name: str = PIPELINE_PARALLEL_AXIS):
    """This stage's output goes to the next stage; returns what the previous
    stage sent here."""
    return shift_forward(output, axis_name)


def send_backward_recv_backward(input_grad, axis_name: str = PIPELINE_PARALLEL_AXIS):
    return shift_backward(input_grad, axis_name)


def send_forward(output, axis_name: str = PIPELINE_PARALLEL_AXIS):
    return shift_forward(output, axis_name)


def recv_forward(output, axis_name: str = PIPELINE_PARALLEL_AXIS):
    return shift_forward(output, axis_name)


def send_backward(grad, axis_name: str = PIPELINE_PARALLEL_AXIS):
    return shift_backward(grad, axis_name)


def recv_backward(grad, axis_name: str = PIPELINE_PARALLEL_AXIS):
    return shift_backward(grad, axis_name)


def send_forward_recv_backward(output, grad, axis_name: str = PIPELINE_PARALLEL_AXIS):
    """The 1F1B steady-state exchange: activations flow down while grads flow
    up, as two opposite-direction permutes XLA runs concurrently."""
    return shift_forward(output, axis_name), shift_backward(grad, axis_name)


def send_backward_recv_forward(grad, output, axis_name: str = PIPELINE_PARALLEL_AXIS):
    return shift_backward(grad, axis_name), shift_forward(output, axis_name)
