"""Wall timers with optional device sync + TensorBoard export.

Parity: ``apex.transformer.pipeline_parallel._timers`` (_timers.py:6-79):
named timers with ``start/stop/elapsed/log/write``; the reference's
``torch.cuda.synchronize`` option maps to ``jax.block_until_ready`` on a
token (or the caller's outputs) — on TPU, dispatch is async exactly like CUDA.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax


class _Timer:
    def __init__(self, name: str):
        self.name_ = name
        self.elapsed_ = 0.0
        self.started_ = False
        self.start_time = time.time()

    def start(self, barrier: bool = False):
        if self.started_:
            raise AssertionError("timer has already been started")
        if barrier:
            jax.effects_barrier()
        self.start_time = time.time()
        self.started_ = True

    def stop(self, barrier: bool = False):
        if not self.started_:
            raise AssertionError("timer is not started")
        if barrier:
            jax.effects_barrier()
        self.elapsed_ += time.time() - self.start_time
        self.started_ = False

    def reset(self):
        self.elapsed_ = 0.0
        self.started_ = False

    def elapsed(self, reset: bool = True) -> float:
        started = self.started_
        if started:
            self.stop()
        e = self.elapsed_
        if reset:
            self.reset()
        if started:
            self.start()
        return e


class Timers:
    """Group of named timers (_timers.py Timers)."""

    def __init__(self):
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def write(self, names: List[str], writer, iteration: int,
              normalizer: float = 1.0, reset: bool = False):
        """TensorBoard export (_timers.py:52-64); ``writer`` is any object
        with ``add_scalar(tag, value, step)``."""
        if normalizer <= 0.0:
            raise AssertionError
        for name in names:
            value = self.timers[name].elapsed(reset=reset) / normalizer
            writer.add_scalar(f"{name}-time", value, iteration)

    def log(self, names: List[str], normalizer: float = 1.0,
            reset: bool = True) -> str:
        if normalizer <= 0.0:
            raise AssertionError
        parts = ["time (ms)"]
        for name in names:
            t = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
            parts.append(f" | {name}: {t:.2f}")
        line = "".join(parts)
        import logging

        logging.getLogger(__name__).info(line)
        return line
