"""Wall timers with optional device sync + TensorBoard export.

Parity surface: ``apex.transformer.pipeline_parallel._timers`` (named timers
with ``start/stop/elapsed/log/write``).  The reference's
``torch.cuda.synchronize`` option maps to ``jax.effects_barrier`` — TPU
dispatch is async exactly like CUDA, so an unsynchronized stop() only times
enqueue cost.

Design (TPU-idiomatic, not a port): a timer is a tiny accumulator with a
``timing()`` contextmanager as the preferred interface; ``start``/``stop``
remain for schedule code that brackets non-lexical regions.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Dict, Iterator, List

import jax

logger = logging.getLogger(__name__)


class _Timer:
    """Accumulating wall timer for one named region."""

    __slots__ = ("name", "total", "running", "_t0")

    def __init__(self, name: str):
        self.name = name
        self.total = 0.0          # accumulated seconds across start/stop pairs
        self.running = False
        self._t0 = 0.0

    def start(self, barrier: bool = False) -> None:
        if self.running:
            raise RuntimeError(f"timer {self.name!r} is already running")
        if barrier:
            jax.effects_barrier()
        # _t0 before running: a concurrent snapshot() that observes
        # running=True must never pair it with the PREVIOUS region's t0
        self._t0 = time.perf_counter()
        self.running = True

    def stop(self, barrier: bool = False) -> None:
        if not self.running:
            raise RuntimeError(f"timer {self.name!r} was never started")
        if barrier:
            jax.effects_barrier()
        # running=False before total+=: a concurrent snapshot() that
        # already read total must not ALSO add the in-flight span
        elapsed = time.perf_counter() - self._t0
        self.running = False
        self.total += elapsed

    @contextlib.contextmanager
    def timing(self, barrier: bool = False) -> Iterator["_Timer"]:
        """``with timers('fwd').timing(): ...`` — the idiomatic bracket."""
        self.start(barrier=barrier)
        try:
            yield self
        finally:
            self.stop(barrier=barrier)

    def reset(self) -> None:
        self.total = 0.0
        self.running = False

    def elapsed(self, reset: bool = True) -> float:
        """Accumulated seconds; pauses/resumes a running timer around the read."""
        was_running = self.running
        if was_running:
            self.stop()
        seconds = self.total
        if reset:
            self.reset()
        if was_running:
            self.start()
        return seconds


class Timers:
    """Registry of named timers; calling it creates on first use."""

    def __init__(self):
        self._timers: Dict[str, _Timer] = {}

    @property
    def timers(self) -> Dict[str, "_Timer"]:
        """Read-only view of the registry (reference surface: ported
        Megatron/apex scripts poke ``timers.timers`` directly)."""
        return self._timers

    def __call__(self, name: str) -> _Timer:
        try:
            return self._timers[name]
        except KeyError:
            t = self._timers[name] = _Timer(name)
            return t

    def snapshot(self) -> Dict[str, dict]:
        """Point-in-time, NON-destructive view of every timer.

        ``{name: {"total_s": accumulated+in-flight seconds, "running":
        bool}}``.  Unlike :meth:`_Timer.elapsed` this mutates nothing —
        it is the read the step watchdog's monitor thread takes while
        the main thread is stuck *inside* a timed region, so a running
        timer's in-flight seconds are included and values may be one
        assignment stale (harmless for diagnostics).
        """
        now = time.perf_counter()
        out: Dict[str, dict] = {}
        for name, t in list(self._timers.items()):
            # read total BEFORE running: paired with stop()'s
            # running=False-then-total+= ordering, a racing stop can make
            # this view one span stale but never double-counted
            total = t.total
            running = t.running
            if running:
                total += now - t._t0
            out[name] = {"total_s": round(total, 6), "running": running}
        return out

    def publish_metrics(self) -> Dict[str, dict]:
        """Export the (non-destructive) :meth:`snapshot` totals as the
        ``apex_timer_seconds{region=...}`` gauge series in the default
        observability registry — every timed region becomes a scrapeable
        cumulative-seconds gauge, the per-region analog of the step-time
        histogram.  Returns the snapshot it published.  The import is
        lazy so this module stays importable without the obs layer."""
        from apex_tpu.obs.bridge import TIMER_SECONDS

        snap = self.snapshot()
        for name, rec in snap.items():
            TIMER_SECONDS.set(rec["total_s"], region=name)
        return snap

    def write(self, names: List[str], writer, iteration: int,
              normalizer: float = 1.0, reset: bool = False) -> None:
        """Export per-name mean seconds to any ``add_scalar(tag, val, step)``
        sink (TensorBoard SummaryWriter shaped)."""
        if normalizer <= 0.0:
            raise ValueError(f"normalizer must be positive, got {normalizer}")
        for name in names:
            seconds = self._timers[name].elapsed(reset=reset) / normalizer
            writer.add_scalar(f"{name}-time", seconds, iteration)

    def log(self, names: List[str], normalizer: float = 1.0,
            reset: bool = True) -> str:
        if normalizer <= 0.0:
            raise ValueError(f"normalizer must be positive, got {normalizer}")
        cells = [
            f"{name}: {self._timers[name].elapsed(reset=reset) * 1e3 / normalizer:.2f}"
            for name in names
        ]
        line = "time (ms) | " + " | ".join(cells)
        logger.info(line)
        return line
