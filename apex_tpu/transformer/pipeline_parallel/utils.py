"""Pipeline-parallel utilities (apex/transformer/pipeline_parallel/utils.py).

Covers: microbatch-calculator singleton (utils.py:58-157), rank-0 printing
(:159-177), mask/position-id builder (:200-250), loss averaging across dp,
param-norm and memory reporting, ``unwrap_model``.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp

from apex_tpu._logging import get_logger
from apex_tpu.transformer.microbatches import build_num_microbatches_calculator
from apex_tpu.transformer.parallel_state import DATA_PARALLEL_AXIS

_GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
_GLOBAL_AUTORESUME = None
_GLOBAL_TIMERS = None


def setup_microbatch_calculator(rank: int, rampup_batch_size: Optional[List[int]],
                                global_batch_size: int, micro_batch_size: int,
                                data_parallel_size: int) -> None:
    """utils.py:58-104 parity (global singleton with ensure-none check)."""
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    if _GLOBAL_NUM_MICROBATCHES_CALCULATOR is not None:
        raise AssertionError("num microbatches calculator is already initialized.")
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size)


def _reconfigure_microbatch_calculator(rank, rampup_batch_size,
                                       global_batch_size, micro_batch_size,
                                       data_parallel_size) -> None:
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size)


def destroy_microbatch_calculator() -> None:
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None


def get_micro_batch_size():
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.micro_batch_size


def get_num_microbatches():
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get()


def get_current_global_batch_size():
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get_current_global_batch_size()


def update_num_microbatches(consumed_samples, consistency_check=True):
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR.update(consumed_samples, consistency_check)


def get_autoresume():
    """ADLR autoresume hook (utils.py:142); no TPU-cluster analog, returns
    the registered object or None."""
    return _GLOBAL_AUTORESUME


def get_timers():
    global _GLOBAL_TIMERS
    if _GLOBAL_TIMERS is None:
        from apex_tpu.transformer.pipeline_parallel._timers import Timers

        _GLOBAL_TIMERS = Timers()
    return _GLOBAL_TIMERS


def print_rank_0(message: str) -> None:
    """Only host process 0 prints (utils.py:159)."""
    if jax.process_index() == 0:
        print(message, flush=True)


def is_last_rank() -> bool:
    return jax.process_index() == jax.process_count() - 1


def print_rank_last(message: str) -> None:
    if is_last_rank():
        print(message, flush=True)


def listify_model(model: Any) -> List[Any]:
    return list(model) if isinstance(model, (list, tuple)) else [model]


def unwrap_model(model, module_instances=None):
    """utils.py unwrap_model parity: no wrapper types exist here, identity
    per chunk."""
    return_list = True
    if not isinstance(model, list):
        model = [model]
        return_list = False
    unwrapped = list(model)
    if not return_list:
        return unwrapped[0]
    return unwrapped


def get_ltor_masks_and_position_ids(data, eod_token=None,
                                    reset_position_ids: bool = False,
                                    reset_attention_mask: bool = False,
                                    eod_mask_loss: bool = False):
    """Left-to-right masks + position ids (utils.py:200-250).

    Returns (attention_mask [b,1,s,s] bool where True = MASKED OUT,
    loss_mask [b,s] fp32, position_ids [b,s] int32).  The per-document reset
    options require host-side loops in the reference; here they are computed
    vectorized so the whole builder stays jittable.
    """
    b, s = data.shape
    # causal: True above the diagonal = masked
    att = jnp.triu(jnp.ones((s, s), jnp.bool_), k=1)
    att = jnp.broadcast_to(att, (b, 1, s, s))

    loss_mask = jnp.ones((b, s), jnp.float32)
    if eod_mask_loss and eod_token is not None:
        loss_mask = jnp.where(data == eod_token, 0.0, loss_mask)

    position_ids = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    if eod_token is not None and reset_position_ids:
        # position restarts after each EOD: pos[i] = i - (index of last EOD ≤ i)
        is_eod = (data == eod_token).astype(jnp.int32)
        # last EOD position before or at i (exclusive of i itself)
        eod_before = jnp.concatenate(
            [jnp.zeros((b, 1), jnp.int32), is_eod[:, :-1]], axis=1)
        seg_start = jax.lax.cummax(
            jnp.where(eod_before == 1,
                      jnp.arange(s, dtype=jnp.int32)[None, :], 0), axis=1)
        position_ids = jnp.arange(s, dtype=jnp.int32)[None, :] - seg_start
    if eod_token is not None and reset_attention_mask:
        is_eod = (data == eod_token).astype(jnp.int32)
        eod_before = jnp.concatenate(
            [jnp.zeros((b, 1), jnp.int32), is_eod[:, :-1]], axis=1)
        seg_id = jnp.cumsum(eod_before, axis=1)  # [b, s]
        same_seg = seg_id[:, :, None] == seg_id[:, None, :]
        att = jnp.logical_or(att, jnp.logical_not(same_seg)[:, None, :, :])
    return att, loss_mask, position_ids


def average_losses_across_data_parallel_group(losses,
                                              axis_name: str = DATA_PARALLEL_AXIS):
    """utils.py:253 parity; call inside shard_map/pmap over dp."""
    stacked = jnp.stack([jnp.asarray(l, jnp.float32) for l in losses])
    return jax.lax.pmean(stacked, axis_name)


def calc_params_l2_norm(params, across_model_parallel: bool = True):
    """Global fp32 L2 norm of params (utils.py calc_params_l2_norm)."""
    from apex_tpu.utils.tree_math import tree_l2norm

    return tree_l2norm(params)


def report_memory(name: str) -> str:
    """utils.py:253 report_memory — TPU HBM stats via device memory stats.

    Backends without memory stats (CPU returns ``None``; some plugins
    raise) degrade to zeros — but say so at debug level instead of
    silently reporting an empty host as healthy.
    """
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
    except (RuntimeError, NotImplementedError, IndexError) as e:
        # RuntimeError covers XlaRuntimeError (backend not initialized /
        # plugin without the API); IndexError = no local devices at all
        get_logger("transformer.pipeline_parallel.utils").debug(
            "memory_stats unavailable on this backend: %s: %s",
            type(e).__name__, e)
        stats = {}
    used = stats.get("bytes_in_use", 0) / 2**30
    peak = stats.get("peak_bytes_in_use", 0) / 2**30
    limit = stats.get("bytes_limit", 0) / 2**30
    msg = (f"[{name}] memory (GiB) | in use: {used:.2f} | "
           f"peak: {peak:.2f} | limit: {limit:.2f}")
    print_rank_0(msg)
    return msg


def print_params_min_max_norm(params) -> None:
    """utils.py:265 parity: per-leaf min/max/norm dump."""
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        leaf32 = leaf.astype(jnp.float32)
        print_rank_0(
            f"{jax.tree_util.keystr(path)}: min={float(leaf32.min()):.3e} "
            f"max={float(leaf32.max()):.3e} "
            f"norm={float(jnp.linalg.norm(leaf32)):.3e}")
