"""FusedScaleMaskSoftmax — the kernel-dispatch wrapper.

Parity target: ``apex.transformer.functional.fused_softmax``
(fused_softmax.py:164-275): one module that routes scale+mask+softmax to the
right fused kernel (causal / masked / generic / plain) or the eager fallback,
based on dtype, mask type, and shape predicates
(``is_kernel_available``: fp16/bf16, 16 < sk ≤ 2048|16384, pow2-ish batching).

On TPU the Pallas kernels have different (weaker) constraints — lane-aligned
sk under a VMEM cap (see :mod:`apex_tpu.ops.softmax`) — and the jnp fallback
is itself fused by XLA, so dispatch cannot change numerics, only speed.  The
predicate structure is preserved for API parity and introspection.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from apex_tpu.ops.softmax import (
    _MAX_SK,
    generic_scaled_masked_softmax,
    scaled_causal_masked_softmax,
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.transformer.enums import AttnMaskType

__all__ = ["FusedScaleMaskSoftmax"]


class FusedScaleMaskSoftmax:
    """fused operation: scaling + mask + softmax (fused_softmax.py:164).

    Args mirror the reference: ``input_in_fp16``/``input_in_bf16`` describe
    the activation dtype, ``attn_mask_type`` selects causal vs padding,
    ``scaled_masked_softmax_fusion`` enables the kernel path,
    ``mask_func``/``softmax_in_fp32``/``scale`` configure the fallback.
    """

    def __init__(
        self,
        input_in_fp16: bool = False,
        input_in_bf16: bool = False,
        attn_mask_type: AttnMaskType = AttnMaskType.padding,
        scaled_masked_softmax_fusion: bool = True,
        mask_func: Optional[Callable] = None,
        softmax_in_fp32: bool = True,
        scale: Optional[float] = None,
    ):
        if input_in_fp16 and input_in_bf16:
            raise RuntimeError("both fp16 and bf16 flags cannot be active at the same time.")
        if scale is not None and not softmax_in_fp32:
            raise RuntimeError("softmax should be in fp32 when scaled")
        self.input_in_fp16 = input_in_fp16
        self.input_in_bf16 = input_in_bf16
        self.input_in_float16 = input_in_fp16 or input_in_bf16
        self.attn_mask_type = attn_mask_type
        self.scaled_masked_softmax_fusion = scaled_masked_softmax_fusion
        self.mask_func = mask_func
        self.softmax_in_fp32 = softmax_in_fp32
        self.scale = scale

    def is_kernel_available(self, mask, b, np_, sq, sk) -> bool:
        """Shape predicate (fused_softmax.py:196-236, TPU constraints)."""
        if not self.scaled_masked_softmax_fusion:
            return False
        if not self.input_in_float16:
            # the CUDA kernels are half-only; the Pallas kernels aren't, but
            # keep the predicate shape for parity.
            pass
        if sk % 128 != 0 or sk > _MAX_SK:
            return False
        if sq % min(128, sq) != 0 or sq < 8:
            return False
        return True

    def __call__(self, inputs, mask=None):
        b, np_, sq, sk = inputs.shape
        scale = self.scale if self.scale is not None else 1.0
        if self.attn_mask_type == AttnMaskType.causal:
            if mask is not None:
                # the reference's upper-triang kernel asserts mask is None;
                # here causal + padding mask compose (a caller passing a
                # padding-only mask still gets causal attention)
                return scaled_causal_masked_softmax(inputs, mask, scale)
            return scaled_upper_triang_masked_softmax(inputs, scale)
        if mask is not None:
            if self.is_kernel_available(mask, b, np_, sq, sk):
                return scaled_masked_softmax(inputs, mask, scale)
            return generic_scaled_masked_softmax(inputs, mask, scale)
        return scaled_softmax(inputs, scale)

    # keep the reference's name for the eager path
    def forward_torch_softmax(self, inputs, mask=None):
        """The unfused reference path (scale → mask → softmax in fp32 when
        ``softmax_in_fp32``), used when the kernel gate declines."""
        x = inputs.astype(jnp.float32) if self.softmax_in_fp32 else inputs
        if self.scale is not None:
            x = x * self.scale
        if mask is not None and self.mask_func is not None:
            x = self.mask_func(x, mask)
        import jax

        probs = jax.nn.softmax(x, axis=-1)
        if self.softmax_in_fp32 and self.input_in_float16:
            probs = probs.astype(inputs.dtype)
        return probs
