"""apex_tpu.transformer.functional — fused softmax dispatcher + fused rope.

Parity: apex/transformer/functional (fused_softmax.py:164-275, fused_rope.py).
"""

from apex_tpu.ops.rope import (
    fused_apply_rotary_pos_emb,
    fused_apply_rotary_pos_emb_2d,
    fused_apply_rotary_pos_emb_cached,
    fused_apply_rotary_pos_emb_thd,
)
from apex_tpu.transformer.functional.fused_softmax import FusedScaleMaskSoftmax

__all__ = [
    "FusedScaleMaskSoftmax",
    "fused_apply_rotary_pos_emb",
    "fused_apply_rotary_pos_emb_2d",
    "fused_apply_rotary_pos_emb_cached",
    "fused_apply_rotary_pos_emb_thd",
]
