"""Global singletons for Megatron-style training scripts.

Parity target: ``apex.transformer.testing.global_vars`` (global_vars.py:26-
190): ``get_args`` / ``get_num_microbatches`` /
``get_current_global_batch_size`` / ``update_num_microbatches`` /
``get_tensorboard_writer`` / ``get_timers`` behind ``set_global_variables``.

The autoresume hook (ADLR cluster infra) has no TPU analog and is omitted;
everything else is shared machinery: the microbatch calculator is
:mod:`apex_tpu.transformer.microbatches`, timers are the pipeline
``Timers``.
"""

from __future__ import annotations

from typing import Optional

from apex_tpu.transformer.microbatches import build_num_microbatches_calculator
from apex_tpu.transformer.pipeline_parallel._timers import Timers
from apex_tpu.transformer.testing.arguments import parse_args

_GLOBAL_ARGS = None
_GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
_GLOBAL_TENSORBOARD_WRITER = None
_GLOBAL_TIMERS = None

__all__ = [
    "get_args", "get_num_microbatches", "get_current_global_batch_size",
    "update_num_microbatches", "get_tensorboard_writer", "get_timers",
    "set_global_variables", "destroy_global_vars",
]


def _ensure_initialized(var, name):
    if var is None:
        raise RuntimeError(f"{name} is not initialized "
                           "(call set_global_variables first)")
    return var


def _ensure_not_initialized(var, name):
    if var is not None:
        raise RuntimeError(f"{name} is already initialized")


def get_args():
    return _ensure_initialized(_GLOBAL_ARGS, "args")


def get_num_microbatches() -> int:
    return _ensure_initialized(
        _GLOBAL_NUM_MICROBATCHES_CALCULATOR, "num microbatches calculator"
    ).get()


def get_current_global_batch_size() -> int:
    return _ensure_initialized(
        _GLOBAL_NUM_MICROBATCHES_CALCULATOR, "num microbatches calculator"
    ).get_current_global_batch_size()


def update_num_microbatches(consumed_samples: int, *,
                            consistency_check: bool = True) -> None:
    _ensure_initialized(
        _GLOBAL_NUM_MICROBATCHES_CALCULATOR, "num microbatches calculator"
    ).update(consumed_samples, consistency_check)


def get_tensorboard_writer():
    return _GLOBAL_TENSORBOARD_WRITER  # optional: None when not configured


def get_timers() -> Timers:
    return _ensure_initialized(_GLOBAL_TIMERS, "timers")


def set_global_variables(extra_args_provider=None, args_defaults=None,
                         override_args=None, ignore_unknown_args=False,
                         args_list=None):
    """Parse args and build every singleton (global_vars.py:87-101)."""
    global _GLOBAL_ARGS, _GLOBAL_NUM_MICROBATCHES_CALCULATOR, _GLOBAL_TIMERS
    global _GLOBAL_TENSORBOARD_WRITER
    _ensure_not_initialized(_GLOBAL_ARGS, "args")
    # build every component BEFORE assigning any global: a failure partway
    # must leave the singleton clean, not half-initialized
    args = parse_args(extra_args_provider=extra_args_provider,
                      defaults=args_defaults, override_args=override_args,
                      ignore_unknown_args=ignore_unknown_args,
                      args_list=args_list)
    calculator = build_num_microbatches_calculator(
        args.rank, args.rampup_batch_size, args.global_batch_size,
        args.micro_batch_size, args.data_parallel_size)
    writer = None
    if args.tensorboard_dir is not None:
        try:
            from torch.utils.tensorboard import SummaryWriter

            writer = SummaryWriter(log_dir=args.tensorboard_dir)
        except ImportError:
            writer = None
    _GLOBAL_ARGS = args
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = calculator
    _GLOBAL_TENSORBOARD_WRITER = writer
    _GLOBAL_TIMERS = Timers()
    return args


def destroy_global_vars():
    """Testing hook mirroring parallel_state.destroy_model_parallel."""
    global _GLOBAL_ARGS, _GLOBAL_NUM_MICROBATCHES_CALCULATOR, _GLOBAL_TIMERS
    global _GLOBAL_TENSORBOARD_WRITER
    _GLOBAL_ARGS = None
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
    _GLOBAL_TENSORBOARD_WRITER = None
    _GLOBAL_TIMERS = None
