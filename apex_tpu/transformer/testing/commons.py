"""Test/bench fixtures: pipeline-ready GPT builder + step closures.

Parity target: ``apex.transformer.testing.commons`` (commons.py:44-291) —
toy models, fwd-step closures, and ``initialize_distributed`` helpers used by
the reference's distributed tests.

The centerpiece here is :func:`build_gpt_pipeline`: a GPT sliced for the SPMD
pipeline schedules — embedding as the first-stage adapter, a block of
``layers_per_stage`` parallel transformer layers as the repeated stage body,
and final-LN + tied logits + vocab-parallel cross entropy as the last-stage
head.  Composes tp (+sequence parallel) inside each stage with pp across
stages and dp outside, which is exactly the 3D layout of
``test_gpt_minimal.py`` / ``gpt_scaling_test.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

import flax.linen as nn

from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.layers import FusedLayerNorm
from apex_tpu.transformer.parallel_state import TENSOR_PARALLEL_AXIS
from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    PipelineStageSpec,
)
from apex_tpu.transformer.testing.standalone_transformer_lm import (
    Embedding,
    ParallelTransformerLayer,
    parallel_lm_logits,
)
from apex_tpu.transformer.tensor_parallel import vocab_parallel_cross_entropy

__all__ = ["GPTPipeConfig", "build_gpt_pipeline", "init_gpt_pipeline_params"]


@dataclasses.dataclass(frozen=True)
class GPTPipeConfig:
    vocab_size: int = 128
    hidden_size: int = 64
    num_attention_heads: int = 4
    layers_per_stage: int = 2
    max_sequence_length: int = 64
    sequence_parallel_enabled: bool = False
    apply_rope: bool = False
    params_dtype: Any = jnp.float32
    axis_name: str = TENSOR_PARALLEL_AXIS


class _StageBlock(nn.Module):
    cfg: GPTPipeConfig

    @nn.compact
    def __call__(self, x):
        for i in range(self.cfg.layers_per_stage):
            x = ParallelTransformerLayer(
                self.cfg.hidden_size, self.cfg.num_attention_heads,
                attn_mask_type=AttnMaskType.causal,
                apply_rope=self.cfg.apply_rope,
                sequence_parallel_enabled=self.cfg.sequence_parallel_enabled,
                params_dtype=self.cfg.params_dtype,
                axis_name=self.cfg.axis_name, name=f"layer_{i}")(x)
        return x


class _Head(nn.Module):
    cfg: GPTPipeConfig

    @nn.compact
    def __call__(self, y, labels, word_embeddings):
        y = FusedLayerNorm(
            self.cfg.hidden_size,
            sequence_parallel_enabled=self.cfg.sequence_parallel_enabled,
            axis_name=self.cfg.axis_name, name="final_layernorm")(y)
        logits = parallel_lm_logits(
            y, word_embeddings.astype(y.dtype), self.cfg.axis_name,
            sequence_parallel_enabled=self.cfg.sequence_parallel_enabled)
        loss = vocab_parallel_cross_entropy(
            logits.transpose(1, 0, 2), labels, axis_name=self.cfg.axis_name)
        return loss.mean()


def build_gpt_pipeline(cfg: GPTPipeConfig) -> PipelineStageSpec:
    """A :class:`PipelineStageSpec` for the SPMD pipeline schedules.

    Params pytree (per pp×tp rank):
    ``{"embed": ..., "block": ..., "head": ...}`` — embed/head are used by
    the first/last adapters (replicated across pp; their grads are the
    masked contributions the reference syncs over the embedding group).
    Microbatch pytree: ``{"ids": [b, s] int32, "labels": [b, s] int32}``.
    """
    embed = Embedding(cfg.hidden_size, cfg.vocab_size, cfg.max_sequence_length,
                      use_position_embedding=not cfg.apply_rope,
                      sequence_parallel_enabled=cfg.sequence_parallel_enabled,
                      params_dtype=cfg.params_dtype, axis_name=cfg.axis_name)
    block = _StageBlock(cfg)
    head = _Head(cfg)

    def first_fn(params, mb):
        return embed.apply(params["embed"], mb["ids"])

    def stage_fn(params, x):
        return block.apply(params["block"], x)

    def last_fn(params, y, mb):
        word = params["embed"]["params"]["word_embeddings"]["embedding"]
        return head.apply(params["head"], y, mb["labels"], word)

    return PipelineStageSpec(stage_fn=stage_fn, first_fn=first_fn,
                             last_fn=last_fn)


def init_gpt_pipeline_params(cfg: GPTPipeConfig, key, sample_ids) -> Any:
    """Init one pp-rank's params (call inside shard_map so tp/pp rank-folded
    init draws the right shards; fold the pp rank for per-stage weights)."""
    embed = Embedding(cfg.hidden_size, cfg.vocab_size, cfg.max_sequence_length,
                      use_position_embedding=not cfg.apply_rope,
                      sequence_parallel_enabled=cfg.sequence_parallel_enabled,
                      params_dtype=cfg.params_dtype, axis_name=cfg.axis_name)
    block = _StageBlock(cfg)
    head = _Head(cfg)

    from apex_tpu.transformer.tensor_parallel.layers import maybe_axis_index

    pp_idx = maybe_axis_index("pp")
    block_key = key if pp_idx is None else jax.random.fold_in(key, pp_idx)

    embed_params = embed.init(jax.random.fold_in(key, 1), sample_ids)
    wire = embed.apply(embed_params, sample_ids)
    block_params = block.init(jax.random.fold_in(block_key, 2), wire)
    wire2 = block.apply(block_params, wire)
    word = embed_params["params"]["word_embeddings"]["embedding"]
    labels = jnp.zeros(sample_ids.shape, jnp.int32)
    head_params = head.init(jax.random.fold_in(key, 3), wire2, labels, word)
    return {"embed": embed_params, "block": block_params, "head": head_params}
