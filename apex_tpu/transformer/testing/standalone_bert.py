"""Standalone BERT (apex/transformer/testing/standalone_bert.py parity).

``BertModel``: padding-mask bidirectional TransformerLanguageModel with
pooler, binary (NSP) head, and tied LM head — the ``test_bert_minimal.py``
model.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

import flax.linen as nn

from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.layers import FusedLayerNorm
from apex_tpu.transformer.parallel_state import TENSOR_PARALLEL_AXIS
from apex_tpu.transformer.tensor_parallel import vocab_parallel_cross_entropy
from apex_tpu.transformer.testing.standalone_transformer_lm import (
    TransformerLanguageModel,
    parallel_lm_logits,
)

__all__ = ["BertModel", "bert_model_provider"]


class Pooler(nn.Module):
    """tanh(dense(first token)) (standalone_bert Pooler)."""

    hidden_size: int

    @nn.compact
    def __call__(self, hidden):  # [s, b, h]
        first = hidden[0]
        return jnp.tanh(nn.Dense(self.hidden_size)(first))


class BertLMHead(nn.Module):
    """LN + gelu dense + tied-embedding logits (standalone_bert LMHead)."""

    hidden_size: int

    @nn.compact
    def __call__(self, hidden):
        h = nn.Dense(self.hidden_size)(hidden)
        h = nn.gelu(h, approximate=True)
        return FusedLayerNorm(self.hidden_size, name="layernorm")(h)


class BertModel(nn.Module):
    num_layers: int = 2
    hidden_size: int = 64
    num_attention_heads: int = 4
    vocab_size: int = 128
    max_sequence_length: int = 64
    add_binary_head: bool = True
    use_flash_attention: bool = True
    params_dtype: Any = jnp.float32
    axis_name: str = TENSOR_PARALLEL_AXIS

    def setup(self):
        self.language_model = TransformerLanguageModel(
            self.num_layers, self.hidden_size, self.num_attention_heads,
            self.vocab_size, self.max_sequence_length,
            attn_mask_type=AttnMaskType.padding,
            use_flash_attention=self.use_flash_attention,
            params_dtype=self.params_dtype, axis_name=self.axis_name)
        self.lm_head = BertLMHead(self.hidden_size)
        if self.add_binary_head:
            self.pooler = Pooler(self.hidden_size)
            self.binary_head = nn.Dense(2)

    def __call__(self, input_ids, attention_mask=None, lm_labels=None,
                 deterministic: bool = True):
        """attention_mask: [b, s] with 1 = keep (BERT convention)."""
        mask4d = None
        segment_ids = None
        if attention_mask is not None:
            keep = attention_mask.astype(jnp.bool_)
            # [b,1,s,s]: mask out keys that are padding (True = mask out)
            mask4d = jnp.logical_not(keep)[:, None, None, :]
            mask4d = jnp.broadcast_to(
                mask4d, (keep.shape[0], 1, keep.shape[1], keep.shape[1]))
            # flash path: pads = segment 0, kept = segment 1 (same kept-token
            # outputs as the 4-D mask; pad-position outputs are don't-cares)
            segment_ids = keep.astype(jnp.int32)
        hidden = self.language_model(input_ids, attention_mask=mask4d,
                                     deterministic=deterministic,
                                     segment_ids=segment_ids)
        lm_hidden = self.lm_head(hidden)
        word_emb = self.language_model.variables["params"]["embedding"][
            "word_embeddings"]["embedding"]
        logits = parallel_lm_logits(lm_hidden, word_emb.astype(lm_hidden.dtype),
                                    self.axis_name)
        binary = self.binary_head(self.pooler(hidden)) if self.add_binary_head else None
        if lm_labels is None:
            return logits, binary
        loss = vocab_parallel_cross_entropy(
            logits.transpose(1, 0, 2), lm_labels, axis_name=self.axis_name)
        return loss, binary


def bert_model_provider(pre_process: bool = True, post_process: bool = True,
                        **kwargs) -> BertModel:
    del pre_process, post_process
    return BertModel(**kwargs)
