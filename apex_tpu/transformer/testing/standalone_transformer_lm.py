"""Standalone Megatron-style transformer LM built from apex_tpu components.

Parity target: ``apex.transformer.testing.standalone_transformer_lm``
(standalone_transformer_lm.py, 1574 LoC): embeddings, ParallelAttention with
the fused softmax dispatcher, ParallelMLP, checkpointed ParallelTransformer
layers, pooler/heads — the realistic model the reference's L0 transformer
suite trains.

Activations are [s, b, h] (Megatron layout) so sequence parallelism shards
dim 0.  Every parallel layer takes ``axis_name='tp'`` and works unmapped
(world=1) for single-chip use.  RoPE (via :mod:`apex_tpu.ops.rope`) is
available where the reference uses learned absolute positions — both are
implemented.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

import flax.linen as nn

from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.functional import FusedScaleMaskSoftmax
from apex_tpu.transformer.layers import FusedLayerNorm
from apex_tpu.transformer.parallel_state import TENSOR_PARALLEL_AXIS
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    copy_to_tensor_model_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    _tp_size,
    parallel_lm_logits,
)
from apex_tpu.ops.flash_attention import flash_attention
from apex_tpu.transformer.moe import ExpertParallelMLP
from apex_tpu.ops.rope import fused_apply_rotary_pos_emb

__all__ = [
    "ParallelMLP",
    "ParallelAttention",
    "ParallelTransformerLayer",
    "ParallelTransformer",
    "Embedding",
    "TransformerLanguageModel",
    "parallel_lm_logits",
]


class ParallelMLP(nn.Module):
    """h → 4h (column) → gelu → 4h → h (row)  (standalone_transformer_lm
    ParallelMLP)."""

    hidden_size: int
    ffn_hidden_size: Optional[int] = None
    sequence_parallel_enabled: bool = False
    params_dtype: Any = jnp.float32
    axis_name: str = TENSOR_PARALLEL_AXIS

    @nn.compact
    @jax.named_scope("parallel_mlp")
    def __call__(self, x):
        from jax.ad_checkpoint import checkpoint_name

        ffn = self.ffn_hidden_size or 4 * self.hidden_size
        h, bias = ColumnParallelLinear(
            self.hidden_size, ffn, gather_output=False, skip_bias_add=True,
            sequence_parallel_enabled=self.sequence_parallel_enabled,
            params_dtype=self.params_dtype, axis_name=self.axis_name,
            name="dense_h_to_4h")(x)
        # named for the 'except_activations' remat policy: the 4h gelu
        # output is the largest per-layer residual and recomputes
        # elementwise from the (saved) matmul output
        h = checkpoint_name(nn.gelu(h + bias.astype(h.dtype),
                                    approximate=True), "mlp_act")
        out = RowParallelLinear(
            ffn, self.hidden_size, input_is_parallel=True,
            sequence_parallel_enabled=self.sequence_parallel_enabled,
            params_dtype=self.params_dtype, axis_name=self.axis_name,
            name="dense_4h_to_h")(h)
        return out


class ParallelAttention(nn.Module):
    """Multi-head self-attention with tp-sharded heads (ParallelAttention).

    The attention core defaults to the Pallas flash kernel
    (:func:`apex_tpu.ops.flash_attention`): causal masks, segment-id
    padding/varlen masks, and attention dropout (in-kernel counter-based
    keep mask) never materialize the [b, np, s, s] score matrix.  Only
    explicit 4-D ``attention_mask`` tensors take the materialized
    ``FusedScaleMaskSoftmax`` path (the reference's fused-softmax
    dispatcher semantics)."""

    hidden_size: int
    num_attention_heads: int
    attn_mask_type: AttnMaskType = AttnMaskType.causal
    attention_dropout: float = 0.0
    apply_rope: bool = False
    use_flash_attention: bool = True
    sequence_parallel_enabled: bool = False
    # long-context: shard the sequence over this mesh axis and run ring
    # attention (transformer.context_parallel) instead of local attention
    context_parallel_axis: Optional[str] = None
    params_dtype: Any = jnp.float32
    axis_name: str = TENSOR_PARALLEL_AXIS

    @nn.compact
    @jax.named_scope("parallel_attention")
    def __call__(self, x, attention_mask=None, deterministic: bool = True,
                 segment_ids=None):
        # x: [s, b, h]
        world = _tp_size(self.axis_name)
        np_local = self.num_attention_heads // world
        hd = self.hidden_size // self.num_attention_heads

        qkv = ColumnParallelLinear(
            self.hidden_size, 3 * self.hidden_size, gather_output=False,
            sequence_parallel_enabled=self.sequence_parallel_enabled,
            params_dtype=self.params_dtype, axis_name=self.axis_name,
            name="query_key_value")(x)
        s, b = qkv.shape[0], qkv.shape[1]
        qkv = qkv.reshape(s, b, np_local, 3 * hd)
        q, k, v = jnp.split(qkv, 3, axis=-1)  # [s, b, np, hd]

        if self.apply_rope:
            # under context parallelism x holds a sequence SHARD; rotary
            # positions must be the global ones for this rank's slice
            offset = 0
            if self.context_parallel_axis is not None:
                offset = jax.lax.axis_index(self.context_parallel_axis) * s
            freqs = _rope_freqs(s, hd, offset=offset)
            q = fused_apply_rotary_pos_emb(q, freqs)
            k = fused_apply_rotary_pos_emb(k, freqs)

        # [b, np, s, hd]
        qt = q.transpose(1, 2, 0, 3)
        kt = k.transpose(1, 2, 0, 3)
        vt = v.transpose(1, 2, 0, 3)
        scale = 1.0 / float(hd) ** 0.5

        causal = self.attn_mask_type == AttnMaskType.causal
        if self.context_parallel_axis is not None:
            if attention_mask is not None or segment_ids is not None:
                raise NotImplementedError(
                    "context parallelism composes with causal masking only; "
                    "express padding by trimming the global sequence")
            if not deterministic and self.attention_dropout > 0.0:
                raise NotImplementedError(
                    "attention dropout under context parallelism would need "
                    "a ring-consistent RNG; disable it for cp training")
            from apex_tpu.transformer.context_parallel import ring_attention

            ctx = ring_attention(qt, kt, vt,
                                 axis_name=self.context_parallel_axis,
                                 causal=causal, scale=scale)
        # segment ids express padding/varlen without a 4-D mask tensor; when
        # a caller supplies both (BERT), the flash path uses the segments and
        # the materialized fallback uses the mask — same kept-token outputs.
        use_flash = (self.context_parallel_axis is None
                     and self.use_flash_attention
                     and (segment_ids is not None
                          or (causal and attention_mask is None)))
        if self.context_parallel_axis is not None:
            pass  # ctx computed by the ring above
        elif use_flash:
            rate, seed = 0.0, None
            if self.attention_dropout > 0.0 and not deterministic:
                # in-kernel counter-based dropout (ops.flash_attention)
                rate = self.attention_dropout
                seed = jax.random.randint(self.make_rng("dropout"), (),
                                          0, 2**31 - 1, dtype=jnp.int32)
            ctx = flash_attention(qt, kt, vt, causal=causal,
                                  segment_ids=segment_ids, scale=scale,
                                  dropout_rate=rate, dropout_seed=seed)
        else:
            scores = jax.lax.dot_general(
                qt, kt, (((3,), (3,)), ((0, 1), (0, 1))),
                preferred_element_type=jnp.float32).astype(qt.dtype)

            softmax = FusedScaleMaskSoftmax(
                input_in_bf16=(qt.dtype == jnp.bfloat16),
                input_in_fp16=(qt.dtype == jnp.float16),
                attn_mask_type=self.attn_mask_type,
                scale=scale)
            probs = softmax(scores, attention_mask)
            if self.attention_dropout > 0.0 and not deterministic:
                probs = nn.Dropout(self.attention_dropout)(
                    probs, deterministic=False)

            ctx = jax.lax.dot_general(
                probs, vt, (((3,), (2,)), ((0, 1), (0, 1))),
                preferred_element_type=jnp.float32).astype(vt.dtype)
        ctx = ctx.transpose(2, 0, 1, 3).reshape(s, b, np_local * hd)

        out = RowParallelLinear(
            self.hidden_size, self.hidden_size, input_is_parallel=True,
            sequence_parallel_enabled=self.sequence_parallel_enabled,
            params_dtype=self.params_dtype, axis_name=self.axis_name,
            name="dense")(ctx)
        return out


def _rope_freqs(s: int, dim: int, offset=0) -> jax.Array:
    inv = 1.0 / (10000.0 ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    t = jnp.arange(s, dtype=jnp.float32) + offset
    f = jnp.outer(t, inv)  # [s, dim/2]
    return jnp.concatenate([f, f], axis=-1)[:, None, None, :]  # [s,1,1,dim]


class MoEParallelMLP(nn.Module):
    """Drop-in MLP replacement routing tokens through expert-parallel
    experts (transformer.moe.ExpertParallelMLP); the load-balancing aux
    loss is stashed in the ``'moe_losses'`` mutable collection so callers
    can add it to the objective (sown, not returned, to keep the layer
    signature identical to ParallelMLP).

    **Training callers must pass** ``mutable=['moe_losses']`` to
    ``Module.apply`` and add the sown values to the loss — flax drops a sow
    into a non-mutable collection silently, which would train with no
    load-balancing pressure.  A trace-time warning fires if that happens
    with ``deterministic=False``."""

    hidden_size: int
    num_experts: int
    ffn_hidden_size: Optional[int] = None
    capacity_factor: float = 1.25
    expert_parallel_axis: Optional[str] = None
    params_dtype: Any = jnp.float32

    @nn.compact
    @jax.named_scope("moe_mlp")
    def __call__(self, x, deterministic: bool = True):
        s, b, h = x.shape
        if h != self.hidden_size:
            raise ValueError(f"input feature dim ({h}) != hidden_size "
                             f"({self.hidden_size})")
        out, aux = ExpertParallelMLP(
            num_experts=self.num_experts, hidden_size=h,
            ffn_hidden_size=self.ffn_hidden_size,
            capacity_factor=self.capacity_factor,
            axis_name=self.expert_parallel_axis,
            param_dtype=self.params_dtype, name="experts")(
            x.reshape(s * b, h))
        stored = self.sow("moe_losses", "load_balancing", aux)
        if not stored and not deterministic and not self.is_initializing():
            import warnings

            warnings.warn(
                "MoE load-balancing loss was sown into 'moe_losses' but the "
                "collection is not mutable in this apply() — the aux loss is "
                "being DROPPED.  Training callers must pass "
                "mutable=['moe_losses'] and add the sown values to the "
                "objective.", stacklevel=2)
        return out.reshape(s, b, h)


class ParallelTransformerLayer(nn.Module):
    """pre-LN block: LN → attn → +res → LN → MLP → +res."""

    hidden_size: int
    num_attention_heads: int
    attn_mask_type: AttnMaskType = AttnMaskType.causal
    hidden_dropout: float = 0.0
    apply_rope: bool = False
    use_flash_attention: bool = True
    sequence_parallel_enabled: bool = False
    context_parallel_axis: Optional[str] = None
    # MoE: replace the dense MLP with num_experts experts (sharded over
    # expert_parallel_axis when set)
    moe_num_experts: Optional[int] = None
    expert_parallel_axis: Optional[str] = None
    moe_capacity_factor: float = 1.25
    params_dtype: Any = jnp.float32
    axis_name: str = TENSOR_PARALLEL_AXIS

    @nn.compact
    def __call__(self, x, attention_mask=None, deterministic: bool = True,
                 segment_ids=None):
        from jax.ad_checkpoint import checkpoint_name

        ln1 = checkpoint_name(FusedLayerNorm(
            self.hidden_size,
            sequence_parallel_enabled=self.sequence_parallel_enabled,
            axis_name=self.axis_name, name="input_layernorm")(x), "ln_out")
        attn = ParallelAttention(
            self.hidden_size, self.num_attention_heads,
            attn_mask_type=self.attn_mask_type, apply_rope=self.apply_rope,
            use_flash_attention=self.use_flash_attention,
            sequence_parallel_enabled=self.sequence_parallel_enabled,
            context_parallel_axis=self.context_parallel_axis,
            params_dtype=self.params_dtype, axis_name=self.axis_name,
            name="self_attention")(ln1, attention_mask, deterministic,
                                   segment_ids)
        if self.hidden_dropout > 0.0 and not deterministic:
            attn = nn.Dropout(self.hidden_dropout)(attn, deterministic=False)
        x = x + attn
        ln2 = checkpoint_name(FusedLayerNorm(
            self.hidden_size,
            sequence_parallel_enabled=self.sequence_parallel_enabled,
            axis_name=self.axis_name, name="post_attention_layernorm")(x),
            "ln_out")
        if self.moe_num_experts:
            if self.sequence_parallel_enabled:
                raise NotImplementedError(
                    "MoE + sequence parallelism needs tp-grad-synced "
                    "replicated experts (copy_to_tensor_model_parallel_"
                    "region on the expert params); route tokens with "
                    "expert_parallel_axis instead")
            mlp = MoEParallelMLP(
                self.hidden_size, num_experts=self.moe_num_experts,
                expert_parallel_axis=self.expert_parallel_axis,
                capacity_factor=self.moe_capacity_factor,
                params_dtype=self.params_dtype, name="mlp")(
                ln2, deterministic=deterministic)
        else:
            mlp = ParallelMLP(
                self.hidden_size,
                sequence_parallel_enabled=self.sequence_parallel_enabled,
                params_dtype=self.params_dtype, axis_name=self.axis_name,
                name="mlp")(ln2)
        if self.hidden_dropout > 0.0 and not deterministic:
            mlp = nn.Dropout(self.hidden_dropout)(mlp, deterministic=False)
        return x + mlp


class ParallelTransformer(nn.Module):
    """Stack of layers with optional per-layer activation checkpointing.

    ``activations_checkpoint_policy`` selects what the remat saves:
    ``None`` (full recompute — the reference's CheckpointFunction),
    ``'dots'`` / ``'dots_no_batch'`` (save matmul outputs, recompute
    elementwise LN/gelu only — no extra MXU work in backward, the cheap
    way to fit a larger batch).  Implies checkpointing when set."""

    num_layers: int
    hidden_size: int
    num_attention_heads: int
    attn_mask_type: AttnMaskType = AttnMaskType.causal
    apply_rope: bool = False
    use_flash_attention: bool = True
    activations_checkpoint: bool = False
    activations_checkpoint_policy: Optional[str] = None
    sequence_parallel_enabled: bool = False
    context_parallel_axis: Optional[str] = None
    moe_num_experts: Optional[int] = None
    expert_parallel_axis: Optional[str] = None
    moe_capacity_factor: float = 1.25
    params_dtype: Any = jnp.float32
    axis_name: str = TENSOR_PARALLEL_AXIS
    final_layernorm: bool = True

    @nn.compact
    def __call__(self, x, attention_mask=None, deterministic: bool = True,
                 segment_ids=None):
        # tensor_parallel.random.CheckpointFunction semantics: recompute each
        # layer in backward when activations_checkpoint is set
        if self.activations_checkpoint or self.activations_checkpoint_policy:
            policy = {
                None: None,
                "dots": jax.checkpoint_policies.checkpoint_dots,
                "dots_no_batch":
                    jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
                # save EVERYTHING except the tagged cheap-to-recompute
                # activations (gelu output, LN outputs): unlike 'dots',
                # custom_vjp outputs (flash attention, LN residuals) stay
                # saved, so backward recompute is elementwise-only
                "except_activations":
                    jax.checkpoint_policies.save_anything_except_these_names(
                        "mlp_act", "ln_out"),
            }[self.activations_checkpoint_policy]
            layer_cls = nn.remat(ParallelTransformerLayer,
                                 static_argnums=(3,), policy=policy)
        else:
            layer_cls = ParallelTransformerLayer
        for i in range(self.num_layers):
            layer = layer_cls(
                self.hidden_size, self.num_attention_heads,
                attn_mask_type=self.attn_mask_type, apply_rope=self.apply_rope,
                use_flash_attention=self.use_flash_attention,
                sequence_parallel_enabled=self.sequence_parallel_enabled,
                context_parallel_axis=self.context_parallel_axis,
                moe_num_experts=self.moe_num_experts,
                expert_parallel_axis=self.expert_parallel_axis,
                moe_capacity_factor=self.moe_capacity_factor,
                params_dtype=self.params_dtype, axis_name=self.axis_name,
                name=f"layer_{i}")
            x = layer(x, attention_mask, deterministic, segment_ids)
        if self.final_layernorm:
            x = FusedLayerNorm(
                self.hidden_size,
                sequence_parallel_enabled=self.sequence_parallel_enabled,
                axis_name=self.axis_name, name="final_layernorm")(x)
        return x


class Embedding(nn.Module):
    """Vocab-parallel token embedding + learned positions (Embedding in the
    reference; RoPE models skip the position table)."""

    hidden_size: int
    vocab_size: int
    max_sequence_length: int
    use_position_embedding: bool = True
    sequence_parallel_enabled: bool = False
    params_dtype: Any = jnp.float32
    axis_name: str = TENSOR_PARALLEL_AXIS

    @nn.compact
    def __call__(self, input_ids, position_ids=None):
        # input_ids: [b, s] → returns [s, b, h]
        emb = VocabParallelEmbedding(
            self.vocab_size, self.hidden_size,
            params_dtype=self.params_dtype, axis_name=self.axis_name,
            name="word_embeddings")(input_ids)
        if self.use_position_embedding:
            pos_table = self.param(
                "position_embeddings", nn.initializers.normal(0.02),
                (self.max_sequence_length, self.hidden_size), self.params_dtype)
            if position_ids is None:
                position_ids = jnp.arange(input_ids.shape[1])[None, :]
            emb = emb + jnp.take(pos_table, position_ids, axis=0).astype(emb.dtype)
        x = emb.transpose(1, 0, 2)  # [s, b, h]
        if self.sequence_parallel_enabled:
            x = scatter_to_sequence_parallel_region(x, self.axis_name)
        return x


class TransformerLanguageModel(nn.Module):
    """Embedding + transformer (+tied LM logits helper via ``compute_logits``).

    With ``moe_num_experts`` set, training applies must pass
    ``mutable=['moe_losses']`` and fold the sown load-balancing losses into
    the objective — see :class:`MoEParallelMLP`."""

    num_layers: int
    hidden_size: int
    num_attention_heads: int
    vocab_size: int
    max_sequence_length: int
    attn_mask_type: AttnMaskType = AttnMaskType.causal
    apply_rope: bool = False
    use_flash_attention: bool = True
    activations_checkpoint: bool = False
    activations_checkpoint_policy: Optional[str] = None
    sequence_parallel_enabled: bool = False
    context_parallel_axis: Optional[str] = None
    moe_num_experts: Optional[int] = None
    expert_parallel_axis: Optional[str] = None
    moe_capacity_factor: float = 1.25
    params_dtype: Any = jnp.float32
    axis_name: str = TENSOR_PARALLEL_AXIS

    @nn.compact
    def __call__(self, input_ids, position_ids=None, attention_mask=None,
                 deterministic: bool = True, segment_ids=None):
        x = Embedding(
            self.hidden_size, self.vocab_size, self.max_sequence_length,
            use_position_embedding=not self.apply_rope,
            sequence_parallel_enabled=self.sequence_parallel_enabled,
            params_dtype=self.params_dtype, axis_name=self.axis_name,
            name="embedding")(input_ids, position_ids)
        x = ParallelTransformer(
            self.num_layers, self.hidden_size, self.num_attention_heads,
            attn_mask_type=self.attn_mask_type, apply_rope=self.apply_rope,
            use_flash_attention=self.use_flash_attention,
            activations_checkpoint=self.activations_checkpoint,
            activations_checkpoint_policy=self.activations_checkpoint_policy,
            sequence_parallel_enabled=self.sequence_parallel_enabled,
            context_parallel_axis=self.context_parallel_axis,
            moe_num_experts=self.moe_num_experts,
            expert_parallel_axis=self.expert_parallel_axis,
            moe_capacity_factor=self.moe_capacity_factor,
            params_dtype=self.params_dtype, axis_name=self.axis_name,
            name="transformer")(x, attention_mask, deterministic, segment_ids)
        return x
