"""Standalone GPT (apex/transformer/testing/standalone_gpt.py parity).

``GPTModel``: causal TransformerLanguageModel with weight-tied LM head and
vocab-parallel cross-entropy ``loss`` method — the model the reference's
``test_gpt_minimal.py`` / ``gpt_scaling_test.py`` trains, and this repo's
benchmark flagship.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

import flax.linen as nn

from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.parallel_state import TENSOR_PARALLEL_AXIS
from apex_tpu.transformer.tensor_parallel import vocab_parallel_cross_entropy
from apex_tpu.transformer.testing.standalone_transformer_lm import (
    TransformerLanguageModel,
    parallel_lm_logits,
)

__all__ = ["GPTModel", "gpt_model_provider"]


class GPTModel(nn.Module):
    num_layers: int = 2
    hidden_size: int = 64
    num_attention_heads: int = 4
    vocab_size: int = 128
    max_sequence_length: int = 64
    apply_rope: bool = False
    use_flash_attention: bool = True
    activations_checkpoint: bool = False
    activations_checkpoint_policy: Optional[str] = None
    sequence_parallel_enabled: bool = False
    params_dtype: Any = jnp.float32
    axis_name: str = TENSOR_PARALLEL_AXIS

    def setup(self):
        self.language_model = TransformerLanguageModel(
            self.num_layers, self.hidden_size, self.num_attention_heads,
            self.vocab_size, self.max_sequence_length,
            attn_mask_type=AttnMaskType.causal,
            apply_rope=self.apply_rope,
            use_flash_attention=self.use_flash_attention,
            activations_checkpoint=self.activations_checkpoint,
            activations_checkpoint_policy=self.activations_checkpoint_policy,
            sequence_parallel_enabled=self.sequence_parallel_enabled,
            params_dtype=self.params_dtype, axis_name=self.axis_name)

    def __call__(self, input_ids, labels=None, position_ids=None,
                 deterministic: bool = True):
        """Returns per-token loss [b, s] when labels given, else logits
        [s, b, vocab/tp]."""
        from apex_tpu.transformer.tensor_parallel.layers import _tp_size

        hidden = self.language_model(input_ids, position_ids,
                                     deterministic=deterministic)
        # weight tying: reuse the vocab-parallel embedding table
        word_emb = self.language_model.variables["params"]["embedding"][
            "word_embeddings"]["embedding"]
        if (labels is not None and _tp_size(self.axis_name) == 1
                and not self.sequence_parallel_enabled):
            # single-shard training: fused head+CE kernel — logits never
            # materialize (ops.fused_lm_head; ~13 ms/step on the v5e bench)
            from apex_tpu.ops.fused_lm_head import fused_lm_head_loss

            loss = fused_lm_head_loss(
                hidden, word_emb.astype(hidden.dtype),
                labels.T)                       # [s, b] token order
            return loss.T                       # [b, s]
        logits = parallel_lm_logits(
            hidden, word_emb.astype(hidden.dtype), self.axis_name,
            sequence_parallel_enabled=self.sequence_parallel_enabled)
        if labels is None:
            return logits
        # logits [s, b, v/tp] → [b, s, v/tp]
        logits = logits.transpose(1, 0, 2)
        return vocab_parallel_cross_entropy(logits, labels,
                                            axis_name=self.axis_name)


def gpt_model_provider(pre_process: bool = True, post_process: bool = True,
                       **kwargs) -> GPTModel:
    """standalone_gpt.gpt_model_provider parity (pre/post flags accepted for
    the virtual-pp ``build_model`` path)."""
    del pre_process, post_process
    return GPTModel(**kwargs)
