"""apex_tpu.transformer.testing — standalone models + test fixtures.

Parity: apex/transformer/testing (standalone_{gpt,bert,transformer_lm},
commons, global_vars, arguments, distributed_test_base — the last replaced by
the CPU-mesh conftest pattern, SURVEY.md §4 "TPU translation").
"""

from apex_tpu.transformer.testing.standalone_bert import BertModel, bert_model_provider
from apex_tpu.transformer.testing.standalone_gpt import GPTModel, gpt_model_provider

__all__ = ["BertModel", "bert_model_provider", "GPTModel", "gpt_model_provider"]
