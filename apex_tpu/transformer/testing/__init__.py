"""apex_tpu.transformer.testing — standalone models + test fixtures.

Parity: apex/transformer/testing (standalone_{gpt,bert,transformer_lm},
commons, global_vars, arguments, distributed_test_base — the last replaced by
the CPU-mesh conftest pattern, SURVEY.md §4 "TPU translation").
"""

from apex_tpu.transformer.testing.arguments import (
    core_transformer_config_from_args,
    parse_args,
)
from apex_tpu.transformer.testing.global_vars import (
    destroy_global_vars,
    get_args,
    get_current_global_batch_size,
    get_num_microbatches,
    get_tensorboard_writer,
    get_timers,
    set_global_variables,
    update_num_microbatches,
)
from apex_tpu.transformer.testing.standalone_bert import BertModel, bert_model_provider
from apex_tpu.transformer.testing.standalone_gpt import GPTModel, gpt_model_provider

__all__ = [
    "BertModel", "bert_model_provider", "GPTModel", "gpt_model_provider",
    "parse_args", "core_transformer_config_from_args",
    "set_global_variables", "destroy_global_vars", "get_args",
    "get_num_microbatches", "get_current_global_batch_size",
    "update_num_microbatches", "get_tensorboard_writer", "get_timers",
]
