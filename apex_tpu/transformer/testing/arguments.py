"""Megatron-style argument parsing for the testing models.

Parity target: ``apex.transformer.testing.arguments.parse_args``
(arguments.py:23-977): the argparse groups (network size, regularization,
training, learning rate, checkpointing, mixed precision, distributed,
validation, data, logging) plus the derivation/validation pass — tp/pp
clamped to world size, dp derived, batch arithmetic checked, dtype picked
from --fp16/--bf16.

TPU adaptation: CUDA-only knobs (``--DDP-impl``, NCCL timeouts, fused
kernels toggles that map to build flags) are absent — the feature registry
(apex_tpu.feature_registry) owns capability switches; flags whose names
user scripts script against are kept verbatim.  ``params_dtype`` becomes a
jnp dtype, and bf16 is the recommended half type.
"""

from __future__ import annotations

import argparse
import os

import jax.numpy as jnp

__all__ = ["parse_args", "core_transformer_config_from_args"]


def parse_args(extra_args_provider=None, defaults=None, override_args=None,
               ignore_unknown_args=False, args_list=None):
    """Build, parse, derive, validate (arguments.py:23-324)."""
    parser = argparse.ArgumentParser(description="apex_tpu transformer args",
                                     allow_abbrev=False)
    for add in (_add_network_size_args, _add_regularization_args,
                _add_training_args, _add_initialization_args,
                _add_learning_rate_args, _add_checkpointing_args,
                _add_mixed_precision_args, _add_distributed_args,
                _add_validation_args, _add_data_args, _add_logging_args):
        parser = add(parser)
    if extra_args_provider is not None:
        parser = extra_args_provider(parser)

    if ignore_unknown_args:
        args, _ = parser.parse_known_args(args_list)
    else:
        args = parser.parse_args(args_list)

    args.rank = int(os.getenv("RANK", "0"))
    args.world_size = int(os.getenv("WORLD_SIZE", "1"))
    for key, value in (override_args or {}).items():
        setattr(args, key, value)
    for key, value in (defaults or {}).items():
        if getattr(args, key, None) is None:
            setattr(args, key, value)

    # --- parallel-geometry derivations (arguments.py:66-99) ---------------
    args.tensor_model_parallel_size = min(args.tensor_model_parallel_size,
                                          args.world_size)
    if args.world_size % args.tensor_model_parallel_size:
        raise ValueError(
            f"world size ({args.world_size}) is not divisible by tensor "
            f"model parallel size ({args.tensor_model_parallel_size})")
    args.pipeline_model_parallel_size = min(
        args.pipeline_model_parallel_size,
        args.world_size // args.tensor_model_parallel_size)
    mp = args.tensor_model_parallel_size * args.pipeline_model_parallel_size
    if args.world_size % mp:
        raise ValueError(
            f"world size ({args.world_size}) is not divisible by tp*pp "
            f"({mp})")
    args.data_parallel_size = args.world_size // mp
    # interleaved-schedule geometry (Megatron arguments.py:101-113)
    args.virtual_pipeline_model_parallel_size = None
    if args.num_layers_per_virtual_pipeline_stage is not None:
        if args.num_layers is None:
            raise ValueError(
                "--num-layers-per-virtual-pipeline-stage needs --num-layers")
        per_pipeline = args.num_layers // args.pipeline_model_parallel_size
        if per_pipeline % args.num_layers_per_virtual_pipeline_stage:
            raise ValueError(
                f"layers per pipeline stage ({per_pipeline}) must divide by "
                "--num-layers-per-virtual-pipeline-stage "
                f"({args.num_layers_per_virtual_pipeline_stage})")
        args.virtual_pipeline_model_parallel_size = (
            per_pipeline // args.num_layers_per_virtual_pipeline_stage)

    # --- batch arithmetic (arguments.py:130-160) --------------------------
    if args.micro_batch_size is None:
        args.micro_batch_size = 1
    if args.global_batch_size is None:
        args.global_batch_size = args.micro_batch_size * args.data_parallel_size
    per_step = args.micro_batch_size * args.data_parallel_size
    if args.global_batch_size % per_step:
        raise ValueError(
            f"global batch size ({args.global_batch_size}) must be a "
            f"multiple of micro_batch_size*dp ({per_step})")

    # --- dtype policy (arguments.py:162-180) ------------------------------
    if args.fp16 and args.bf16:
        raise ValueError("--fp16 and --bf16 are mutually exclusive")
    args.params_dtype = jnp.float32
    if args.fp16:
        args.params_dtype = jnp.float16
    if args.bf16:
        args.params_dtype = jnp.bfloat16
    if args.loss_scale is None and args.fp16:
        args.loss_scale = "dynamic"

    # --- network derivations (arguments.py:190-240) -----------------------
    for required in ("num_layers", "hidden_size", "num_attention_heads"):
        if getattr(args, required) is None:
            raise ValueError(
                f"--{required.replace('_', '-')} is required")
    if args.ffn_hidden_size is None:
        args.ffn_hidden_size = 4 * args.hidden_size
    if args.kv_channels is None:
        if args.hidden_size % args.num_attention_heads:
            raise ValueError("hidden size must divide by attention heads")
        args.kv_channels = args.hidden_size // args.num_attention_heads
    if args.seq_length is not None and args.max_position_embeddings is not None:
        if args.max_position_embeddings < args.seq_length:
            raise ValueError("max_position_embeddings must cover seq_length")
    if args.checkpoint_activations:
        args.recompute_granularity = "full"

    if args.rank == 0:
        print(f"using world size: {args.world_size}, "
              f"data-parallel-size: {args.data_parallel_size}, "
              f"tensor-model-parallel size: {args.tensor_model_parallel_size}, "
              f"pipeline-model-parallel size: "
              f"{args.pipeline_model_parallel_size}", flush=True)
    return args


def core_transformer_config_from_args(args) -> dict:
    """The kwargs the testing models consume, from parsed args."""
    return dict(
        num_layers=args.num_layers,
        hidden_size=args.hidden_size,
        num_attention_heads=args.num_attention_heads,
        vocab_size=args.padded_vocab_size or args.vocab_size or 0,
        max_sequence_length=args.seq_length or args.max_position_embeddings,
        params_dtype=args.params_dtype,
    )


def _add_network_size_args(parser):
    g = parser.add_argument_group(title="network size")
    g.add_argument("--num-layers", type=int, default=None)
    g.add_argument("--hidden-size", type=int, default=None)
    g.add_argument("--ffn-hidden-size", type=int, default=None)
    g.add_argument("--num-attention-heads", type=int, default=None)
    g.add_argument("--kv-channels", type=int, default=None)
    g.add_argument("--max-position-embeddings", type=int, default=None)
    g.add_argument("--vocab-size", type=int, default=None)
    g.add_argument("--padded-vocab-size", type=int, default=None)
    g.add_argument("--make-vocab-size-divisible-by", type=int, default=128)
    g.add_argument("--layernorm-epsilon", type=float, default=1e-5)
    g.add_argument("--apply-residual-connection-post-layernorm",
                   action="store_true")
    g.add_argument("--openai-gelu", action="store_true")
    g.add_argument("--onnx-safe", action="store_true")
    return parser


def _add_regularization_args(parser):
    g = parser.add_argument_group(title="regularization")
    g.add_argument("--attention-dropout", type=float, default=0.1)
    g.add_argument("--hidden-dropout", type=float, default=0.1)
    g.add_argument("--weight-decay", type=float, default=0.01)
    g.add_argument("--clip-grad", type=float, default=1.0)
    g.add_argument("--adam-beta1", type=float, default=0.9)
    g.add_argument("--adam-beta2", type=float, default=0.999)
    g.add_argument("--adam-eps", type=float, default=1e-8)
    g.add_argument("--sgd-momentum", type=float, default=0.9)
    return parser


def _add_training_args(parser):
    g = parser.add_argument_group(title="training")
    g.add_argument("--micro-batch-size", type=int, default=None)
    g.add_argument("--global-batch-size", type=int, default=None)
    g.add_argument("--rampup-batch-size", nargs="*", default=None)
    g.add_argument("--train-iters", type=int, default=None)
    g.add_argument("--train-samples", type=int, default=None)
    g.add_argument("--log-interval", type=int, default=100)
    g.add_argument("--exit-interval", type=int, default=None)
    g.add_argument("--checkpoint-activations", action="store_true")
    g.add_argument("--recompute-granularity", type=str, default=None,
                   choices=["full", "selective", None])
    g.add_argument("--optimizer", type=str, default="adam",
                   choices=["adam", "sgd", "lamb", "novograd", "adagrad"])
    g.add_argument("--use-cpu-initialization", action="store_true")
    return parser


def _add_initialization_args(parser):
    g = parser.add_argument_group(title="initialization")
    g.add_argument("--seed", type=int, default=1234)
    g.add_argument("--init-method-std", type=float, default=0.02)
    return parser


def _add_learning_rate_args(parser):
    g = parser.add_argument_group(title="learning rate")
    g.add_argument("--lr", type=float, default=None)
    g.add_argument("--lr-decay-style", type=str, default="linear",
                   choices=["constant", "linear", "cosine"])
    g.add_argument("--lr-decay-iters", type=int, default=None)
    g.add_argument("--lr-decay-samples", type=int, default=None)
    g.add_argument("--lr-warmup-fraction", type=float, default=None)
    g.add_argument("--lr-warmup-iters", type=int, default=0)
    g.add_argument("--min-lr", type=float, default=0.0)
    return parser


def _add_checkpointing_args(parser):
    g = parser.add_argument_group(title="checkpointing")
    g.add_argument("--save", type=str, default=None)
    g.add_argument("--save-interval", type=int, default=None)
    g.add_argument("--load", type=str, default=None)
    g.add_argument("--no-load-optim", action="store_true")
    g.add_argument("--no-load-rng", action="store_true")
    g.add_argument("--no-save-optim", action="store_true")
    g.add_argument("--no-save-rng", action="store_true")
    return parser


def _add_mixed_precision_args(parser):
    g = parser.add_argument_group(title="mixed precision")
    g.add_argument("--fp16", action="store_true")
    g.add_argument("--bf16", action="store_true")
    g.add_argument("--loss-scale", type=float, default=None)
    g.add_argument("--initial-loss-scale", type=float, default=2 ** 32)
    g.add_argument("--min-loss-scale", type=float, default=1.0)
    g.add_argument("--loss-scale-window", type=float, default=1000)
    g.add_argument("--hysteresis", type=int, default=2)
    g.add_argument("--accumulate-allreduce-grads-in-fp32",
                   action="store_true")
    return parser


def _add_distributed_args(parser):
    g = parser.add_argument_group(title="distributed")
    g.add_argument("--tensor-model-parallel-size", type=int, default=1)
    g.add_argument("--pipeline-model-parallel-size", type=int, default=1)
    g.add_argument("--pipeline-model-parallel-split-rank", type=int,
                   default=None)
    g.add_argument("--num-layers-per-virtual-pipeline-stage", type=int,
                   default=None)
    g.add_argument("--sequence-parallel", action="store_true")
    g.add_argument("--use-distributed-optimizer", action="store_true")
    g.add_argument("--local_rank", type=int, default=None)
    return parser


def _add_validation_args(parser):
    g = parser.add_argument_group(title="validation")
    g.add_argument("--eval-iters", type=int, default=100)
    g.add_argument("--eval-interval", type=int, default=1000)
    return parser


def _add_data_args(parser):
    g = parser.add_argument_group(title="data and dataloader")
    g.add_argument("--data-path", nargs="*", default=None)
    g.add_argument("--split", type=str, default="969, 30, 1")
    g.add_argument("--seq-length", type=int, default=None)
    g.add_argument("--encoder-seq-length", type=int, default=None)
    g.add_argument("--decoder-seq-length", type=int, default=None)
    g.add_argument("--num-workers", type=int, default=2)
    g.add_argument("--reset-position-ids", action="store_true")
    g.add_argument("--reset-attention-mask", action="store_true")
    g.add_argument("--eod-mask-loss", action="store_true")
    return parser


def _add_logging_args(parser):
    g = parser.add_argument_group(title="logging")
    g.add_argument("--log-params-norm", action="store_true")
    g.add_argument("--log-num-zeros-in-grad", action="store_true")
    g.add_argument("--tensorboard-dir", type=str, default=None)
    g.add_argument("--log-timers-to-tensorboard", action="store_true")
    g.add_argument("--timing-log-level", type=int, default=0,
                   choices=range(0, 3))
    return parser
