"""Ring attention — context parallelism for long sequences.

Capability target: the reference scales long sequences with its fused
attention + sequence-parallel machinery; the TPU-native answer for
sequences too long for one chip is *context parallelism*: shard the
sequence over a mesh axis and rotate key/value blocks around the ring
(Ring Attention, Liu et al. 2023), so each chip only ever holds
``s_local = s / cp`` keys at a time — online softmax keeps attention
memory free of any [s, s] term and the KV transfers ride ICI neighbor
links.

Design:
- one ``lax.fori``-style scan over ``cp`` ring steps; the carry is the
  online-softmax state (running max, normalizer, weighted accumulator)
  plus the in-flight KV block; each step ends with a neighbor
  ``ppermute`` — exactly the flash-attention accumulation pattern, with
  blocks arriving over the wire instead of from HBM.
- causal masking is block-level: a KV block from a later ring position is
  skipped outright, the diagonal block gets the in-block causal mask,
  earlier blocks attend fully — no [s, s] score matrix ever exists.
- backward: JAX differentiates the scan/ppermute (cotangents traverse the
  reverse ring); with ``jax.checkpoint`` around the per-step kernel, the
  saved state is O(cp · block) wire tensors, the ring-attention memory
  bound.

Compose with tp (heads) and dp (batch) freely: cp only owns the sequence
axis, e.g. ``Mesh(..., ("dp", "cp", "tp"))``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["ring_self_attention", "ring_attention"]

_NEG_INF = -1e30


def _block_attend(q, k, v, *, scale, mask):
    """Unnormalized block attention: returns (scores_max, exp-sum, o_partial)
    with fp32 accumulation; mask is [sq, sk] bool or None."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)                                   # [b,h,sq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                                   # [b,h,sq]
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return m, l, o


def ring_attention(q, k, v, *, axis_name: str, causal: bool = True,
                   scale: Optional[float] = None):
    """Attention over a sequence sharded on ``axis_name``.

    q/k/v: local shards ``[b, h, s_local, d]`` (rank r holds global
    positions ``[r*s_local, (r+1)*s_local)``).  Returns the local output
    shard ``[b, h, s_local, d]`` in q's dtype; numerics match dense
    attention over the gathered sequence.
    """
    n = jax.lax.psum(1, axis_name)  # static size; 0.4.x has no axis_size
    my = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    ring_perm = [(i, (i + 1) % n) for i in range(n)]  # KV moves rank i -> i+1

    tri = jnp.tril(jnp.ones((s_local, s_local), bool)) if causal else None

    @jax.checkpoint
    def step_math(q, k_blk, v_blk, src, m_acc, l_acc, o_acc):
        """One block accumulation; src is the block's origin rank (traced).

        Block-level causal structure: src > my → block fully masked;
        src == my → in-block triangle; src < my → full attention.  One
        _block_attend with a dynamically selected mask covers all three.
        """
        mask = None
        if causal:
            mask = jnp.logical_or(tri, src != my)  # triangle only on-diag
        m_blk, l_blk, o_blk = _block_attend(q, k_blk, v_blk, scale=scale,
                                            mask=mask)
        if causal:
            dead = src > my
            m_blk = jnp.where(dead, _NEG_INF, m_blk)
            l_blk = jnp.where(dead, 0.0, l_blk)
            o_blk = jnp.where(dead, 0.0, o_blk)

        # online-softmax merge
        m_new = jnp.maximum(m_acc, m_blk)
        a = jnp.exp(m_acc - m_new)
        bfac = jnp.exp(m_blk - m_new)
        l_new = l_acc * a + l_blk * bfac
        o_new = o_acc * a[..., None] + o_blk * bfac[..., None]
        return m_new, l_new, o_new

    # step 0 attends the local block (no transfer); steps 1..n-1 each
    # rotate KV one hop then attend — n-1 total transfers, none wasted.
    # src of the block held after r rotations is (my - r) mod n: pure
    # arithmetic, not a collective.
    m_acc = jnp.full((b, h, s_local), _NEG_INF, jnp.float32)
    l_acc = jnp.zeros((b, h, s_local), jnp.float32)
    o_acc = jnp.zeros((b, h, s_local, d), jnp.float32)
    m_acc, l_acc, o_acc = step_math(q, k, v, my, m_acc, l_acc, o_acc)

    def ring_step(carry, r):
        k_blk, v_blk, m_acc, l_acc, o_acc = carry
        k_blk = jax.lax.ppermute(k_blk, axis_name, ring_perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, ring_perm)
        src = jnp.mod(my - r, n)
        m_acc, l_acc, o_acc = step_math(q, k_blk, v_blk, src,
                                        m_acc, l_acc, o_acc)
        return (k_blk, v_blk, m_acc, l_acc, o_acc), None

    if n > 1:
        (_, _, m_acc, l_acc, o_acc), _ = jax.lax.scan(
            ring_step, (k, v, m_acc, l_acc, o_acc), jnp.arange(1, n))

    # fully-masked rows (none in self-attention, defensive) give zeros
    safe_l = jnp.where(l_acc == 0.0, 1.0, l_acc)
    return (o_acc / safe_l[..., None]).astype(q.dtype)


def ring_self_attention(qkv, *, axis_name: str, causal: bool = True,
                        scale: Optional[float] = None):
    """Convenience for fused qkv ``[b, h, s_local, 3, d]``."""
    q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
    return ring_attention(q, k, v, axis_name=axis_name, causal=causal,
                          scale=scale)
