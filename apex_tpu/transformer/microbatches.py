"""Microbatch calculators, including batch-size rampup.

Parity target: ``apex.transformer.microbatches`` (microbatches.py:26-168) and
``setup_microbatch_calculator`` (pipeline_parallel/utils.py:58-104): the
global singleton that answers ``get_micro_batch_size`` /
``get_num_microbatches`` / ``get_current_global_batch_size``, with a
constant and a ramp-up implementation.
"""

from __future__ import annotations

import logging
from typing import List, Optional

logger = logging.getLogger(__name__)

__all__ = [
    "build_num_microbatches_calculator",
    "ConstantNumMicroBatches",
    "RampupBatchsizeNumMicroBatches",
]


def build_num_microbatches_calculator(
    rank: int,
    rampup_batch_size: Optional[List[int]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
):
    """Select the constant or ramp-up calculator (microbatches.py:26-63)."""
    if rampup_batch_size is None:
        calculator = ConstantNumMicroBatches(
            global_batch_size, micro_batch_size, data_parallel_size)
        if rank == 0:
            logger.info("using a constant microbatch count of %d",
                        calculator.get())
        return calculator

    if len(rampup_batch_size) != 3:
        raise ValueError(
            "rampup_batch_size takes exactly three values: "
            "[start_batch_size, batch_size_increment, rampup_samples]; "
            f"got {rampup_batch_size!r}")
    start, increment, samples = (int(v) for v in rampup_batch_size)
    return RampupBatchsizeNumMicroBatches(
        start, increment, samples,
        global_batch_size, micro_batch_size, data_parallel_size)


class NumMicroBatchesCalculator:
    def __init__(self):
        self.num_micro_batches = None
        self.current_global_batch_size = None

    def get(self):
        return self.num_micro_batches

    def get_current_global_batch_size(self):
        return self.current_global_batch_size

    def update(self, consumed_samples, consistency_check):
        raise NotImplementedError


class ConstantNumMicroBatches(NumMicroBatchesCalculator):
    """Fixed global batch → fixed microbatch count (microbatches.py:66-84)."""

    def __init__(self, global_batch_size, micro_batch_size, data_parallel_size):
        super().__init__()
        per_step = micro_batch_size * data_parallel_size
        if global_batch_size % per_step != 0:
            raise ValueError(
                f"global_batch_size={global_batch_size} must be a multiple of "
                f"micro_batch_size*dp = {micro_batch_size}*{data_parallel_size}"
                f" = {per_step}")
        self.num_micro_batches = global_batch_size // per_step
        if self.num_micro_batches < 1:
            raise ValueError(
                f"config yields {self.num_micro_batches} microbatches; "
                "need at least one")
        self.current_global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size

    def update(self, consumed_samples, consistency_check):
        pass


class RampupBatchsizeNumMicroBatches(NumMicroBatchesCalculator):
    """Batch-size rampup (microbatches.py:87-168): global batch grows from
    ``start_batch_size`` by ``batch_size_increment`` every
    ``rampup_samples / steps`` consumed samples."""

    def __init__(self, start_batch_size, batch_size_increment, rampup_samples,
                 global_batch_size, micro_batch_size, data_parallel_size):
        super().__init__()
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size)

        for label, value in (("micro_batch_size*dp",
                              self.micro_batch_times_data_parallel_size),
                             ("start_batch_size", start_batch_size),
                             ("global_batch_size", global_batch_size),
                             ("batch_size_increment", batch_size_increment)):
            if value <= 0:
                raise ValueError(f"{label} must be positive, got {value}")
        if rampup_samples < 0:
            raise ValueError(
                f"rampup_samples must be non-negative, got {rampup_samples}")

        self.start_batch_size = start_batch_size
        self.global_batch_size = global_batch_size
        self.batch_size_increment = batch_size_increment
        self.ramup_samples = rampup_samples  # legacy-compatible attribute name

        span = global_batch_size - start_batch_size
        if span < 0:
            raise ValueError(
                f"start_batch_size={start_batch_size} exceeds "
                f"global_batch_size={global_batch_size}")
        if span % batch_size_increment != 0:
            raise ValueError(
                f"the ramp from {start_batch_size} to {global_batch_size} "
                f"(span {span}) must be a whole number of "
                f"{batch_size_increment}-sized increments")
        num_increments = span // batch_size_increment
        if num_increments == 0 or rampup_samples == 0:
            # degenerate ramp (start == global, or no samples to ramp over):
            # jump straight to the target batch size
            self.rampup_samples_per_increment = float("inf")
        else:
            self.rampup_samples_per_increment = rampup_samples / num_increments
        self.update(0, False)

    def update(self, consumed_samples, consistency_check):
        if consumed_samples >= self.ramup_samples:
            self.current_global_batch_size = self.global_batch_size
        else:
            completed = int(consumed_samples / self.rampup_samples_per_increment)
            self.current_global_batch_size = (
                self.start_batch_size + completed * self.batch_size_increment)
            if self.current_global_batch_size > self.global_batch_size:
                raise RuntimeError(
                    "rampup overshot the target global batch size "
                    f"({self.current_global_batch_size} > "
                    f"{self.global_batch_size})")
        if consistency_check:
            per_step = self.micro_batch_times_data_parallel_size
            if self.current_global_batch_size % per_step != 0:
                raise ValueError(
                    f"ramped global batch {self.current_global_batch_size} is "
                    f"not a multiple of micro_batch_size*dp = {per_step}")
        self.num_micro_batches = (
            self.current_global_batch_size
            // self.micro_batch_times_data_parallel_size)
