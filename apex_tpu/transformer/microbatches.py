"""Microbatch calculators, including batch-size rampup.

Parity target: ``apex.transformer.microbatches`` (microbatches.py:26-168) and
``setup_microbatch_calculator`` (pipeline_parallel/utils.py:58-104): the
global singleton that answers ``get_micro_batch_size`` /
``get_num_microbatches`` / ``get_current_global_batch_size``, with a
constant and a ramp-up implementation.
"""

from __future__ import annotations

from typing import List, Optional

__all__ = [
    "build_num_microbatches_calculator",
    "ConstantNumMicroBatches",
    "RampupBatchsizeNumMicroBatches",
]


def build_num_microbatches_calculator(
    rank: int,
    rampup_batch_size: Optional[List[int]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
):
    """microbatches.py:26-63 parity (same validation and selection)."""
    if rampup_batch_size is None:
        calculator = ConstantNumMicroBatches(
            global_batch_size, micro_batch_size, data_parallel_size)
        if rank == 0:
            import logging

            logging.getLogger(__name__).info(
                "setting number of micro-batches to constant %d",
                calculator.get())
    else:
        if len(rampup_batch_size) != 3:
            raise ValueError(
                "expected the following format: --rampup-batch-size "
                "<start batch size> <batch size increment> <ramp-up samples>")
        start_batch_size = int(rampup_batch_size[0])
        batch_size_increment = int(rampup_batch_size[1])
        ramup_samples = int(rampup_batch_size[2])
        calculator = RampupBatchsizeNumMicroBatches(
            start_batch_size, batch_size_increment, ramup_samples,
            global_batch_size, micro_batch_size, data_parallel_size)
    return calculator


class NumMicroBatchesCalculator:
    def __init__(self):
        self.num_micro_batches = None
        self.current_global_batch_size = None

    def get(self):
        return self.num_micro_batches

    def get_current_global_batch_size(self):
        return self.current_global_batch_size

    def update(self, consumed_samples, consistency_check):
        raise NotImplementedError


class ConstantNumMicroBatches(NumMicroBatchesCalculator):
    """microbatches.py:66-84."""

    def __init__(self, global_batch_size, micro_batch_size, data_parallel_size):
        super().__init__()
        micro_batch_times_data_parallel = micro_batch_size * data_parallel_size
        if global_batch_size % micro_batch_times_data_parallel != 0:
            raise AssertionError(
                f"global batch size ({global_batch_size}) is not divisible by "
                f"micro batch size ({micro_batch_size}) times data parallel "
                f"size ({data_parallel_size})")
        self.num_micro_batches = global_batch_size // micro_batch_times_data_parallel
        if self.num_micro_batches < 1:
            raise AssertionError("number of micro-batches should be at least 1")
        self.current_global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size

    def update(self, consumed_samples, consistency_check):
        pass


class RampupBatchsizeNumMicroBatches(NumMicroBatchesCalculator):
    """Batch-size rampup (microbatches.py:87-168): global batch grows from
    ``start_batch_size`` by ``batch_size_increment`` every
    ``rampup_samples / steps`` consumed samples."""

    def __init__(self, start_batch_size, batch_size_increment, ramup_samples,
                 global_batch_size, micro_batch_size, data_parallel_size):
        super().__init__()
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size)
        if self.micro_batch_times_data_parallel_size <= 0:
            raise AssertionError
        if start_batch_size <= 0:
            raise AssertionError
        self.start_batch_size = start_batch_size
        if global_batch_size <= 0:
            raise AssertionError
        self.global_batch_size = global_batch_size
        diff_batch_size = self.global_batch_size - self.start_batch_size
        if diff_batch_size < 0:
            raise AssertionError(
                "expected global batch size to be greater than or equal to "
                "start batch size")
        if batch_size_increment <= 0:
            raise AssertionError
        self.batch_size_increment = batch_size_increment
        if diff_batch_size % batch_size_increment != 0:
            raise AssertionError(
                "expected gbs interval ({}) to be divisible by batch size "
                "increment ({})".format(diff_batch_size, batch_size_increment))
        num_increments = diff_batch_size // self.batch_size_increment
        self.ramup_samples = ramup_samples
        if self.ramup_samples < 0:
            raise AssertionError
        self.rampup_samples_per_increment = self.ramup_samples / num_increments
        self.update(0, False)

    def update(self, consumed_samples, consistency_check):
        if consumed_samples > self.ramup_samples:
            self.current_global_batch_size = self.global_batch_size
        else:
            steps = int(consumed_samples / self.rampup_samples_per_increment)
            self.current_global_batch_size = (
                self.start_batch_size + steps * self.batch_size_increment)
            if self.current_global_batch_size > self.global_batch_size:
                raise AssertionError
        if consistency_check:
            if (self.current_global_batch_size
                    % self.micro_batch_times_data_parallel_size != 0):
                raise AssertionError(
                    "current global batch size ({}) is not divisible by "
                    "micro-batch-size ({}) times data parallel size ({})".format(
                        self.current_global_batch_size, self.micro_batch_size,
                        self.data_parallel_size))
        self.num_micro_batches = (
            self.current_global_batch_size
            // self.micro_batch_times_data_parallel_size)
