"""DP-sharded batch samplers (apex/transformer/_data/_batchsampler.py:38-160).

Framework-agnostic: they yield lists of dataset indices for this data-parallel
rank, usable with any loader (numpy, tf.data, grain, torch DataLoader).
"""

from __future__ import annotations

import numpy as np

__all__ = ["MegatronPretrainingSampler", "MegatronPretrainingRandomSampler"]


class _Base:
    def __init__(self, total_samples, consumed_samples, micro_batch_size,
                 data_parallel_rank, data_parallel_size):
        if total_samples <= 0:
            raise RuntimeError(f"no sample to consume: {total_samples}")
        if micro_batch_size <= 0:
            raise RuntimeError(f"micro_batch_size size must be greater than 0, but {micro_batch_size}")
        if data_parallel_size <= 0:
            raise RuntimeError(f"data parallel size must be greater than 0, but {data_parallel_size}")
        if data_parallel_rank >= data_parallel_size:
            raise RuntimeError(
                f"data_parallel_rank should be smaller than data size, but "
                f"{data_parallel_rank} >= {data_parallel_size}")
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self.micro_batch_size = micro_batch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size)


class MegatronPretrainingSampler(_Base):
    """Sequential DP-sharded sampler (_batchsampler.py:38-94)."""

    def __init__(self, total_samples, consumed_samples, micro_batch_size,
                 data_parallel_rank, data_parallel_size,
                 drop_last: bool = True):
        super().__init__(total_samples, consumed_samples, micro_batch_size,
                         data_parallel_rank, data_parallel_size)
        self.drop_last = drop_last

    def __len__(self):
        return self.total_samples

    def get_start_end_idx(self):
        start = self.data_parallel_rank * self.micro_batch_size
        return start, start + self.micro_batch_size

    def __iter__(self):
        batch = []
        for idx in range(self.consumed_samples, self.total_samples):
            batch.append(idx)
            if len(batch) == self.micro_batch_times_data_parallel_size:
                s, e = self.get_start_end_idx()
                yield batch[s:e]
                batch = []
        if len(batch) > 0 and not self.drop_last:
            s, e = self.get_start_end_idx()
            yield batch[s:e]


class MegatronPretrainingRandomSampler(_Base):
    """Shuffled epoch-bucketed sampler (_batchsampler.py:97-160)."""

    def __init__(self, total_samples, consumed_samples, micro_batch_size,
                 data_parallel_rank, data_parallel_size):
        super().__init__(total_samples, consumed_samples, micro_batch_size,
                         data_parallel_rank, data_parallel_size)
        self.last_batch_size = (
            self.total_samples % self.micro_batch_times_data_parallel_size)

    def __len__(self):
        return self.total_samples

    def __iter__(self):
        active_total_samples = self.total_samples - self.last_batch_size
        self.epoch = self.consumed_samples // active_total_samples
        current_epoch_samples = self.consumed_samples % active_total_samples
        if current_epoch_samples % self.micro_batch_times_data_parallel_size != 0:
            raise AssertionError

        # data sharding and random sampling
        bucket_size = ((self.total_samples // self.micro_batch_times_data_parallel_size)
                       * self.micro_batch_size)
        bucket_offset = current_epoch_samples // self.data_parallel_size
        start_idx = self.data_parallel_rank * bucket_size

        g = np.random.default_rng(self.epoch)
        random_idx = g.permutation(bucket_size).tolist()
        idx_range = [start_idx + x for x in random_idx[bucket_offset:]]

        batch = []
        for idx in idx_range:
            batch.append(idx)
            if len(batch) == self.micro_batch_size:
                self.consumed_samples += self.micro_batch_times_data_parallel_size
                yield batch
                batch = []
