"""Vocab-parallel cross entropy (apex/transformer/tensor_parallel/cross_entropy.py:23-132).

The logits' vocab dim is sharded across tp ranks; the loss is computed without
gathering the full vocab:

1. max over local shard → all-reduce(max) for stability,
2. local masked gather of the target logit → all-reduce(sum),
3. local sum(exp) → all-reduce(sum) → log,
4. loss = log(sum_exp) - target_logit, optional label smoothing
   (cross_entropy.py:85-108).

The backward (softmax - one_hot, scaled) is derived by autodiff through the
same collectives — each op here has the exact custom-vjp pairing Megatron
hand-writes in ``_VocabParallelCrossEntropy.backward``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TENSOR_PARALLEL_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import (
    reduce_from_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.utils import VocabUtility

__all__ = ["vocab_parallel_cross_entropy"]


def vocab_parallel_cross_entropy(vocab_parallel_logits, target,
                                 label_smoothing: float = 0.0,
                                 axis_name: str = TENSOR_PARALLEL_AXIS):
    """Per-token loss for [..., vocab/tp] logits and [...] int targets.

    Runs inside shard_map over the tp axis (world size 1 works too, outside).
    """
    try:
        world = jax.lax.psum(1, axis_name)
        rank = jax.lax.axis_index(axis_name)
        mapped = True
    except NameError:
        world, rank, mapped = 1, 0, False

    logits32 = vocab_parallel_logits.astype(jnp.float32)
    partition_vocab = logits32.shape[-1]

    local_max = jnp.max(logits32, axis=-1)
    if mapped:
        global_max = jax.lax.pmax(jax.lax.stop_gradient(local_max), axis_name)
    else:
        global_max = jax.lax.stop_gradient(local_max)
    # the max subtraction is for numerical stability only and carries no
    # gradient (the reference's backward likewise ignores it)
    logits32 = logits32 - global_max[..., None]

    first, last = VocabUtility.vocab_range_from_per_partition_vocab_size(
        partition_vocab, rank, world)
    in_range = jnp.logical_and(target >= first, target < last)
    masked_target = jnp.where(in_range, target - first, 0)
    target_logit = jnp.take_along_axis(
        logits32, masked_target[..., None], axis=-1)[..., 0]
    target_logit = jnp.where(in_range, target_logit, 0.0)

    exp_logits = jnp.exp(logits32)
    sum_exp = jnp.sum(exp_logits, axis=-1)
    if mapped:
        # psum with *identity* backward: the loss is replicated across tp
        # ranks and each rank backpropagates the same cotangent once (raw
        # lax.psum would re-sum cotangents — JAX's summed-loss convention —
        # quadrupling grads).  Matches _VocabParallelCrossEntropy.backward.
        target_logit = reduce_from_tensor_model_parallel_region(
            target_logit, axis_name)
        sum_exp = reduce_from_tensor_model_parallel_region(sum_exp, axis_name)

    loss = jnp.log(sum_exp) - target_logit

    if label_smoothing > 0:
        # cross_entropy.py:85-108: smoothed loss mixes in the mean log-prob
        # over the vocab.
        vocab_size = partition_vocab * world
        smoothing = label_smoothing * vocab_size / (vocab_size - 1)
        log_probs = logits32 - jnp.log(sum_exp)[..., None]
        mean_log_probs = jnp.sum(log_probs, axis=-1) / vocab_size
        if mapped:
            mean_log_probs = reduce_from_tensor_model_parallel_region(
                mean_log_probs, axis_name)
        loss = (1.0 - smoothing) * loss - smoothing * mean_log_probs

    return loss
