"""TP collective mappings with explicit forward/backward pairing.

Parity target: ``apex.transformer.tensor_parallel.mappings``
(mappings.py:141-301) — the Megatron f/g autograd functions:

| reference                                | fwd            | bwd            |
|------------------------------------------|----------------|----------------|
| _CopyToModelParallelRegion               | identity       | all-reduce     |
| _ReduceFromModelParallelRegion           | all-reduce     | identity       |
| _ScatterToModelParallelRegion            | split last dim | all-gather     |
| _GatherFromModelParallelRegion           | all-gather     | split last dim |
| _ScatterToSequenceParallelRegion         | split dim 0    | all-gather     |
| _GatherFromSequenceParallelRegion        | all-gather 0   | reduce-scatter |
| _ReduceScatterToSequenceParallelRegion   | reduce-scatter | all-gather 0   |

All functions run inside ``shard_map`` over the tp axis; the pairing is made
explicit with ``custom_vjp`` so the backward collective is exactly the one
Megatron specifies (not whatever transpose JAX would derive).  On TPU these
lower to XLA all-reduce / all-gather / reduce-scatter over ICI.
"""

from __future__ import annotations

import contextlib
import functools

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TENSOR_PARALLEL_AXIS

__all__ = [
    "copy_to_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "scatter_to_sequence_parallel_region",
    "gather_from_sequence_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
    "override_forward_allreduce",
]


def _axis(axis_name):
    return TENSOR_PARALLEL_AXIS if axis_name is None else axis_name


def _bound(axis_name) -> bool:
    """True when the tp axis is bound in the current trace (inside
    shard_map/pmap).  Unbound = world-size-1 semantics: every mapping is the
    identity, so single-chip code uses the same model unchanged."""
    try:
        jax.lax.axis_index(_axis(axis_name))
        return True
    except NameError:
        return False


def _split_my_shard(x, dim, axis_name):
    """Keep this rank's chunk of x along dim (mappings.py _split)."""
    n = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    chunk = x.shape[dim] // n
    return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=dim)


def _all_gather_dim(x, dim, axis_name):
    return jax.lax.all_gather(x, axis_name, axis=dim, tiled=True)


def _reduce_scatter_dim(x, dim, axis_name):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=dim, tiled=True)


# --- copy / reduce ---------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _copy_impl(x, axis_name=None):
    """Identity fwd / all-reduce bwd (the Megatron ``f``; mappings.py:141)."""
    return x


def _copy_fwd(x, axis_name):
    return x, None


def _copy_bwd(axis_name, _, g):
    return (jax.lax.psum(g, _axis(axis_name)),)


_copy_impl.defvjp(_copy_fwd, _copy_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _reduce_impl(x, axis_name=None):
    """All-reduce fwd / identity bwd (the Megatron ``g``; mappings.py:164)."""
    return jax.lax.psum(x, _axis(axis_name))


def _reduce_fwd(x, axis_name):
    return jax.lax.psum(x, _axis(axis_name)), None


def _reduce_bwd(axis_name, _, g):
    return (g,)


_reduce_impl.defvjp(_reduce_fwd, _reduce_bwd)


# --- last-dim scatter/gather (model-parallel region) -----------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _scatter_impl(x, axis_name=None):
    """Split last dim fwd / all-gather bwd (mappings.py:187)."""
    return _split_my_shard(x, -1, _axis(axis_name))


def _scatter_fwd(x, axis_name):
    return _split_my_shard(x, -1, _axis(axis_name)), None


def _scatter_bwd(axis_name, _, g):
    return (_all_gather_dim(g, g.ndim - 1, _axis(axis_name)),)


_scatter_impl.defvjp(_scatter_fwd, _scatter_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _gather_impl(x, axis_name=None):
    """All-gather last dim fwd / split bwd (mappings.py:200)."""
    return _all_gather_dim(x, x.ndim - 1, _axis(axis_name))


def _gather_fwd(x, axis_name):
    return _all_gather_dim(x, x.ndim - 1, _axis(axis_name)), None


def _gather_bwd(axis_name, _, g):
    return (_split_my_shard(g, -1, _axis(axis_name)),)


_gather_impl.defvjp(_gather_fwd, _gather_bwd)


# --- sequence-parallel (first-dim) region (mappings.py:213-301) ------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _sp_scatter_impl(x, axis_name=None):
    """Split dim 0 fwd / all-gather bwd (_ScatterToSequenceParallelRegion)."""
    return _split_my_shard(x, 0, _axis(axis_name))


def _sp_scatter_fwd(x, axis_name):
    return _split_my_shard(x, 0, _axis(axis_name)), None


def _sp_scatter_bwd(axis_name, _, g):
    return (_all_gather_dim(g, 0, _axis(axis_name)),)


_sp_scatter_impl.defvjp(_sp_scatter_fwd, _sp_scatter_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _sp_gather_impl(x, axis_name=None,
                                         tensor_parallel_output_grad=True):
    """All-gather dim 0 fwd; bwd is reduce-scatter (when the consumer is a
    tensor-parallel op producing partial grads) or plain split
    (_GatherFromSequenceParallelRegion, mappings.py:296)."""
    return _all_gather_dim(x, 0, _axis(axis_name))


def _sp_gather_fwd(x, axis_name, tensor_parallel_output_grad):
    return _all_gather_dim(x, 0, _axis(axis_name)), None


def _sp_gather_bwd(axis_name, tensor_parallel_output_grad, _, g):
    if tensor_parallel_output_grad:
        return (_reduce_scatter_dim(g, 0, _axis(axis_name)),)
    return (_split_my_shard(g, 0, _axis(axis_name)),)


_sp_gather_impl.defvjp(_sp_gather_fwd, _sp_gather_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _sp_rs_impl(x, axis_name=None):
    """Reduce-scatter dim 0 fwd / all-gather bwd
    (_ReduceScatterToSequenceParallelRegion)."""
    return _reduce_scatter_dim(x, 0, _axis(axis_name))


def _sp_rs_fwd(x, axis_name):
    return _reduce_scatter_dim(x, 0, _axis(axis_name)), None


def _sp_rs_bwd(axis_name, _, g):
    return (_all_gather_dim(g, 0, _axis(axis_name)),)


_sp_rs_impl.defvjp(_sp_rs_fwd, _sp_rs_bwd)


# --- public wrappers: identity when the axis is unbound (world size 1) -----


def copy_to_tensor_model_parallel_region(x, axis_name=None):
    """Identity fwd / all-reduce bwd (the Megatron ``f``; mappings.py:141)."""
    return _copy_impl(x, axis_name) if _bound(axis_name) else x


# trace-time forward-allreduce override: an opt-in replacement for the
# Megatron ``g``'s forward psum, consulted per call-site ``kind``.  The
# serving engine installs a quantized grouped-scale allreduce here for
# the per-layer Row-parallel psum pair only (kind="row_linear") —
# VocabParallelEmbedding's reduce keeps the default "generic" kind and
# stays exact.  Forward-only by contract: an override is a serving
# (inference) construct, so entering the scope around a traced autodiff
# region is rejected by construction (the override fn carries no vjp).
_FWD_ALLREDUCE_OVERRIDE: dict = {"fn": None, "kinds": ()}


@contextlib.contextmanager
def override_forward_allreduce(fn, kinds=("row_linear",)):
    """Within the scope, :func:`reduce_from_tensor_model_parallel_region`
    calls with a matching ``kind`` trace through ``fn(x, axis_name)``
    instead of the exact psum.  Trace-time state: wrap the *tracing* of
    a program (e.g. a ``shard_map`` body under ``jit``), not its
    execution.  Not reentrant with a different fn on purpose — nested
    conflicting overrides would make the traced collective ambiguous."""
    prev = dict(_FWD_ALLREDUCE_OVERRIDE)
    if (_FWD_ALLREDUCE_OVERRIDE["fn"] is not None
            and _FWD_ALLREDUCE_OVERRIDE["fn"] is not fn):
        raise RuntimeError(
            "override_forward_allreduce is already active with a "
            "different replacement — nested conflicting overrides are "
            "not supported")
    _FWD_ALLREDUCE_OVERRIDE["fn"] = fn
    _FWD_ALLREDUCE_OVERRIDE["kinds"] = tuple(kinds)
    try:
        yield
    finally:
        _FWD_ALLREDUCE_OVERRIDE.update(prev)


def reduce_from_tensor_model_parallel_region(x, axis_name=None, *,
                                             kind="generic"):
    """All-reduce fwd / identity bwd (the Megatron ``g``; mappings.py:164).

    ``kind`` names the call site for the opt-in forward override
    (:func:`override_forward_allreduce`): Row-parallel linears tag their
    psum ``"row_linear"``; everything else defaults to ``"generic"``
    and always takes the exact psum.
    """
    if not _bound(axis_name):
        return x
    fn = _FWD_ALLREDUCE_OVERRIDE["fn"]
    if fn is not None and kind in _FWD_ALLREDUCE_OVERRIDE["kinds"]:
        return fn(x, _axis(axis_name))
    return _reduce_impl(x, axis_name)


def scatter_to_tensor_model_parallel_region(x, axis_name=None):
    """Split last dim fwd / all-gather bwd (mappings.py:187)."""
    return _scatter_impl(x, axis_name) if _bound(axis_name) else x


def gather_from_tensor_model_parallel_region(x, axis_name=None):
    """All-gather last dim fwd / split bwd (mappings.py:200)."""
    return _gather_impl(x, axis_name) if _bound(axis_name) else x


def scatter_to_sequence_parallel_region(x, axis_name=None):
    """Split dim 0 fwd / all-gather bwd (_ScatterToSequenceParallelRegion)."""
    return _sp_scatter_impl(x, axis_name) if _bound(axis_name) else x


def gather_from_sequence_parallel_region(x, axis_name=None,
                                         tensor_parallel_output_grad=True):
    """All-gather dim 0 fwd; reduce-scatter (or split) bwd
    (_GatherFromSequenceParallelRegion, mappings.py:296)."""
    if not _bound(axis_name):
        return x
    return _sp_gather_impl(x, axis_name, tensor_parallel_output_grad)


def reduce_scatter_to_sequence_parallel_region(x, axis_name=None):
    """Reduce-scatter dim 0 fwd / all-gather bwd
    (_ReduceScatterToSequenceParallelRegion)."""
    return _sp_rs_impl(x, axis_name) if _bound(axis_name) else x
