"""TP utility helpers (apex/transformer/tensor_parallel/utils.py parity)."""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp


def ensure_divisibility(numerator: int, denominator: int) -> None:
    if numerator % denominator != 0:
        raise AssertionError(f"{numerator} is not divisible by {denominator}")


def divide(numerator: int, denominator: int) -> int:
    ensure_divisibility(numerator, denominator)
    return numerator // denominator


def split_tensor_along_last_dim(tensor, num_partitions: int):
    """Split along the last dim into equal chunks (utils.py split_tensor...)."""
    last = tensor.shape[-1]
    chunk = divide(last, num_partitions)
    return tuple(
        jnp.take(tensor, jnp.arange(i * chunk, (i + 1) * chunk), axis=-1)
        for i in range(num_partitions)
    )


class VocabUtility:
    """Vocab-range bookkeeping for the vocab-parallel embedding/xent
    (utils.py VocabUtility)."""

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(
            per_partition_vocab_size: int, rank, world_size: int) -> Tuple:
        """[first, last) global vocab ids owned by ``rank`` given the
        per-rank partition size."""
        first = rank * per_partition_vocab_size
        return first, first + per_partition_vocab_size

    @staticmethod
    def vocab_range_from_global_vocab_size(global_vocab_size: int, rank,
                                           world_size: int) -> Tuple:
        """[first, last) global vocab ids owned by ``rank``; the global size
        must divide evenly (same contract as the reference)."""
        per_partition = divide(global_vocab_size, world_size)
        return VocabUtility.vocab_range_from_per_partition_vocab_size(
            per_partition, rank, world_size)
