"""Tensor-parallel layers: Column/Row linear + vocab-parallel embedding.

Parity target: ``apex.transformer.tensor_parallel.layers``
(layers.py:174-813): ``VocabParallelEmbedding``, ``ColumnParallelLinear``,
``RowParallelLinear`` built on ``LinearWithGradAccumulationAndAsyncCommunication``
(layers.py:279-438).

TPU-native design: the layers are flax modules meant to run **inside
shard_map over the tp axis** — each rank holds its weight shard and the
forward/backward collectives are the explicit custom-vjp mappings
(:mod:`.mappings`), giving exactly Megatron's communication schedule:

- column fwd: identity (or SP all-gather, layers.py:311-325); bwd: grad-input
  all-reduce (or SP reduce-scatter, layers.py:379-412).
- row fwd: all-reduce (or SP reduce-scatter); bwd: identity.

What does NOT carry over, by design (SURVEY.md §7 "wgrad accumulation"):

- ``gradient_accumulation_fusion`` / ``main_grad`` (layers.py:413-425): JAX
  grads are functional; accumulation into a persistent fp32 buffer is the
  optimizer/accumulator's job and XLA fuses the wgrad GEMM with the add when
  the buffer is donated.  The flag is accepted and ignored.
- async-communication overlap (layers.py:345-376): XLA's latency-hiding
  scheduler overlaps the all-gather/reduce-scatter with the wgrad GEMMs; the
  ``no_async_tensor_model_parallel_allreduce`` knob is accepted and ignored.

Neither claim is taken on faith: ``tests/test_hlo_comm_plan.py`` compiles
this MLP fwd+bwd and asserts, on the optimized HLO, the exact Megatron
collective plan (SP: 2 all-gather + 2 reduce-scatter, zero all-reduce;
plain TP: 2 all-reduce) and that the wgrads survive as single dot
contractions (bf16-operand on TPU).

Weight shards are initialized with a rank-folded RNG so the full (gathered)
weight matches a single full-size initialization draw pattern
(_initialize_affine_weight_gpu's per-rank seed, random.py:124-235 semantics).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

import flax.linen as nn

from apex_tpu.transformer.parallel_state import TENSOR_PARALLEL_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.utils import VocabUtility, divide

__all__ = [
    "ColumnParallelLinear",
    "RowParallelLinear",
    "VocabParallelEmbedding",
]


def maybe_axis_index(axis_name: str):
    """axis_index if inside a mapped context over ``axis_name``, else None."""
    try:
        return jax.lax.axis_index(axis_name)
    except NameError:
        return None


def _tp_size(axis_name: str) -> int:
    """Static tp world size: the mapped axis size when inside shard_map over
    ``axis_name``, else 1 (single-chip semantics, even when a global mesh
    exists — binding, not mesh presence, decides)."""
    if maybe_axis_index(axis_name) is None:
        return 1
    # psum of a literal is evaluated statically (the idiom
    # parallel.distributed._bound_axis_size uses); jax 0.4.x has no
    # jax.lax.axis_size
    return int(jax.lax.psum(1, axis_name))


def _shard_init(init_fn: Callable, axis_name: str) -> Callable:
    """Fold the tp rank into the RNG so shards draw independent values."""

    def wrapped(key, shape, dtype):
        idx = maybe_axis_index(axis_name)
        if idx is not None:
            key = jax.random.fold_in(key, idx)
        return init_fn(key, shape, dtype)

    return wrapped


def _matmul(x, kernel):
    precision = (jax.lax.Precision.HIGHEST
                 if x.dtype == jnp.float32 else jax.lax.Precision.DEFAULT)
    return jax.lax.dot_general(
        x, kernel, (((x.ndim - 1,), (0,)), ((), ())),
        precision=precision, preferred_element_type=jnp.float32).astype(x.dtype)


class ColumnParallelLinear(nn.Module):
    """Y = XA + b with A sharded along its output (column) dim
    (layers.py:460-640).

    Input is replicated across tp ranks (or sequence-sharded when
    ``sequence_parallel_enabled``); output is the rank's column shard unless
    ``gather_output``.
    """

    input_size: int
    output_size: int
    use_bias: bool = True
    gather_output: bool = True
    init_method: Callable = nn.initializers.lecun_normal()
    skip_bias_add: bool = False
    sequence_parallel_enabled: bool = False
    no_async_tensor_model_parallel_allreduce: bool = False  # accepted, unused
    gradient_accumulation_fusion: bool = False  # accepted, unused (see module doc)
    params_dtype: Any = jnp.float32
    axis_name: str = TENSOR_PARALLEL_AXIS

    @nn.compact
    def __call__(self, x):
        world = _tp_size(self.axis_name)
        out_per_rank = divide(self.output_size, world)
        kernel = self.param(
            "kernel", _shard_init(self.init_method, self.axis_name),
            (self.input_size, out_per_rank), self.params_dtype)
        bias = (self.param("bias", nn.initializers.zeros, (out_per_rank,),
                           self.params_dtype) if self.use_bias else None)

        if self.sequence_parallel_enabled:
            if world > 1:
                x = gather_from_sequence_parallel_region(
                    x, self.axis_name, True)
        elif world > 1:
            x = copy_to_tensor_model_parallel_region(x, self.axis_name)

        y = _matmul(x, kernel.astype(x.dtype))
        if bias is not None and not self.skip_bias_add:
            y = y + bias.astype(y.dtype)

        if self.gather_output:
            if self.sequence_parallel_enabled:
                raise RuntimeError(
                    "gather_output is incompatible with sequence parallelism"
                )  # layers.py:520 same constraint
            if world > 1:
                y = gather_from_tensor_model_parallel_region(y, self.axis_name)

        if self.skip_bias_add:
            return y, bias
        return y


class RowParallelLinear(nn.Module):
    """Y = XA + b with A sharded along its input (row) dim (layers.py:660-813).

    Input is expected already split along its last dim across tp ranks
    (``input_is_parallel``, the usual case after a column-parallel layer);
    output is all-reduced (or reduce-scattered under sequence parallelism).
    """

    input_size: int
    output_size: int
    use_bias: bool = True
    input_is_parallel: bool = False
    init_method: Callable = nn.initializers.lecun_normal()
    skip_bias_add: bool = False
    sequence_parallel_enabled: bool = False
    gradient_accumulation_fusion: bool = False  # accepted, unused
    params_dtype: Any = jnp.float32
    axis_name: str = TENSOR_PARALLEL_AXIS

    @nn.compact
    def __call__(self, x):
        world = _tp_size(self.axis_name)
        in_per_rank = divide(self.input_size, world)
        kernel = self.param(
            "kernel", _shard_init(self.init_method, self.axis_name),
            (in_per_rank, self.output_size), self.params_dtype)
        bias = (self.param("bias", nn.initializers.zeros, (self.output_size,),
                           self.params_dtype) if self.use_bias else None)

        if not self.input_is_parallel:
            if self.sequence_parallel_enabled:
                raise RuntimeError(
                    "To enable `sequence_parallel_enabled`, "
                    "`input_is_parallel` must be `True`")  # layers.py:720
            if world > 1:
                x = scatter_to_tensor_model_parallel_region(x, self.axis_name)

        y = _matmul(x, kernel.astype(x.dtype))
        if world > 1:
            if self.sequence_parallel_enabled:
                y = reduce_scatter_to_sequence_parallel_region(y, self.axis_name)
            else:
                # tagged "row_linear": this is the per-layer psum pair
                # (attention o_proj + MLP down_proj) the serving quant
                # subsystem may override with a grouped-scale int8
                # allreduce; the embedding/logits reduces stay "generic"
                # and therefore always exact
                y = reduce_from_tensor_model_parallel_region(
                    y, self.axis_name, kind="row_linear")

        if self.skip_bias_add:
            return y, bias
        if bias is not None:
            y = y + bias.astype(y.dtype)
        return y


class VocabParallelEmbedding(nn.Module):
    """Embedding with the vocab dim sharded across tp ranks
    (layers.py:174-278): masked local lookup + all-reduce.
    """

    num_embeddings: int
    embedding_dim: int
    # Megatron's init_method_normal(0.02) default (arguments.py init-method-std)
    init_method: Callable = nn.initializers.normal(stddev=0.02)
    params_dtype: Any = jnp.float32
    axis_name: str = TENSOR_PARALLEL_AXIS

    @nn.compact
    def __call__(self, ids):
        world = _tp_size(self.axis_name)
        per_rank = divide(self.num_embeddings, world)
        weight = self.param(
            "embedding", _shard_init(self.init_method, self.axis_name),
            (per_rank, self.embedding_dim), self.params_dtype)

        if world == 1:
            return jnp.take(weight, ids, axis=0)

        rank = jax.lax.axis_index(self.axis_name)
        first, last = VocabUtility.vocab_range_from_per_partition_vocab_size(
            per_rank, rank, world)
        in_range = jnp.logical_and(ids >= first, ids < last)
        masked = jnp.where(in_range, ids - first, 0)
        out = jnp.take(weight, masked, axis=0)
        out = jnp.where(in_range[..., None], out, 0.0)
        return reduce_from_tensor_model_parallel_region(out, self.axis_name)


def parallel_lm_logits(hidden, word_embeddings, axis_name: str = TENSOR_PARALLEL_AXIS,
                       sequence_parallel_enabled: bool = False,
                       gather_output: bool = False):
    """Logits = H @ E^T with E vocab-sharded (the reference's
    parallel_lm_logits): output is [s, b, vocab/tp] unless gathered."""
    from apex_tpu.transformer.tensor_parallel.mappings import (
        copy_to_tensor_model_parallel_region,
        gather_from_sequence_parallel_region,
        gather_from_tensor_model_parallel_region,
    )

    if sequence_parallel_enabled:
        hidden = gather_from_sequence_parallel_region(hidden, axis_name, True)
    else:
        hidden = copy_to_tensor_model_parallel_region(hidden, axis_name)
    logits = jax.lax.dot_general(
        hidden, word_embeddings,
        (((hidden.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if gather_output:
        logits = gather_from_tensor_model_parallel_region(logits, axis_name)
    return logits


# public names for model composition (apex_tpu.models builds on these)
tp_world_size = _tp_size
shard_init = _shard_init
