"""apex_tpu.transformer.tensor_parallel — Megatron TP primitives on a mesh.

Parity: apex/transformer/tensor_parallel/__init__.py export surface.
"""

from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.data import broadcast_data
from apex_tpu.transformer.tensor_parallel.layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    parallel_lm_logits,
    shard_init,
    tp_world_size,
)
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.memory import MemoryBuffer, RingMemBuffer
from apex_tpu.transformer.tensor_parallel.random import (
    RNGStatesTracker,
    checkpoint,
    get_rng_state_tracker,
    model_parallel_cuda_manual_seed,
    model_parallel_seed,
)
from apex_tpu.transformer.tensor_parallel.utils import (
    VocabUtility,
    divide,
    split_tensor_along_last_dim,
)

__all__ = [
    "vocab_parallel_cross_entropy",
    "broadcast_data",
    "ColumnParallelLinear",
    "parallel_lm_logits",
    "shard_init",
    "tp_world_size",
    "RowParallelLinear",
    "VocabParallelEmbedding",
    "copy_to_tensor_model_parallel_region",
    "gather_from_sequence_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
    "scatter_to_sequence_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "MemoryBuffer",
    "RingMemBuffer",
    "RNGStatesTracker",
    "checkpoint",
    "get_rng_state_tracker",
    "model_parallel_cuda_manual_seed",
    "model_parallel_seed",
    "VocabUtility",
    "divide",
    "split_tensor_along_last_dim",
]
