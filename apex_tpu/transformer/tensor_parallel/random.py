"""RNG state tracking + activation checkpointing for model parallelism.

Parity target: ``apex.transformer.tensor_parallel.random`` (random.py:48-330):

- ``CudaRNGStatesTracker`` — named RNG states, forked per tp rank so dropout
  inside model-parallel regions differs across ranks while data-parallel
  regions agree (``model_parallel_cuda_manual_seed``: tp state seeded with
  ``seed + 2718 + tp_rank``, random.py:124-235).
- ``checkpoint`` / ``CheckpointFunction`` — activation checkpointing with RNG
  fork/restore and optional sharded saved-activations
  (distribute_saved_activations, random.py:237-330).

TPU-native design: JAX RNG is already explicit and functional, so the tracker
manages *keys*, not device state — forking is ``jax.random.fold_in`` and
"restore" is simply reusing the same key, which makes checkpoint-recompute
determinism automatic (the property the reference needs fork/restore for).
Activation checkpointing maps to ``jax.checkpoint`` (rematerialization);
``distribute_saved_activations`` corresponds to saving the inputs sharded
over tp, which under sequence parallelism is the layout already.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TENSOR_PARALLEL_AXIS
from apex_tpu.transformer.tensor_parallel.layers import maybe_axis_index

__all__ = [
    "RNGStatesTracker",
    "CudaRNGStatesTracker",  # alias for API familiarity
    "get_rng_state_tracker",
    "model_parallel_seed",
    "model_parallel_cuda_manual_seed",  # alias
    "checkpoint",
]

_MODEL_PARALLEL_RNG = "model-parallel-rng"
_DATA_PARALLEL_RNG = "data-parallel-rng"
# the reference's magic offset (random.py:189: tensor_model_parallel_seed =
# offset + tensor_model_parallel_rank with offset = seed + 2718)
_TP_SEED_OFFSET = 2718


class RNGStatesTracker:
    """Named jax.random keys with fork semantics (CudaRNGStatesTracker parity).

    ``add(name, seed)`` registers a stream; ``fork(name)`` yields a fresh
    subkey each use while keeping streams independent; ``get_states``/
    ``set_states`` snapshot for checkpointing (random.py:48-123).
    """

    def __init__(self):
        self.states_: Dict[str, jax.Array] = {}
        self.counters_: Dict[str, int] = {}

    def reset(self):
        """Drop every tracked RNG state (random.py reset parity)."""
        self.states_.clear()
        self.counters_.clear()

    def get_states(self) -> Dict[str, Any]:
        """Snapshot of all tracked keys/counters (checkpointable)."""
        return {"keys": dict(self.states_), "counters": dict(self.counters_)}

    def set_states(self, states: Dict[str, Any]) -> None:
        """Restore a :meth:`get_states` snapshot (exact-trajectory resume)."""
        self.states_ = dict(states["keys"])
        self.counters_ = dict(states["counters"])

    def add(self, name: str, seed) -> None:
        """Register a named RNG stream from an int seed or PRNG key; the
        tensor-model-parallel stream is seeded per-rank (random.py:
        model_parallel_cuda_manual_seed parity)."""
        if name in self.states_:
            raise Exception(f"cuda rng state {name} already exists")
        if isinstance(seed, int):
            key = jax.random.PRNGKey(seed)
        else:
            key = seed
        self.states_[name] = key
        self.counters_[name] = 0

    @contextlib.contextmanager
    def fork(self, name: str = _MODEL_PARALLEL_RNG):
        """Yield a fresh subkey of the named stream (random.py fork ctx).

        In place of save/restore of device RNG state, each fork yields
        ``fold_in(key, counter)`` and bumps the counter — deterministic and
        jit-friendly.
        """
        if name not in self.states_:
            raise Exception(f"cuda rng state {name} is not added")
        key = jax.random.fold_in(self.states_[name], self.counters_[name])
        self.counters_[name] += 1
        yield key


CudaRNGStatesTracker = RNGStatesTracker

_GLOBAL_TRACKER = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    """random.py get_cuda_rng_tracker parity."""
    return _GLOBAL_TRACKER


def model_parallel_seed(seed: int, axis_name: str = TENSOR_PARALLEL_AXIS) -> None:
    """Install the two canonical streams (random.py:124-235).

    - data-parallel stream: same ``seed`` on every rank.
    - model-parallel stream: ``seed + 2718`` folded with the tp rank, so
      dropout in tp regions decorrelates across shards.  When called outside
      a mapped context the fold happens lazily at first use inside one.
    """
    _GLOBAL_TRACKER.reset()
    _GLOBAL_TRACKER.add(_DATA_PARALLEL_RNG, seed)
    base = jax.random.PRNGKey(seed + _TP_SEED_OFFSET)
    idx = maybe_axis_index(axis_name)
    if idx is not None:
        base = jax.random.fold_in(base, idx)
    _GLOBAL_TRACKER.add(_MODEL_PARALLEL_RNG, base)


model_parallel_cuda_manual_seed = model_parallel_seed


def checkpoint(fn: Callable, distribute_saved_activations: bool = False,
               *args, policy: Optional[Callable] = None):
    """Activation checkpointing (random.py:237-330 CheckpointFunction).

    ``jax.checkpoint`` recomputes ``fn`` in backward; determinism of any
    RNG use inside comes from explicit keys (pass them as args), replacing
    the reference's RNG fork/restore dance.  ``distribute_saved_activations``
    saved the input sharded over tp; with sequence parallelism the input
    already lives sharded, so the flag only selects a remat policy that
    prefers offloading nothing extra.
    """
    ckpt = jax.checkpoint(fn, policy=policy)
    return ckpt(*args)
