"""Pre-allocated memory buffers (apex/transformer/tensor_parallel/memory.py:25-168).

The reference's ``MemoryBuffer``/``RingMemBuffer`` exist because torch's
caching allocator fragments under Megatron's allocation pattern; XLA owns TPU
memory and donation/aliasing removes the need.  The classes are provided for
API parity: ``MemoryBuffer`` hands out views of one flat array (useful for
packed optimizer state), ``RingMemBuffer`` rotates over N of them.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np


class MemoryBuffer:
    def __init__(self, name: str, numel: int, dtype):
        self.name = name
        self.numel = numel
        self.dtype = dtype
        self.data = jnp.zeros((numel,), dtype)
        self._offset = 0

    def reset(self):
        """Rewind the bump-allocator offset; existing views stay valid."""
        self._offset = 0

    def get(self, shape: Tuple[int, ...]):
        """A view-sized slice of the flat buffer (memory.py:79-96)."""
        size = int(np.prod(shape))
        if self._offset + size > self.numel:
            raise AssertionError("MemoryBuffer out of space")
        out = self.data[self._offset:self._offset + size].reshape(shape)
        self._offset += size
        return out


class RingMemBuffer:
    def __init__(self, name: str, num_buffers: int, numel: int, dtype):
        self.num_buffers = num_buffers
        self.buffers = [
            MemoryBuffer(f"{name} {i}", numel, dtype) for i in range(num_buffers)
        ]
        self._index = -1

    def get_next_buffer(self) -> MemoryBuffer:
        """Round-robin to the next buffer in the ring and reset it."""
        self._index = (self._index + 1) % self.num_buffers
        buf = self.buffers[self._index]
        buf.reset()
        return buf
