"""Batch broadcast across the tensor-parallel group
(apex/transformer/tensor_parallel/data.py:80 ``broadcast_data``).

The reference moves the batch to rank 0 of each tp group and broadcasts
(keys/sizes/flattened payload).  On TPU, data fed through
``jax.device_put`` with a sharding that replicates over tp IS the broadcast —
XLA materializes one copy per tp rank.  These helpers provide the same API
for explicit shard_map code, plus the sharding constructor for pjit code.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.transformer.parallel_state import (
    DATA_PARALLEL_AXIS,
    TENSOR_PARALLEL_AXIS,
    get_mesh,
)

__all__ = ["broadcast_data", "tp_replicated_sharding"]


def broadcast_data(keys, data: Dict[str, Any], datatype=None,
                   axis_name: str = TENSOR_PARALLEL_AXIS) -> Dict[str, Any]:
    """Make every tp rank see rank 0's values (inside shard_map).

    Under jit the broadcast compiles away when the operand is already
    replicated — matching the reference's intent (one host read per tp
    group), not its mechanism.
    """
    out = {}
    for k in keys:
        v = jnp.asarray(data[k], datatype)
        gathered = jax.lax.all_gather(v, axis_name)
        out[k] = gathered[0]
    return out


def tp_replicated_sharding(batch_dim_over_dp: bool = True) -> NamedSharding:
    """Sharding for input batches: dim 0 over dp, replicated over tp/pp."""
    mesh = get_mesh()
    spec = P(DATA_PARALLEL_AXIS) if batch_dim_over_dp else P()
    return NamedSharding(mesh, spec)
