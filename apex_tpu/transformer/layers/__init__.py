"""apex_tpu.transformer.layers — norm layers aware of sequence parallelism.

Parity: ``apex.transformer.layers.FusedLayerNorm``
(layers/layer_norm.py:26-88): a LayerNorm whose params are tagged
``sequence_parallel_enabled`` so the trainer all-reduces their grads across
the TP group (under SP, each rank sees only s/tp of the tokens, so LN param
grads are partial sums).

TPU design: the tagging mechanism becomes explicit — the module reduces its
*gradient contributions* via the custom-vjp trick below instead of asking the
trainer to find tagged params: a ``psum``-in-backward wrapper around the
params makes the grads come out already reduced, which composes with any
optimizer.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

import flax.linen as nn

from apex_tpu.normalization import FusedLayerNorm as _BaseLayerNorm
from apex_tpu.ops.layer_norm import fused_layer_norm_affine
from apex_tpu.transformer.parallel_state import TENSOR_PARALLEL_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
)

__all__ = ["FusedLayerNorm"]


class FusedLayerNorm(nn.Module):
    """LayerNorm for (optionally) sequence-parallel activations.

    With ``sequence_parallel_enabled`` the input is [s/tp, b, h] per rank;
    normalization is per-token so the forward needs no communication, and the
    weight/bias grads are all-reduced across tp in backward via
    ``copy_to_tensor_model_parallel_region`` applied to the params (identity
    fwd / psum bwd — exactly the grad-sync the reference defers to the
    trainer, layer_norm.py:26-52).
    """

    hidden_size: int
    eps: float = 1e-5
    memory_efficient: bool = False
    sequence_parallel_enabled: bool = False
    axis_name: str = TENSOR_PARALLEL_AXIS
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        weight = self.param("scale", nn.initializers.ones,
                            (self.hidden_size,), self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.hidden_size,), self.param_dtype)
        if self.sequence_parallel_enabled:
            weight = copy_to_tensor_model_parallel_region(weight, self.axis_name)
            bias = copy_to_tensor_model_parallel_region(bias, self.axis_name)
        return fused_layer_norm_affine(x, weight, bias, (self.hidden_size,),
                                       self.eps,
                                       memory_efficient=self.memory_efficient)
