"""apex_tpu.transformer — Megatron-style model parallelism on a jax Mesh.

Parity target: ``apex.transformer`` (SURVEY.md §2.3 L6): parallel_state,
tensor_parallel, pipeline_parallel, microbatches, amp.GradScaler, functional
(fused softmax/rope), layers, _data.
"""

import importlib as _importlib

from apex_tpu.transformer import parallel_state  # light, always loaded
from apex_tpu.transformer.enums import AttnMaskType, AttnType, LayerType, ModelType

_SUBMODULES = (
    "tensor_parallel",
    "pipeline_parallel",
    "context_parallel",
    "moe",
    "functional",
    "layers",
    "amp",
    "testing",
    "_data",
    "log_util",
)


def __getattr__(name):
    if name in _SUBMODULES:
        module = _importlib.import_module(f"apex_tpu.transformer.{name}")
        globals()[name] = module
        return module
    raise AttributeError(f"module 'apex_tpu.transformer' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals().keys()) + list(_SUBMODULES))
