"""Model-parallel topology state — the Megatron ``mpu`` on a jax Mesh.

Parity target: ``apex.transformer.parallel_state`` (parallel_state.py:155-760):
``initialize_model_parallel(tp, pp, vpp)`` builds TP/PP/DP (+embedding)
process groups from the world; getters expose per-rank group handles, ranks,
and world sizes; virtual-pipeline rank state lives here too.

TPU-native design (SURVEY.md §2.5): ONE ``jax.sharding.Mesh`` with axes
``('dp', 'pp', 'tp')`` replaces every process group.  Axis order encodes the
topology the reference configures by hand with ``NUM_GPUS_PER_IB_BLOCK`` /
NCCL_NET routing: the *last* mesh axis maps to the fastest (most-adjacent)
device dimension, so ``tp`` rides intra-slice ICI while ``dp`` spans the
slower (DCN) dimension — the same placement Megatron's rank-ordering achieves.
Group getters become axis names (for ``shard_map`` collectives) and mesh-shape
queries; *rank* getters are traced values (``lax.axis_index``) only meaningful
inside a mapped context, exactly like the reference's getters are only
meaningful after ``init_process_group``.

Multi-host: call :func:`initialize_distributed` (wraps
``jax.distributed.initialize``) first; the mesh then spans all hosts'
devices.  ``default_backend``/``p2p_backend`` (UCC vs NCCL, parallel_state.py
:162-211) have no TPU meaning — ICI/DCN routing is the mesh layout — so they
are accepted and ignored.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis names (the reference's group names)
DATA_PARALLEL_AXIS = "dp"
PIPELINE_PARALLEL_AXIS = "pp"
TENSOR_PARALLEL_AXIS = "tp"

# Module-level state, mirroring the reference's group globals
# (parallel_state.py:31-66).
_MESH: Optional[Mesh] = None
_VIRTUAL_PIPELINE_WORLD_SIZE: Optional[int] = None
_VIRTUAL_PIPELINE_RANK: Optional[int] = None
_PIPELINE_SPLIT_RANK: Optional[int] = None


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host init (``torch.distributed.init_process_group`` analog).

    Wraps ``jax.distributed.initialize``; on single-host or when the TPU
    runtime auto-detects the topology, it is a no-op-safe call.
    """
    try:
        jax.distributed.initialize(coordinator_address, num_processes, process_id)
    except (ValueError, RuntimeError):
        # already initialized, or single-process run
        pass


def initialize_model_parallel(
    tensor_model_parallel_size_: int = 1,
    pipeline_model_parallel_size_: int = 1,
    virtual_pipeline_model_parallel_size_: Optional[int] = None,
    pipeline_model_parallel_split_rank_: Optional[int] = None,
    *,
    devices: Optional[Sequence] = None,
    default_backend: Optional[str] = None,
    p2p_backend: Optional[str] = None,
) -> Mesh:
    """Build and install the global ('dp','pp','tp') mesh.

    Parity: parallel_state.py:155-418.  world = dp × pp × tp must divide the
    device count exactly, with the same validation errors.  Device order maps
    tp to the innermost (fastest/ICI-adjacent) axis.
    """
    global _MESH, _VIRTUAL_PIPELINE_WORLD_SIZE, _VIRTUAL_PIPELINE_RANK
    global _PIPELINE_SPLIT_RANK

    del default_backend, p2p_backend  # no TPU meaning; see module docstring
    devs = list(devices) if devices is not None else list(jax.devices())
    world = len(devs)
    tp = tensor_model_parallel_size_
    pp = pipeline_model_parallel_size_
    if world % (tp * pp) != 0:
        raise RuntimeError(
            f"world size ({world}) is not divisible by tensor parallel size "
            f"({tp}) times pipeline parallel size ({pp})")
    dp = world // (tp * pp)
    if virtual_pipeline_model_parallel_size_ is not None and pp < 2:
        raise RuntimeError(
            "pipeline-model-parallel size should be greater than 1 with "
            "interleaved schedule")
    _VIRTUAL_PIPELINE_WORLD_SIZE = virtual_pipeline_model_parallel_size_
    _VIRTUAL_PIPELINE_RANK = 0 if virtual_pipeline_model_parallel_size_ else None
    _PIPELINE_SPLIT_RANK = pipeline_model_parallel_split_rank_

    # Megatron rank order is tp-fastest, then dp, then pp
    # (parallel_state.py:237-266: tp groups are contiguous ranks).  jax
    # device order is ICI-adjacent-first, so tp must be the *last* mesh dim.
    arr = np.array(devs).reshape(pp, dp, tp).transpose(1, 0, 2)  # (dp, pp, tp)
    _MESH = Mesh(arr, (DATA_PARALLEL_AXIS, PIPELINE_PARALLEL_AXIS,
                       TENSOR_PARALLEL_AXIS))
    return _MESH


def model_parallel_is_initialized() -> bool:
    return _MESH is not None


def get_mesh() -> Mesh:
    if _MESH is None:
        raise AssertionError("model parallel mesh is not initialized")
    return _MESH


def destroy_model_parallel() -> None:
    """parallel_state.py:761 parity."""
    global _MESH, _VIRTUAL_PIPELINE_WORLD_SIZE, _VIRTUAL_PIPELINE_RANK
    global _PIPELINE_SPLIT_RANK
    _MESH = None
    _VIRTUAL_PIPELINE_WORLD_SIZE = None
    _VIRTUAL_PIPELINE_RANK = None
    _PIPELINE_SPLIT_RANK = None


# --- "group" getters: axis names for shard_map collectives -----------------


def get_tensor_model_parallel_group() -> str:
    get_mesh()
    return TENSOR_PARALLEL_AXIS


def get_pipeline_model_parallel_group() -> str:
    get_mesh()
    return PIPELINE_PARALLEL_AXIS


def get_data_parallel_group() -> str:
    get_mesh()
    return DATA_PARALLEL_AXIS


def get_embedding_group() -> str:
    """First+last pp stages share embedding grads (parallel_state.py:282-305).

    On a mesh this is not a separate group: the embedding-grad allreduce is a
    masked psum over the pp axis (see pipeline_parallel.utils).
    """
    return PIPELINE_PARALLEL_AXIS


# --- world sizes (static, from mesh shape) ---------------------------------


def get_tensor_model_parallel_world_size() -> int:
    return get_mesh().shape[TENSOR_PARALLEL_AXIS]


def get_pipeline_model_parallel_world_size() -> int:
    return get_mesh().shape[PIPELINE_PARALLEL_AXIS]


def get_data_parallel_world_size() -> int:
    return get_mesh().shape[DATA_PARALLEL_AXIS]


def get_model_parallel_world_size() -> int:
    return get_tensor_model_parallel_world_size() * get_pipeline_model_parallel_world_size()


# --- ranks (traced; valid inside shard_map/pmap over the mesh) -------------


def get_tensor_model_parallel_rank():
    return jax.lax.axis_index(TENSOR_PARALLEL_AXIS)


def get_pipeline_model_parallel_rank():
    return jax.lax.axis_index(PIPELINE_PARALLEL_AXIS)


def get_data_parallel_rank():
    return jax.lax.axis_index(DATA_PARALLEL_AXIS)


def is_pipeline_first_stage(ignore_virtual: bool = False):
    """Traced predicate (parallel_state.py:589-610)."""
    if not ignore_virtual and _VIRTUAL_PIPELINE_WORLD_SIZE is not None:
        if _VIRTUAL_PIPELINE_RANK != 0:
            return False
    return get_pipeline_model_parallel_rank() == 0


def is_pipeline_last_stage(ignore_virtual: bool = False):
    if not ignore_virtual and _VIRTUAL_PIPELINE_WORLD_SIZE is not None:
        if _VIRTUAL_PIPELINE_RANK != _VIRTUAL_PIPELINE_WORLD_SIZE - 1:
            return False
    return (get_pipeline_model_parallel_rank()
            == get_pipeline_model_parallel_world_size() - 1)


# --- virtual pipeline state (parallel_state.py:54-55, 675-697) -------------


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _VIRTUAL_PIPELINE_WORLD_SIZE


def get_virtual_pipeline_model_parallel_rank() -> Optional[int]:
    return _VIRTUAL_PIPELINE_RANK


def set_virtual_pipeline_model_parallel_rank(rank: int) -> None:
    global _VIRTUAL_PIPELINE_RANK
    _VIRTUAL_PIPELINE_RANK = rank


def get_pipeline_model_parallel_split_rank() -> Optional[int]:
    return _PIPELINE_SPLIT_RANK


def set_pipeline_model_parallel_split_rank(rank: int) -> None:
    global _PIPELINE_SPLIT_RANK
    _PIPELINE_SPLIT_RANK = rank


def get_rank_info() -> str:
    """Short rank descriptor for logging (parallel_state.py get_rank_info)."""
    if _MESH is None:
        return "uninitialized"
    return (f"mesh(dp={get_data_parallel_world_size()}, "
            f"pp={get_pipeline_model_parallel_world_size()}, "
            f"tp={get_tensor_model_parallel_world_size()}), "
            f"process={jax.process_index()}")


def mesh_axis_sizes() -> Optional[dict]:
    """``{'dp': N, 'pp': N, 'tp': N}`` of the installed mesh, or None.

    The machine-readable companion of :func:`get_rank_info` — checkpoint
    manifests and orchestrator heartbeats embed this so an external
    restart can tell *which* mesh shape wrote a file without parsing
    prose (elastic restarts resume onto whatever slice is available).
    """
    if _MESH is None:
        return None
    return {name: int(size) for name, size in _MESH.shape.items()}
