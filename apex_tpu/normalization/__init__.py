"""apex_tpu.normalization — Fused LayerNorm / RMSNorm.

Parity target: ``apex.normalization`` (apex/normalization/fused_layer_norm.py:16-472)
— ``FusedLayerNorm`` / ``FusedRMSNorm`` modules, the ``Mixed*`` Megatron-compat
mixed-dtype subclasses, and the functional forms — backed here by the Pallas
kernels in :mod:`apex_tpu.ops.layer_norm` with a jnp fallback (the reference
falls back to ``torch.nn.functional.layer_norm`` off-GPU the same way).

Modules are lightweight parameter-factories in the JAX style: ``init(key)``
returns a params dict, ``apply(params, x)`` runs the op.  A flax.linen wrapper
is provided for each for drop-in use in linen models.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple, Union

import jax.numpy as jnp

import flax.linen as nn

from apex_tpu.ops.layer_norm import (
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
)

__all__ = [
    "FusedLayerNorm",
    "FusedRMSNorm",
    "MixedFusedLayerNorm",
    "MixedFusedRMSNorm",
    "fused_layer_norm",
    "fused_layer_norm_affine",
    "fused_rms_norm",
    "fused_rms_norm_affine",
]

Shape = Union[int, Sequence[int]]


def _canon(normalized_shape: Shape) -> Tuple[int, ...]:
    if isinstance(normalized_shape, int):
        return (normalized_shape,)
    return tuple(int(s) for s in normalized_shape)


class FusedLayerNorm(nn.Module):
    """LayerNorm with fused Pallas kernels (apex.normalization.FusedLayerNorm).

    ``memory_efficient=True`` saves the output instead of the input for
    backward (fused_layer_norm.py ``memory_efficient`` flag).
    """

    normalized_shape: Shape
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        shape = _canon(self.normalized_shape)
        if not self.elementwise_affine:
            return fused_layer_norm(x, shape, self.eps,
                                    memory_efficient=self.memory_efficient)
        weight = self.param("scale", nn.initializers.ones, shape, self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, shape, self.param_dtype)
        return fused_layer_norm_affine(x, weight, bias, shape, self.eps,
                                       memory_efficient=self.memory_efficient)


class FusedRMSNorm(nn.Module):
    """RMSNorm with fused Pallas kernels (apex.normalization.FusedRMSNorm)."""

    normalized_shape: Shape
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        shape = _canon(self.normalized_shape)
        if not self.elementwise_affine:
            return fused_rms_norm(x, shape, self.eps,
                                  memory_efficient=self.memory_efficient)
        weight = self.param("scale", nn.initializers.ones, shape, self.param_dtype)
        return fused_rms_norm_affine(x, weight, shape, self.eps,
                                     memory_efficient=self.memory_efficient)


class MixedFusedLayerNorm(FusedLayerNorm):
    """Megatron-compat variant: params stay fp32 while activations are half.

    The reference's ``MixedFusedLayerNorm`` (fused_layer_norm.py) exists
    because its plain kernels required input dtype == weight dtype; the mixed
    subclass lifts that.  Our kernels are mixed-dtype natively (internals are
    fp32), so this subclass only pins ``param_dtype`` to fp32.
    """

    param_dtype: Any = jnp.float32


class MixedFusedRMSNorm(FusedRMSNorm):
    param_dtype: Any = jnp.float32
