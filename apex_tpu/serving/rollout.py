"""Rolling fleet upgrades: health-gated rolling reload, a canary
replica, and automatic fleet rollback (ISSUE 18, ROADMAP item 4's
second half).

PR 16 gave one engine a hot reload with a validation gate and a
one-step rollback; PR 17 built the fleet router whose ``drain()`` /
``rejoin()`` pair and restore-ahead ``prefetch()`` were designed as
"the rolling-reload hook".  This module is the missing orchestrator:
a :class:`RollingReloadController` that upgrades every replica of a
live fleet to a new committed checkpoint with **zero dropped
streams**, per replica::

    prefetch()  ->  drain()  ->  reload()  ->  rejoin()
    (off-path)     (lossless     (swap-only    (health-gated)
                    evacuation)   pause)

one replica (configurable K) at a time, with a **health window**
between waves: the rejoined replica must re-beat HEALTHY and complete
a configurable number of clean router steps before the next drain.

The first upgraded replica is the **canary**: the router pins a
seeded deterministic fraction of new traffic to it
(:meth:`~apex_tpu.serving.fleet.FleetRouter.pin_traffic`, reusing the
shadow/A-B :func:`~apex_tpu.serving.reload.assign_arm` rid hash), and
a :class:`CanaryGate` compares the canary arm's SLO report against
the old-version arms over the same window.  Pass promotes the rollout
to the remaining replicas; fail — or a refused/corrupt candidate, or
any replica dying mid-rollout — triggers automatic **halt + fleet
rollback**: every already-upgraded replica rolls back byte-exact from
its retained previous buffer (the reloader's double buffer), newest
first.  The terminal state (``promoted`` / ``aborted`` + reason) is a
first-class outcome, not an exception.

Why rollback is byte-exact: :meth:`HotReloader.rollback` swaps back
the *displaced buffer itself* — the very arrays that were serving
before the upgrade, retained, never copied through a checkpoint
round-trip — through the same ``swap_weights`` mechanism, so a halted
rollout leaves every replica serving bit-identical weights to the
pre-rollout fleet (pinned by the chaos tests).

Mixed-version caveat: mid-rollout the fleet serves two versions.  A
drain moves streams to survivors, and a captured (KV-intact) stream
restores bit-exactly only into a *same-version* engine — the router
degrades a cross-version capture to a bare requeue (deterministic
replay re-earns the tokens end-to-end on ONE version), so no stream
is ever a hybrid of two models.  ``weights_step`` rides every
routed/finished event to make the mixed window observable.
"""

from __future__ import annotations

import dataclasses
import math
from collections import deque
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

from apex_tpu._logging import emit_event, get_logger
from apex_tpu.obs.alerts import Condition
from apex_tpu.serving.fleet import ReplicaState

logger = get_logger("serving.rollout")

__all__ = [
    "CanaryGate",
    "CanaryVerdict",
    "RolloutConfig",
    "RollingReloadController",
]


# ---------------------------------------------------------------------------
# the gate


@dataclasses.dataclass(frozen=True)
class CanaryGate:
    """SLO comparison thresholds for the canary verdict.

    The gate compares the canary arm's
    :class:`~apex_tpu.obs.slo.SLOReport` against the old-version
    baseline arm over the same pinned window.  It **fails closed**: a
    canary that completed fewer than ``min_samples`` requests in the
    window fails the gate (a canary serving nothing is itself a
    regression signal), and every threshold breach is recorded as a
    reason so the halt event says *why*.

    A latency series only participates when both arms produced finite
    samples — on a single-process virtual clock the baseline arm is
    always populated under load, but the guard keeps the gate honest
    on thin windows.
    """

    tpot_ratio: float = 1.5       # canary tpot p95 may be <= ratio x baseline
    ttft_ratio: float = 1.5       # canary ttft p95 may be <= ratio x baseline
    completion_margin: float = 0.1  # completion rate may trail by <= this
    goodput_margin: float = 0.05    # goodput may trail by <= this (when known)
    min_samples: int = 1

    def __post_init__(self):
        if self.tpot_ratio <= 0 or self.ttft_ratio <= 0:
            raise ValueError("gate ratios must be > 0")
        if self.min_samples < 1:
            raise ValueError(
                f"min_samples must be >= 1, got {self.min_samples}")

    @staticmethod
    def _p95(series: dict) -> Optional[float]:
        v = series.get("p95") if isinstance(series, dict) else None
        if v is None or not math.isfinite(v):
            return None
        return float(v)

    def verdict(self, canary, baseline) -> Tuple[bool, List[str]]:
        """Compare two :class:`~apex_tpu.obs.slo.SLOReport` arms;
        returns ``(passed, reasons)`` with one reason per breached
        threshold (empty on pass).

        Every regression check evaluates through
        :class:`~apex_tpu.obs.alerts.Condition` — the same comparison
        atom the fleet's :class:`~apex_tpu.obs.alerts.AlertEngine`
        rules run on, so gating and alerting share one evaluation core
        (the arithmetic is unchanged: each check builds the identical
        float bound the inline comparisons used)."""
        reasons: List[str] = []
        if canary.completed < self.min_samples:
            reasons.append(
                f"canary completed {canary.completed} < min_samples "
                f"{self.min_samples} (fail-closed)")
            return False, reasons
        if baseline.completed >= self.min_samples:
            for series, limit in (("tpot", self.tpot_ratio),
                                  ("ttft", self.ttft_ratio)):
                c = self._p95(getattr(canary, series))
                b = self._p95(getattr(baseline, series))
                if c is not None and b is not None and b > 0 \
                        and Condition(">", b * limit).holds(c):
                    reasons.append(
                        f"{series} p95 {c:.4f}s > {limit:g}x baseline "
                        f"{b:.4f}s")
            c_rate = canary.completed / max(canary.offered, 1)
            b_rate = baseline.completed / max(baseline.offered, 1)
            if Condition("<", b_rate - self.completion_margin).holds(
                    c_rate):
                reasons.append(
                    f"completion rate {c_rate:.3f} trails baseline "
                    f"{b_rate:.3f} by more than {self.completion_margin}")
            if canary.goodput is not None and baseline.goodput is not None \
                    and Condition(
                        "<", baseline.goodput - self.goodput_margin
                    ).holds(canary.goodput):
                reasons.append(
                    f"goodput {canary.goodput:.3f} trails baseline "
                    f"{baseline.goodput:.3f} by more than "
                    f"{self.goodput_margin}")
        return (not reasons), reasons


@dataclasses.dataclass(frozen=True)
class CanaryVerdict:
    """One canary window's outcome: the pass/fail decision, the
    per-threshold reasons, and a compact numeric summary of each arm
    (full reports are the recorder's business — the verdict carries
    what the halt event and the bench need)."""

    passed: bool
    reasons: Tuple[str, ...]
    canary: dict                  # compact arm summary
    baseline: dict
    window_steps: int
    duration_s: float


def _arm_summary(report) -> dict:
    return {
        "offered": report.offered,
        "completed": report.completed,
        "tpot_p95": (report.tpot or {}).get("p95"),
        "ttft_p95": (report.ttft or {}).get("p95"),
        "goodput": report.goodput,
    }


# ---------------------------------------------------------------------------
# the controller


@dataclasses.dataclass
class RolloutConfig:
    """One rollout's shape.

    ``gate=None`` disables the canary phase entirely (no pin, no
    verdict — a straight health-gated rolling reload).  That is the
    *dangerous* mode: a regressing candidate promotes to the whole
    fleet; the chaos bench exists to show its goodput cost.
    """

    step: Optional[int] = None           # target; None = newest committed
    batch_size: int = 1                  # replicas upgraded per wave (K)
    health_window_steps: int = 2         # clean HEALTHY steps between waves
    canary_fraction: float = 0.25        # traffic share pinned to the canary
    canary_seed: int = 0
    canary_window_steps: int = 16        # verdict window length
    gate: Optional[CanaryGate] = dataclasses.field(
        default_factory=CanaryGate)

    def __post_init__(self):
        if self.batch_size < 1:
            raise ValueError(
                f"batch_size must be >= 1, got {self.batch_size}")
        if self.health_window_steps < 0:
            raise ValueError(f"health_window_steps must be >= 0, got "
                             f"{self.health_window_steps}")
        if not 0.0 < self.canary_fraction <= 1.0:
            raise ValueError(f"canary_fraction must be in (0, 1], got "
                             f"{self.canary_fraction}")
        if self.canary_window_steps < 1:
            raise ValueError(f"canary_window_steps must be >= 1, got "
                             f"{self.canary_window_steps}")


class RollingReloadController:
    """Drive a fleet-wide weight upgrade over the existing primitives.

    Install as the :class:`~apex_tpu.serving.loadgen.LoadGenerator`
    ``step_hook`` (it is callable with the ``(step, router)`` hook
    signature) — or call :meth:`advance` once per router step boundary
    yourself — after :meth:`start`.  Each call advances a small state
    machine at most one phase:

    - ``prefetch``: stage the wave's candidate off the serving path
      (restore + validate now; the later reload pause is swap-only).
    - ``upgrade``: per wave replica — ``drain()`` (lossless evacuation
      to survivors) → ``reload()`` consuming the stage → ``rejoin()``.
      A refused candidate (corrupt bytes, spec mismatch) aborts.
    - ``health``: wait for every wave replica to be HEALTHY for
      ``health_window_steps`` *consecutive* clean steps (a SUSPECT
      beat resets the count; a death aborts).
    - ``canary`` (first wave only, when gated): pin
      ``canary_fraction`` of new traffic to the upgraded replica for
      ``canary_window_steps``, then split the window's request records
      into arms by the router's pin log and ask the
      :class:`CanaryGate` for a verdict.  Pass promotes; fail halts.

    Abort (gate fail, refused candidate, replica death) rolls every
    already-upgraded replica back from its retained previous buffer —
    newest first, drain-evacuated where a healthy survivor exists,
    in-place otherwise (the swap itself is lossless) — and lands in
    terminal state ``aborted`` with :attr:`abort_reason`; a clean run
    lands in ``promoted``.  Both are first-class: the controller never
    raises for a bad candidate, because the fleet must keep serving.

    ``recorder`` (an :func:`apex_tpu.obs.recording_requests` recorder
    sharing the run's clock) is required when gated — the verdict is
    computed from its records.  ``deadlines``/``arrivals`` (rid-keyed,
    as for :func:`~apex_tpu.obs.slo.build_report`) flow into the
    per-arm goodput when provided.
    """

    def __init__(self, router, reloaders: Mapping[str, Any], *,
                 config: Optional[RolloutConfig] = None,
                 recorder: Any = None,
                 deadlines: Optional[Mapping[str, Optional[float]]] = None,
                 arrivals: Optional[Mapping[str, float]] = None):
        self.router = router
        self.reloaders: Dict[str, Any] = dict(reloaders)
        self.config = config if config is not None else RolloutConfig()
        self.recorder = recorder
        self.deadlines = deadlines
        self.arrivals = arrivals
        names = list(router.replica_names)
        if set(self.reloaders) != set(names):
            raise ValueError(
                f"reloaders must cover the fleet exactly: fleet "
                f"{sorted(names)}, reloaders {sorted(self.reloaders)}")
        for name in names:
            if self.reloaders[name].scheduler is not router.replica(name):
                raise ValueError(
                    f"reloader[{name!r}] wraps a different scheduler "
                    f"than the router's replica {name!r}")
        if self.config.gate is not None and recorder is None:
            raise ValueError(
                "a gated rollout needs the run's request recorder "
                "(apex_tpu.obs.recording_requests) to build the "
                "per-arm canary reports — pass recorder=, or gate=None "
                "for an ungated rolling reload")
        self.state = "idle"            # idle|running|promoted|aborted
        self.abort_reason: Optional[str] = None
        self.verdict: Optional[CanaryVerdict] = None
        self.canary: Optional[str] = None
        self.swap_pauses: Dict[str, float] = {}
        self._order: List[str] = []
        self._pending: deque = deque()
        self._wave: List[str] = []
        self._upgraded: List[str] = []
        self._target: Optional[int] = None
        self._from_steps: Dict[str, Optional[int]] = {}
        self._phase: Optional[str] = None
        self._health_left = 0
        self._canary_left = 0
        self._canary_done = False
        self._pinned = False
        self._t0 = 0.0
        self._window_t0 = 0.0

    # ---- introspection ---------------------------------------------------
    @property
    def done(self) -> bool:
        return self.state in ("promoted", "aborted")

    @property
    def upgraded(self) -> List[str]:
        return list(self._upgraded)

    @property
    def target_step(self) -> Optional[int]:
        return self._target

    @property
    def phase(self) -> Optional[str]:
        return self._phase

    @property
    def status(self) -> Dict[str, Any]:
        return {
            "state": self.state,
            "phase": self._phase,
            "target_step": self._target,
            "canary": self.canary,
            "upgraded": list(self._upgraded),
            "pending": list(self._pending),
            "abort_reason": self.abort_reason,
            "verdict": (None if self.verdict is None else {
                "passed": self.verdict.passed,
                "reasons": list(self.verdict.reasons)}),
            "swap_pauses": dict(self.swap_pauses),
        }

    # ---- lifecycle -------------------------------------------------------
    def start(self, *, step: Optional[int] = None) -> int:
        """Arm the rollout toward ``step`` (or ``config.step``, or the
        newest committed step any reloader's watcher can see).
        Returns the resolved target step.  The first :meth:`advance`
        call begins prefetching."""
        if self.state != "idle":
            raise RuntimeError(
                f"start() on a rollout in state {self.state!r} — one "
                f"controller drives one rollout")
        target = step if step is not None else self.config.step
        if target is None:
            for rl in self.reloaders.values():
                target = rl.watcher.committed_step()
                if target is not None:
                    break
        if target is None:
            raise ValueError("no target step: none given and no "
                             "committed checkpoint visible")
        names = list(self.router.replica_names)
        if len(names) < 2:
            raise ValueError(
                "a rolling reload needs >= 2 replicas (drain() must "
                "have a healthy survivor to evacuate to)")
        self._target = int(target)
        self._order = names
        self._pending = deque(names)
        self.canary = names[0] if self.config.gate is not None else None
        self._from_steps = {n: self.reloaders[n].current_step
                            for n in names}
        self._t0 = self.router.clock()
        self.state = "running"
        self._phase = "prefetch"
        emit_event("serving_rollout_started", step=self._target,
                   replicas=len(names), canary=self.canary,
                   fraction=(self.config.canary_fraction
                             if self.config.gate is not None else None),
                   gated=self.config.gate is not None,
                   batch_size=self.config.batch_size,
                   from_steps=dict(self._from_steps))
        logger.info("rollout -> step %s over %d replicas (canary=%s)",
                    self._target, len(names), self.canary)
        return self._target

    def __call__(self, step: int = 0, router: Any = None) -> None:
        """``LoadGenerator`` ``step_hook`` adapter."""
        self.advance()

    def advance(self) -> None:
        """Advance the state machine at most one phase.  Call once per
        router step boundary; no-op when idle or terminal."""
        if self.state != "running":
            return
        for name in self._order:
            if self.router.state_of(name) is ReplicaState.DEAD:
                self._abort(f"replica_died:{name}")
                return
        if self._phase == "prefetch":
            self._do_prefetch()
        elif self._phase == "upgrade":
            self._do_upgrade()
        elif self._phase == "health":
            self._do_health()
        elif self._phase == "canary":
            self._do_canary()

    # ---- phases ----------------------------------------------------------
    def _next_wave(self) -> List[str]:
        if not self._upgraded and self.config.gate is not None:
            return [self._pending.popleft()]      # the canary goes alone
        k = min(self.config.batch_size, len(self._pending))
        return [self._pending.popleft() for _ in range(k)]

    def _do_prefetch(self) -> None:
        self._wave = self._next_wave()
        for name in self._wave:
            staged = self.reloaders[name].prefetch(step=self._target)
            if staged is None:
                # nothing staged (restore failure / spec mismatch):
                # proceed — reload() re-walks the full path and refuses
                # first-class, which aborts with the real reason
                logger.warning("rollout prefetch staged nothing for %s "
                               "(step %s)", name, self._target)
        self._phase = "upgrade"

    def _do_upgrade(self) -> None:
        for i, name in enumerate(self._wave):
            rl = self.reloaders[name]
            prefetched = rl.staged_step == self._target
            try:
                self.router.drain(name)
            except ValueError as e:
                self._wave = self._wave[i:]  # un-upgraded tail, for abort
                self._abort(f"drain_refused:{name}: {e}")
                return
            out = rl.reload(step=self._target)
            if not out.ok:
                # the replica still serves its old weights, untouched
                # (the double-buffer guarantee) — return it to service
                # before rolling the fleet back
                self.router.rejoin(name)
                self._abort(f"reload_refused:{name}: {out.reason}")
                return
            self.router.rejoin(name)
            self._upgraded.append(name)
            self.swap_pauses[name] = out.swap_s
            emit_event("serving_rollout_replica_upgraded", replica=name,
                       step=self._target, from_step=out.from_step,
                       swap_s=round(out.swap_s, 6),
                       prefetched=prefetched,
                       canary=name == self.canary)
        self._health_left = self.config.health_window_steps
        self._phase = "health"

    def _do_health(self) -> None:
        if all(self.router.state_of(n) is ReplicaState.HEALTHY
               for n in self._wave):
            self._health_left -= 1
        else:
            # a SUSPECT beat resets the window: the gate wants
            # *consecutive* clean steps, not clean steps eventually
            self._health_left = self.config.health_window_steps
            return
        if self._health_left > 0:
            return
        if (self.config.gate is not None and not self._canary_done
                and self._wave and self._wave[0] == self.canary):
            self.router.pin_traffic(
                self.canary, fraction=self.config.canary_fraction,
                seed=self.config.canary_seed)
            self._pinned = True
            self._canary_left = self.config.canary_window_steps
            self._window_t0 = self.router.clock()
            self._phase = "canary"
            return
        self._next_wave_or_promote()

    def _do_canary(self) -> None:
        self._canary_left -= 1
        if self._canary_left > 0:
            return
        from apex_tpu.obs.slo import build_report

        log = self.router.unpin_traffic()
        self._pinned = False
        duration_s = self.router.clock() - self._window_t0
        records = [r for r in self.recorder.records() if r.rid in log]
        arm = {True: [], False: []}
        for r in records:
            arm[log[r.rid] == self.canary].append(r)
        offered_c = sum(1 for v in log.values() if v == self.canary)

        def _report(recs, offered):
            dl = (None if self.deadlines is None
                  else {r.rid: self.deadlines.get(r.rid) for r in recs})
            ar = (None if self.arrivals is None
                  else {r.rid: self.arrivals[r.rid] for r in recs
                        if r.rid in self.arrivals})
            return build_report(recs, offered=offered, deadlines=dl,
                                arrivals=ar)

        c_report = _report(arm[True], max(offered_c, len(
            [r for r in arm[True] if r.complete])))
        b_report = _report(arm[False], max(len(log) - offered_c, len(
            [r for r in arm[False] if r.complete])))
        passed, reasons = self.config.gate.verdict(c_report, b_report)
        self.verdict = CanaryVerdict(
            passed=passed, reasons=tuple(reasons),
            canary=_arm_summary(c_report),
            baseline=_arm_summary(b_report),
            window_steps=self.config.canary_window_steps,
            duration_s=duration_s)
        emit_event("serving_rollout_canary_verdict",
                   verdict="pass" if passed else "fail",
                   canary=self.canary,
                   window_steps=self.config.canary_window_steps,
                   duration_s=round(duration_s, 6),
                   reasons="; ".join(reasons)[:500],
                   canary_completed=c_report.completed,
                   baseline_completed=b_report.completed)
        self._canary_done = True
        if passed:
            self._next_wave_or_promote()
        else:
            self._abort("canary_failed: " + "; ".join(reasons))

    def _next_wave_or_promote(self) -> None:
        if self._pending:
            self._phase = "prefetch"
        else:
            self._promote()

    # ---- terminals -------------------------------------------------------
    def _promote(self) -> None:
        self.state = "promoted"
        self._phase = "done"
        duration_s = self.router.clock() - self._t0
        emit_event("serving_rollout_promoted", step=self._target,
                   replicas=len(self._order),
                   duration_s=round(duration_s, 6))
        logger.info("rollout promoted: step %s on %d replicas in %.3fs",
                    self._target, len(self._order), duration_s)

    def _abort(self, reason: str) -> None:
        reason = reason[:500]
        logger.warning("rollout halted: %s (rolling back %d upgraded "
                       "replicas)", reason, len(self._upgraded))
        emit_event("serving_rollout_halted", reason=reason,
                   step=self._target, upgraded=len(self._upgraded),
                   duration_s=round(self.router.clock() - self._t0, 6))
        if self._pinned:
            self.router.unpin_traffic()
            self._pinned = False
        rolled: List[str] = []
        for name in reversed(self._upgraded):
            if self.router.state_of(name) is ReplicaState.DEAD:
                continue                 # scheduler closed at failover
            rl = self.reloaders[name]
            if not rl.can_rollback:
                continue
            drained = False
            try:
                self.router.drain(name)
                drained = True
            except ValueError:
                # no healthy survivor to evacuate to: roll back in
                # place — the swap itself is lossless (streams keep
                # their slots and continue under the restored weights)
                pass
            rl.rollback()
            if drained:
                self.router.rejoin(name)
            rolled.append(name)
        emit_event("serving_rollout_rolled_back", replicas=len(rolled),
                   names=",".join(rolled), step=self._target)
        self.state = "aborted"
        self.abort_reason = reason
        self._phase = "done"
