"""Fault-tolerant fleet serving: a replica router with health checks,
prefix-affinity placement, and lossless stream failover.

Everything below the router is one engine behind one queue — a single
wedged or killed engine loses every in-flight stream.
:class:`FleetRouter` fronts N independent
:class:`~apex_tpu.serving.scheduler.ContinuousBatchingScheduler` +
:class:`~apex_tpu.serving.engine.DecodeEngine` replicas behind the
exact scheduler surface a
:class:`~apex_tpu.serving.loadgen.LoadGenerator` drives (``submit`` /
``step`` / ``results`` / ``clock`` / the pending-work counters), so
fleet and single-engine runs share one harness.

**Placement** (per :meth:`FleetRouter.submit`):

1. *Prefix affinity* — the prompt is chain-hashed with the prefix
   cache's own block hash and probed **read-only**
   (:meth:`~apex_tpu.serving.prefix_cache.PrefixCache.probe` — no LRU
   touch, no hit/miss pollution) against every healthy replica's
   cache; the replica covering the most prompt tokens wins, so
   shared-prefix tenants keep landing where their blocks live.
2. *Smooth WRR by load* — with no cache coverage anywhere, the
   nginx-style smooth weighted round-robin from
   :mod:`apex_tpu.serving.policy` draws the replica (replica names
   play the tenant role; per-replica weights ride
   :attr:`FleetConfig.weights`).
3. *Bounded deterministic backoff* — a replica's ``QueueFull`` moves
   the submission to the next-best candidate (affinity order first,
   then repeated WRR draws over the untried); when every healthy
   replica refuses, the router sheds (``serving_fleet_shed`` +
   re-raised ``QueueFull`` — the open-loop loadgen records it).

**Health** is a per-replica heartbeat on the *shared* scheduler clock
(the :mod:`~apex_tpu.resilience.supervisor` deadline pattern, fleet
-sized): every completed ``replica.step()`` beats; a beat older than
``suspect_after_s`` drives HEALTHY → SUSPECT (no new placements, still
stepped), older than ``dead_after_s`` drives SUSPECT → DEAD
(failover).  A suspect replica that completes a step again recovers to
HEALTHY with its WRR credits reset — exactly like a rejoin, so a
recovered straggler cannot burst-claim the traffic it "missed".

**Failover** drains a dead replica through
:meth:`~apex_tpu.serving.scheduler.ContinuousBatchingScheduler.export_streams`:

- a *wedged-but-intact* replica (watchdog death, :meth:`drain`)
  exports with ``capture=True`` — dense DECODE streams carry their
  cache bytes and resume on a survivor **mid-stream, bit-exactly**
  (the PR 13 ``capture_slot`` → ``restore_prefix`` contract, pinned
  cross-engine by PR 14; under tp the documented ~2.5e-7 psum drift
  makes this argmax-tier: token-identical, not bit-identical logits);
- a *hard-killed* replica (:meth:`kill` — device memory gone) exports
  bare records: victims re-queue on survivors with their original
  submit stamps and **replay deterministically** (sampler keys fold
  from the request seed by token index), so the final token stream is
  still bit-identical to an uninterrupted run;
- paged replicas always fail over by requeue (paged capture is by
  block reference into a per-engine pool — the bytes cannot cross
  engines).

Re-placement runs highest priority first (PR 13's class semantics at
fleet granularity); when no surviving capacity exists the
lowest-priority victims shed first.  The killed replica's scheduler is
routed through ``close()`` so its prefix-cache pins and paged block
holds are released, never leaked.  :meth:`drain` is the rolling-reload
hook (ROADMAP item 4): drain → reload the idle replica → ``rejoin``.

**Chaos + grading**: :class:`~apex_tpu.resilience.fault_injection`
grows ``KillReplica`` / ``WedgeReplica`` / ``SlowReplica``, all wired
through ``LoadGenerator(step_hook=)`` on one virtual clock; the
``serving_fleet_*`` events feed ``apex_serving_fleet_*`` metrics
(replicas-healthy gauge, routed/failover/resume/shed counters, a
failover-latency histogram) via :mod:`apex_tpu.obs.bridge`.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Callable, Dict, List, Mapping, Optional

from apex_tpu._logging import emit_event, get_logger
from apex_tpu.obs import bridge as obs_bridge
from apex_tpu.obs import metrics as obs_metrics
from apex_tpu.serving.policy import SchedulingPolicy, WeightedRoundRobin
from apex_tpu.serving.reload import assign_arm
from apex_tpu.serving.scheduler import (
    QueueFull,
    Request,
    RequestResult,
    StreamExport,
)

__all__ = ["FleetConfig", "FleetRouter", "ReplicaState"]

logger = get_logger("serving.fleet")


class ReplicaState(enum.Enum):
    HEALTHY = "healthy"
    SUSPECT = "suspect"      # missed beats: no new placements, watched
    DEAD = "dead"            # failed over; engine presumed unusable
    DRAINING = "draining"    # rolling-reload drain: no new placements


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Router knobs.  The heartbeat thresholds are in scheduler-clock
    seconds — on a :class:`~apex_tpu.serving.loadgen.VirtualClock`
    with ``step_time_s`` they are exact multiples of the step time, so
    every health transition in a test is deterministic.

    ``failover=False`` is the honesty baseline the bench grades
    against: a dead replica's streams are *shed* instead of moved
    (what a router without the export/adopt machinery would do)."""

    suspect_after_s: float = 1.0
    dead_after_s: float = 3.0
    affinity: bool = True              # prefix-affinity first placement
    failover: bool = True              # False: dead replica's work sheds
    weights: Optional[Mapping[str, float]] = None   # replica WRR weights

    def __post_init__(self):
        if self.suspect_after_s <= 0:
            raise ValueError(f"suspect_after_s must be > 0, got "
                             f"{self.suspect_after_s}")
        if self.dead_after_s <= self.suspect_after_s:
            raise ValueError(
                f"dead_after_s ({self.dead_after_s}) must exceed "
                f"suspect_after_s ({self.suspect_after_s}) — a replica "
                f"must pass through SUSPECT before it can die")


@dataclasses.dataclass
class _Replica:
    name: str
    scheduler: object                     # ContinuousBatchingScheduler
    state: ReplicaState = ReplicaState.HEALTHY
    last_beat: float = 0.0
    wedged: bool = False                  # hard hang: step never returns
    stalled: bool = False                 # one-step straggler mark


@dataclasses.dataclass
class _Pending:
    """A failover victim awaiting re-placement (captured records wait
    for a free slot; bare records wait for queue room)."""

    exp: StreamExport
    from_replica: str
    t_failed: float                       # when the donor was drained


class FleetRouter:
    """N scheduler replicas behind one serving surface.

    >>> router = FleetRouter({"r0": sched0, "r1": sched1, "r2": sched2})
    >>> gen = LoadGenerator(router, workload, step_time_s=0.25)
    >>> out = gen.run()

    All replicas must share one clock object (the virtual-clock
    determinism contract — same check as
    :class:`~apex_tpu.serving.reload.ShadowABScheduler`), and replica
    iteration order is the insertion order of ``replicas`` — placement,
    stepping, and failover all walk it deterministically.
    """

    def __init__(self, replicas: Mapping[str, object], *,
                 config: FleetConfig = FleetConfig(),
                 alerts: Optional[object] = None):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        names = list(replicas)
        clock = replicas[names[0]].clock
        engines = set()
        sched_names = set()
        for name in names:
            sched = replicas[name]
            if sched.clock is not clock:
                raise ValueError(
                    f"replica {name!r} does not share the fleet clock "
                    f"object — construct every scheduler with the same "
                    f"(virtual) clock so heartbeats, deadlines and "
                    f"latencies live on one timeline")
            eid = id(sched.engine)
            if eid in engines:
                raise ValueError(
                    f"replica {name!r} shares an engine with another "
                    f"replica — a fleet is N independent engines (two "
                    f"schedulers over one engine fight for slots)")
            engines.add(eid)
            # named schedulers stamp their name onto every metric as
            # the 'replica' label; two replicas sharing one scheduler
            # name would silently merge into one metric identity
            sname = getattr(sched, "name", None)
            if sname is not None:
                if sname in sched_names:
                    raise ValueError(
                        f"replica {name!r}: scheduler name {sname!r} is "
                        f"already used by another replica — per-replica "
                        f"metric attribution needs unique names")
                sched_names.add(sname)
        # the fleet size IS the replica label's cardinality bound
        # (widen-only, so replacement replicas with fresh names fit)
        obs_metrics.REGISTRY.declare_scope("replica", len(names))
        self._alerts = alerts
        self.config = config
        self._clock: Callable[[], float] = clock
        now = clock()
        self._replicas: Dict[str, _Replica] = {
            name: _Replica(name=name, scheduler=replicas[name],
                           last_beat=now)
            for name in names}
        # smooth WRR over replica names (names play the tenant role);
        # credits persist while a replica is ineligible, and reset on
        # rejoin/recovery via _reset_credits
        weights = dict(config.weights or {})
        unknown = set(weights) - set(names)
        if unknown:
            raise ValueError(f"weights for unknown replicas: "
                             f"{sorted(unknown)}")
        self._wrr = WeightedRoundRobin(SchedulingPolicy(
            tenant_weights=weights))
        self._steps = 0
        self._pending: List[_Pending] = []
        self._placed: Dict[str, str] = {}       # rid -> replica name
        self._routed_total = 0
        self._failovers_total = 0
        self._resumed_total = 0
        self._shed_total = 0
        # canary traffic pin (rolling rollout): (name, fraction, seed)
        # while active, plus the window's rid -> replica log
        self._pin: Optional[tuple] = None
        self._pin_log: Dict[str, str] = {}

    # ---- introspection (the LoadGenerator surface + fleet extras) --------
    @property
    def clock(self) -> Callable[[], float]:
        return self._clock

    @property
    def engine(self):
        """The first replica's engine (single-engine-compat
        convenience; per-replica engines ride ``replica(name).engine``)."""
        return next(iter(self._replicas.values())).scheduler.engine

    def replica(self, name: str):
        """The named replica's scheduler (introspection for tests)."""
        return self._replicas[name].scheduler

    @property
    def replica_names(self) -> List[str]:
        return list(self._replicas)

    def state_of(self, name: str) -> ReplicaState:
        return self._replicas[name].state

    @property
    def replicas_healthy(self) -> int:
        return sum(1 for r in self._replicas.values()
                   if r.state is ReplicaState.HEALTHY)

    def placement_of(self, rid: str) -> Optional[str]:
        """The replica currently serving ``rid`` (None once its result
        was claimed, or for a rid the router never placed)."""
        return self._placed.get(rid)

    @property
    def queue_depth(self) -> int:
        return (sum(r.scheduler.queue_depth
                    for r in self._live_replicas())
                + len(self._pending))

    @property
    def active_count(self) -> int:
        return sum(r.scheduler.active_count
                   for r in self._live_replicas())

    @property
    def suspended_count(self) -> int:
        return sum(r.scheduler.suspended_count
                   for r in self._live_replicas())

    @property
    def steps_run(self) -> int:
        return self._steps

    @property
    def weights_steps(self) -> Dict[str, Optional[int]]:
        """Per-replica checkpoint step being served (``None`` =
        unknown provenance) — the mixed-version-fleet dashboard a
        rolling upgrade is watched on."""
        return {name: getattr(r.scheduler, "weights_step", None)
                for name, r in self._replicas.items()}

    @property
    def fleet_stats(self) -> Dict[str, int]:
        """Cumulative router accounting: placements, failed-over
        streams, capture-resumes, fleet-level sheds."""
        return {"routed": self._routed_total,
                "failovers": self._failovers_total,
                "resumed": self._resumed_total,
                "shed": self._shed_total}

    @property
    def results(self) -> Dict[str, RequestResult]:
        out: Dict[str, RequestResult] = {}
        for r in self._replicas.values():
            out.update(r.scheduler.results)
        return out

    def pop_result(self, rid: str) -> RequestResult:
        for r in self._replicas.values():
            if rid in r.scheduler.results:
                self._placed.pop(rid, None)
                return r.scheduler.pop_result(rid)
        raise KeyError(rid)

    def pop_results(self) -> Dict[str, RequestResult]:
        out: Dict[str, RequestResult] = {}
        for r in self._replicas.values():
            out.update(r.scheduler.pop_results())
        for rid in out:
            self._placed.pop(rid, None)
        return out

    def replica_reports(self, records, *,
                        deadlines: Optional[Dict[str, Optional[float]]]
                        = None,
                        arrivals: Optional[Dict[str, float]] = None,
                        duration_s: Optional[float] = None
                        ) -> Dict[str, Any]:
        """Per-replica + fleet-aggregate
        :class:`~apex_tpu.obs.slo.SLOReport` over request-trace
        ``records`` (the :func:`apex_tpu.obs.recording_requests`
        output for a fleet run).  A stream counts toward the replica
        that FINISHED it — a failover victim reports on its survivor,
        which is where its latency was actually served.  The
        ``"fleet"`` entry aggregates every placed record; records the
        router never placed (shed before placement) are charged to the
        fleet aggregate only.  Call before claiming results
        (``pop_results`` forgets placements)."""
        from apex_tpu.obs.slo import build_report

        records = list(records)
        by_replica: Dict[str, list] = {}
        for rec in records:
            name = self._placed.get(rec.rid)
            if name is not None:
                by_replica.setdefault(name, []).append(rec)

        def _report(recs, offered):
            dl = (None if deadlines is None
                  else {r.rid: deadlines.get(r.rid) for r in recs})
            ar = (None if arrivals is None
                  else {r.rid: arrivals[r.rid] for r in recs
                        if r.rid in arrivals})
            return build_report(recs, offered=offered, deadlines=dl,
                                arrivals=ar, duration_s=duration_s)

        reports: Dict[str, Any] = {
            name: _report(recs, len(recs))
            for name, recs in sorted(by_replica.items())}
        reports["fleet"] = _report(records, max(len(records), 1))
        return reports

    def _live_replicas(self) -> List[_Replica]:
        return [r for r in self._replicas.values()
                if r.state is not ReplicaState.DEAD]

    # ---- placement -------------------------------------------------------
    def _eligible(self) -> List[_Replica]:
        """Replicas new placements may target: HEALTHY only (SUSPECT is
        watched, DRAINING is emptying, DEAD is gone)."""
        return [r for r in self._replicas.values()
                if r.state is ReplicaState.HEALTHY]

    def _candidate_order(self, prompt) -> List[str]:
        """The deterministic retry order for one submission: replicas
        with prefix-cache coverage first (most covered tokens wins,
        insertion order breaks ties — probed READ-ONLY so placement
        never skews a replica's own cache stats), then the uncovered
        remainder by repeated smooth-WRR draws."""
        eligible = self._eligible()
        covered: List[tuple] = []
        rest: List[str] = []
        for idx, r in enumerate(eligible):
            cache = (r.scheduler.prefix_cache
                     if self.config.affinity else None)
            c = cache.probe(prompt) if cache is not None else 0
            if c > 0:
                covered.append((-c, idx, r.name))
            else:
                rest.append(r.name)
        order = [name for _, _, name in sorted(covered)]
        remaining = set(rest)
        while remaining:
            pick = self._wrr.pick(remaining)
            order.append(pick)
            remaining.discard(pick)
        return order

    def pin_traffic(self, name: str, *, fraction: float,
                    seed: int = 0) -> None:
        """Pin a seeded deterministic ``fraction`` of new placements to
        replica ``name`` (the canary), reusing the shadow/A-B
        :func:`~apex_tpu.serving.reload.assign_arm` rid hash: a rid
        hashing under ``fraction`` places on the canary first, every
        other rid avoids it — the split is exact and reproducible, not
        statistical.  While pinned the router logs every placement
        (rid → replica) so a :class:`~apex_tpu.serving.rollout.
        CanaryGate` can split the window's request records into arms
        after the fact; :meth:`unpin_traffic` returns the log.

        The pin biases, it never strands: a full canary falls back to
        the normal candidate order (losslessness outranks an exact
        fraction), and a canary that leaves HEALTHY is simply skipped.
        """
        if name not in self._replicas:
            raise KeyError(name)
        if not 0.0 < fraction <= 1.0:
            raise ValueError(
                f"pin fraction must be in (0, 1], got {fraction}")
        self._pin = (name, float(fraction), int(seed))
        self._pin_log = {}

    def unpin_traffic(self) -> Dict[str, str]:
        """Clear the canary pin; returns the pinned window's placement
        log (rid → replica) and forgets it."""
        log, self._pin, self._pin_log = self._pin_log, None, {}
        return log

    def submit(self, request: Request) -> None:
        """Place one request: affinity-first, WRR fallback, next-best
        retry on ``QueueFull``, fleet shed when every healthy replica
        refuses (the re-raised ``QueueFull`` is the open-loop
        loadgen's shed signal)."""
        order = self._candidate_order(request.prompt)
        if self._pin is not None:
            pin_name, fraction, seed = self._pin
            if self._replicas[pin_name].state is ReplicaState.HEALTHY:
                if assign_arm(request.rid, fraction=fraction, seed=seed):
                    order = ([pin_name]
                             + [n for n in order if n != pin_name])
                else:
                    rest = [n for n in order if n != pin_name]
                    # never strand a request to honor the fraction: the
                    # canary stays last-resort for the control arm
                    order = rest + ([pin_name] if pin_name in order
                                    else [])
        if not order:
            self._shed_total += 1
            emit_event("serving_fleet_shed", rid=request.rid,
                       priority=request.priority, reason="no_replica")
            raise QueueFull("no healthy replica accepts placements")
        retries = 0
        for name in order:
            sched = self._replicas[name].scheduler
            try:
                sched.submit(request)
            except QueueFull:
                retries += 1
                continue
            self._placed[request.rid] = name
            if self._pin is not None:
                self._pin_log[request.rid] = name
            self._routed_total += 1
            emit_event("serving_fleet_routed", rid=request.rid,
                       replica=name, retries=retries,
                       weights_step=getattr(sched, "weights_step",
                                            None))
            return
        self._shed_total += 1
        emit_event("serving_fleet_shed", rid=request.rid,
                   priority=request.priority, reason="all_full")
        raise QueueFull(
            f"every healthy replica at capacity ({len(order)} tried)")

    # ---- health + failover -----------------------------------------------
    def _transition(self, r: _Replica, to: ReplicaState) -> None:
        if r.state is to:
            return
        emit_event("serving_fleet_replica_state", replica=r.name,
                   state=to.value, from_state=r.state.value)
        logger.info("replica %s: %s -> %s", r.name, r.state.value,
                    to.value)
        r.state = to

    def _reset_credits(self, name: str) -> None:
        """Zero one replica's WRR credit on rejoin/recovery: a replica
        away for N rounds must not burst-claim the traffic it missed."""
        state = dict(self._wrr.snapshot())
        state[name] = 0.0
        self._wrr.restore(state)

    def _check_health(self) -> None:
        now = self._clock()
        for r in self._replicas.values():
            if r.state in (ReplicaState.DEAD, ReplicaState.DRAINING):
                continue
            age = now - r.last_beat
            if age >= self.config.dead_after_s:
                self._transition(r, ReplicaState.DEAD)
                self._fail_over(r, capture=True)
            elif (age >= self.config.suspect_after_s
                  and r.state is ReplicaState.HEALTHY):
                self._transition(r, ReplicaState.SUSPECT)

    def _fail_over(self, r: _Replica, *, capture: bool) -> None:
        """Drain a dead replica: export its streams (captured when the
        host/device state is intact and the engine is dense; bare
        otherwise), close it so prefix pins and paged block holds are
        released, and park the victims for priority-ordered
        re-placement.  With ``config.failover=False`` the victims shed
        instead — the no-failover baseline the bench grades against."""
        capture = capture and r.scheduler.engine.paged is None
        now = self._clock()
        exports = r.scheduler.export_streams(capture=capture)
        # a drained scheduler closes cleanly: the prefix cache drops
        # its entries (paged: derefs the pool blocks) and the reclaim
        # hook unhooks — a killed replica must never leak pins
        r.scheduler.close()
        for exp in exports:
            self._placed.pop(exp.request.rid, None)
            if not self.config.failover:
                self._shed_total += 1
                emit_event("serving_fleet_shed", rid=exp.request.rid,
                           priority=exp.request.priority,
                           reason="no_failover")
                continue
            mode = "capture-resume" if exp.kv is not None else "requeue"
            self._failovers_total += 1
            emit_event("serving_fleet_failover", rid=exp.request.rid,
                       replica=r.name, mode=mode,
                       new_tokens=len(exp.tokens))
            self._pending.append(_Pending(exp=exp, from_replica=r.name,
                                          t_failed=now))
        # priority classes survive first; FIFO (export order) within
        # a class — stable sort keeps it
        self._pending.sort(key=lambda p: -p.exp.request.priority)

    def _place_pending(self) -> None:
        """Re-place failover victims, highest priority first.  A bare
        record that fits nowhere right now is SHED lowest-priority
        first (fleet capacity genuinely dropped — holding it would
        just let its deadline rot); a captured record waits for a free
        slot (its tokens are already earned — shedding it would throw
        away served work) and is counted in :attr:`queue_depth` so
        drains keep stepping."""
        if not self._pending:
            return
        still: List[_Pending] = []
        for p in self._pending:
            placed = False
            order = self._candidate_order(p.exp.request.prompt)

            def _capture_ok(name: str) -> bool:
                # captured bytes restore bit-exactly only into a dense
                # engine serving the SAME weights version: a cross-
                # version resume would splice two models into one
                # stream (hybrid tokens no single-version run could
                # ever produce)
                sched = self._replicas[name].scheduler
                return (sched.engine.paged is None
                        and getattr(sched, "weights_step", None)
                        == p.exp.weights_step)

            if p.exp.kv is not None and not any(
                    _capture_ok(n) for n in order):
                # no same-version dense survivor (mixed fleet, or a
                # rollout moved every peer to another weights step):
                # degrade to a bare requeue — deterministic replay
                # re-earns the tokens end-to-end on ONE version;
                # holding the capture would deadlock the drain
                p.exp.kv = None
                p.exp.tokens = []
                p.exp.t_first = 0.0
            for name in order:
                sched = self._replicas[name].scheduler
                if p.exp.kv is not None and not _capture_ok(name):
                    continue
                try:
                    ok = sched.adopt_stream(p.exp)
                except QueueFull:
                    continue
                if not ok:
                    continue             # captured record, no free slot
                self._placed[p.exp.request.rid] = name
                if p.exp.kv is not None:
                    self._resumed_total += 1
                emit_event(
                    "serving_fleet_resumed", rid=p.exp.request.rid,
                    replica=name, from_replica=p.from_replica,
                    mode=("capture-resume" if p.exp.kv is not None
                          else "requeue"),
                    duration_s=round(self._clock() - p.t_failed, 6))
                placed = True
                break
            if placed:
                continue
            if p.exp.kv is not None or not order:
                still.append(p)
            else:
                # bare record, every healthy queue full: fleet
                # capacity dropped below the offered load — shed
                # (lowest priority lands here first: placement walks
                # the priority-sorted list, so higher classes already
                # took the remaining room)
                self._shed_total += 1
                emit_event("serving_fleet_shed",
                           rid=p.exp.request.rid,
                           priority=p.exp.request.priority,
                           reason="capacity")
        self._pending = still

    # ---- fault/ops entry points ------------------------------------------
    def kill(self, name: str) -> None:
        """Hard-kill a replica NOW (device memory lost): its streams
        re-queue from their host-side request records and replay
        deterministically on survivors.  Idempotent on a dead
        replica."""
        r = self._replicas[name]
        if r.state is ReplicaState.DEAD:
            return
        self._transition(r, ReplicaState.DEAD)
        self._fail_over(r, capture=False)

    def wedge(self, name: str) -> None:
        """Mark a replica hard-hung: its step never completes, so it
        stops beating — the watchdog walks it HEALTHY → SUSPECT → DEAD
        and drains it via preempt-capture (host state intact)."""
        self._replicas[name].wedged = True

    def stall(self, name: str) -> None:
        """Mark a replica a straggler for the NEXT router step only
        (the step does not complete in time — one missed beat).  Long
        enough runs of stalls drive SUSPECT and then DEAD; short runs
        recover with WRR credits reset."""
        self._replicas[name].stalled = True

    def drain(self, name: str) -> List[str]:
        """Rolling-reload hook: stop placing onto ``name``, move its
        live streams to the surviving replicas (capture-resume where
        the engine allows), and return the moved rids.  The replica's
        scheduler stays open and empty — reload it idle, then
        :meth:`rejoin`."""
        r = self._replicas[name]
        if r.state is ReplicaState.DEAD:
            raise ValueError(f"drain({name!r}): replica is dead")
        if not any(x.state is ReplicaState.HEALTHY
                   for x in self._replicas.values() if x is not r):
            raise ValueError(
                f"drain({name!r}): no other healthy replica to move "
                f"its streams to")
        self._transition(r, ReplicaState.DRAINING)
        capture = r.scheduler.engine.paged is None
        now = self._clock()
        exports = r.scheduler.export_streams(capture=capture)
        moved = []
        for exp in exports:
            self._placed.pop(exp.request.rid, None)
            mode = "capture-resume" if exp.kv is not None else "requeue"
            self._failovers_total += 1
            emit_event("serving_fleet_failover", rid=exp.request.rid,
                       replica=name, mode=mode,
                       new_tokens=len(exp.tokens))
            self._pending.append(_Pending(exp=exp, from_replica=name,
                                          t_failed=now))
            moved.append(exp.request.rid)
        self._pending.sort(key=lambda p: -p.exp.request.priority)
        return moved

    def rejoin(self, name: str) -> None:
        """Return a drained (or recovered/rebuilt) replica to service
        with its WRR credits reset.  A DEAD replica may rejoin only
        because the caller rebuilt it (the router closed its
        scheduler) — pass the same name with a fresh scheduler via
        :meth:`replace`."""
        r = self._replicas[name]
        if r.state is ReplicaState.DEAD:
            raise ValueError(
                f"rejoin({name!r}): the router closed this replica's "
                f"scheduler at failover — rebuild it and call "
                f"replace() instead")
        r.wedged = False
        r.stalled = False
        r.last_beat = self._clock()
        self._transition(r, ReplicaState.HEALTHY)
        self._reset_credits(name)

    def replace(self, name: str, scheduler) -> None:
        """Swap in a rebuilt scheduler for a DEAD replica (same shared
        clock required) and rejoin it fresh.  Refuses a replica that is
        not DEAD: a live scheduler may hold in-flight streams, and
        silently discarding it would drop them without a failover —
        ``drain()`` + ``rejoin()`` is the live-replica path, ``kill()``
        the destructive one."""
        if scheduler.clock is not self._clock:
            raise ValueError(
                f"replace({name!r}): the new scheduler must share the "
                f"fleet clock object")
        r = self._replicas[name]
        if r.state is not ReplicaState.DEAD:
            raise ValueError(
                f"replace({name!r}): replica is {r.state.value}, not "
                f"dead — replacing a live scheduler would drop its "
                f"in-flight streams; drain() it first (or kill() it "
                f"to force a failover)")
        r.scheduler = scheduler
        r.wedged = False
        r.stalled = False
        r.last_beat = self._clock()
        self._transition(r, ReplicaState.HEALTHY)
        self._reset_credits(name)

    # ---- the loop --------------------------------------------------------
    def step(self) -> List[str]:
        """One fleet step boundary: watchdog sweep (suspect/dead
        transitions + failover drains), re-place pending victims, then
        step every live replica — a completed step IS the heartbeat.
        Returns rids that reached a terminal state, fleet-wide."""
        self._check_health()
        self._place_pending()
        finished: List[str] = []
        for r in self._replicas.values():
            if r.state is ReplicaState.DEAD or r.wedged:
                continue                 # a wedged step never returns
            if r.stalled:
                r.stalled = False        # one missed beat, then retry
                continue
            finished.extend(r.scheduler.step())
            r.last_beat = self._clock()
            if r.state is ReplicaState.SUSPECT:
                # a completed beat clears suspicion; credits reset so
                # the comeback cannot burst-claim missed traffic
                self._transition(r, ReplicaState.HEALTHY)
                self._reset_credits(r.name)
        self._steps += 1
        obs_bridge.SERVING_FLEET_REPLICAS_HEALTHY.set(
            self.replicas_healthy)
        if self._alerts is not None:
            # the fleet step boundary is the alert engine's evaluation
            # tick: every gauge/counter above is freshly set, and the
            # shared clock makes the firing/resolved ledger a
            # deterministic function of the workload
            self._alerts.evaluate(now=self._clock())
        return finished

    def run(self, max_steps: Optional[int] = None
            ) -> Dict[str, RequestResult]:
        """Drain the whole fleet; returns rid -> result."""
        steps = 0
        bound = max_steps if max_steps is not None else (
            64 + sum(r.scheduler._derived_step_bound()
                     for r in self._live_replicas()))
        while (self.queue_depth or self.active_count
               or self.suspended_count):
            if steps >= bound:
                raise RuntimeError(
                    f"fleet drain stalled after {steps} steps: "
                    f"{self.queue_depth} queued, {self.active_count} "
                    f"active, {self.suspended_count} suspended, "
                    f"{len(self._pending)} pending failover")
            self.step()
            steps += 1
        return self.results
