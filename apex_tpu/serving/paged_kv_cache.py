"""Paged decode KV cache: a global block pool + per-slot block tables.

The dense cache (:mod:`apex_tpu.serving.kv_cache`) preallocates
``[layers, slots, max_len, ...]`` — worst-case memory per slot, cap on
concurrency at ``slots``, and a prefix cache that must *copy* K/V
through host-dispatched span reads.  The paged layout replaces the
per-slot buffer with a **global pool of fixed-size blocks**

    ``k`` / ``v``: ``[layers, num_blocks, block_size, kv_heads, head_dim]``

plus a per-slot **block table** ``tables[slot, i] -> pool block id``:
memory scales with *used* tokens (a slot holding 40 tokens pins
``ceil(40 / block_size)`` blocks, not ``max_len`` rows), concurrency is
priced in blocks, and cross-request prefix reuse becomes **table
aliasing**: a hit appends the shared block ids to the new slot's table
— zero device reads, zero copies — with host-side refcounts deciding
when a block really frees.  Copy-on-write keeps sharers bit-isolated:
any write into a block referenced more than once first copies it.

Exactness is the same story as the dense cache, told through a gather:
attention reads a slot's K/V as the fixed-extent view
``pool[table[slot]] -> [max_len, kv_heads, head_dim]`` (one static
gather shape for every slot state), masked at the flash kernels' exact
``-1e30`` so rows past the committed length — stale garbage, bucket
padding, other streams' bytes behind un-CoW'd shared blocks — carry
exactly zero weight.  Valid rows hold bit-for-bit the values the dense
cache would hold at the same positions, the reduction extents are
identical, and therefore the logits are **bit-identical** to the dense
engine (pinned by ``tests/test_serving_paged.py`` against both the
dense engine and the uncached shape-stable forward).

Under tensor-parallel serving the pool shards exactly like the dense
cache — ``kv_heads`` is the split axis (``[layers, num_blocks,
block_size, kv_heads/tp, head_dim]`` per rank) while ``tables`` and
``lengths`` replicate, so every rank routes rows through the *same*
block ids and the host-side manager (refcounts, CoW planning) stays
mesh-oblivious: one table flush commits identically to all ranks.

Layout invariants the device ops rely on:

- **Block 0 is the null block**: never allocated, never read unmasked.
  Free slots' table entries are 0, so a gather through a fresh table
  lands on finite zeros (masked reads must never see NaN — ``0 * NaN``
  would poison the PV matmul where masked probabilities are exact 0).
- Writes are **drop-safe scatters**: a row whose table entry is the
  null block (bucket padding past the allocated frontier) or whose
  position is ``< 0`` (an inactive decode lane's sentinel) or
  ``>= max_len`` redirects to physical index ``num_blocks`` and is
  dropped by the ``mode="drop"`` scatter — unlike the dense cache,
  padding is never written at all, so a stale table can never route a
  garbage row into another stream's live block.
- The host :class:`PagedCacheManager` owns allocation, refcounts, CoW
  planning and the table mirror; the device ``tables`` array is a
  snapshot flushed (one small host->device transfer) only on steps
  whose allocation actually changed — the common decode step inside a
  block crosses no boundary and flushes nothing.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

from apex_tpu._logging import get_logger
from apex_tpu.amp.quant import dequantize_int8, quantize_int8

__all__ = ["PagedCacheConfig", "PagedKVCache", "QuantPagedKVCache",
           "BlockPoolExhausted", "PagedCacheManager", "init_paged_cache",
           "init_quant_paged_cache", "paged_prefill_write",
           "paged_append", "decode_view", "prefill_view",
           "bytes_per_block"]

logger = get_logger("serving.paged_kv_cache")

NULL_BLOCK = 0          # reserved: finite zeros, never allocated


class BlockPoolExhausted(RuntimeError):
    """No free block in the pool (and reclaim, if any, freed none) —
    block-granular out-of-memory backpressure.  Raised, never clamped:
    a clamped write would silently corrupt another stream's block."""


@dataclasses.dataclass(frozen=True)
class PagedCacheConfig:
    """Opt-in knob for the paged cache layout
    (``DecodeEngine(..., paged=PagedCacheConfig(...))``).

    ``block_size``: tokens per pool block.  ``num_blocks``: total pool
    blocks *including* the reserved null block (``None`` — sized for
    dense-capacity parity: ``slots * ceil(max_len / block_size) + 1``,
    so every slot can still fill to ``max_len`` with zero sharing).
    """

    block_size: int = 16
    num_blocks: Optional[int] = None

    def __post_init__(self):
        if self.block_size < 1:
            raise ValueError(
                f"block_size must be >= 1, got {self.block_size}")
        if self.num_blocks is not None and self.num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (the null block plus at least "
                f"one allocatable), got {self.num_blocks}")


@functools.partial(jax.tree_util.register_dataclass,
                   data_fields=("k", "v", "tables", "lengths"),
                   meta_fields=("max_len",))
@dataclasses.dataclass(frozen=True)
class PagedKVCache:
    """Block-pool decode cache.

    ``k`` / ``v``: ``[layers, num_blocks, block_size, kv_heads,
    head_dim]``; ``tables``: ``[slots, blocks_per_slot]`` int32 pool
    block ids (0 = the null block / unallocated); ``lengths``:
    ``[slots]`` int32 valid tokens per slot.  ``max_len`` is pytree
    *metadata* (a static int): the per-slot capacity, which the table
    extent ``blocks_per_slot * block_size`` may slightly exceed when
    ``max_len`` is not a block multiple — reads slice the gathered view
    back to exactly ``max_len`` rows so every reduction extent matches
    the dense cache bit for bit.
    """

    k: jax.Array
    v: jax.Array
    tables: jax.Array
    lengths: jax.Array
    max_len: int

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def block_size(self) -> int:
        return self.k.shape[2]

    @property
    def num_slots(self) -> int:
        return self.tables.shape[0]

    @property
    def blocks_per_slot(self) -> int:
        return self.tables.shape[1]

    @property
    def dtype(self):
        return self.k.dtype


@functools.partial(jax.tree_util.register_dataclass,
                   data_fields=("k", "v", "k_scale", "v_scale", "tables",
                                "lengths"),
                   meta_fields=("max_len",))
@dataclasses.dataclass(frozen=True)
class QuantPagedKVCache:
    """KV-int8 twin of :class:`PagedKVCache`: the same block pool and
    table routing, the payload stored as symmetric int8 with one fp32
    scale per pooled (row, head) — scales live in a parallel pool
    ``[layers, num_blocks, block_size, kv_heads]`` indexed by the SAME
    block ids, so aliasing, CoW, fork, and release move payload and
    scales together by construction (a shared block shares its scales;
    a CoW copy copies both).

    Every drop-safe-scatter/null-block/fixed-extent-gather invariant of
    the fp pool holds unchanged; reads dequantize through the gathered
    scales.  ``kv_heads`` sits at axis 3 of both pools, so under tensor
    parallelism the scale pool shards on the same
    ``P(None, None, None, 'tp')`` spec as the payload.
    """

    k: jax.Array
    v: jax.Array
    k_scale: jax.Array
    v_scale: jax.Array
    tables: jax.Array
    lengths: jax.Array
    max_len: int

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def block_size(self) -> int:
        return self.k.shape[2]

    @property
    def num_slots(self) -> int:
        return self.tables.shape[0]

    @property
    def blocks_per_slot(self) -> int:
        return self.tables.shape[1]

    @property
    def dtype(self):
        """Payload dtype (int8); reads dequantize to fp32."""
        return self.k.dtype


def blocks_per_slot(max_len: int, block_size: int) -> int:
    """Table width: blocks covering ``max_len`` rows (ceil division)."""
    return -(-int(max_len) // int(block_size))


def bytes_per_block(cache) -> int:
    """True resident bytes one pool block pins across every layer and
    pool array.  For the fp pool that is the k+v payload; for the quant
    pool the fp32 scale pools ride the same block ids, so their bytes
    are part of the block (an accounting that read ``k.dtype.itemsize``
    alone would undercount an int8 pool by its scale overhead)."""
    pools = [cache.k, cache.v]
    if isinstance(cache, QuantPagedKVCache):
        pools += [cache.k_scale, cache.v_scale]
    total = 0
    for arr in pools:
        shape = arr.shape            # [L, num_blocks, block_size, ...]
        per = int(np.prod((shape[0],) + shape[2:]))
        total += jnp.dtype(arr.dtype).itemsize * per
    return int(total)


def init_paged_cache(config: Any, *, slots: int, max_len: int,
                     block_size: int, num_blocks: int,
                     dtype=jnp.float32) -> PagedKVCache:
    """Zero-filled pool for ``config`` (``LlamaConfig``-shaped).  Block
    0 is the null block; all table entries start there."""
    head_dim = config.hidden_size // config.num_attention_heads
    shape = (config.num_hidden_layers, num_blocks, block_size,
             config.kv_heads, head_dim)
    bps = blocks_per_slot(max_len, block_size)
    return PagedKVCache(
        k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
        tables=jnp.zeros((slots, bps), jnp.int32),
        lengths=jnp.zeros((slots,), jnp.int32), max_len=int(max_len))


def init_quant_paged_cache(config: Any, *, slots: int, max_len: int,
                           block_size: int,
                           num_blocks: int) -> QuantPagedKVCache:
    """Zero-filled KV-int8 block pool.  Scales start at 1.0 (the
    zero-amax convention): the null block — and every unallocated block
    — dequantizes to exact finite zeros, preserving the masked-read
    ``0 * NaN``-safety invariant."""
    head_dim = config.hidden_size // config.num_attention_heads
    shape = (config.num_hidden_layers, num_blocks, block_size,
             config.kv_heads, head_dim)
    bps = blocks_per_slot(max_len, block_size)
    return QuantPagedKVCache(
        k=jnp.zeros(shape, jnp.int8), v=jnp.zeros(shape, jnp.int8),
        k_scale=jnp.ones(shape[:-1], jnp.float32),
        v_scale=jnp.ones(shape[:-1], jnp.float32),
        tables=jnp.zeros((slots, bps), jnp.int32),
        lengths=jnp.zeros((slots,), jnp.int32), max_len=int(max_len))


# ---------------------------------------------------------------------------
# device ops: drop-safe scatter writes + fixed-extent gather reads
# ---------------------------------------------------------------------------


def _route_rows(cache: PagedKVCache, table_row, rows):
    """Map logical slot rows -> ``(physical block id, offset in
    block)``, with every undroppable-unsafe row redirected to
    ``num_blocks`` (out of pool range, dropped by ``mode="drop"``):
    rows ``< 0`` (inactive-lane sentinel), rows ``>= max_len``
    (bucket-padding overhang past capacity), and rows whose table
    entry is the null block (padding past the allocated frontier, or a
    released slot's zeroed table).  Real rows always route to a live
    allocated block — the host manager guarantees the table covers the
    declared write span before the dispatch."""
    bs = cache.block_size
    safe = jnp.clip(rows, 0, cache.max_len - 1)
    blk = jnp.clip(safe // bs, 0, cache.blocks_per_slot - 1)
    if table_row.ndim == 2:
        # batched append: row i must read SLOT i's own table (the
        # diagonal), not every slot's entry at offset blk[i] — a plain
        # take here is an outer product that scatters each lane's token
        # through every other slot's table
        entry = jnp.take_along_axis(table_row, blk[:, None],
                                    axis=-1)[:, 0]
    else:
        entry = jnp.take(table_row, blk, axis=-1)
    ok = (rows >= 0) & (rows < cache.max_len) & (entry > NULL_BLOCK)
    phys = jnp.where(ok, entry, cache.num_blocks)
    return phys, safe % bs


def paged_prefill_write(cache: PagedKVCache, layer: int, slot, k_seq,
                        v_seq, start=0) -> PagedKVCache:
    """Write one (padded) prompt chunk's K/V through ``slot``'s block
    table at offset ``start`` — the paged twin of
    :func:`~apex_tpu.serving.kv_cache.prefill_into_slot`.

    ``k_seq`` / ``v_seq``: ``[chunk_len, kv_heads, head_dim]``;
    ``slot`` / ``start`` may be traced, ``layer`` is a Python int.
    Rows routing to the null block (bucket padding past the allocated
    frontier) or past ``max_len`` are DROPPED — the paged cache never
    writes padding into a block, so no stale table can route one into
    a live neighbor.  ``lengths`` is untouched (the caller commits
    once per model call, exactly like the dense primitive).
    """
    rows = jnp.asarray(start, jnp.int32) + jnp.arange(
        k_seq.shape[0], dtype=jnp.int32)
    table_row = lax.dynamic_index_in_dim(
        cache.tables, jnp.asarray(slot, jnp.int32), axis=0,
        keepdims=False)
    phys, within = _route_rows(cache, table_row, rows)
    if isinstance(cache, QuantPagedKVCache):
        # scales scatter through the SAME (phys, within) routing as the
        # payload: a dropped padding row drops both, a live row lands
        # both in the same block
        kq, ks = quantize_int8(k_seq, axis=-1)
        vq, vs = quantize_int8(v_seq, axis=-1)
        return dataclasses.replace(
            cache,
            k=cache.k.at[layer, phys, within].set(kq, mode="drop"),
            v=cache.v.at[layer, phys, within].set(vq, mode="drop"),
            k_scale=cache.k_scale.at[layer, phys, within].set(
                ks, mode="drop"),
            v_scale=cache.v_scale.at[layer, phys, within].set(
                vs, mode="drop"))
    return dataclasses.replace(
        cache,
        k=cache.k.at[layer, phys, within].set(k_seq.astype(cache.dtype),
                                              mode="drop"),
        v=cache.v.at[layer, phys, within].set(v_seq.astype(cache.dtype),
                                              mode="drop"))


def paged_append(cache: PagedKVCache, layer: int, k_tok, v_tok,
                 positions) -> PagedKVCache:
    """Write one token's K/V per slot at that slot's own position —
    the paged twin of :func:`~apex_tpu.serving.kv_cache.append_token`.

    ``k_tok`` / ``v_tok``: ``[slots, kv_heads, head_dim]``;
    ``positions``: ``[slots]`` int32 — the slot's current depth, or
    ``-1`` for an inactive lane (dense appends park inactive writes in
    the lane's own masked rows; a paged table has no such private
    scratch, so inactive lanes are DROPPED instead of routed).  One
    shape-stable scatter covers every lane.
    """
    pos = jnp.asarray(positions, jnp.int32)
    phys, within = _route_rows(cache, cache.tables, pos)
    if isinstance(cache, QuantPagedKVCache):
        kq, ks = quantize_int8(k_tok, axis=-1)
        vq, vs = quantize_int8(v_tok, axis=-1)
        return dataclasses.replace(
            cache,
            k=cache.k.at[layer, phys, within].set(kq, mode="drop"),
            v=cache.v.at[layer, phys, within].set(vq, mode="drop"),
            k_scale=cache.k_scale.at[layer, phys, within].set(
                ks, mode="drop"),
            v_scale=cache.v_scale.at[layer, phys, within].set(
                vs, mode="drop"))
    return dataclasses.replace(
        cache,
        k=cache.k.at[layer, phys, within].set(k_tok.astype(cache.dtype),
                                              mode="drop"),
        v=cache.v.at[layer, phys, within].set(v_tok.astype(cache.dtype),
                                              mode="drop"))


def _gathered(cache: PagedKVCache, arr, tables) -> jax.Array:
    """``arr[layer]`` rows gathered through ``tables`` and re-laid as
    contiguous token rows, sliced to exactly ``max_len`` — the
    fixed-extent read every attention caller shares.  The gather shape
    is static (``tables``' shape), so one compiled program serves
    every slot state."""
    g = jnp.take(arr, tables, axis=0)     # [..., bps, bs, kvh, hd]
    flat = g.reshape(g.shape[:-4] + (g.shape[-4] * g.shape[-3],)
                     + g.shape[-2:])
    return flat[..., :cache.max_len, :, :]


def _gathered_scale(cache, arr, tables) -> jax.Array:
    """The scale-pool twin of :func:`_gathered`: ``arr[layer]`` rows
    (``[num_blocks, block_size, kv_heads]`` — no head_dim axis)
    gathered through ``tables`` and re-laid as contiguous token rows,
    sliced to exactly ``max_len``."""
    g = jnp.take(arr, tables, axis=0)     # [..., bps, bs, kvh]
    flat = g.reshape(g.shape[:-3] + (g.shape[-3] * g.shape[-2],)
                     + g.shape[-1:])
    return flat[..., :cache.max_len, :]


def decode_view(cache, layer: int) -> Tuple[jax.Array, jax.Array]:
    """Every slot's K/V as ``[slots, max_len, kv_heads, head_dim]`` —
    the batched decode read (same shape, same masked-read contract,
    same reduction extents as the dense ``cache.k[layer]``).  A
    :class:`QuantPagedKVCache` dequantizes through the gathered
    per-(row, head) scales; unallocated rows carry q=0/scale=1 and so
    stay exact finite zeros."""
    if isinstance(cache, QuantPagedKVCache):
        return (dequantize_int8(
                    _gathered(cache, cache.k[layer], cache.tables),
                    _gathered_scale(cache, cache.k_scale[layer],
                                    cache.tables)),
                dequantize_int8(
                    _gathered(cache, cache.v[layer], cache.tables),
                    _gathered_scale(cache, cache.v_scale[layer],
                                    cache.tables)))
    return (_gathered(cache, cache.k[layer], cache.tables),
            _gathered(cache, cache.v[layer], cache.tables))


def prefill_view(cache, layer: int, slot) -> Tuple[jax.Array, jax.Array]:
    """One slot's K/V as ``[max_len, kv_heads, head_dim]`` — the
    chunked-prefill read (``slot`` may be traced), dequantized for a
    :class:`QuantPagedKVCache` exactly like :func:`decode_view`."""
    table_row = lax.dynamic_index_in_dim(
        cache.tables, jnp.asarray(slot, jnp.int32), axis=0,
        keepdims=False)
    if isinstance(cache, QuantPagedKVCache):
        return (dequantize_int8(
                    _gathered(cache, cache.k[layer], table_row),
                    _gathered_scale(cache, cache.k_scale[layer],
                                    table_row)),
                dequantize_int8(
                    _gathered(cache, cache.v[layer], table_row),
                    _gathered_scale(cache, cache.v_scale[layer],
                                    table_row)))
    return (_gathered(cache, cache.k[layer], table_row),
            _gathered(cache, cache.v[layer], table_row))


# ---------------------------------------------------------------------------
# host-side allocation: refcounts, block tables, CoW planning
# ---------------------------------------------------------------------------


class PagedCacheManager:
    """Host bookkeeping for one :class:`PagedKVCache`: a free-list
    allocator with per-block refcounts, the per-slot table mirror, and
    copy-on-write planning.

    Everything here is pure host state updated at dispatch boundaries;
    the engine flushes the table mirror to the device (one small
    transfer) only when :meth:`consume_dirty` reports a change, and
    runs the CoW copy pairs :meth:`ensure` returns *before* the write
    that needed them.  Refcount semantics: every user of a block holds
    one reference — the owning slot's table, each aliasing slot's
    table, and each prefix-cache entry.  A block frees (returns to the
    LIFO free list — deterministic ids for replayable tests) when its
    count reaches zero; a write into a block with count > 1 must CoW
    first, which is what keeps sharers bit-isolated.

    ``reclaim``: optional callback ``(n_blocks) -> freed`` consulted
    once when the free list runs dry (the scheduler wires prefix-cache
    eviction here); if the pool is still empty afterwards the
    allocation raises :class:`BlockPoolExhausted`.
    """

    def __init__(self, *, slots: int, max_len: int, block_size: int,
                 num_blocks: int):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if not 1 <= block_size <= max_len:
            raise ValueError(
                f"block_size {block_size} must be in [1, max_len "
                f"{max_len}]")
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (null block + 1), got "
                f"{num_blocks}")
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.blocks_per_slot = blocks_per_slot(max_len, block_size)
        self._refs = np.zeros((num_blocks,), np.int64)
        # LIFO free list, block 0 (null) excluded forever
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._tables = np.zeros((self.slots, self.blocks_per_slot),
                                np.int32)
        self._owned = np.zeros((self.slots,), np.int64)
        self._dirty = True          # fresh mirror vs whatever device held
        self.reclaim: Optional[Callable[[int], int]] = None
        # cumulative structural accounting (bench + metrics read these)
        self.allocated_total = 0
        self.freed_total = 0
        self.cow_total = 0
        self.aliased_total = 0

    # ---- introspection ---------------------------------------------------
    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        """Allocated (non-null) blocks — the pool-residency numerator."""
        return self.num_blocks - 1 - len(self._free)

    @property
    def utilization(self) -> float:
        """Allocated blocks / allocatable blocks, in ``[0, 1]``."""
        return self.used_blocks / max(self.num_blocks - 1, 1)

    def refcount(self, block_id: int) -> int:
        return int(self._refs[block_id])

    def slot_block_ids(self, slot: int) -> List[int]:
        """The slot's allocated pool blocks, in token order."""
        return [int(b) for b in self._tables[slot, :self._owned[slot]]]

    def owned_blocks(self, slot: int) -> int:
        """How many table entries the slot holds — O(1), no list
        materialization (the admission gate reads this per active
        stream per step)."""
        return int(self._owned[slot])

    def table_snapshot(self) -> np.ndarray:
        return self._tables.copy()

    def consume_dirty(self) -> bool:
        """True exactly once after any mirror change — the engine's
        flush-only-when-needed signal."""
        dirty, self._dirty = self._dirty, False
        return dirty

    def stats(self) -> dict:
        return {"num_blocks": self.num_blocks,
                "block_size": self.block_size,
                "used_blocks": self.used_blocks,
                "free_blocks": self.free_blocks,
                "allocated_total": self.allocated_total,
                "freed_total": self.freed_total,
                "cow_total": self.cow_total,
                "aliased_total": self.aliased_total}

    # ---- refcounting -----------------------------------------------------
    def ref(self, block_ids: Sequence[int]) -> None:
        """Add one reference per block (a prefix-cache entry, an
        aliasing slot).  All-or-nothing: every id is validated before
        any count moves, so a stale id mid-list (a block freed between
        capture and alias) cannot leak permanent references onto the
        earlier ids."""
        for b in block_ids:
            if not 0 < b < self.num_blocks:
                raise ValueError(f"block id {b} out of pool range "
                                 f"(1, {self.num_blocks})")
            if self._refs[b] < 1:
                raise ValueError(
                    f"ref of free block {b} — a reference must derive "
                    f"from a live owner (alias what exists, never "
                    f"resurrect)")
        for b in block_ids:
            self._refs[b] += 1

    def deref(self, block_ids: Sequence[int]) -> int:
        """Drop one reference per block; blocks reaching zero return to
        the free list.  Returns how many actually freed.
        All-or-nothing like :meth:`ref`: a mispaired id raises before
        any count moves (duplicates in one call count against the
        same refcount)."""
        seen: dict = {}
        for b in block_ids:
            seen[b] = seen.get(b, 0) + 1
            if self._refs[b] < seen[b]:
                raise ValueError(f"deref of unreferenced block {b} — "
                                 f"ref/deref must pair")
        freed = 0
        for b in block_ids:
            self._refs[b] -= 1
            if self._refs[b] == 0:
                self._free.append(int(b))
                self.freed_total += 1
                freed += 1
        return freed

    # ---- allocation + CoW ------------------------------------------------
    def _alloc_one(self) -> int:
        if not self._free and self.reclaim is not None:
            self.reclaim(1)
        if not self._free:
            raise BlockPoolExhausted(
                f"KV block pool exhausted ({self.num_blocks - 1} blocks, "
                f"all referenced) — release streams, evict prefix-cache "
                f"entries, or size num_blocks for the offered load")
        b = self._free.pop()
        self._refs[b] = 1
        self.allocated_total += 1
        return b

    def ensure(self, slot: int, start: int, stop: int
               ) -> List[Tuple[int, int]]:
        """Make rows ``[start, stop)`` of ``slot`` writable in place:
        allocate table entries the span needs, and plan a copy-on-write
        for every already-owned span block whose refcount exceeds one
        (someone else — an aliasing slot or a prefix-cache entry — can
        see its bytes).  Returns ``(src, dst)`` block-id pairs the
        caller must device-copy *before* the write dispatch.  Raises
        :class:`BlockPoolExhausted` (never clamps) when the pool can't
        cover the span."""
        if not 0 <= slot < self.slots:
            raise ValueError(f"slot {slot} out of range [0, {self.slots})")
        if not 0 <= start < stop <= self.max_len:
            raise ValueError(
                f"write span [{start}, {stop}) outside [0, "
                f"{self.max_len}]")
        bs = self.block_size
        cow: List[Tuple[int, int]] = []
        for idx in range(start // bs, -(-stop // bs)):
            if idx >= self._owned[slot]:
                # the span grows the slot: fresh exclusive blocks.
                # Growth is contiguous by construction (writes extend
                # the frontier), but guard it anyway — a gap would
                # leave a null entry under committed rows
                if idx != self._owned[slot]:
                    raise ValueError(
                        f"non-contiguous table growth for slot {slot}: "
                        f"block {idx} past frontier {self._owned[slot]}")
                self._tables[slot, idx] = self._alloc_one()
                self._owned[slot] += 1
                self._dirty = True
            else:
                old = int(self._tables[slot, idx])
                if self._refs[old] > 1:
                    new = self._alloc_one()
                    self._refs[old] -= 1     # the slot's own reference
                    self._tables[slot, idx] = new
                    self._dirty = True
                    self.cow_total += 1
                    cow.append((old, new))
        return cow

    def alias(self, slot: int, block_ids: Sequence[int],
              tokens: int) -> None:
        """Point an empty slot's table at shared blocks (a prefix-cache
        hit): zero device reads, zero copies — each block just gains a
        reference.  ``tokens`` is the valid-row count the ids cover
        (the caller commits it as the slot length)."""
        if not 0 <= slot < self.slots:
            raise ValueError(f"slot {slot} out of range [0, {self.slots})")
        if self._owned[slot]:
            raise ValueError(
                f"alias into slot {slot} which owns "
                f"{int(self._owned[slot])} blocks — release it first")
        ids = [int(b) for b in block_ids]
        if len(ids) > self.blocks_per_slot:
            raise ValueError(
                f"{len(ids)} blocks exceed the table width "
                f"{self.blocks_per_slot}")
        if not 0 < tokens <= len(ids) * self.block_size:
            raise ValueError(
                f"{tokens} tokens not coverable by {len(ids)} blocks of "
                f"{self.block_size}")
        self.ref(ids)                      # validates liveness first
        self._tables[slot, :len(ids)] = ids
        self._owned[slot] = len(ids)
        self._dirty = True
        self.aliased_total += len(ids)

    def fork(self, src: int, dst: int) -> List[int]:
        """Share every block of ``src`` into empty slot ``dst`` (the
        parallel-sampling / n-best branch point).  Both slots' next
        write into any shared block — including the partial tail block
        both are about to append into — triggers CoW, so the streams
        stay bit-isolated.  Returns the shared ids."""
        ids = self.slot_block_ids(src)
        if not ids:
            raise ValueError(f"fork of empty slot {src}")
        self.alias(dst, ids, tokens=len(ids) * self.block_size)
        self.aliased_total -= len(ids)     # alias() counted; fork is not
        return ids                         # a prefix-cache hit

    def release(self, slot: int) -> int:
        """Drop the slot's references (blocks free unless shared) and
        zero its table row.  Returns blocks actually freed."""
        ids = self.slot_block_ids(slot)
        freed = self.deref(ids) if ids else 0
        if ids:
            self._tables[slot, :] = NULL_BLOCK
            self._owned[slot] = 0
            self._dirty = True
        return freed
