"""Zero-downtime weight lifecycle: hot reload, rollback, shadow/A-B.

The serving stack boots its weights once (:mod:`.weights`); a fleet
that "serves while you train" (ROADMAP item 4) cannot drain and
restart every engine each time training publishes a checkpoint.  This
module closes the loop with three layers, all **default-off** — a
scheduler that never constructs them behaves byte-for-byte as before:

- :class:`WeightWatcher` — polls for newer *committed* steps, by
  preference order: an in-process
  :class:`~apex_tpu.resilience.async_checkpoint.AsyncCheckpointer`'s
  ``last_committed`` (exact, GIL-atomic), a supervisor heartbeat file's
  ``ckpt_path`` pointer (the cross-process contract from the
  resilience PR — written strictly *after* commit, so the pointed-at
  step is always whole), or a raw root walk that skips steps the
  live-writer registry marks in flight
  (:func:`~apex_tpu.resilience.checkpoint.in_flight_steps`).

- :class:`HotReloader` — the **double-buffered** reload: the candidate
  is restored through the existing validated path
  (:func:`~apex_tpu.serving.weights.load_serving_params` — v1 and v2
  manifests, fused CRC validation, direct-onto-mesh ``shardings=`` for
  tp engines, optional :class:`~apex_tpu.resilience.retry.RetryPolicy`
  on transient I/O) into a *fresh* buffer that never aliases the
  serving params; a failed restore, a corrupt candidate, or a
  shape/dtype-incompatible tree leaves the engine serving the last
  good weights untouched.  The swap itself is
  :meth:`~apex_tpu.serving.scheduler.ContinuousBatchingScheduler.
  swap_weights` at a step boundary: in-flight streams are preserved
  (decode state is weight-independent), the prefix cache is
  version-bumped (old-weights K/V can never resume a new-weights
  stream), and the displaced buffer is retained so :meth:`~HotReloader.
  rollback` can swap back by the same mechanism.  Every compiled
  program family re-dispatches unchanged — a swap adds **zero** new
  compiles (the engine enforces the same-spec contract that makes that
  true).

- Shadow/A-B (:func:`assign_arm`, :class:`ShadowABScheduler`) — two
  weight versions behind one serving facade: a deterministic
  traffic-fraction mirror (seeded rid hash — stable across runs and
  processes) labels each request's arm; mirrored requests are COPIED
  to a shadow scheduler serving the candidate weights while their
  originals keep serving from the incumbent, so users only ever see
  incumbent output.  Per-arm SLO reports
  (:func:`~apex_tpu.obs.slo.build_report` over the request-trace
  recorder's records) compare candidate vs incumbent before a
  promotion decision.

Chaos coverage (``tests/test_serving_reload.py``) drives the whole
lifecycle under :mod:`~apex_tpu.resilience.fault_injection`: corrupt /
truncated candidates mid-reload, a :class:`SimulatedWriterCrash`
racing the watcher against a live ``AsyncCheckpointer``, and a reload
storm under 2x overload — every perturbation must leave the engine
serving the last-good weights with all streams intact.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import time
from typing import Any, Callable, Dict, List, Optional

from apex_tpu._logging import emit_event, get_logger
from apex_tpu.resilience import checkpoint as _ckpt
from apex_tpu.resilience.retry import RetryPolicy, retry_transient
from apex_tpu.serving.weights import load_serving_params

__all__ = ["WeightWatcher", "HotReloader", "ReloadOutcome",
           "ABConfig", "ShadowABScheduler", "assign_arm"]

logger = get_logger("serving.reload")


def _step_of_ckpt_path(path: str) -> Optional[int]:
    """The step a committed checkpoint path names, or None — the
    heartbeat's ``ckpt_path`` is the cross-process committed pointer."""
    name = os.path.basename(os.path.normpath(path))
    if not name.startswith(_ckpt._STEP_PREFIX):
        return None
    try:
        return int(name[len(_ckpt._STEP_PREFIX):])
    except ValueError:
        return None


class WeightWatcher:
    """Poll for a newer committed checkpoint step than the one served.

    Exactly one source is used, by constructor argument:

    - ``checkpointer=`` — an in-process ``AsyncCheckpointer``; its
      ``last_committed`` property is set strictly after the atomic
      commit rename, so the returned step is always whole.
    - ``heartbeat_path=`` — a supervisor heartbeat file; its
      ``ckpt_path`` field points at the last *committed* checkpoint
      (written after commit by the training loop's heartbeat).  An
      unreadable / half-missing heartbeat is "nothing new", never an
      error: liveness files are best-effort by contract.
    - neither — walk ``root`` for the newest listed step, skipping
      steps the live-writer registry marks in flight (a re-save swaps
      the committed dir aside mid-commit; selecting it would race the
      writer).  Listing only ever sees committed ``step_*`` dirs —
      temp dirs are invisible by construction.

    ``poll()`` returns a step strictly newer than ``last_seen`` (or
    None); the reloader calls ``mark(step)`` after a successful swap so
    a refused candidate is re-offered every poll until it is repaired
    or superseded — a corrupt candidate must not wedge the watcher.
    """

    def __init__(self, root: str, *,
                 heartbeat_path: Optional[str] = None,
                 checkpointer: Any = None,
                 last_seen: Optional[int] = None):
        if heartbeat_path is not None and checkpointer is not None:
            raise ValueError("pass heartbeat_path= or checkpointer=, "
                             "not both — one committed-step source")
        self.root = root
        self.heartbeat_path = heartbeat_path
        self.checkpointer = checkpointer
        self.last_seen = last_seen
        self._polls = 0

    def committed_step(self) -> Optional[int]:
        """Newest committed step the source reports right now."""
        if self.checkpointer is not None:
            lc = self.checkpointer.last_committed
            return None if lc is None else int(lc[0])
        if self.heartbeat_path is not None:
            try:
                from apex_tpu.resilience.supervisor import read_heartbeat

                hb = read_heartbeat(self.heartbeat_path)
            except (OSError, ValueError) as e:
                logger.debug("heartbeat unreadable: %s", e)
                return None
            path = hb.get("ckpt_path")
            return None if not path else _step_of_ckpt_path(str(path))
        live = _ckpt.in_flight_steps(self.root)
        committed = [s for s in _ckpt._list_steps(self.root)
                     if s not in live]
        return committed[-1] if committed else None

    def poll(self) -> Optional[int]:
        """A committed step strictly newer than ``last_seen``, or None."""
        self._polls += 1
        step = self.committed_step()
        if step is None:
            return None
        if self.last_seen is not None and step <= self.last_seen:
            return None
        return step

    def mark(self, step: int) -> None:
        """Record ``step`` as applied; later polls only report newer."""
        if self.last_seen is None or step > self.last_seen:
            self.last_seen = int(step)

    @property
    def polls(self) -> int:
        return self._polls


@dataclasses.dataclass
class ReloadOutcome:
    """One reload (or rollback) attempt's result + phase timings."""

    ok: bool
    step: Optional[int]          # step now served (ok) / refused (not)
    from_step: Optional[int]     # step served before the attempt
    version: int                 # engine weights_version after
    reason: Optional[str] = None       # refusal reason (ok=False)
    restore_s: float = 0.0
    validate_s: float = 0.0
    swap_s: float = 0.0
    rollback: bool = False


class HotReloader:
    """Double-buffered hot weight reload over one scheduler.

    >>> reloader = HotReloader(sched, root, like=train_state,
    ...                        params_key="params", watcher=watcher)
    >>> out = reloader.maybe_reload()      # at any step boundary
    >>> reloader.rollback()                # one-step undo, same swap

    The lifecycle invariants (each pinned by tier-1):

    - **Failed validate never serves.**  The candidate restores into a
      fresh buffer through the fused-validation path; any
      :class:`CheckpointError` (corrupt bytes, truncation, structure
      mismatch) or spec mismatch against the served tree refuses the
      swap with the serving params untouched — bit-exactly.
    - **Streams survive the swap.**  The swap happens through
      ``scheduler.swap_weights`` at a step boundary: active slots keep
      their KV cache / lengths / sampler state and continue under the
      new weights; nothing is dropped, and the post-swap tokens are
      bit-identical to a fresh engine booted on the new weights and
      fed the same state.
    - **Rollback is a swap.**  The displaced buffer is retained
      (double buffering — one previous version, the production
      playbook's one-step undo); ``rollback()`` swaps it back through
      the identical mechanism, prefix-cache invalidation included.
    - **Restore-ahead.**  :meth:`prefetch` stages the next candidate
      (restore + validate) off the serving path at any time; the
      matching ``reload()`` then pauses serving only for the pointer
      swap.  The staged buffer is a third, invisible buffer — staging
      never touches the serving or rollback params.

    ``retry`` (a :class:`RetryPolicy`) retries *transient* I/O during
    the restore; deterministic corruption propagates immediately into
    the refusal path.  ``shardings`` (or a tp engine's own layout,
    derived automatically) restores the candidate directly onto the
    mesh — the swap's ``device_put`` is then a no-op transfer.
    """

    def __init__(self, scheduler, root: str, *, like: Any,
                 params_key: Optional[str] = None,
                 policy: Any = None,
                 shardings: Any = None,
                 retry: Optional[RetryPolicy] = None,
                 watcher: Optional[WeightWatcher] = None,
                 current_step: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.scheduler = scheduler
        self.engine = scheduler.engine
        self.root = root
        self.like = like
        self.params_key = params_key
        self.policy = policy
        self.retry = retry
        self.watcher = watcher if watcher is not None else WeightWatcher(
            root, last_seen=current_step)
        self._clock = clock
        if shardings is None and getattr(self.engine, "mesh", None) is not None:
            from apex_tpu.serving.engine import tp_param_shardings

            shardings = tp_param_shardings(self.engine.params,
                                           self.engine.mesh)
        self.shardings = shardings
        self._current_step = current_step
        self._previous: Optional[tuple] = None   # (params, step)
        self._staged: Optional[tuple] = None     # (params, step,
        #                                           restore_s, validate_s)
        self._reloads = 0
        self._refusals = 0
        self._prefetches = 0
        self._discarded_stages = 0
        # seed the scheduler's served-version tag so routed/finished
        # events carry the boot step from the first request on
        if current_step is not None and hasattr(scheduler,
                                                "weights_step"):
            scheduler.weights_step = int(current_step)

    # ---- introspection ---------------------------------------------------
    @property
    def current_step(self) -> Optional[int]:
        """Step of the weights being served (None = boot params of
        unknown step)."""
        return self._current_step

    @property
    def previous_step(self) -> Optional[int]:
        """Step of the retained rollback buffer, or None."""
        return self._previous[1] if self._previous is not None else None

    @property
    def can_rollback(self) -> bool:
        return self._previous is not None

    @property
    def staged_step(self) -> Optional[int]:
        """Step of the restore-ahead candidate staged by
        :meth:`prefetch`, or None when nothing is staged."""
        return self._staged[1] if self._staged is not None else None

    @property
    def stats(self) -> Dict[str, int]:
        return {"reloads": self._reloads, "refusals": self._refusals,
                "prefetches": self._prefetches,
                "discarded_stages": self._discarded_stages,
                "watcher_polls": self.watcher.polls}

    # ---- the lifecycle ---------------------------------------------------
    def maybe_reload(self) -> Optional[ReloadOutcome]:
        """Poll the watcher; reload if a newer committed step exists.
        Returns None when there is nothing new (the steady-state path:
        one cheap poll, zero device work, zero events)."""
        step = self.watcher.poll()
        if step is None:
            return None
        return self.reload(step=step)

    def _refuse(self, step: Optional[int], reason: str,
                restore_s: float, validate_s: float) -> ReloadOutcome:
        self._refusals += 1
        logger.warning("reload refused (step %s): %s", step, reason)
        emit_event("serving_reload_failed", step=step,
                   reason=reason[:500],
                   serving_step=self._current_step)
        return ReloadOutcome(
            ok=False, step=step, from_step=self._current_step,
            version=int(self.engine.weights_version), reason=reason,
            restore_s=restore_s, validate_s=validate_s)

    def prefetch(self, *, step: Optional[int] = None) -> Optional[int]:
        """Restore-ahead: stage the next candidate off the serving
        path, so the step-boundary :meth:`reload` pause is just the
        pointer swap (``swap_s``, ~1 ms) instead of being dominated by
        the restore (~tens of ms for even a small model).

        Restores and validates the candidate into a staged buffer
        right now (safe at any time — the serving params are never
        touched) and returns the staged step, or None when nothing
        could be staged (no committed step, restore failure, or spec
        mismatch — logged, not a formal refusal: nothing was offered
        for serving, and the later :meth:`reload` re-walks the full
        path and refuses first-class).  A later ``reload()`` whose
        target matches the staged step consumes the stage and skips
        straight to the swap; a non-matching target discards the stale
        stage and restores fresh.
        """
        if step is None:
            step = self.watcher.committed_step()
            if step is None:
                return None
        if (self._staged is not None
                and self._staged[1] == int(step)):
            return int(step)             # already staged — idempotent
        t0 = self._clock()

        def _restore():
            return load_serving_params(
                self.root, self.like, params_key=self.params_key,
                policy=self.policy, step=step, shardings=self.shardings)

        try:
            if self.retry is not None:
                candidate, got = retry_transient(
                    _restore, policy=self.retry, what="serving_reload")
            else:
                candidate, got = _restore()
        except Exception as e:
            logger.warning("prefetch failed (step %s): %s: %s",
                           step, type(e).__name__, e)
            return None
        restore_s = self._clock() - t0
        t1 = self._clock()
        mismatch = self._spec_mismatch(candidate)
        validate_s = self._clock() - t1
        if mismatch is not None:
            logger.warning("prefetch staged nothing (step %s): %s",
                           got, mismatch)
            return None
        self._staged = (candidate, int(got), restore_s, validate_s)
        self._prefetches += 1
        return int(got)

    def reload(self, *, step: Optional[int] = None) -> ReloadOutcome:
        """Restore → validate → swap, double-buffered.

        ``step`` pins the candidate (the watcher path); ``None`` takes
        the newest valid committed step.  Call at a step boundary only
        (between ``scheduler.step()`` calls — e.g. a loadgen
        ``step_hook``).  Never raises for a bad candidate: refusal is
        an outcome (``ok=False`` + a ``serving_reload_failed`` event),
        because the server must keep serving.

        When :meth:`prefetch` staged this exact step, the restore and
        validate phases were already paid off the serving path: the
        boundary pause here is only the swap.  The emitted timings
        keep the staged restore_s/validate_s (the work was real — it
        just didn't stall serving) plus ``prefetched=True``.
        """
        candidate = None
        prefetched = False
        if self._staged is not None:
            want = step if step is not None \
                else self.watcher.committed_step()
            if want is not None and int(want) == self._staged[1]:
                candidate, got, restore_s, validate_s = self._staged
                prefetched = True
            else:
                self._discarded_stages += 1
                logger.info("discarding stale stage (step %s): reload "
                            "target is %s", self._staged[1], want)
            self._staged = None          # consumed or stale either way

        if candidate is None:
            t0 = self._clock()

            def _restore():
                return load_serving_params(
                    self.root, self.like, params_key=self.params_key,
                    policy=self.policy, step=step,
                    shardings=self.shardings)

            try:
                if self.retry is not None:
                    candidate, got = retry_transient(
                        _restore, policy=self.retry,
                        what="serving_reload")
                else:
                    candidate, got = _restore()
            except Exception as e:
                # the double-buffer guarantee: the failure happened
                # entirely inside the candidate buffer — serving
                # params untouched
                return self._refuse(step, f"{type(e).__name__}: {e}",
                                    self._clock() - t0, 0.0)
            restore_s = self._clock() - t0

            # validation gate against the SERVED tree: structure +
            # leaf shape/dtype must match or every compiled program
            # would retrace.  swap_params enforces this too — checking
            # here makes the refusal a first-class outcome instead of
            # an exception, and times the phase separately from the
            # pointer swap.
            t1 = self._clock()
            mismatch = self._spec_mismatch(candidate)
            validate_s = self._clock() - t1
            if mismatch is not None:
                return self._refuse(got, mismatch, restore_s,
                                    validate_s)

        t2 = self._clock()
        displaced = self.scheduler.swap_weights(candidate,
                                                step=int(got))
        swap_s = self._clock() - t2
        self._previous = (displaced, self._current_step)
        from_step = self._current_step
        self._current_step = int(got)
        self._reloads += 1
        self.watcher.mark(int(got))
        version = int(self.engine.weights_version)
        emit_event("serving_weights_swapped", step=int(got),
                   from_step=from_step, version=version, rollback=False,
                   prefetched=prefetched,
                   restore_s=round(restore_s, 6),
                   validate_s=round(validate_s, 6),
                   swap_s=round(swap_s, 6))
        return ReloadOutcome(ok=True, step=int(got), from_step=from_step,
                             version=version, restore_s=restore_s,
                             validate_s=validate_s, swap_s=swap_s)

    def rollback(self) -> ReloadOutcome:
        """Swap back to the retained previous buffer (step-boundary
        call, same mechanism as a reload's swap — prefix-cache
        invalidation included).  The displaced current buffer becomes
        the new rollback target, so rollback twice toggles."""
        if self._previous is None:
            raise RuntimeError("rollback() with no retained previous "
                               "weights — no reload has succeeded yet")
        if self._staged is not None:
            # the stage belongs to the version line being abandoned: a
            # later reload() consuming it would silently re-promote the
            # rolled-back direction.  Discard it, counted.
            self._discarded_stages += 1
            logger.info("rollback discards staged step %s",
                        self._staged[1])
            self._staged = None
        params, prev_step = self._previous
        t0 = self._clock()
        displaced = self.scheduler.swap_weights(params, step=prev_step)
        swap_s = self._clock() - t0
        from_step = self._current_step
        self._previous = (displaced, from_step)
        self._current_step = prev_step
        version = int(self.engine.weights_version)
        # no restore_s/validate_s: a rollback restores nothing, and the
        # bridge must not observe fabricated 0.0 phase samples
        emit_event("serving_weights_swapped", step=prev_step,
                   from_step=from_step, version=version, rollback=True,
                   swap_s=round(swap_s, 6))
        return ReloadOutcome(ok=True, step=prev_step, from_step=from_step,
                             version=version, swap_s=swap_s,
                             rollback=True)

    def _spec_mismatch(self, candidate: Any) -> Optional[str]:
        """None when ``candidate`` is swap-compatible with the served
        params, else the human-readable refusal reason."""
        import jax
        import jax.numpy as jnp

        old_leaves, old_def = jax.tree_util.tree_flatten(
            self.engine.params)
        new_leaves, new_def = jax.tree_util.tree_flatten(candidate)
        if new_def != old_def:
            return (f"candidate tree structure differs from served "
                    f"params ({new_def} != {old_def})")
        for i, (o, n) in enumerate(zip(old_leaves, new_leaves)):
            if (tuple(o.shape) != tuple(n.shape)
                    or jnp.dtype(o.dtype) != jnp.dtype(n.dtype)):
                return (f"leaf {i}: candidate "
                        f"{tuple(n.shape)}/{jnp.dtype(n.dtype)} vs "
                        f"served {tuple(o.shape)}/{jnp.dtype(o.dtype)}")
        return None


# --------------------------------------------------------------------------
# shadow / A-B serving
# --------------------------------------------------------------------------


def assign_arm(rid: str, *, fraction: float, seed: int = 0) -> bool:
    """Deterministic traffic-fraction mirror decision: True == this rid
    is mirrored to the candidate arm.  A seeded blake2b hash of the rid
    maps to ``[0, 1)`` and compares against ``fraction`` — stable
    across runs, processes, and submission order (the property the
    seed-deterministic A/B acceptance pins), with no shared RNG state
    to race."""
    if not 0.0 <= fraction <= 1.0:
        raise ValueError(f"fraction must be in [0, 1], got {fraction}")
    digest = hashlib.blake2b(f"{seed}:{rid}".encode(),
                             digest_size=8).digest()
    u = int.from_bytes(digest, "big") / 2.0 ** 64
    return u < fraction


@dataclasses.dataclass(frozen=True)
class ABConfig:
    """Shadow/A-B mirror configuration.

    ``fraction`` of requests (deterministically chosen by
    :func:`assign_arm` under ``seed``) are mirrored: the original keeps
    serving from the incumbent scheduler — users only ever see
    incumbent output — while a copy with rid ``mirror_prefix + rid``
    runs on the shadow scheduler's candidate weights.  Per-arm SLO
    reports then compare the two on identical traffic."""

    fraction: float = 0.1
    seed: int = 0
    mirror_prefix: str = "shadow:"

    def __post_init__(self):
        if not 0.0 <= self.fraction <= 1.0:
            raise ValueError(
                f"fraction must be in [0, 1], got {self.fraction}")
        if not self.mirror_prefix:
            raise ValueError("mirror_prefix must be non-empty (mirror "
                             "rids must be distinguishable)")


class ShadowABScheduler:
    """Two weight versions behind one serving facade.

    Duck-types the scheduler surface a :class:`~apex_tpu.serving.
    loadgen.LoadGenerator` drives (``submit`` / ``step`` /
    ``queue_depth`` / ``active_count`` / ``suspended_count`` /
    ``results`` / ``clock``), delegating to the *primary* (incumbent)
    scheduler; mirrored submissions are copied to the *shadow*
    scheduler (candidate weights), which is stepped in the same
    boundary.  Both schedulers must share one clock object (the
    virtual-clock determinism contract); the facade checks.

    Shed semantics: a full primary queue raises ``QueueFull`` exactly
    like a plain scheduler (open-loop loadgen sheds it); a full
    *shadow* queue silently drops only the mirror copy (shadow traffic
    must never degrade incumbent service) and counts it in
    ``mirror_shed``.
    """

    def __init__(self, primary, shadow, config: ABConfig):
        if primary.clock is not shadow.clock:
            raise ValueError(
                "primary and shadow schedulers must share one clock "
                "object — construct both with the same (virtual) clock "
                "so mirrored timing is comparable")
        if primary is shadow or primary.engine is shadow.engine:
            raise ValueError("primary and shadow must be distinct "
                             "schedulers over distinct engines (two "
                             "weight buffers)")
        self.primary = primary
        self.shadow = shadow
        self.config = config
        self._mirrored: List[str] = []     # rids mirrored, in order
        self._mirror_shed = 0

    # ---- the LoadGenerator-facing surface --------------------------------
    @property
    def clock(self):
        return self.primary.clock

    @property
    def engine(self):
        return self.primary.engine

    # pending-work counts cover BOTH arms: a LoadGenerator (or any
    # drain loop) polling them must keep stepping until the shadow's
    # mirror streams finish too, or the candidate arm's records would
    # be truncated mid-flight
    @property
    def queue_depth(self) -> int:
        return self.primary.queue_depth + self.shadow.queue_depth

    @property
    def active_count(self) -> int:
        return self.primary.active_count + self.shadow.active_count

    @property
    def suspended_count(self) -> int:
        return (self.primary.suspended_count
                + self.shadow.suspended_count)

    @property
    def steps_run(self) -> int:
        return self.primary.steps_run

    @property
    def results(self):
        return self.primary.results

    def pop_result(self, rid: str):
        return self.primary.pop_result(rid)

    def pop_results(self):
        return self.primary.pop_results()

    def submit(self, request) -> None:
        """Submit to the incumbent; mirror a deterministic fraction to
        the shadow.  ``QueueFull`` propagates from the PRIMARY submit
        only, and only after any mirror copy was decided — the arm
        assignment is a pure rid hash, so a shed request sheds in both
        arms identically."""
        mirrored = assign_arm(request.rid, fraction=self.config.fraction,
                              seed=self.config.seed)
        self.primary.submit(request)        # may raise QueueFull
        if mirrored:
            self._mirrored.append(request.rid)
            copy = dataclasses.replace(
                request, rid=self.config.mirror_prefix + request.rid)
            try:
                self.shadow.submit(copy)
            except Exception as e:
                # shadow capacity must never hurt incumbent service:
                # drop the mirror, keep the original
                self._mirror_shed += 1
                logger.debug("mirror %s shed: %s", copy.rid, e)

    def step(self) -> List[str]:
        """One facade step: primary first (user-visible service), then
        the shadow if it has work.  Returns the PRIMARY's finished rids
        — shadow completions are never user-visible."""
        out = self.primary.step()
        if (self.shadow.queue_depth or self.shadow.active_count
                or self.shadow.suspended_count):
            self.shadow.step()
        return out

    def run(self, max_steps: Optional[int] = None):
        """Drain both arms; returns the primary's results."""
        steps = 0
        bound = max_steps if max_steps is not None else (
            self.primary._derived_step_bound()
            + self.shadow._derived_step_bound())
        while (self.primary.queue_depth or self.primary.active_count
               or self.primary.suspended_count
               or self.shadow.queue_depth or self.shadow.active_count
               or self.shadow.suspended_count):
            if steps >= bound:
                raise RuntimeError(
                    f"A/B drain stalled after {steps} steps")
            self.step()
            steps += 1
        return self.primary.results

    # ---- per-arm accounting ----------------------------------------------
    @property
    def mirrored_rids(self) -> List[str]:
        """Rids assigned to the mirror (submission order)."""
        return list(self._mirrored)

    @property
    def mirror_shed(self) -> int:
        return self._mirror_shed

    def arm_of(self, rid: str) -> str:
        """``"candidate"`` for a mirror-copy rid, ``"incumbent"`` for a
        mirrored original, ``"unmirrored"`` otherwise."""
        if rid.startswith(self.config.mirror_prefix):
            return "candidate"
        return ("incumbent" if rid in set(self._mirrored)
                else "unmirrored")

    def arm_records(self, records) -> Dict[str, list]:
        """Partition request-trace records by arm: ``candidate`` =
        shadow mirror copies, ``incumbent`` = their primary originals —
        the SAME traffic on both weight versions, which is what makes
        the per-arm comparison fair.  Unmirrored records are excluded
        from both arms."""
        mirrored = set(self._mirrored)
        prefix = self.config.mirror_prefix
        out: Dict[str, list] = {"incumbent": [], "candidate": []}
        for rec in records:
            rid = rec.rid
            if rid.startswith(prefix) and rid[len(prefix):] in mirrored:
                out["candidate"].append(rec)
            elif rid in mirrored:
                out["incumbent"].append(rec)
        return out

    def arm_reports(self, records, *,
                    deadlines: Optional[Dict[str, Optional[float]]] = None,
                    arrivals: Optional[Dict[str, float]] = None,
                    duration_s: Optional[float] = None) -> Dict[str, Any]:
        """Per-arm :class:`~apex_tpu.obs.slo.SLOReport` over the SAME
        mirrored traffic: candidate vs incumbent, the promotion
        comparison.  ``deadlines``/``arrivals`` are keyed by ORIGINAL
        rid (e.g. straight from a ``LoadgenResult``); the candidate
        arm's mirror rids are mapped back automatically."""
        from apex_tpu.obs.slo import build_report

        arms = self.arm_records(records)
        prefix = self.config.mirror_prefix

        def base_rid(rid: str) -> str:
            return rid[len(prefix):] if rid.startswith(prefix) else rid

        reports = {}
        for arm, recs in arms.items():
            dl = (None if deadlines is None
                  else {r.rid: deadlines.get(base_rid(r.rid))
                        for r in recs})
            ar = (None if arrivals is None
                  else {r.rid: arrivals[base_rid(r.rid)] for r in recs
                        if base_rid(r.rid) in arrivals})
            reports[arm] = build_report(
                recs, offered=len(recs), deadlines=dl, arrivals=ar,
                duration_s=duration_s)
        return reports
