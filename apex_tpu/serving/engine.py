"""DecodeEngine: jitted prefill + single-token decode over a KV cache.

Wraps :class:`~apex_tpu.models.llama.LlamaForCausalLM` with exactly two
compiled programs — a **prefill** (full-prompt forward that also fills
one cache slot) and a **batched decode step** (one token per slot) —
both shape-stable by construction: prompts are padded to a fixed
``prefill_len``, decode always runs all ``slots`` lanes, and the cache
is preallocated (:mod:`apex_tpu.serving.kv_cache`).  After the warmup
call each function's jit cache holds exactly one entry no matter how
requests arrive (`tests/test_serving.py` asserts this via
``jax.jit``'s ``_cache_size``).

Numerics contract (the acceptance bar): greedy incremental decode
through the cache is **bit-identical** — same f32 logits — to the
*shape-stable* uncached full-context forward (context padded to
``max_len``, the recompile-free form a TPU server would actually run)
at every length, and produces the identical greedy argmax stream as the
unpadded forward, including GQA configs.  Ingredients: rope applied at
the true position through ``_rope_freqs``'s vector-offset path,
attention reads masked with the flash kernels' exact ``-1e30`` (masked
``exp`` underflows to 0.0, so same-extent reductions round
identically; see ``models.llama._decode_attention``), and logits
through the same ``parallel_lm_logits`` head matmul as the plain
forward (the fused LM *head-loss* kernel is training-only — serving
has no labels).

Sampling is a pure function of ``(logits, key, temperature, top_k)``
with explicit PRNG keys — no ambient state, so a replayed request
reproduces its exact token stream.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from apex_tpu._logging import get_logger
from apex_tpu.serving.kv_cache import KVCache, init_cache

__all__ = ["DecodeEngine", "sample_tokens", "request_key", "token_key"]

logger = get_logger("serving.engine")


def _sample_one(logits, base_key, index, temperature, top_k):
    """One token from one ``[vocab]`` logits row — fully traced, so the
    vmapped form never retraces on per-request sampling params.

    The per-token key is derived *inside* the jitted sampler
    (``fold_in(base_key, index)``, identical to :func:`token_key`): the
    host hands over one base key per stream plus an integer index, so a
    whole decode step's sampling is ONE dispatch — no per-slot fold_in
    ops or device->host syncs on the serving hot path.

    ``temperature <= 0`` is greedy (argmax).  ``top_k > 0`` keeps only
    the k highest logits (threshold from a descending sort — ``top_k``
    is a *traced* scalar, so mixed-k batches share one compile);
    ``top_k <= 0`` means no truncation.
    """
    key = jax.random.fold_in(base_key, index)
    vocab = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    thresh = jnp.sort(logits)[::-1][jnp.clip(top_k - 1, 0, vocab - 1)]
    masked = jnp.where((top_k > 0) & (logits < thresh), -jnp.inf, logits)
    temp = jnp.where(temperature > 0, temperature, 1.0)
    tok = jax.random.categorical(key, masked / temp).astype(jnp.int32)
    return jnp.where(temperature > 0, tok, greedy)


sample_tokens = jax.jit(jax.vmap(_sample_one))
"""Batched sampler: ``(logits [n, vocab], base_keys [n, 2], indices [n],
temperatures [n], top_ks [n]) -> tokens [n]`` — deterministic per
``(base_key, index)``; equals sampling with ``token_key(base, index)``."""


def request_key(seed: int) -> jax.Array:
    """Base PRNG key for one request (explicit, replayable)."""
    return jax.random.PRNGKey(seed)


def token_key(base: jax.Array, index) -> jax.Array:
    """Key for the ``index``-th generated token of a request."""
    return jax.random.fold_in(base, index)


class DecodeEngine:
    """KV-cached incremental decoding for a Llama-family model.

    >>> eng = DecodeEngine(model, params, slots=8, max_len=512,
    ...                    prefill_len=64)
    >>> first_logits = eng.prefill(slot=0, tokens=prompt_ids)
    >>> logits = eng.decode(tokens, active)       # one step, all slots
    >>> eng.release(0)                            # O(1) slot reuse

    The engine owns the cache functionally: every call swaps in the
    updated :class:`KVCache`.  ``slots``/``max_len``/``prefill_len`` are
    compile-time constants — choose ``prefill_len`` as the prompt-length
    ceiling (prompts are right-padded to it; the padded K/V are written
    but never readable, because per-slot lengths mask them).
    """

    def __init__(self, model, params, *, slots: int = 8,
                 max_len: int = 512, prefill_len: int = 64,
                 cache_dtype=None):
        if prefill_len < 2:
            raise ValueError("prefill_len must be >= 2 (a length-1 "
                             "prefill is indistinguishable from a decode "
                             "step; pad the buffer)")
        if prefill_len > max_len:
            raise ValueError(f"prefill_len {prefill_len} > max_len "
                             f"{max_len}")
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.model = model
        self.params = params
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.prefill_len = int(prefill_len)
        if cache_dtype is None:
            # serve in the params' own precision (bf16 params -> bf16
            # cache); fall back to f32 for exotic all-int trees
            floats = [l.dtype for l in jax.tree.leaves(params)
                      if hasattr(l, "dtype")
                      and jnp.issubdtype(l.dtype, jnp.floating)]
            cache_dtype = floats[0] if floats else jnp.float32
        self._cache = init_cache(model.config, slots=slots,
                                 max_len=max_len, dtype=cache_dtype)
        # host mirror of per-slot lengths: lets every call validate slot
        # bounds and cache capacity WITHOUT a device->host sync on the
        # decode hot path (dynamic_update_slice clamps out-of-range
        # indices silently — overflow must be an error, not corruption)
        self._lengths_host = np.zeros((self.slots,), np.int64)

        def _prefill(params, cache, ids, slot, length):
            # ids [1, prefill_len]; returns the logits at the LAST REAL
            # position (the next-token distribution) + the filled cache
            logits, cache = model.apply(params, ids, kv_cache=cache,
                                        slot=slot)
            cache = dataclasses.replace(
                cache, lengths=cache.lengths.at[slot].set(length))
            last = lax.dynamic_index_in_dim(logits[:, 0, :], length - 1,
                                            axis=0, keepdims=False)
            return last.astype(jnp.float32), cache

        def _decode(params, cache, tokens, active):
            # tokens [slots] int32 (last sampled per slot); active [slots]
            # bool — inactive lanes still compute (shape stability) but
            # never advance their length, so their writes are unreadable
            position = cache.lengths
            logits, cache = model.apply(params, tokens[:, None],
                                        kv_cache=cache, position=position)
            cache = dataclasses.replace(
                cache,
                lengths=cache.lengths + active.astype(jnp.int32))
            return logits[0].astype(jnp.float32), cache

        # the cache argument is donated: the engine discards the old
        # functional copy on every call, and without aliasing each
        # one-token step would copy the whole preallocated k/v pair
        self._prefill = jax.jit(_prefill, donate_argnums=(1,))
        self._decode = jax.jit(_decode, donate_argnums=(1,))
        logger.debug("DecodeEngine: slots=%d max_len=%d prefill_len=%d "
                     "cache_dtype=%s", self.slots, self.max_len,
                     self.prefill_len, jnp.dtype(cache_dtype).name)

    # ---- cache/slot state ------------------------------------------------
    @property
    def cache(self) -> KVCache:
        return self._cache

    def lengths(self) -> np.ndarray:
        """Per-slot valid-token counts (0 = free), from the host mirror
        — no device sync."""
        return self._lengths_host.copy()

    def free_slots(self) -> list[int]:
        return [i for i, n in enumerate(self._lengths_host) if n == 0]

    def cache_utilization(self) -> float:
        """Filled cache positions / total capacity, in ``[0, 1]`` — from
        the host mirror, so sampling it every step costs no device sync.
        The number an admission controller actually wants: slot
        occupancy says how many streams are live, utilization says how
        much of the preallocated KV memory their tokens fill."""
        return float(self._lengths_host.sum()) / float(
            self.slots * self.max_len)

    def _check_slot(self, slot: int) -> None:
        if not 0 <= slot < self.slots:
            raise ValueError(f"slot {slot} out of range [0, {self.slots})")

    def release(self, slot: int) -> None:
        """Evict a slot (O(1)); its bytes stay masked until overwritten."""
        from apex_tpu.serving.kv_cache import release_slot

        self._check_slot(slot)
        self._cache = release_slot(self._cache, slot)
        self._lengths_host[slot] = 0

    def reset(self) -> None:
        """Free every slot (keeps compiled programs and allocations)."""
        self._cache = dataclasses.replace(
            self._cache, lengths=jnp.zeros((self.slots,), jnp.int32))
        self._lengths_host[:] = 0

    def decode_compiles(self) -> int:
        """Number of distinct compiles of the decode step (1 == the
        shape-stable contract held: no per-request retraces)."""
        return self._decode._cache_size()

    # ---- the two compiled programs ---------------------------------------
    def prefill(self, slot: int, tokens: Sequence[int]) -> jax.Array:
        """Fill ``slot`` with a prompt; return its next-token logits
        ``[vocab]`` (f32)."""
        self._check_slot(slot)
        if self._lengths_host[slot]:
            raise ValueError(
                f"slot {slot} is occupied ({self._lengths_host[slot]} "
                f"tokens); release() it before prefilling — silently "
                f"clobbering a live stream is the corruption class these "
                f"guards exist for")
        n = len(tokens)
        if not 1 <= n <= self.prefill_len:
            raise ValueError(f"prompt length {n} not in [1, "
                             f"{self.prefill_len}]")
        ids = np.zeros((1, self.prefill_len), np.int32)
        ids[0, :n] = np.asarray(tokens, np.int32)
        logits, self._cache = self._prefill(
            self.params, self._cache, jnp.asarray(ids),
            jnp.int32(slot), jnp.int32(n))
        self._lengths_host[slot] = n
        return logits

    def decode(self, tokens, active) -> jax.Array:
        """One batched decode step: append ``tokens[slot]`` to every
        active slot, return per-slot next-token logits ``[slots, vocab]``
        (f32).  Inactive lanes return garbage rows — callers mask by
        ``active``.  Raises when an active slot is already at
        ``max_len`` (the append would silently clobber the last cached
        token otherwise)."""
        act = np.asarray(active, bool)
        full = act & (self._lengths_host >= self.max_len)
        if full.any():
            raise ValueError(
                f"slots {np.flatnonzero(full).tolist()} are at cache "
                f"capacity ({self.max_len}); release or raise max_len")
        empty = act & (self._lengths_host == 0)
        if empty.any():
            raise ValueError(
                f"slots {np.flatnonzero(empty).tolist()} are active but "
                f"never prefilled — a decode step would expose a garbage "
                f"token as their whole context")
        logits, self._cache = self._decode(
            self.params, self._cache,
            jnp.asarray(tokens, jnp.int32), jnp.asarray(act))
        self._lengths_host[act] += 1
        return logits

    # ---- sampling --------------------------------------------------------
    @staticmethod
    def sample(logits, base_keys, indices, temperatures,
               top_ks) -> jax.Array:
        """Vectorized deterministic sampling (see :func:`sample_tokens`)."""
        return sample_tokens(
            jnp.asarray(logits), jnp.asarray(base_keys),
            jnp.asarray(indices, jnp.int32),
            jnp.asarray(temperatures, jnp.float32),
            jnp.asarray(top_ks, jnp.int32))
