"""DecodeEngine: bucketed chunked prefill + single-token decode +
speculative multi-token verify over a KV cache.

Wraps :class:`~apex_tpu.models.llama.LlamaForCausalLM` with a *bounded*
set of compiled programs — one **prefill chunk** program per bucket in
a small power-of-two bucket table (a short prompt costs a short
dispatch instead of a full ``prefill_len``-sized one), exactly one
**batched decode step** (one token per slot), and one **speculative
verify** program per entry in a small ``draft_buckets`` table (scores
a pending token plus up to ``max_draft`` drafted candidates in one
cached multi-token forward — see :meth:`DecodeEngine.verify_draft`) —
all shape-stable by construction: chunks and drafts are padded to the
smallest covering bucket, decode always runs all ``slots`` lanes, and
the cache is preallocated (:mod:`apex_tpu.serving.kv_cache`).  The
cross-request prefix cache adds two more bounded families: a
**prefix restore** program per prefill bucket (previously captured K/V
written back verbatim — :meth:`DecodeEngine.restore_prefix`) and a
fixed-extent **region read** for block capture
(:meth:`DecodeEngine.read_region`; one compile per span extent,
bounded by the blocks-per-chunk count).  After warmup the decode jit cache
holds exactly one entry and the prefill / verify / restore jit caches
at most one entry per bucket, no matter how requests arrive
(`tests/test_serving.py` / `tests/test_serving_spec.py` /
`tests/test_serving_prefix.py` assert them through
:func:`apex_tpu.utils.compat.compile_count`).

Prompts longer than ``prefill_len`` are served by **chunked cached
prefill**: the prompt is split into ``prefill_len``-sized chunks (tail
bucketed), and each chunk's causal block attends previously cached
tokens through the same masked fixed-extent read the decode step uses —
any prompt up to ``max_len`` serves, and splitting never changes a bit.
(That fixed extent is also the cost model: a chunk's attention reads
the full ``max_len`` axis — ``O(bucket * max_len)`` — while the
bucket-scaled projections/MLP/head dominate at transformer widths; see
``docs/api/serving.md`` for the honest accounting.)

Numerics contract (the acceptance bar): prefill *and* greedy
incremental decode through the cache are **bit-identical** — same f32
logits — to the *shape-stable* uncached full-context forward (context
padded to ``max_len``, the recompile-free form a TPU server would
actually run) at every length and under every chunk split, and produce
the identical greedy argmax stream as the unpadded forward, including
GQA configs.  Ingredients: rope applied at the true position through
``_rope_freqs``'s offset paths, attention reads masked with the flash
kernels' exact ``-1e30`` (masked ``exp`` underflows to 0.0, so
same-extent reductions round identically; see
``models.llama._cached_attention``), and logits through the same
``parallel_lm_logits`` head matmul as the plain forward (the fused LM
*head-loss* kernel is training-only — serving has no labels).

Sampling is a pure function of ``(logits, key, temperature, top_k)``
with explicit PRNG keys — no ambient state, so a replayed request
reproduces its exact token stream.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec

from apex_tpu._logging import emit_event, get_logger
from apex_tpu.serving.kv_cache import (
    KVCache,
    commit_slot_length,
    gather_slot_rows,
    init_cache,
    init_quant_cache,
    release_slot,
    value_dtype,
    write_slot_region,
)
from apex_tpu.serving.paged_kv_cache import (
    PagedCacheConfig,
    PagedCacheManager,
    PagedKVCache,
    QuantPagedKVCache,
    blocks_per_slot,
    init_paged_cache,
    init_quant_paged_cache,
)
from apex_tpu.serving.quant import (
    QuantConfig,
    dequant_params,
    is_quantized,
    quantize_params,
    quantized_allreduce,
    serving_param_spec,
)
from apex_tpu.utils.compat import (
    NO_REP_CHECK,
    SERVING_TP_AXIS,
    compile_count,
    serving_mesh,
    shard_map,
)

__all__ = ["DecodeEngine", "TPConfig", "default_prefill_buckets",
           "default_draft_buckets", "sample_tokens", "request_key",
           "token_key", "tp_param_shardings"]

logger = get_logger("serving.engine")


@dataclasses.dataclass(frozen=True)
class TPConfig:
    """Opt-in tensor-parallel serving over a 1-D ``size``-chip mesh.

    ``DecodeEngine(..., tp=TPConfig(size=2))`` lays the serving params
    out with the Megatron column/row split the training forward already
    uses, shards the KV cache head-wise (dense ``[layers, slots,
    max_len, kv_heads/tp, head_dim]`` and the paged block pool alike),
    replicates slot lengths and block tables, and wraps every compiled
    program family in ``shard_map`` over the mesh — so the per-layer
    psum pair (attention o_proj + MLP down_proj) runs exactly as it
    does in training.  The default (``tp=None``) keeps the single-chip
    engine byte-for-byte untouched.
    """

    size: int

    def __post_init__(self):
        if int(self.size) < 1:
            raise ValueError(f"tp size must be >= 1, got {self.size}")


def tp_param_shardings(params, mesh) -> "jax.tree_util.PyTreeDef":
    """Per-leaf :class:`NamedSharding` tree for serving params on a tp
    mesh, derived from :func:`apex_tpu.models.llama.tp_param_spec` (the
    model owns its column/row layout).  Hand this to
    :func:`apex_tpu.serving.weights.load_serving_params` to restore a
    checkpoint *directly onto the serving mesh* — no host-replicated
    detour — or ``jax.device_put`` a host tree with it.  Quant-aware:
    a weight-quantized tree's QTensor payload/scale leaves get the
    layout :func:`apex_tpu.serving.quant.serving_param_spec` derives
    from the kernel they replaced (plain fp leaves keep the exact
    ``tp_param_spec`` layout as before)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _: NamedSharding(mesh, serving_param_spec(
            path, SERVING_TP_AXIS)), params)


def _sample_one(logits, base_key, index, temperature, top_k):
    """One token from one ``[vocab]`` logits row — fully traced, so the
    vmapped form never retraces on per-request sampling params.

    The per-token key is derived *inside* the jitted sampler
    (``fold_in(base_key, index)``, identical to :func:`token_key`): the
    host hands over one base key per stream plus an integer index, so a
    whole decode step's sampling is ONE dispatch — no per-slot fold_in
    ops or device->host syncs on the serving hot path.

    ``temperature <= 0`` is greedy (argmax).  ``top_k > 0`` keeps only
    the k highest logits (threshold from a descending sort — ``top_k``
    is a *traced* scalar, so mixed-k batches share one compile);
    ``top_k <= 0`` means no truncation.
    """
    key = jax.random.fold_in(base_key, index)
    vocab = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    thresh = jnp.sort(logits)[::-1][jnp.clip(top_k - 1, 0, vocab - 1)]
    masked = jnp.where((top_k > 0) & (logits < thresh), -jnp.inf, logits)
    temp = jnp.where(temperature > 0, temperature, 1.0)
    tok = jax.random.categorical(key, masked / temp).astype(jnp.int32)
    return jnp.where(temperature > 0, tok, greedy)


sample_tokens = jax.jit(jax.vmap(_sample_one))
"""Batched sampler: ``(logits [n, vocab], base_keys [n, 2], indices [n],
temperatures [n], top_ks [n]) -> tokens [n]`` — deterministic per
``(base_key, index)``; equals sampling with ``token_key(base, index)``."""


def default_prefill_buckets(prefill_len: int,
                            floor: int = 16) -> tuple:
    """Power-of-two chunk-size table ``(floor, 2*floor, ...,
    prefill_len)`` — the compile-count budget of the prefill path.

    A prompt (or prompt chunk) is padded to the smallest covering
    bucket, so a short prompt costs a short dispatch while the number
    of distinct compiled prefill programs stays ``len(buckets)`` —
    logarithmic in ``prefill_len``, bounded and asserted rather than
    hoped (``DecodeEngine.prefill_compiles()``).
    """
    if floor < 2:
        # floor <= 0 would loop forever below (0 * 2 == 0); 1-row
        # chunks are rejected by the engine anyway (decode ambiguity)
        raise ValueError(f"bucket floor must be >= 2, got {floor}")
    if prefill_len <= floor:
        return (prefill_len,)
    out, b = [], floor
    while b < prefill_len:
        out.append(b)
        b *= 2
    out.append(prefill_len)
    return tuple(out)


def default_draft_buckets(max_draft: int) -> tuple:
    """Power-of-two draft-length table ``(1, 2, 4, ..., max_draft)`` —
    the compile-count budget of the speculative verify path.

    A k-token draft is padded to the smallest covering bucket (the
    verify program's width is ``bucket + 1``: the pending token plus
    the padded draft), so the number of distinct compiled verify
    programs stays ``len(buckets)`` — logarithmic in ``max_draft``,
    bounded and asserted via :meth:`DecodeEngine.verify_compiles`
    exactly like the prefill buckets.
    """
    if max_draft < 1:
        raise ValueError(f"max_draft must be >= 1, got {max_draft}")
    out, b = [], 1
    while b < max_draft:
        out.append(b)
        b *= 2
    out.append(max_draft)
    return tuple(out)


def request_key(seed: int) -> jax.Array:
    """Base PRNG key for one request (explicit, replayable)."""
    return jax.random.PRNGKey(seed)


def token_key(base: jax.Array, index) -> jax.Array:
    """Key for the ``index``-th generated token of a request."""
    return jax.random.fold_in(base, index)


class DecodeEngine:
    """KV-cached incremental decoding for a Llama-family model.

    >>> eng = DecodeEngine(model, params, slots=8, max_len=512,
    ...                    prefill_len=64)
    >>> first_logits = eng.prefill(slot=0, tokens=prompt_ids)
    >>> logits = eng.decode(tokens, active)       # one step, all slots
    >>> eng.release(0)                            # O(1) slot reuse

    The engine owns the cache functionally: every call swaps in the
    updated :class:`KVCache`.  ``slots``/``max_len``/``prefill_len``/
    ``prefill_buckets`` are compile-time constants — ``prefill_len`` is
    the *chunk-size* ceiling (prompts up to ``max_len`` serve; anything
    longer than ``prefill_len`` is split into chunks), and each chunk
    is padded to the smallest covering bucket (the padded K/V are
    written but never readable, because per-slot lengths mask them).
    """

    def __init__(self, model, params, *, slots: int = 8,
                 max_len: int = 512, prefill_len: int = 64,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 draft_buckets: Optional[Sequence[int]] = None,
                 cache_dtype=None,
                 paged: Optional[PagedCacheConfig] = None,
                 tp: Optional[TPConfig] = None,
                 quant: Optional[QuantConfig] = None):
        if prefill_len < 2:
            raise ValueError("prefill_len must be >= 2 (a length-1 "
                             "prefill is indistinguishable from a decode "
                             "step; pad the buffer)")
        if prefill_len > max_len:
            raise ValueError(f"prefill_len {prefill_len} > max_len "
                             f"{max_len}")
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if prefill_buckets is None:
            prefill_buckets = default_prefill_buckets(int(prefill_len))
        buckets = tuple(int(b) for b in prefill_buckets)
        if not buckets or list(buckets) != sorted(set(buckets)):
            raise ValueError(f"prefill_buckets must be non-empty, "
                             f"strictly ascending ints, got {buckets}")
        if buckets[0] < 2:
            raise ValueError(f"prefill buckets must be >= 2 (a 1-row "
                             f"chunk is indistinguishable from a decode "
                             f"step), got {buckets}")
        if buckets[-1] != int(prefill_len):
            raise ValueError(
                f"the largest prefill bucket must equal prefill_len "
                f"{prefill_len} (it is the full-chunk program), got "
                f"{buckets}")
        if draft_buckets is None:
            # a verify writes bucket+1 rows, so the widest default
            # draft must leave room in even the smallest cache
            draft_buckets = default_draft_buckets(min(8, int(max_len) - 1))
        dbuckets = tuple(int(b) for b in draft_buckets)
        if not dbuckets or list(dbuckets) != sorted(set(dbuckets)):
            raise ValueError(f"draft_buckets must be non-empty, strictly "
                             f"ascending ints, got {dbuckets}")
        if dbuckets[0] < 1:
            raise ValueError(f"draft buckets must be >= 1 (a 0-token "
                             f"draft has nothing to verify), got "
                             f"{dbuckets}")
        if dbuckets[-1] >= int(max_len):
            raise ValueError(
                f"largest draft bucket {dbuckets[-1]} must be < max_len "
                f"{max_len} (a verify writes bucket+1 rows into the "
                f"cache)")
        # opt-in quantized serving: validate the lever combination up
        # front (quant=None keeps every code path below byte-for-byte
        # untouched — same traces, same events, same token bytes)
        self._quant_cfg = quant
        if quant is not None:
            if quant.allreduce and tp is None:
                raise ValueError(
                    "QuantConfig(allreduce=True) without tp= — the "
                    "quantized collective replaces the per-layer tp "
                    "psum pair; a single-chip engine has no psum to "
                    "quantize")
            if quant.kv and cache_dtype is not None:
                raise ValueError(
                    "cache_dtype with QuantConfig(kv=True) — the KV-"
                    "int8 cache owns its storage dtype (int8 payload "
                    "+ fp32 scales); drop one of the two")
        self.model = model
        self.params = params
        self.slots = int(slots)
        # opt-in tensor parallelism: validate the head/vocab split up
        # front (a bad divisor must fail at construction, not as an XLA
        # sharding error three calls later) and build the serving mesh.
        # tp=None (the default) leaves every code path below untouched.
        self._tp_cfg = tp
        self._mesh = None
        if tp is not None:
            from apex_tpu.models.llama import validate_tp_divisibility
            validate_tp_divisibility(model.config, tp.size)
            self._mesh = serving_mesh(tp.size)
        self.max_len = int(max_len)
        self.prefill_len = int(prefill_len)
        self.prefill_buckets = buckets
        self.draft_buckets = dbuckets
        if cache_dtype is None:
            # serve in the params' own precision (bf16 params -> bf16
            # cache); fall back to f32 for exotic all-int trees
            floats = [l.dtype for l in jax.tree.leaves(params)
                      if hasattr(l, "dtype")
                      and jnp.issubdtype(l.dtype, jnp.floating)]
            cache_dtype = floats[0] if floats else jnp.float32
        # weight-int8 at boot, AFTER the cache dtype inference (the
        # quantized tree's fp leaves are the scales — inferring from
        # them would serve a bf16 model with an f32 cache).  A pre-
        # quantized tree (load_serving_params(quantize=True), or a
        # rollback buffer) passes through untouched.
        if quant is not None and quant.weights and not is_quantized(params):
            params = quantize_params(params)
            self.params = params
        # opt-in paged layout: a global block pool + per-slot block
        # tables, host-managed by a PagedCacheManager (allocation,
        # refcounts, CoW planning).  None (the default) keeps the dense
        # per-slot cache byte-for-byte as before — every PR-4..9
        # guarantee stays provable side by side.
        self._paged_cfg = paged
        self._pager: Optional[PagedCacheManager] = None
        if paged is not None:
            bs = int(paged.block_size)
            if bs > max_len:
                raise ValueError(
                    f"paged block_size {bs} exceeds max_len {max_len}")
            nblk = paged.num_blocks
            if nblk is None:
                # dense-capacity parity: every slot can still fill to
                # max_len with zero sharing (plus the null block)
                nblk = slots * blocks_per_slot(max_len, bs) + 1
            self._pager = PagedCacheManager(
                slots=slots, max_len=max_len, block_size=bs,
                num_blocks=int(nblk))
        # commit the fresh cache to its device up front: the first
        # prefill otherwise sees UNCOMMITTED zeros while every later
        # call sees the jit output's committed placement — same trace,
        # but pjit specializes a SECOND executable for the changed
        # placement, and the "compiles bounded by the bucket table"
        # contract would be off by one (environment-dependently)
        kv_int8 = quant is not None and quant.kv
        if self._pager is not None:
            fresh = (init_quant_paged_cache(
                         model.config, slots=slots, max_len=max_len,
                         block_size=self._pager.block_size,
                         num_blocks=self._pager.num_blocks)
                     if kv_int8 else
                     init_paged_cache(
                         model.config, slots=slots, max_len=max_len,
                         block_size=self._pager.block_size,
                         num_blocks=self._pager.num_blocks,
                         dtype=cache_dtype))
            self._pager.consume_dirty()     # device holds this snapshot
        elif kv_int8:
            fresh = init_quant_cache(model.config, slots=slots,
                                     max_len=max_len)
        else:
            fresh = init_cache(model.config, slots=slots, max_len=max_len,
                               dtype=cache_dtype)
        if tp is None:
            # _host_target is where host-side snapshots (table flushes,
            # length mirrors, restore chunks) get committed before a
            # dispatch — the single local device here, a replicated
            # NamedSharding under tp.  Same committed-placement rule
            # either way.
            self._device = jax.local_devices()[0]
            self._host_target = self._device
            self._cache_specs = None
            self._cache = jax.device_put(fresh, self._device)
            # pin (commit) the params too: jit keys its executable
            # cache on input placement, so an uncommitted boot tree
            # followed by a committed checkpoint-restored swap
            # candidate would retrace every program family once —
            # the zero-compile hot-swap contract needs one placement
            # signature from boot onward
            self.params = jax.device_put(params, self._device)
        else:
            self._device = jax.local_devices()[0]
            P = PartitionSpec
            # head-wise cache split: dense [layers, slots, max_len,
            # kv_heads, head_dim] and the paged pool [layers, blocks,
            # block_size, kv_heads, head_dim] both carry kv_heads on
            # axis 3; lengths and block tables are replicated (every
            # rank needs them to mask/route identically)
            # no trailing None: jit outputs carry the canonical short
            # spec, and the init-time placement must hash identically
            # or the first post-decode prefill retraces
            # the KV-int8 scale arrays (dense [layers, slots, max_len,
            # kv_heads], paged pools [layers, blocks, block_size,
            # kv_heads]) carry kv_heads on axis 3 exactly like the
            # payload, so one spec covers all four fields
            kvspec = P(None, None, None, SERVING_TP_AXIS)
            self._cache_specs = jax.tree_util.tree_map_with_path(
                lambda path, _: (kvspec
                                 if jax.tree_util.keystr(path) in
                                 (".k", ".v", ".k_scale", ".v_scale")
                                 else P()), fresh)
            self._host_target = NamedSharding(self._mesh, P())
            # restore/read chunks are [layers, rows, kv_heads, head_dim]
            # — kv_heads on axis 2 outside the cache container
            self._kv_chunk_sharding = NamedSharding(
                self._mesh, P(None, None, SERVING_TP_AXIS))
            cache_shardings = jax.tree.map(
                lambda s: NamedSharding(self._mesh, s), self._cache_specs,
                is_leaf=lambda x: isinstance(x, PartitionSpec))
            self._cache = jax.device_put(fresh, cache_shardings)
            # lay the params out column/row-split on the mesh (a no-op
            # transfer when weights.load_serving_params already restored
            # them onto this very layout)
            self.params = jax.device_put(
                params, tp_param_shardings(params, self._mesh))
        # slots whose K/V arrived via restore_prefix (slot -> restored
        # token count): the ONLY slots prefill() accepts a nonzero
        # resume offset for — an arbitrary occupied slot is still
        # rejected loudly (the PR-4 clobber guard), but a slot the
        # engine itself verified and restored may legitimately resume
        # mid-prompt
        self._restored: dict[int, int] = {}
        # host mirror of per-slot lengths: lets every call validate slot
        # bounds and cache capacity WITHOUT a device->host sync on the
        # decode hot path (dynamic_update_slice clamps out-of-range
        # indices silently — overflow must be an error, not corruption)
        self._lengths_host = np.zeros((self.slots,), np.int64)
        # monotonic weight-buffer generation: bumped by swap_params so
        # host layers (the prefix cache's version tags, the reloader's
        # rollback bookkeeping) can tell which weights produced a byte
        self._weights_version = 0

        # weight-int8: every program body expands QTensor leaves back
        # to fp INSIDE its jit (XLA fuses the int8*scale read into the
        # surrounding matmul; the HBM-resident tree stays int8).  The
        # off path binds the identity — the traced graph is the byte-
        # identical fp graph, so quant=None engines keep every compile
        # and numerics contract untouched.
        if quant is not None and quant.weights:
            dq = dequant_params
        else:
            def dq(p):
                return p

        def _prefill(params, cache, ids, slot, offset, length):
            # ids [1, B] (one bucket's shape — jit compiles one program
            # per bucket, never per prompt length); offset = tokens
            # already cached in the slot; length = REAL tokens in this
            # chunk.  Returns the logits at the chunk's last real
            # position (the next-token distribution after the final
            # chunk) + the filled cache.
            logits, cache = model.apply(dq(params), ids, kv_cache=cache,
                                        slot=slot, position=offset)
            cache = commit_slot_length(cache, slot, offset + length)
            last = lax.dynamic_index_in_dim(logits[:, 0, :], length - 1,
                                            axis=0, keepdims=False)
            return last.astype(jnp.float32), cache

        def _decode(params, cache, tokens, active):
            # tokens [slots] int32 (last sampled per slot); active [slots]
            # bool — inactive lanes still compute (shape stability) but
            # never advance their length, so their writes are unreadable.
            # Dense lanes park inactive writes in their own masked rows;
            # a paged table has no private scratch (a stale entry could
            # route the row into another stream's live block), so
            # inactive lanes carry the -1 sentinel and their writes are
            # DROPPED by the paged append's drop-safe scatter.  The
            # branch is on the cache's pytree type — a trace-time
            # constant, so each engine still compiles exactly one
            # decode program and the dense trace is untouched.
            if isinstance(cache, (PagedKVCache, QuantPagedKVCache)):
                position = jnp.where(active, cache.lengths,
                                     jnp.int32(-1))
            else:
                position = cache.lengths
            logits, cache = model.apply(dq(params), tokens[:, None],
                                        kv_cache=cache, position=position)
            cache = dataclasses.replace(
                cache,
                lengths=cache.lengths + active.astype(jnp.int32))
            return logits[0].astype(jnp.float32), cache

        def _verify(params, cache, ids, slot, offset, length):
            # ids [1, W] where W = draft_bucket + 1: the slot's PENDING
            # token (sampled but not yet cached — decode's invariant)
            # followed by the (padded) draft.  Runs the chunked-prefill
            # machinery — rope at the true positions, K/V written at
            # offset.., per-row causal bounds over the whole masked
            # cache — but keeps EVERY row's logits instead of slicing
            # the last one: row i is the next-token distribution after
            # ids[0, :i+1], bit-identical to the single-token decode
            # logits at that depth (same fixed-extent reductions).
            # Acceptance runs on device so dispatch + rollback is ONE
            # program: a = longest prefix where the target's own argmax
            # agrees with the draft (only the length-1 REAL draft rows
            # count), and the length commit rolls the slot back to
            # offset + a + 1 — the rejected rows' K/V become unreadable
            # in the same program that wrote them.
            logits, cache = model.apply(dq(params), ids, kv_cache=cache,
                                        slot=slot, position=offset)
            rows = logits[:, 0, :].astype(jnp.float32)   # [W, vocab]
            if tp is not None:
                # under shard_map each rank holds only its vocab shard
                # of the rows; acceptance must argmax the FULL vocab
                # identically on every rank (a shard-local argmax would
                # diverge per rank and corrupt the replicated committed
                # length), so gather the shards back before deciding
                rows = lax.all_gather(rows, SERVING_TP_AXIS, axis=1,
                                      tiled=True)
            greedy = jnp.argmax(rows, axis=-1).astype(jnp.int32)
            w = ids.shape[1]
            real = jnp.arange(w - 1, dtype=jnp.int32) < (length - 1)
            match = (greedy[:-1] == ids[0, 1:]) & real
            accepted = jnp.sum(jnp.cumprod(match.astype(jnp.int32)))
            cache = commit_slot_length(cache, slot, offset + accepted + 1)
            return greedy, rows, accepted.astype(jnp.int32), cache

        def _restore(cache, k_blk, v_blk, slot, start, length):
            # k_blk / v_blk [layers, B, kvh, hd] (one restore bucket's
            # shape — compiles are bounded by the prefill bucket table,
            # never per prefix length); start = rows already restored,
            # length = REAL rows in this chunk (padding rows past it
            # land beyond the committed length: masked garbage, exactly
            # like a prefill chunk's bucket padding, and any overhang
            # past max_len is dropped by the per-row scatter)
            cache = write_slot_region(cache, slot, start, k_blk, v_blk)
            return commit_slot_length(cache, slot, start + length)

        def _cow(cache, src, dst):
            # copy-on-write block copy: pool block src -> dst across
            # every layer, ONE compiled program for every (src, dst)
            # pair (both traced scalars).  Runs BEFORE the write that
            # needed it, so the writer lands on a private copy while
            # the sharers keep the original bytes — bit-isolation by
            # construction.
            s = jnp.asarray(src, jnp.int32)
            d = jnp.asarray(dst, jnp.int32)
            k_blk = lax.dynamic_index_in_dim(cache.k, s, axis=1,
                                             keepdims=False)
            v_blk = lax.dynamic_index_in_dim(cache.v, s, axis=1,
                                             keepdims=False)
            new = dict(k=cache.k.at[:, d].set(k_blk),
                       v=cache.v.at[:, d].set(v_blk))
            if isinstance(cache, QuantPagedKVCache):
                # a KV-int8 block's bytes are payload + scales: a CoW
                # that copied one without the other would dequantize
                # the writer's copy through the sharers' scales —
                # trace-time dispatch, same single compiled program
                new["k_scale"] = cache.k_scale.at[:, d].set(
                    lax.dynamic_index_in_dim(cache.k_scale, s, axis=1,
                                             keepdims=False))
                new["v_scale"] = cache.v_scale.at[:, d].set(
                    lax.dynamic_index_in_dim(cache.v_scale, s, axis=1,
                                             keepdims=False))
            return dataclasses.replace(cache, **new)

        def _read(cache, slot, start, *, n):
            # the traced-start twin of kv_cache.read_slot_region (same
            # row gather; the module primitive takes host ints while a
            # capture wants ONE compiled program for every block offset
            # — static extent, traced start).  gather_slot_rows hands a
            # KV-int8 cache's rows back DEQUANTIZED fp32, so prefix
            # capture and preemption snapshots stay quant-oblivious.
            rows = jnp.asarray(start, jnp.int32) + jnp.arange(
                n, dtype=jnp.int32)
            return gather_slot_rows(cache, slot, rows)

        if quant is not None and quant.allreduce:
            # grouped-scale int8 psum: the override is TRACE-time state
            # (reduce_from consults it while the body's jaxpr is built),
            # and jit runs the python body exactly once per program
            # family/shape — so wrapping the bodies swaps the collective
            # into every traced program while the executed XLA keeps no
            # python in the loop.  Scoped to kind="row_linear": only the
            # per-layer o_proj/down_proj psum pair quantizes; embedding
            # and logits reductions stay exact.
            from apex_tpu.transformer.tensor_parallel.mappings import (
                override_forward_allreduce,
            )

            def _with_quant_psum(body):
                def wrapped(*args):
                    with override_forward_allreduce(quantized_allreduce):
                        return body(*args)
                return wrapped

            _prefill = _with_quant_psum(_prefill)
            _decode = _with_quant_psum(_decode)
            _verify = _with_quant_psum(_verify)

        # the cache argument is donated: the engine discards the old
        # functional copy on every call, and without aliasing each
        # one-token step would copy the whole preallocated k/v pair
        if tp is None:
            self._prefill = jax.jit(_prefill, donate_argnums=(1,))
            self._decode = jax.jit(_decode, donate_argnums=(1,))
            self._verify = jax.jit(_verify, donate_argnums=(1,))
            self._restore = jax.jit(_restore, donate_argnums=(0,))
            self._cow = jax.jit(_cow, donate_argnums=(0,))
            # NOT donated: a region read must leave the cache intact,
            # and its outputs are fresh owned buffers the prefix cache
            # keeps alive across later (donating) engine calls
            self._read = jax.jit(_read, static_argnames=("n",))
        else:
            # tensor-parallel wiring: the SAME program bodies, wrapped
            # in shard_map over the serving mesh inside the same jit
            # (donation included).  The tensor_parallel layers probe
            # the mapped axis via tp_world_size("tp") — bound inside
            # the shard_map they shard automatically, so model code
            # needs no serving-specific branches, and each family still
            # compiles the same bounded program count (asserted in
            # tests/test_serving_tp.py via the same compile witnesses).
            P = PartitionSpec
            TP = SERVING_TP_AXIS
            mesh = self._mesh
            cspec = self._cache_specs
            # serving_param_spec == tp_param_spec on fp leaves; QTensor
            # payload/scale leaves get the layout derived from the
            # kernel they replaced
            pspec = jax.tree_util.tree_map_with_path(
                lambda path, _: serving_param_spec(path, TP), params)
            blk = P(None, None, TP, None)   # [layers, rows, kvh, hd]
            S = P()                         # replicated scalars/ids

            def smap(body, in_specs, out_specs):
                return shard_map(body, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **NO_REP_CHECK)

            self._prefill = jax.jit(
                smap(_prefill, (pspec, cspec, S, S, S, S),
                     (P(TP), cspec)), donate_argnums=(1,))
            self._decode = jax.jit(
                smap(_decode, (pspec, cspec, S, S),
                     (P(None, TP), cspec)), donate_argnums=(1,))
            # verify's greedy/rows/accepted leave replicated: the body
            # all_gathers the vocab shards before the argmax decides
            self._verify = jax.jit(
                smap(_verify, (pspec, cspec, S, S, S, S),
                     (S, S, S, cspec)), donate_argnums=(1,))
            self._restore = jax.jit(
                smap(_restore, (cspec, blk, blk, S, S, S), cspec),
                donate_argnums=(0,))
            self._cow = jax.jit(
                smap(_cow, (cspec, S, S), cspec), donate_argnums=(0,))

            def _read_tp(cache, slot, start, *, n):
                # shard_map takes no static args: bind the extent in a
                # closure and build the mapped program inside the jit —
                # one trace per distinct n, exactly like the plain
                # static_argnames form (and still NOT donated)
                def body(c, s, t):
                    return _read(c, s, t, n=n)
                return smap(body, (cspec, S, S), (blk, blk))(
                    cache, slot, start)

            self._read = jax.jit(_read_tp, static_argnames=("n",))
        logger.debug("DecodeEngine: slots=%d max_len=%d prefill_len=%d "
                     "buckets=%s cache_dtype=%s", self.slots,
                     self.max_len, self.prefill_len,
                     self.prefill_buckets, jnp.dtype(fresh.dtype).name)
        if quant is not None:
            # quant=None emits nothing: the default-off event stream is
            # byte-identical to the fp engine's
            emit_event("serving_quant_enabled",
                       weights=bool(quant.weights), kv=bool(quant.kv),
                       allreduce=bool(quant.allreduce), tp=self.tp_size,
                       paged=self._pager is not None)

    # ---- cache/slot state ------------------------------------------------
    @property
    def cache(self) -> KVCache:
        return self._cache

    @property
    def quant(self) -> Optional[QuantConfig]:
        """The quantization config, or ``None`` on an fp engine."""
        return self._quant_cfg

    @property
    def tp(self) -> Optional[TPConfig]:
        """The tensor-parallel config, or ``None`` on a single-chip
        engine."""
        return self._tp_cfg

    @property
    def tp_size(self) -> int:
        """Mesh width the serving programs run over (1 = single-chip)."""
        return 1 if self._tp_cfg is None else int(self._tp_cfg.size)

    @property
    def mesh(self):
        """The 1-D serving tp :class:`jax.sharding.Mesh`, or ``None``
        on a single-chip engine."""
        return self._mesh

    def lengths(self) -> np.ndarray:
        """Per-slot valid-token counts (0 = free), from the host mirror
        — no device sync."""
        return self._lengths_host.copy()

    def free_slots(self) -> list[int]:
        return [i for i, n in enumerate(self._lengths_host) if n == 0]

    def cache_utilization(self) -> float:
        """Filled cache positions / total capacity, in ``[0, 1]`` — from
        the host mirror, so sampling it every step costs no device sync.
        The number an admission controller actually wants: slot
        occupancy says how many streams are live, utilization says how
        much of the preallocated KV memory their tokens fill."""
        return float(self._lengths_host.sum()) / float(
            self.slots * self.max_len)

    def _check_slot(self, slot: int) -> None:
        if not 0 <= slot < self.slots:
            raise ValueError(f"slot {slot} out of range [0, {self.slots})")

    def release(self, slot: int) -> None:
        """Evict a slot (O(1)); its bytes stay masked until overwritten.
        Paged engines also drop the slot's block references — blocks
        shared with a prefix-cache entry or another slot survive; the
        rest return to the pool."""
        self._check_slot(slot)
        self._cache = release_slot(self._cache, slot)
        self._lengths_host[slot] = 0
        self._restored.pop(slot, None)
        if self._pager is not None:
            self._pager.release(slot)
            self._flush_tables()

    def reset(self) -> None:
        """Free every slot (keeps compiled programs and allocations)."""
        zeros = (jnp.zeros((self.slots,), jnp.int32)
                 if self._tp_cfg is None
                 # replicated committed placement, like _flush_tables
                 else jax.device_put(np.zeros((self.slots,), np.int32),
                                     self._host_target))
        self._cache = dataclasses.replace(self._cache, lengths=zeros)
        self._lengths_host[:] = 0
        self._restored.clear()
        if self._pager is not None:
            for slot in range(self.slots):
                self._pager.release(slot)
            self._flush_tables()

    # ---- hot weight swap (serving/reload.py's engine surface) ------------
    @property
    def weights_version(self) -> int:
        """Monotonic generation counter of the served weight buffer
        (0 == the boot params; bumped by every :meth:`swap_params`,
        including rollbacks)."""
        return self._weights_version

    def swap_params(self, params) -> Any:
        """Replace the served params with ``params``; returns the old
        buffer (the caller's rollback copy).

        The replacement tree must match the current one exactly —
        structure, leaf shapes, leaf dtypes — because every compiled
        program family (prefill, decode, verify, restore, capture
        read, CoW) takes ``params`` as a *traced* argument: a
        same-spec tree re-dispatches the already-compiled executables
        with **zero** new compiles, while a mismatched one would
        silently retrace.  The check makes the retrace impossible, so
        a validated-but-wrong candidate (e.g. a different model's
        checkpoint that happens to restore) is refused here rather
        than served.  KV cache, block tables, and per-slot lengths are
        untouched: decode state is weight-independent, so in-flight
        streams continue under the new weights with no drop.

        Under tensor parallelism the new tree is laid out onto the tp
        mesh exactly like ``__init__`` did (a no-op transfer when
        ``weights.load_serving_params(shardings=...)`` already
        restored it there).  The swap itself is a host pointer write —
        the engine is between dispatches at every scheduler step
        boundary, which is the only place a reloader calls this.
        """
        if (self._quant_cfg is not None and self._quant_cfg.weights
                and not is_quantized(params)):
            # a reloader hands the engine a freshly restored fp tree;
            # quantize it the same way boot did so the structural check
            # below compares like with like.  An already-quantized
            # candidate (the rollback buffer swap_params itself
            # returned) passes through untouched.
            params = quantize_params(params)
        old_leaves, old_def = jax.tree_util.tree_flatten(self.params)
        new_leaves, new_def = jax.tree_util.tree_flatten(params)
        if new_def != old_def:
            raise ValueError(
                f"swap_params: candidate tree structure does not match "
                f"the served params ({new_def} != {old_def}) — the "
                f"compiled programs would retrace; refuse the swap")
        for i, (o, n) in enumerate(zip(old_leaves, new_leaves)):
            if (tuple(o.shape) != tuple(n.shape)
                    or jnp.dtype(o.dtype) != jnp.dtype(n.dtype)):
                raise ValueError(
                    f"swap_params: leaf {i} is "
                    f"{tuple(n.shape)}/{jnp.dtype(n.dtype)} but the "
                    f"served params have "
                    f"{tuple(o.shape)}/{jnp.dtype(o.dtype)} — a "
                    f"different model's weights cannot be hot-swapped")
        if self._tp_cfg is not None:
            # committed mesh placement, same as __init__ — a no-op
            # when the restore already landed on these shardings
            params = jax.device_put(
                params, tp_param_shardings(params, self._mesh))
        else:
            # same committed single-device placement as __init__
            # (zero-copy when already there): committed-vs-uncommitted
            # is a jit cache key, and a placement flip would retrace
            params = jax.device_put(params, self._device)
        old = self.params
        self.params = params
        self._weights_version += 1
        return old

    # ---- paged-cache state (no-ops / None on dense engines) --------------
    @property
    def paged(self) -> Optional[PagedCacheConfig]:
        """The paged-cache config, or ``None`` on a dense engine."""
        return self._paged_cfg

    @property
    def block_pool(self) -> Optional[PagedCacheManager]:
        """The host block manager (allocation, refcounts, tables) —
        ``None`` on a dense engine."""
        return self._pager

    @property
    def block_size(self) -> Optional[int]:
        return None if self._pager is None else self._pager.block_size

    def free_blocks(self) -> Optional[int]:
        """Unallocated pool blocks (``None`` on a dense engine) — the
        admission-pricing number."""
        return None if self._pager is None else self._pager.free_blocks

    def block_pool_utilization(self) -> float:
        """Allocated pool blocks / allocatable blocks in ``[0, 1]``
        (0.0 on a dense engine) — feeds the
        ``apex_serving_block_pool_utilization`` gauge."""
        return 0.0 if self._pager is None else self._pager.utilization

    def slot_block_ids(self, slot: int) -> list[int]:
        """The pool block ids backing a slot, in token order — what a
        paged prefix cache captures (by reference, zero-copy)."""
        self._check_slot(slot)
        if self._pager is None:
            raise ValueError("slot_block_ids on a dense engine — "
                             "construct with paged=PagedCacheConfig(...)")
        return self._pager.slot_block_ids(slot)

    def block_stats(self) -> dict:
        """Cumulative pool accounting (alloc/free/CoW/alias counts) —
        empty on a dense engine."""
        return {} if self._pager is None else self._pager.stats()

    def set_block_reclaim(self, callback) -> None:
        """Install the pool's last-resort reclaim hook
        (``(n_blocks) -> freed``), consulted once before an allocation
        raises :class:`~apex_tpu.serving.paged_kv_cache.BlockPoolExhausted`
        — the scheduler wires prefix-cache eviction here."""
        if self._pager is None:
            raise ValueError("set_block_reclaim on a dense engine")
        self._pager.reclaim = callback

    def cow_compiles(self) -> int:
        """Number of distinct compiles of the copy-on-write block copy
        (<= 1: src/dst are traced scalars).  Zero until the first CoW —
        the witness that unshared workloads never pay the program."""
        return compile_count(self._cow)

    def _flush_tables(self, *, with_lengths: bool = False) -> None:
        """Install the host table mirror on the device cache — one
        small transfer, only when allocation actually changed (the
        common within-block decode step flushes nothing).  With
        ``with_lengths`` the committed-length mirror travels in the
        SAME functional replace (alias/fork commit a table and a
        length together — the zero-copy dispatch witness is that this
        is the call's only device traffic)."""
        if self._pager is not None and self._pager.consume_dirty():
            # committed placement on purpose: an uncommitted jnp array
            # here would make pjit specialize a SECOND executable for
            # the changed placement, breaking the one-decode-compile
            # contract (same trap as the init-time device_put).  Under
            # tp the target is the replicated NamedSharding — tables
            # and lengths must land identically on every rank.
            kwargs = {"tables": jax.device_put(self._pager.table_snapshot(),
                                               self._host_target)}
            if with_lengths:
                kwargs["lengths"] = jax.device_put(
                    self._lengths_host.astype(np.int32), self._host_target)
            self._cache = dataclasses.replace(self._cache, **kwargs)
        elif with_lengths:
            self._cache = dataclasses.replace(
                self._cache,
                lengths=jax.device_put(self._lengths_host.astype(np.int32),
                                       self._host_target))

    def _ensure_paged(self, writes) -> None:
        """Pre-dispatch allocation for a batch of write spans
        ``(slot, start, stop)``: allocate table entries, run the CoW
        copies any shared block needs (one compiled program per pair,
        BEFORE the write lands), and flush the table mirror once for
        the whole batch — the per-step device cost is bounded by
        [0 table flushes on within-block steps, 1 otherwise] plus one
        tiny copy per CoW'd block."""
        if self._pager is None:
            return
        pairs = []
        for slot, start, stop in writes:
            pairs.extend(self._pager.ensure(slot, start, stop))
        for src, dst in pairs:
            self._cache = self._cow(self._cache, np.int32(src),
                                    np.int32(dst))
        if pairs:
            emit_event("serving_block_cow", blocks=len(pairs))
        self._flush_tables()

    def alias_prefix(self, slot: int, block_ids: Sequence[int],
                     length: int) -> None:
        """Zero-copy prefix reuse: point a free slot's block table at
        already-resident shared blocks and commit ``length`` valid
        tokens — the paged replacement for :meth:`restore_prefix`.
        No K/V bytes move and no compiled program runs (the whole call
        is host bookkeeping plus one table/length snapshot transfer);
        each block just gains a reference, and the slot's later writes
        into any shared block copy-on-write first.  After the call
        :meth:`prefill`/``prefill_chunk`` may resume the prompt at
        offset ``length``, exactly like a restore."""
        self._check_slot(slot)
        if self._pager is None:
            raise ValueError("alias_prefix on a dense engine — use "
                             "restore_prefix (copy-based) instead")
        if self._lengths_host[slot]:
            raise ValueError(
                f"slot {slot} is occupied ({self._lengths_host[slot]} "
                f"tokens); release() it before aliasing into it")
        length = int(length)
        if not 1 <= length <= self.max_len - 1:
            raise ValueError(
                f"aliased prefix of {length} tokens not in [1, "
                f"{self.max_len - 1}] (the resume chunk must still fit)")
        bs = self._pager.block_size
        want = blocks_per_slot(length, bs)
        if len(block_ids) != want:
            raise ValueError(
                f"{len(block_ids)} blocks cannot hold exactly {length} "
                f"tokens at block_size {bs} (want {want})")
        self._pager.alias(slot, block_ids, length)
        self._lengths_host[slot] = length
        self._restored[slot] = length
        self._flush_tables(with_lengths=True)

    def fork_slot(self, src: int, dst: int) -> None:
        """Branch a live stream: share every block of ``src`` into free
        slot ``dst`` (zero-copy — refcounts only) and commit the same
        length.  Both streams may keep decoding; the first write either
        side makes into a shared block — including the partial tail
        block both are about to append into — triggers copy-on-write,
        so the streams stay bit-isolated from that point on (the
        parallel-sampling / n-best primitive)."""
        self._check_slot(src)
        self._check_slot(dst)
        if self._pager is None:
            raise ValueError("fork_slot on a dense engine — the dense "
                             "layout has no shareable blocks")
        if not self._lengths_host[src]:
            raise ValueError(f"fork of empty slot {src}")
        if self._lengths_host[dst]:
            raise ValueError(
                f"slot {dst} is occupied ({self._lengths_host[dst]} "
                f"tokens); release() it before forking into it")
        self._pager.fork(src, dst)
        self._lengths_host[dst] = self._lengths_host[src]
        self._flush_tables(with_lengths=True)

    def decode_compiles(self) -> int:
        """Number of distinct compiles of the decode step (1 == the
        shape-stable contract held: no per-request retraces)."""
        return compile_count(self._decode)

    def prefill_compiles(self) -> int:
        """Number of distinct compiles of the prefill-chunk program —
        bounded by ``len(prefill_buckets)`` (each bucket is one input
        shape), asserted in tier-1 and by the bench regression guard."""
        return compile_count(self._prefill)

    def restore_compiles(self) -> int:
        """Number of distinct compiles of the prefix-restore program —
        bounded by ``len(prefill_buckets)`` (a restore chunk pads to
        the same bucket table prefill uses), asserted in tier-1 and by
        the bench regression guard.  Zero until the first
        :meth:`restore_prefix` call — the witness that leaving prefix
        caching off leaves the compiled-program set untouched."""
        return compile_count(self._restore)

    def verify_compiles(self) -> int:
        """Number of distinct compiles of the speculative verify
        program — bounded by ``len(draft_buckets)`` (each bucket is one
        input width), asserted in tier-1 and by the bench regression
        guard.  Zero until the first :meth:`verify_draft` call — the
        witness that disabling speculation leaves the compiled-program
        set untouched."""
        return compile_count(self._verify)

    @property
    def max_draft(self) -> int:
        """Widest draft :meth:`verify_draft` accepts (the largest
        draft bucket)."""
        return self.draft_buckets[-1]

    def bucket_for(self, n: int) -> int:
        """Smallest prefill bucket covering an ``n``-token chunk."""
        if not 1 <= n <= self.prefill_len:
            raise ValueError(f"chunk length {n} not in [1, "
                             f"{self.prefill_len}]")
        return next(b for b in self.prefill_buckets if b >= n)

    def draft_bucket_for(self, k: int) -> int:
        """Smallest draft bucket covering a ``k``-token draft."""
        if not 1 <= k <= self.draft_buckets[-1]:
            raise ValueError(f"draft length {k} not in [1, "
                             f"{self.draft_buckets[-1]}]")
        return next(b for b in self.draft_buckets if b >= k)

    # ---- the compiled programs -------------------------------------------
    def prefill_chunk(self, slot: int, tokens: Sequence[int]) -> jax.Array:
        """Cache one prompt chunk (``<= prefill_len`` tokens) at
        ``slot``'s current depth; returns the next-token logits
        ``[vocab]`` (f32) after the chunk's last real token — the
        first-token distribution when this was the prompt's final chunk,
        an intermediate prediction otherwise.

        The chunk is padded to the smallest covering bucket (one compile
        per bucket, ever) and its causal block attends everything the
        slot already cached, so ``prefill_chunk`` *continues* a slot:
        callers own the slot's lifecycle and must feed chunks of one
        prompt in order (the scheduler does; for one-shot use call
        :meth:`prefill`, which also guards against clobbering a live
        stream).
        """
        self._check_slot(slot)
        n = len(tokens)
        bucket = self.bucket_for(n)      # raises on n < 1 / n too long
        offset = int(self._lengths_host[slot])
        if offset + n > self.max_len:
            raise ValueError(
                f"chunk of {n} tokens at offset {offset} overruns cache "
                f"max_len {self.max_len}")
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :n] = np.asarray(tokens, np.int32)
        # paged: allocate/CoW the REAL rows' blocks before the write
        # lands (bucket-padding rows past the frontier route to the
        # null table entry and are dropped by the scatter)
        self._ensure_paged([(slot, offset, offset + n)])
        # np scalars, not jnp: a jnp.int32() wrapper costs a device_put
        # (~35us) EACH on the dispatching host thread — three of them
        # tripled this call's host cost (see PERF_NOTES; same move as
        # read_region)
        logits, self._cache = self._prefill(
            self.params, self._cache, ids,
            np.int32(slot), np.int32(offset), np.int32(n))
        self._lengths_host[slot] = offset + n
        return logits

    def prefill(self, slot: int, tokens: Sequence[int], *,
                resume: int = 0) -> jax.Array:
        """Fill ``slot`` with a whole prompt (chunked as needed); return
        its next-token logits ``[vocab]`` (f32).  Prompts up to
        ``max_len`` serve — anything longer than ``prefill_len`` runs as
        ``prefill_len``-sized chunks plus a bucketed tail.

        ``resume`` (default 0) resumes prefill mid-prompt over
        restored cache state: it must equal the token count a preceding
        :meth:`restore_prefix` placed into this slot, and ``tokens`` is
        still the WHOLE prompt — only the uncovered suffix
        ``tokens[resume:]`` is computed.  Because the restored K/V are
        bit-identical to what prefill would have written, the resumed
        chunks (and everything after) are bit-identical to a cold
        prefill of the full prompt.  Any other nonzero-offset use is
        still rejected loudly: silently clobbering (or silently
        trusting) a live stream is the corruption class these guards
        exist for.
        """
        self._check_slot(slot)
        resume = int(resume)
        n = len(tokens)
        if not 1 <= n <= self.max_len:
            raise ValueError(f"prompt length {n} not in [1, "
                             f"{self.max_len}] (cache capacity)")
        if resume:
            if (self._restored.get(slot) != resume
                    or self._lengths_host[slot] != resume):
                raise ValueError(
                    f"prefill(resume={resume}) on slot {slot}: the slot "
                    f"holds {self._lengths_host[slot]} tokens of which "
                    f"{self._restored.get(slot, 0)} are engine-restored "
                    f"— resume must equal the restore_prefix() length "
                    f"exactly")
            if n <= resume:
                raise ValueError(
                    f"prompt of {n} tokens has no suffix past "
                    f"the {resume} restored tokens — at least the final "
                    f"prompt token must be computed to produce the "
                    f"next-token logits")
            # every argument validated: the slot is a live stream from
            # here on — a second resume (or a re-restore) over it must
            # fail the guards above.  (The mark is consumed only after
            # validation so a rejected call stays side-effect-free: the
            # caller may retry with a corrected prompt instead of
            # re-paying the whole device restore.)
            self._restored.pop(slot, None)
        elif self._lengths_host[slot]:
            raise ValueError(
                f"slot {slot} is occupied ({self._lengths_host[slot]} "
                f"tokens); release() it before prefilling — silently "
                f"clobbering a live stream is the corruption class these "
                f"guards exist for")
        logits = None
        for start in range(resume, n, self.prefill_len):
            logits = self.prefill_chunk(
                slot, tokens[start:start + self.prefill_len])
        return logits

    # ---- prefix-cache primitives (capture + restore) ---------------------
    def read_region(self, slot: int, start: int, stop: int
                    ) -> tuple[jax.Array, jax.Array]:
        """Snapshot ``[start, stop)`` of a slot's cached K/V across every
        layer: ``(k, v)`` of shape ``[layers, stop - start, kv_heads,
        head_dim]`` — fresh owned buffers (safe to hold across later
        donated cache updates).  Only *valid* rows may be read (the span
        must sit inside the slot's committed length — bytes past it are
        masked garbage by contract).  One compiled program per distinct
        extent; block-granular prefix capture batches each chunk's new
        blocks into one span read, so its compiles are bounded by
        ``ceil(prefill_len / block_size)`` distinct extents."""
        self._check_slot(slot)
        if self._pager is not None:
            raise ValueError(
                "read_region on a paged engine — prefix capture is "
                "by-reference there (slot_block_ids + refcounts), not "
                "by copy")
        start, stop = int(start), int(stop)
        if not 0 <= start < stop <= int(self._lengths_host[slot]):
            raise ValueError(
                f"region [{start}, {stop}) outside slot {slot}'s valid "
                f"length {int(self._lengths_host[slot])} — rows past the "
                f"committed length are masked garbage and must never be "
                f"handed out")
        # np scalars, not jnp: a jnp.int32() wrapper costs a device_put
        # (~35us) per argument, tripling this dispatch's host cost —
        # and capture rides the serving hot path
        return self._read(self._cache, np.int32(slot), np.int32(start),
                          n=stop - start)

    def capture_slot(self, slot: int) -> tuple[np.ndarray, np.ndarray, int]:
        """Snapshot a live slot's ENTIRE valid K/V to the host —
        ``(k, v, length)`` with ``k`` / ``v`` of shape ``[layers,
        length, kv_heads, head_dim]`` — the lossless-preemption capture
        primitive: :meth:`restore_prefix` of exactly these arrays into
        a free slot reproduces the slot's cache state bit for bit (the
        bytes ARE the cache's bytes), so a preempted DECODE stream
        resumes with identical f32 logits.

        The snapshot runs as :meth:`read_region` spans decomposed over
        the *prefill bucket table* (greedy largest-bucket-first, the
        sub-floor tail overlap-read inside a floor-sized span), so the
        read program's compile count stays bounded by
        ``len(prefill_buckets)`` plus at most ``prefill_buckets[0] - 1``
        sub-floor whole-slot extents — no new program family
        (:meth:`capture_compiles` is the witness).  Dense engines only:
        a paged slot is captured by *reference*
        (:meth:`slot_block_ids` + pool refcounts), never by copy.
        """
        self._check_slot(slot)
        if self._pager is not None:
            raise ValueError(
                "capture_slot on a paged engine — capture by reference "
                "instead (slot_block_ids + block_pool.ref; resume via "
                "alias_prefix)")
        length = int(self._lengths_host[slot])
        if length < 1:
            raise ValueError(f"capture of empty slot {slot}")
        buckets = self.prefill_buckets
        parts_k, parts_v = [], []
        pos = 0
        while pos < length:
            rem = length - pos
            if length < buckets[0]:
                # whole slot shorter than the smallest bucket: one
                # sub-floor read (extent < buckets[0], bounded)
                lo, hi = 0, length
            elif rem >= buckets[0]:
                b = max(x for x in buckets if x <= rem)
                lo, hi = pos, pos + b
            else:
                # sub-floor tail of a longer slot: overlap-read the
                # last floor-sized span and trim the replayed rows
                lo, hi = length - buckets[0], length
            k_span, v_span = self.read_region(slot, lo, hi)
            skip = pos - lo                    # rows already captured
            parts_k.append(np.asarray(k_span)[:, skip:])
            parts_v.append(np.asarray(v_span)[:, skip:])
            pos = hi
        k = parts_k[0] if len(parts_k) == 1 else np.concatenate(
            parts_k, axis=1)
        v = parts_v[0] if len(parts_v) == 1 else np.concatenate(
            parts_v, axis=1)
        return k, v, length

    def capture_compiles(self) -> int:
        """Number of distinct compiles of the region-read program
        (shared by prefix-cache capture and preemption capture) —
        bounded by the distinct span extents those callers use
        (block-granular capture: ``ceil(prefill_len / block_size)``;
        preemption: the prefill bucket table plus sub-floor whole-slot
        lengths).  Zero until the first read — the witness that a run
        with neither feature compiles nothing extra."""
        return compile_count(self._read)

    def restore_prefix(self, slot: int, kv, length: int) -> None:
        """Place previously captured K/V back into a free slot: after
        the call the slot holds ``length`` cached tokens, bit-for-bit
        the state a cold prefill of those tokens would have produced
        (the arrays ARE prefill's output, snapshotted via
        :meth:`read_region`), and :meth:`prefill`/``prefill_chunk`` may
        resume the prompt at offset ``length``.

        ``kv`` is ``(k, v)`` with shape ``[layers, >= length, kv_heads,
        head_dim]`` (extra rows are ignored).  The write runs as
        ``prefill_len``-sized chunks padded to the prefill bucket
        table, so restore compiles are bounded by ``len(
        prefill_buckets)`` (:meth:`restore_compiles`).  ``length`` is
        capped at ``max_len - 1``: a full-cache restore could never
        compute the next-token logits the stream needs.
        """
        self._check_slot(slot)
        if self._pager is not None:
            raise ValueError(
                "restore_prefix on a paged engine — hits alias shared "
                "blocks zero-copy (alias_prefix), never write K/V back")
        if self._lengths_host[slot]:
            raise ValueError(
                f"slot {slot} is occupied ({self._lengths_host[slot]} "
                f"tokens); release() it before restoring into it")
        k, v = kv
        length = int(length)
        layers = self._cache.num_layers
        tail = self._cache.k.shape[3:]          # (kv_heads, head_dim)
        for name, arr in (("k", k), ("v", v)):
            shape = tuple(getattr(arr, "shape", ()))
            if (len(shape) != 4 or shape[0] != layers
                    or shape[2:] != tail):
                raise ValueError(
                    f"restore {name} shape {shape} does not match the "
                    f"cache's [layers={layers}, n, kv_heads={tail[0]}, "
                    f"head_dim={tail[1]}] layout")
        if not 1 <= length <= min(k.shape[1], v.shape[1]):
            raise ValueError(
                f"restore length {length} not in [1, "
                f"{min(k.shape[1], v.shape[1])}] (rows provided)")
        if length > self.max_len - 1:
            raise ValueError(
                f"restored prefix of {length} tokens leaves no room in "
                f"a max_len={self.max_len} cache for the resume chunk "
                f"that must produce the next-token logits")
        # the VALUE dtype, not the storage dtype: staging a restore
        # chunk in a KV-int8 cache's int8 payload dtype would crush the
        # captured fp rows to garbage before the in-program requantize
        dtype = value_dtype(self._cache)
        for start in range(0, length, self.prefill_len):
            n = min(self.prefill_len, length - start)
            bucket = self.bucket_for(n)
            k_blk = jnp.zeros((layers, bucket) + tail, dtype)
            v_blk = jnp.zeros((layers, bucket) + tail, dtype)
            k_blk = k_blk.at[:, :n].set(
                jnp.asarray(k[:, start:start + n], dtype))
            v_blk = v_blk.at[:, :n].set(
                jnp.asarray(v[:, start:start + n], dtype))
            if self._tp_cfg is not None:
                # commit the chunk head-sharded BEFORE the dispatch:
                # an uncommitted block would cost a resharding copy
                # per chunk and a second compiled placement variant
                k_blk = jax.device_put(k_blk, self._kv_chunk_sharding)
                v_blk = jax.device_put(v_blk, self._kv_chunk_sharding)
            self._cache = self._restore(
                self._cache, k_blk, v_blk, np.int32(slot),
                np.int32(start), np.int32(n))
        self._lengths_host[slot] = length
        self._restored[slot] = length

    def decode(self, tokens, active) -> jax.Array:
        """One batched decode step: append ``tokens[slot]`` to every
        active slot, return per-slot next-token logits ``[slots, vocab]``
        (f32).  Inactive lanes return garbage rows — callers mask by
        ``active``.  Raises when an active slot is already at
        ``max_len`` (the append would silently clobber the last cached
        token otherwise)."""
        act = np.asarray(active, bool)
        full = act & (self._lengths_host >= self.max_len)
        if full.any():
            raise ValueError(
                f"slots {np.flatnonzero(full).tolist()} are at cache "
                f"capacity ({self.max_len}); release or raise max_len")
        empty = act & (self._lengths_host == 0)
        if empty.any():
            raise ValueError(
                f"slots {np.flatnonzero(empty).tolist()} are active but "
                f"never prefilled — a decode step would expose a garbage "
                f"token as their whole context")
        if self._pager is not None:
            # one batched allocation pass for every active lane, ONE
            # table flush at most (none at all on the (block_size-1)
            # of block_size steps that cross no block boundary)
            self._ensure_paged(
                [(int(s), int(self._lengths_host[s]),
                  int(self._lengths_host[s]) + 1)
                 for s in np.flatnonzero(act)])
        if self._tp_cfg is None:
            logits, self._cache = self._decode(
                self.params, self._cache,
                np.asarray(tokens, np.int32), act)
        else:
            # time the step wall-to-wall and publish it as
            # serving_tp_step: an honest UPPER BOUND on the per-step
            # collective cost (dispatch + compute + the per-layer psum
            # pair; exact collective attribution needs a profiler).
            # The block_until_ready adds ~nothing — the caller samples
            # from these logits immediately, syncing anyway.  tp=None
            # emits nothing: the default-off event stream is identical.
            t0 = time.perf_counter()
            logits, self._cache = self._decode(
                self.params, self._cache,
                np.asarray(tokens, np.int32), act)
            jax.block_until_ready(logits)
            # a fleet scheduler stamps its replica name onto the engine
            # (anonymous engines splat nothing — byte-identical stream)
            replica = getattr(self, "name", None)
            emit_event("serving_tp_step", tp=self.tp_size,
                       active=int(act.sum()),
                       duration_s=time.perf_counter() - t0,
                       **({"replica": replica}
                          if isinstance(replica, str) else {}))
        self._lengths_host[act] += 1
        return logits

    def verify_draft(self, slot: int, tokens: Sequence[int]
                     ) -> tuple[int, np.ndarray, jax.Array]:
        """One speculative verify: score ``tokens`` (the slot's pending
        last-sampled token followed by 1..``max_draft`` drafted
        candidates) in ONE cached multi-token forward, accept the
        longest draft prefix the target's greedy argmax agrees with,
        and roll the slot back to the accepted depth.

        Returns ``(accepted, greedy, logits)``: ``accepted`` = draft
        tokens accepted (0 == immediate rejection); ``greedy[i]`` =
        the target's argmax after ``tokens[:i+1]`` (so the step emits
        ``tokens[1:1+accepted] + [greedy[accepted]]`` — the accepted
        draft plus the bonus token the verify forward computed for
        free, exactly the stream ``accepted + 1`` plain decode steps
        would emit, bit for bit); ``logits`` = the per-row f32
        next-token distributions ``[bucket+1, vocab]`` (rows past
        ``accepted`` scored rejected/padded context — valid for
        inspection, already rolled back on device).

        The draft is padded to the smallest covering ``draft_buckets``
        entry (one compile per bucket, ever — padded rows' K/V land
        past the committed length, unreadable like every other masked
        byte).  After the call the slot's length is
        ``offset + accepted + 1``: the pending token and accepted
        draft are cached, the bonus token is the new pending token —
        the same invariant a plain decode step leaves.
        """
        self._check_slot(slot)
        k = len(tokens) - 1
        if k < 1:
            raise ValueError(
                f"verify_draft needs the pending token plus >= 1 draft "
                f"token, got {len(tokens)} token(s) — with no draft to "
                f"verify, run the plain decode step")
        bucket = self.draft_bucket_for(k)    # raises past max_draft
        offset = int(self._lengths_host[slot])
        if offset == 0:
            raise ValueError(
                f"slot {slot} was never prefilled — a verify would "
                f"expose garbage as its whole context")
        if offset + k + 1 > self.max_len:
            raise ValueError(
                f"verify of {k + 1} tokens at offset {offset} overruns "
                f"cache max_len {self.max_len}")
        ids = np.zeros((1, bucket + 1), np.int32)
        ids[0, :k + 1] = np.asarray(tokens, np.int32)
        # paged: cover the pending token + the whole real draft; a
        # rollback leaves the surplus blocks owned by the slot (refs
        # untouched), so the re-decode over them re-allocates nothing
        self._ensure_paged([(slot, offset, offset + k + 1)])
        greedy, rows, accepted, self._cache = self._verify(
            self.params, self._cache, ids, np.int32(slot),
            np.int32(offset), np.int32(k + 1))
        a = int(accepted)
        self._lengths_host[slot] = offset + a + 1
        return a, np.asarray(greedy), rows

    # ---- sampling --------------------------------------------------------
    @staticmethod
    def sample(logits, base_keys, indices, temperatures,
               top_ks) -> jax.Array:
        """Vectorized deterministic sampling (see :func:`sample_tokens`)."""
        return sample_tokens(
            jnp.asarray(logits), jnp.asarray(base_keys),
            jnp.asarray(indices, jnp.int32),
            jnp.asarray(temperatures, jnp.float32),
            jnp.asarray(top_ks, jnp.int32))
