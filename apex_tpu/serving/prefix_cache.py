"""Cross-request prefix cache: reuse shared-prompt K/V, bit-exactly.

Production serving traffic is dominated by requests sharing long common
prefixes — system prompts, few-shot templates, chat history — yet a
plain scheduler re-runs full chunked prefill over every admitted
prompt.  PR 6 made chunk boundaries "scheduling, not numerics": chunked
cached prefill is bit-identical to the one-shot forward at ANY split
point, which means a previously computed prefix's K/V can be reused
*verbatim* and prefill resumed mid-prompt with zero numerical cost —
the RadixAttention-style insight (win by eliminating redundant work,
not approximating it).

The store is **chunk-granular**: a prompt is hashed as a chain of
fixed-size token blocks (``block_size`` aligned to the engine's
smallest prefill bucket by default), and each entry holds

- the per-layer K/V for its block span as **owned device arrays**
  (captured via ``DecodeEngine.read_region`` immediately after the
  prefill chunk that completed the block — a snapshot of exactly the
  bytes prefill wrote, so a later restore is bit-for-bit the state a
  cold prefill would have produced).  Blocks captured together from
  one chunk share one *span* buffer (ONE device round trip captures a
  whole chunk's blocks — per-block copies would make the
  zero-overlap workload pay a dispatch per block) and slice out of it
  lazily on the hit path; a span's bytes are freed when its last
  entry is evicted, so one surviving block can transiently pin up to
  a chunk's span (bounded by ``prefill_len`` tokens, reported
  honestly by ``cached_bytes``); and
- the **chain hash** linking it to its parent block: ``H(parent_hash,
  block_tokens)``.  Two prompts share an entry iff they share the
  whole token prefix up to that block — position is encoded by the
  chain, so there are no false hits.

Admission does a **longest-chain match** (capped at ``len(prompt) - 1``
tokens: the final prompt token is always recomputed, because the hit's
resume chunk must produce the next-token logits the first sampled token
comes from).  Eviction is LRU under a configurable token budget with
two hard rules:

- an entry whose ref-count is nonzero is NEVER evicted (the scheduler
  pins a request's matched + self-inserted chain until its prompt is
  fully cached, so the chain it is extending block-by-block cannot be
  ripped out from under it mid-prefill), and
- eviction is leaf-first (an entry with live children is not
  evictable): every cached chain stays reachable from the root — no
  orphaned, unmatchable entries leaking budget.  For the same reason
  :meth:`PrefixCache.put` refuses an insert whose parent is gone.

Everything here is host-side bookkeeping; the only device work a hit
costs is the engine's bucketed ``restore_prefix`` writes (and the only
device work capture costs is one fixed-extent region read per new
block).  Opt-in via ``ContinuousBatchingScheduler(...,
prefix_caching=PrefixCacheConfig(...))``; the default (off) leaves
every existing serving path byte-for-byte untouched.

Tensor-parallel serving changes nothing in this module either: capture
reads come back *gathered* (``read_region`` out-specs reassemble the
full ``kv_heads`` axis), so entries hold mesh-oblivious global arrays,
and a restore re-shards them head-wise on the way in — a prefix
captured on a tp engine restores bit-exactly on that engine, which is
the reuse contract (entries are per-engine owned state, never shared
across engines of different numerics).
"""

from __future__ import annotations

import dataclasses
import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax.numpy as jnp
import numpy as np

from apex_tpu._logging import get_logger

__all__ = ["PrefixCacheConfig", "PrefixCache"]

logger = get_logger("serving.prefix_cache")

_ROOT = "root"          # chain hash of the empty prefix


@dataclasses.dataclass(frozen=True)
class PrefixCacheConfig:
    """Opt-in knob for cross-request prefix caching.

    ``block_size``: tokens per hashed block (``None`` — the scheduler
    aligns it to the engine's smallest prefill bucket, so a restored
    chain always lands on bucket-friendly chunk boundaries).
    ``max_tokens``: cached-token budget — LRU eviction keeps the store
    at or under it whenever any unpinned, childless entry exists
    (pinned chains may transiently exceed it; see
    :meth:`PrefixCache.put`).
    """

    block_size: Optional[int] = None
    max_tokens: int = 1 << 20

    def __post_init__(self):
        if self.block_size is not None and self.block_size < 1:
            raise ValueError(
                f"block_size must be >= 1, got {self.block_size}")
        if self.max_tokens < 1:
            raise ValueError(
                f"max_tokens must be >= 1, got {self.max_tokens}")


@dataclasses.dataclass
class _Span:
    """One captured region's owned device buffers, shared by the blocks
    captured together (``live`` counts the entries still referencing
    it; its bytes are freed — the arrays dropped — when the last one
    is evicted)."""

    k: object                    # [layers, rows, kv_heads, head_dim]
    v: object
    nbytes: int
    live: int = 0


@dataclasses.dataclass
class _Entry:
    chain: str                   # this entry's chain hash
    parent: str                  # parent block's chain hash (or root)
    tokens: Tuple[int, ...]      # the block's tokens (len == block_size)
    span: Optional[_Span] = None  # shared captured buffers (dense mode)
    lo: int = 0                  # this block's row offset inside span
    refs: int = 0                # live pins; > 0 == never evictable
    block_id: Optional[int] = None  # pool block id (paged mode)
    version: int = 0             # weights generation the K/V came from


class PrefixCache:
    """Chain-hashed block store over captured K/V (host bookkeeping).

    >>> cache = PrefixCache(block_size=16, max_tokens=4096)
    >>> covered, entries = cache.match(prompt)       # longest chain
    >>> cache.acquire(entries)                       # pin while feeding
    >>> h = cache.put(parent_hash, block, k, v)      # insert-on-miss
    >>> cache.release(entries)                       # prompt cached

    Not thread-safe by design: the continuous-batching scheduler is a
    single host loop, and every call here happens at a step boundary.
    """

    ROOT = _ROOT

    def __init__(self, *, block_size: int, max_tokens: int,
                 pool=None, bytes_per_block: int = 0):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if max_tokens < 1:
            raise ValueError(f"max_tokens must be >= 1, got {max_tokens}")
        self.block_size = int(block_size)
        self.max_tokens = int(max_tokens)
        # paged mode: entries hold pool block IDS (captured by
        # reference via put_block_ids — the cache holds one allocator
        # reference per entry, dropped at eviction so the pool block
        # frees once no slot shares it).  ``pool`` is the engine's
        # PagedCacheManager (or anything with ref/deref);
        # ``bytes_per_block`` feeds the honest cached_bytes figure.
        self._pool = pool
        self._bytes_per_block = int(bytes_per_block)
        # LRU order IS the dict order: touch == move_to_end, eviction
        # scans from the oldest end for the first evictable entry —
        # O(1) in the common case instead of a full min() scan of a
        # store that can hold tens of thousands of blocks at budget
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._children: Dict[str, Set[str]] = {}
        self._span_bytes = 0     # bytes of spans with >= 1 live entry
        self._hits = 0
        self._misses = 0
        self._inserted = 0
        self._evicted = 0
        self._refused = 0
        # current weights generation: entries are stamped at insert and
        # only same-version entries match — cached K/V captured under
        # old weights must never resume a new-weights stream (the hot
        # reload invalidation contract; see bump_version)
        self._version = 0

    # ---- hashing ---------------------------------------------------------
    @staticmethod
    def chain_hash(parent: str, tokens: Sequence[int]) -> str:
        """``H(parent_hash, block_tokens)`` — equal iff the WHOLE token
        prefix up to and including this block is equal, so a chain hash
        encodes both content and position.  BLAKE2b over the raw int64
        token bytes: hashing rides the serving hot path (every block of
        every admitted prompt), and a string-join digest measurably
        taxed the zero-overlap no-regression bar."""
        h = hashlib.blake2b(parent.encode("ascii"), digest_size=16)
        h.update(np.asarray(tokens, dtype="<i8").tobytes())
        return h.hexdigest()

    # ---- introspection ---------------------------------------------------
    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, chain: str) -> bool:
        return chain in self._entries

    @property
    def cached_tokens(self) -> int:
        return len(self._entries) * self.block_size

    @property
    def cached_bytes(self) -> int:
        """Bytes of live span buffers — the honest device-memory
        figure: a span stays allocated until its LAST entry is evicted,
        so this can exceed ``cached_tokens``-worth of bytes while a
        partially evicted span survives (bounded by one chunk's rows
        per surviving span).  In paged mode this is entries *
        bytes_per_block — the pool bytes the cache's references PIN;
        a block also referenced by a live slot costs no *extra* memory
        beyond this figure (the reference is the whole point)."""
        if self._pool is not None:
            return len(self._entries) * self._bytes_per_block
        return self._span_bytes

    def stats(self) -> Dict[str, int]:
        """Cumulative structural accounting (the bench and tests read
        this; per-request hit/miss telemetry rides the scheduler's
        ``serving_prefix_{hit,miss}`` events)."""
        return {"entries": len(self._entries),
                "cached_tokens": self.cached_tokens,
                "cached_bytes": self.cached_bytes,
                "hits": self._hits, "misses": self._misses,
                "inserted": self._inserted, "evicted": self._evicted,
                "refused": self._refused,
                "version": self._version,
                "stale_entries": self.stale_entries}

    @property
    def version(self) -> int:
        """Current weights generation new inserts are stamped with."""
        return self._version

    @property
    def stale_entries(self) -> int:
        """Entries surviving from an older weights generation — pinned
        by (or mid-chain under) streams admitted before a swap.  They
        are unmatchable and un-extendable; LRU eviction reclaims them
        as their pins release."""
        return sum(1 for e in self._entries.values()
                   if e.version != self._version)

    def bump_version(self) -> int:
        """Invalidate every cached entry for a weight swap: the store's
        version advances, so existing entries stop matching (old-weights
        K/V must never resume a new-weights stream) and droppable ones
        are reclaimed immediately.  Entries pinned by live slots — a
        pre-swap stream still mid-prompt — survive *as storage* (their
        stream's own restore already happened and its decode state is
        self-consistent) but can never feed a new admission; they drop
        when their pins release and eviction reaches them.  Returns the
        new version."""
        self._version += 1
        # fixpoint sweep, leaves first: a stale parent becomes droppable
        # once its stale children are gone
        while True:
            victim = next(
                (e for e in self._entries.values()
                 if e.version != self._version and self._evictable(e)),
                None)
            if victim is None:
                break
            self._drop(victim)
        return self._version

    # ---- lookup ----------------------------------------------------------
    def _touch(self, entry: _Entry) -> None:
        self._entries.move_to_end(entry.chain)

    def match(self, prompt: Sequence[int]) -> Tuple[int, List[_Entry]]:
        """Longest cached chain covering a prefix of ``prompt``; returns
        ``(covered_tokens, entries)`` with ``covered_tokens`` a multiple
        of ``block_size`` and **at most** ``len(prompt) - 1`` (the final
        token is always recomputed so the resume chunk yields the
        next-token logits).  Matched entries are LRU-touched but NOT
        pinned — call :meth:`acquire` before any host work that could
        insert (and therefore evict)."""
        n = len(prompt)
        h = _ROOT
        out: List[_Entry] = []
        pos = 0
        while pos + self.block_size <= n - 1:
            h = self.chain_hash(h, prompt[pos:pos + self.block_size])
            entry = self._entries.get(h)
            if entry is None or entry.version != self._version:
                # a stale-version entry is K/V from pre-swap weights:
                # restoring it would resume a new-weights stream from
                # old-weights bytes — treated as absent (and left
                # untouched, so LRU eviction reclaims it first)
                break
            out.append(entry)
            pos += self.block_size
        for entry in out:
            self._touch(entry)
        if out:
            self._hits += 1
        else:
            self._misses += 1
        return pos, out

    def lookup(self, chain: str) -> Optional[_Entry]:
        """The live entry for a chain hash (LRU-touched), or ``None`` —
        the cheap presence probe capture uses to skip the device read
        for a block another stream already inserted."""
        entry = self._entries.get(chain)
        if entry is None or entry.version != self._version:
            return None          # stale == absent (see match)
        self._touch(entry)
        return entry

    def probe(self, prompt: Sequence[int]) -> int:
        """READ-ONLY coverage probe: tokens the longest cached chain
        would cover for ``prompt`` (same walk and ``len(prompt) - 1``
        cap as :meth:`match`) with **no side effects** — no LRU touch,
        no hit/miss accounting, no pinning.  The fleet router's
        prefix-affinity placement probes every replica's cache per
        submission; :meth:`match` here would skew each replica's own
        hit-rate stats and recency order with placement traffic the
        replica never served."""
        n = len(prompt)
        h = _ROOT
        pos = 0
        while pos + self.block_size <= n - 1:
            h = self.chain_hash(h, prompt[pos:pos + self.block_size])
            entry = self._entries.get(h)
            if entry is None or entry.version != self._version:
                break
            pos += self.block_size
        return pos

    # ---- pinning ---------------------------------------------------------
    def acquire(self, entries: Sequence[_Entry]) -> None:
        """Pin entries feeding a live slot: refs > 0 blocks eviction."""
        for entry in entries:
            entry.refs += 1

    def release(self, entries: Sequence[_Entry]) -> None:
        """Drop one pin per entry (the prompt they fed is fully cached)."""
        for entry in entries:
            if entry.refs < 1:
                raise ValueError(
                    f"release of unpinned entry {entry.chain[:12]} — "
                    f"acquire/release must pair")
            entry.refs -= 1

    # ---- insert + eviction -----------------------------------------------
    def _insert_site(self, chain: str) -> Tuple[Optional[_Entry], bool]:
        """Resolve ``chain`` for an insert: ``(live entry, blocked)``.
        A current-version entry is the idempotent-reinsert case; a
        stale-version one is replaced when droppable, else the insert
        is BLOCKED (a pinned/mid-chain stale entry cannot be dropped,
        and chaining fresh K/V onto it would make the new entry
        reachable only through an unmatchable parent)."""
        entry = self._entries.get(chain)
        if entry is None or entry.version == self._version:
            return entry, False
        if self._evictable(entry):
            self._drop(entry)
            return None, False
        return None, True

    def _parent_live(self, parent: str) -> bool:
        if parent == _ROOT:
            return True
        entry = self._entries.get(parent)
        return entry is not None and entry.version == self._version

    def put(self, parent: str, tokens: Sequence[int], k, v
            ) -> Optional[_Entry]:
        """Insert one captured block (its own single-block span) — the
        convenience form of :meth:`put_blocks` for direct engine users
        and tests; the scheduler inserts a whole chunk's blocks at once
        with one shared span."""
        out = self.put_blocks(parent, [tokens], k, v)
        return out[0] if out else None

    def put_block_ids(self, parent: str,
                      blocks: Sequence[Sequence[int]],
                      block_ids: Sequence[int]) -> List[_Entry]:
        """Paged-mode insert: capture consecutive completed blocks **by
        reference** — each new entry records the pool block id the
        prompt's K/V already lives in and takes one allocator reference
        (zero device reads, zero copies; the owning slot keeps its own
        reference and both decay independently).  Same chain semantics
        as :meth:`put_blocks`: idempotent per block (an existing entry
        is touched and returned — its block stays THE shared copy; the
        caller's duplicate block simply frees when its slot releases),
        stops at the first orphaned parent, and runs the LRU eviction
        pass with this call's own fresh entries protected."""
        if self._pool is None:
            raise ValueError("put_block_ids on a span-mode cache — "
                             "construct with pool=... (a paged engine's "
                             "block_pool)")
        if len(block_ids) != len(blocks):
            raise ValueError(
                f"{len(block_ids)} block ids for {len(blocks)} blocks")
        out: List[_Entry] = []
        created: List[_Entry] = []
        for block, bid in zip(blocks, block_ids):
            tokens = tuple(map(int, block))
            if len(tokens) != self.block_size:
                raise ValueError(
                    f"block of {len(tokens)} tokens != block_size "
                    f"{self.block_size} — only whole blocks are "
                    f"hashable")
            chain = self.chain_hash(parent, tokens)
            entry, blocked = self._insert_site(chain)
            if blocked:
                self._refused += 1
                break
            if entry is None:
                if not self._parent_live(parent):
                    self._refused += 1
                    logger.debug("prefix put refused: parent %.12s "
                                 "evicted", parent)
                    break
                self._pool.ref([int(bid)])
                entry = _Entry(chain=chain, parent=parent, tokens=tokens,
                               block_id=int(bid), version=self._version)
                self._entries[chain] = entry
                self._children.setdefault(parent, set()).add(chain)
                self._inserted += 1
                created.append(entry)
            self._touch(entry)
            out.append(entry)
            parent = chain
        for entry in created:       # protected through the pass below
            entry.refs += 1
        try:
            self._evict_to_budget()
        finally:
            for entry in created:
                entry.refs -= 1
        return out

    def put_blocks(self, parent: str, blocks: Sequence[Sequence[int]],
                   k_span, v_span) -> List[_Entry]:
        """Insert consecutive captured blocks sharing ONE span buffer
        pair (``k_span`` / ``v_span``: ``[layers, len(blocks) *
        block_size, kv_heads, head_dim]`` — exactly the rows block 0
        starts at, in order).  Idempotent per block: an existing chain
        entry is touched and returned as-is (its original span is THE
        copy; a re-capture of the same chain is bit-identical by the
        exactness contract anyway).  Stops — returning the entries
        inserted so far — at the first block whose parent chain is gone
        (evicted mid-prefill under a tight budget): an orphaned entry
        could never be matched and would leak budget forever.

        After the inserts, evicts LRU-childless-unpinned entries until
        the token budget holds again — this call's own fresh entries
        are protected from its own eviction pass, so the returned
        entries are always LIVE (callers pin them before any later
        insert can run).  When every entry is pinned or has live
        children the store may transiently exceed the budget rather
        than corrupt a chain a live slot is feeding.
        """
        if self._pool is not None:
            raise ValueError("put_blocks on a paged cache — capture is "
                             "by reference there (put_block_ids)")
        rows = int(k_span.shape[1])
        if rows != len(blocks) * self.block_size:
            raise ValueError(
                f"span of {rows} rows != {len(blocks)} blocks x "
                f"block_size {self.block_size}")
        nbytes = (int(getattr(k_span, "nbytes", 0))
                  + int(getattr(v_span, "nbytes", 0)))
        span = _Span(k=k_span, v=v_span, nbytes=nbytes)
        out: List[_Entry] = []
        created: List[_Entry] = []
        for i, block in enumerate(blocks):
            tokens = tuple(map(int, block))
            if len(tokens) != self.block_size:
                raise ValueError(
                    f"block of {len(tokens)} tokens != block_size "
                    f"{self.block_size} — only whole blocks are "
                    f"hashable")
            chain = self.chain_hash(parent, tokens)
            entry, blocked = self._insert_site(chain)
            if blocked:
                self._refused += 1
                break
            if entry is None:
                if not self._parent_live(parent):
                    self._refused += 1
                    logger.debug("prefix put refused: parent %.12s "
                                 "evicted", parent)
                    break
                entry = _Entry(chain=chain, parent=parent, tokens=tokens,
                               span=span, lo=i * self.block_size,
                               version=self._version)
                self._entries[chain] = entry
                self._children.setdefault(parent, set()).add(chain)
                if span.live == 0:
                    self._span_bytes += span.nbytes
                span.live += 1
                self._inserted += 1
                created.append(entry)
            self._touch(entry)
            out.append(entry)
            parent = chain
        # the call's own fresh entries are pinned THROUGH the eviction
        # pass: without this, a tight budget whose every other entry is
        # pinned would evict the blocks just inserted before the caller
        # can acquire them — handing back dead entries, killing the
        # chain a live prefill is extending, and (downstream) breaking
        # the capture path's bounded-compile contract.  The returned
        # entries are guaranteed live; callers pin them before any
        # later insert can run.
        for entry in created:
            entry.refs += 1
        try:
            self._evict_to_budget()
        finally:
            for entry in created:
                entry.refs -= 1
        return out

    @staticmethod
    def gather_kv(entries: Sequence[_Entry]) -> Tuple[object, object]:
        """Concatenate a matched chain's K/V for restore, slicing each
        span at most once: consecutive entries from the same span
        coalesce into one slice (a whole span passes through with no
        device op at all), so restoring a chain captured from one
        chunk costs one slice — not one per block."""
        if not entries:
            raise ValueError("gather_kv of an empty chain")
        if any(e.span is None for e in entries):
            raise ValueError("gather_kv of paged (by-reference) entries "
                             "— alias their block_ids instead of "
                             "materializing K/V")
        parts_k, parts_v = [], []
        i = 0
        while i < len(entries):
            first = entries[i]
            j = i
            while (j + 1 < len(entries)
                   and entries[j + 1].span is first.span
                   and entries[j + 1].lo == entries[j].lo
                   + len(entries[j].tokens)):
                j += 1
            hi = entries[j].lo + len(entries[j].tokens)
            if first.lo == 0 and hi == int(first.span.k.shape[1]):
                parts_k.append(first.span.k)
                parts_v.append(first.span.v)
            else:
                parts_k.append(first.span.k[:, first.lo:hi])
                parts_v.append(first.span.v[:, first.lo:hi])
            i = j + 1
        if len(parts_k) == 1:
            return parts_k[0], parts_v[0]
        return (jnp.concatenate(parts_k, axis=1),
                jnp.concatenate(parts_v, axis=1))

    def _evictable(self, entry: _Entry) -> bool:
        return not entry.refs and not self._children.get(entry.chain)

    def _drop(self, victim: _Entry) -> int:
        """Remove one entry and release its payload: span accounting in
        dense mode, one allocator dereference in paged mode.  Returns
        pool blocks actually freed (0 unless paged and no slot still
        shares the block)."""
        del self._entries[victim.chain]
        siblings = self._children.get(victim.parent)
        if siblings is not None:
            siblings.discard(victim.chain)
            if not siblings:
                del self._children[victim.parent]
        self._children.pop(victim.chain, None)
        freed = 0
        if victim.block_id is not None:
            freed = self._pool.deref([victim.block_id])
        else:
            victim.span.live -= 1
            if victim.span.live == 0:
                # last entry of the span gone: its device buffers are
                # droppable now (nothing else references them)
                self._span_bytes -= victim.span.nbytes
        self._evicted += 1
        return freed

    def _evict_to_budget(self) -> None:
        while self.cached_tokens > self.max_tokens:
            victim = next(
                (e for e in self._entries.values() if self._evictable(e)),
                None)               # oldest-first: dict order IS LRU order
            if victim is None:
                # everything left is pinned or mid-chain: exceeding the
                # budget transiently beats corrupting a live chain
                logger.debug(
                    "prefix cache over budget (%d > %d tokens) with no "
                    "evictable entry", self.cached_tokens, self.max_tokens)
                return
            self._drop(victim)

    # ---- paged-mode reclaim ----------------------------------------------
    def evictable_blocks(self) -> int:
        """Blocks eviction could return to the pool RIGHT NOW: unpinned
        childless entries whose pool block nothing else references (a
        shared block survives its entry's eviction until every aliasing
        slot releases, so counting it would let admission overcommit —
        the gate's reservation math needs a pessimistic floor, and
        deeper chain links freed by cascading evictions only make the
        true count higher).  Span-mode entries always free with their
        entry."""
        return sum(
            1 for e in self._entries.values()
            if self._evictable(e) and (
                self._pool is None
                or self._pool.refcount(e.block_id) == 1))

    def evict_blocks(self, n_blocks: int) -> int:
        """Free pool blocks under memory pressure by evicting LRU
        unpinned leaf entries until ``n_blocks`` blocks actually
        returned to the pool (or nothing evictable remains) — the
        block-granular backpressure hook a paged engine's allocator
        calls before raising ``BlockPoolExhausted``.  Returns blocks
        freed; pinned chains are never touched (a live prefill's chain
        beats new admissions)."""
        if self._pool is None:
            raise ValueError("evict_blocks on a span-mode cache")
        freed = 0
        while freed < n_blocks:
            victim = next(
                (e for e in self._entries.values() if self._evictable(e)),
                None)
            if victim is None:
                break
            freed += self._drop(victim)
        return freed

    def clear(self) -> None:
        """Drop every entry (refuses while any entry is pinned — a live
        slot is still being fed from the store)."""
        pinned = [e.chain for e in self._entries.values() if e.refs]
        if pinned:
            raise ValueError(
                f"clear() with {len(pinned)} pinned entr"
                f"{'y' if len(pinned) == 1 else 'ies'} — release the "
                f"live slots first")
        if self._pool is not None:
            self._pool.deref([e.block_id for e in self._entries.values()
                              if e.block_id is not None])
        self._entries.clear()
        self._children.clear()
        self._span_bytes = 0
