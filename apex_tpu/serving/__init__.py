"""apex_tpu.serving — KV-cached decode + continuous batching.

The ROADMAP's north star serves heavy traffic; this subsystem is the
inference-side counterpart of the training stack, reusing its kernels
(flash attention's masked read path, the rope offset machinery, the LM
head matmul), its amp policies, and its resilience checkpoints:

- :mod:`.kv_cache` — preallocated slot-indexed decode cache
  (``[layers, slots, max_len, kv_heads, head_dim]``) with per-slot
  lengths and pure shape-stable updates (drop-mode row scatter for
  prefill chunks, vmapped ``lax.dynamic_update_slice`` for decode
  appends): one static shape for every decode step, zero recompiles
  after warmup.
- :mod:`.paged_kv_cache` — the opt-in **paged** layout
  (``DecodeEngine(..., paged=PagedCacheConfig(...))``): a global pool
  of fixed-size K/V blocks (``[layers, num_blocks, block_size,
  kv_heads, head_dim]``) read through per-slot block tables by
  fixed-extent gathers at the same ``-1e30`` mask convention — greedy
  streams stay **bit-identical** to the dense engine while memory
  scales with *used* tokens (several times more concurrent streams
  per byte; admission prices blocks).  Prefix-cache hits become
  zero-copy block-table aliasing with refcounts
  (``DecodeEngine.alias_prefix``), ``DecodeEngine.fork_slot``
  branches a live stream the same way, and copy-on-write keeps every
  sharer of a block bit-isolated.
- :mod:`.engine` — :class:`DecodeEngine`: length-bucketed **chunked
  prefill** (a prompt chunk is padded to the smallest covering
  power-of-two bucket, so a short prompt costs a short dispatch and
  compile count is bounded by the bucket table; prompts up to
  ``max_len`` serve — chunks past the first read the cached context
  through the decode path's masked fixed-extent attention) + a jitted
  batched single-token decode step, with deterministic
  greedy/temperature/top-k sampling from explicit PRNG keys.  Prefill
  AND cached incremental decode are bit-identical to the shape-stable
  uncached full-context forward (the tier-1 acceptance tests).
  Opt-in **tensor parallelism** (``tp=TPConfig(size=N)``) wraps the
  same program bodies in ``shard_map`` over a 1-D serving mesh:
  params take the training stack's Megatron column/row split, the KV
  cache shards head-wise, lengths/tables replicate, and tp=2/4 greedy
  streams stay token-identical to the single-chip engine (logits
  argmax-tier — the psum's reduction order genuinely differs).
- :mod:`.draft` — prompt-lookup drafting for **exact-greedy
  speculative decoding**: a host-side longest-suffix n-gram match over
  each request's prompt + generated history proposes up to k candidate
  tokens (no draft model, zero device cost); the engine's bucketed
  **verify** program scores all k+1 positions in one cached
  multi-token forward and accepts the longest prefix the target's own
  greedy argmax agrees with — the emitted stream is bit-identical to
  plain one-token decode by construction, and the per-request draft
  length adapts to the measured acceptance.
- :mod:`.prefix_cache` — **cross-request prefix caching**: prompts are
  hashed as a chain of fixed-size token blocks, each entry holding the
  captured per-layer K/V for its span as owned device arrays; at
  admission the scheduler restores the longest cached chain into the
  fresh slot (``DecodeEngine.restore_prefix``) and spends prefill only
  on the uncovered suffix — bit-identical to a cold admission, because
  the restored bytes ARE what prefill would have written.  LRU
  eviction under a token budget, ref-count pinning for entries feeding
  live slots, insert-on-miss capture.  Opt-in
  (``prefix_caching=PrefixCacheConfig(...)``), default off.
- :mod:`.scheduler` — :class:`ContinuousBatchingScheduler`: bounded
  FIFO queue, slot admission at step boundaries, a per-step
  ``prefill_budget`` (in tokens) that interleaves prompt chunks with
  the shared decode step — a long admission never stalls live streams
  for its whole prefill — QUEUED → PREFILL → DECODE → DONE per-request
  state machine, EOS/max-token eviction with immediate slot reuse, and
  structured telemetry (queue depth, prefill backlog, per-chunk
  dispatch time, TTFT, per-token latency, tokens/s) via
  ``emit_event``.
- :mod:`.policy` — the **serving control plane** knob
  (``ContinuousBatchingScheduler(..., policy=SchedulingPolicy(...))``):
  priority classes with **lossless preemption** (a low-priority DECODE
  stream is evicted by capturing its cache state — dense bucketed
  snapshot or paged block references — and resumed *bit-exactly*
  later: same tokens, same f32 logits), request ``cancel(rid)``,
  arrival-relative deadline load shedding at admission and mid-queue,
  and per-tenant smooth-weighted-round-robin admission with in-flight
  caps.  Default off: a scheduler without ``policy=`` is byte-for-byte
  the FIFO scheduler.
- :mod:`.loadgen` — deterministic **open-loop workload generation**:
  seeded arrival processes (uniform / Poisson / burst trains), the
  canonical prompt mixes (shared-prefix fleet, zero-overlap, the
  bench's short-skewed length recipe), per-request deadlines, and a
  :class:`LoadGenerator` that drives the scheduler at controlled
  offered load on its injectable clock — sleep-free and bit-
  reproducible on a :class:`VirtualClock`, shedding arrivals at
  :class:`QueueFull` so overload shows up as goodput, not as a slowed
  arrival process.  Pairs with
  :class:`apex_tpu.obs.RequestTraceRecorder` +
  :func:`apex_tpu.obs.build_report` for p50/p95/p99 TTFT / TPOT /
  queue-wait and goodput SLO reports.
- :mod:`.quant` — **quantized serving** (``DecodeEngine(...,
  quant=QuantConfig(...))``, default off): int8 weights (per-output-
  channel scales, dequant fused into the existing jitted program
  families — no new compiles), int8 KV cache (per-(position, head)
  scales beside the dense slots or the paged block pool; capture hands
  out dequantized fp32 so prefix caching, speculation, preemption, and
  fleet failover stay quantization-oblivious), and an opt-in grouped-
  scale int8 tp allreduce for the per-layer psum pair.  Acceptance is
  agreement-tier: pinned greedy-stream agreement + bounded per-
  position logit error vs the fp32 engine, and ≥1.8x decode streams
  per byte of KV budget.
- :mod:`.weights` — :func:`load_serving_params`: newest *valid* step
  from a resilience checkpoint root (v1 whole-tree and v2 sharded both
  work), params subtree selection, bf16 serving casts through
  ``amp.policy``, and mesh-direct restore for tensor-parallel serving
  (``shardings=tp_param_shardings(...)`` places every leaf onto the
  serving mesh inside the restore itself — no host-replicated detour).
- :mod:`.reload` — **zero-downtime weight lifecycle** over a live
  scheduler: :class:`WeightWatcher` polls for newer *committed*
  training steps (in-process ``AsyncCheckpointer``, supervisor
  heartbeat pointer, or registry-aware root walk);
  :class:`HotReloader` restores the candidate double-buffered through
  the validated path, gates on a structural/spec check, swaps at a
  step boundary with in-flight streams preserved and the prefix cache
  version-invalidated, retains the displaced buffer for one-step
  :meth:`~HotReloader.rollback`; :class:`ShadowABScheduler` mirrors a
  deterministic traffic fraction onto a shadow engine serving
  candidate weights and builds per-arm SLO reports for the promotion
  decision.  Default off: a scheduler that never constructs these is
  byte-for-byte unchanged.

End-to-end recipe (the shape ``tests/test_serving.py`` drives)::

    from apex_tpu import serving as sv
    from apex_tpu import amp

    params, step = sv.load_serving_params(
        "/ckpts/run7", like=train_state_template, params_key="params",
        policy=amp.policy.O2())
    eng = sv.DecodeEngine(model, params, slots=8, max_len=2048,
                          prefill_len=256)
    sched = sv.ContinuousBatchingScheduler(eng, max_queue=64)
    sched.submit(sv.Request("r0", prompt_ids, max_new_tokens=128,
                            eos_id=2, temperature=0.7, top_k=40, seed=7))
    results = sched.run()              # rid -> RequestResult
"""

from apex_tpu.serving.draft import SpeculationConfig, adapt_k, propose
from apex_tpu.serving.loadgen import (
    LoadGenerator,
    LoadgenResult,
    OpenLoopWorkload,
    VirtualClock,
    burst_arrivals,
    chain_hooks,
    make_workload,
    mixed_length_prompts,
    poisson_arrivals,
    shared_prefix_prompts,
    uniform_arrivals,
    zero_overlap_prompts,
)
from apex_tpu.serving.engine import (
    DecodeEngine,
    TPConfig,
    default_draft_buckets,
    default_prefill_buckets,
    request_key,
    sample_tokens,
    token_key,
    tp_param_shardings,
)
from apex_tpu.serving.kv_cache import (
    KVCache,
    QuantKVCache,
    append_token,
    init_cache,
    init_quant_cache,
    prefill_into_slot,
    read_slot_region,
    release_slot,
    valid_token_mask,
    value_dtype,
    write_slot_region,
)
from apex_tpu.serving.paged_kv_cache import (
    BlockPoolExhausted,
    PagedCacheConfig,
    PagedCacheManager,
    PagedKVCache,
    QuantPagedKVCache,
    init_paged_cache,
    init_quant_paged_cache,
)
from apex_tpu.serving.quant import (
    QTensor,
    QuantConfig,
    dequant_params,
    evaluate_quant,
    is_quantized,
    kv_bytes_per_token,
    max_logit_error,
    param_bytes,
    quantize_params,
    quantized_allreduce,
    serving_param_spec,
    stream_agreement,
)
from apex_tpu.serving.policy import SchedulingPolicy, WeightedRoundRobin
from apex_tpu.serving.prefix_cache import PrefixCache, PrefixCacheConfig
from apex_tpu.serving.scheduler import (
    SERVED_REASONS,
    ContinuousBatchingScheduler,
    QueueFull,
    Request,
    RequestPhase,
    RequestResult,
    SchedulerStalled,
    StreamExport,
)
from apex_tpu.serving.fleet import FleetConfig, FleetRouter, ReplicaState
from apex_tpu.serving.reload import (
    ABConfig,
    HotReloader,
    ReloadOutcome,
    ShadowABScheduler,
    WeightWatcher,
    assign_arm,
)
from apex_tpu.serving.rollout import (
    CanaryGate,
    CanaryVerdict,
    RollingReloadController,
    RolloutConfig,
)
from apex_tpu.serving.weights import load_serving_params

__all__ = [
    "KVCache",
    "append_token",
    "init_cache",
    "prefill_into_slot",
    "read_slot_region",
    "release_slot",
    "valid_token_mask",
    "write_slot_region",
    "BlockPoolExhausted",
    "PagedCacheConfig",
    "PagedCacheManager",
    "PagedKVCache",
    "QuantKVCache",
    "QuantPagedKVCache",
    "init_paged_cache",
    "init_quant_cache",
    "init_quant_paged_cache",
    "value_dtype",
    "QTensor",
    "QuantConfig",
    "dequant_params",
    "evaluate_quant",
    "is_quantized",
    "kv_bytes_per_token",
    "max_logit_error",
    "param_bytes",
    "quantize_params",
    "quantized_allreduce",
    "serving_param_spec",
    "stream_agreement",
    "PrefixCache",
    "PrefixCacheConfig",
    "DecodeEngine",
    "TPConfig",
    "tp_param_shardings",
    "SpeculationConfig",
    "adapt_k",
    "default_draft_buckets",
    "default_prefill_buckets",
    "propose",
    "request_key",
    "sample_tokens",
    "token_key",
    "ContinuousBatchingScheduler",
    "QueueFull",
    "Request",
    "RequestPhase",
    "RequestResult",
    "SchedulerStalled",
    "StreamExport",
    "FleetConfig",
    "FleetRouter",
    "ReplicaState",
    "SchedulingPolicy",
    "WeightedRoundRobin",
    "SERVED_REASONS",
    "LoadGenerator",
    "LoadgenResult",
    "OpenLoopWorkload",
    "VirtualClock",
    "burst_arrivals",
    "chain_hooks",
    "make_workload",
    "mixed_length_prompts",
    "poisson_arrivals",
    "shared_prefix_prompts",
    "uniform_arrivals",
    "zero_overlap_prompts",
    "load_serving_params",
    "ABConfig",
    "HotReloader",
    "ReloadOutcome",
    "ShadowABScheduler",
    "WeightWatcher",
    "assign_arm",
    "CanaryGate",
    "CanaryVerdict",
    "RollingReloadController",
    "RolloutConfig",
]
